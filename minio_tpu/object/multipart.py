"""Multipart uploads over one erasure set.

The analogue of the reference's erasure multipart lifecycle
(cmd/erasure-multipart.go:521 NewMultipartUpload, :570 PutObjectPart,
:1093 CompleteMultipartUpload): uploads live under a system volume
staging area until complete, each part is an INDEPENDENT erasure encode
(so parts stream/retry/parallelise freely and the final object's read
path walks parts), and completion validates the client's part list
against stored part metadata before atomically assembling the final
object through the same rename-commit used by plain puts.

Part encoding is the same batched device pass as put_object — a 16x5MiB
concurrent multipart upload turns into 16 independent stripe-batch
encodes (BASELINE.json configs[4])."""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Optional

from minio_tpu.object.types import (InvalidArgument, ObjectInfo, PutOptions,
                                    WriteQuorumError)
from minio_tpu.storage.meta import (ErasureInfo, FileInfo, FileNotFoundErr,
                                    ObjectPartInfo, new_uuid, now_ns)

MIN_PART_SIZE = 5 * (1 << 20)   # all but the last part (AWS rule)
MAX_PARTS = 10_000


class UploadNotFound(Exception):
    pass


class InvalidPart(Exception):
    pass


class InvalidPartOrder(Exception):
    pass


class EntityTooSmall(Exception):
    pass


def _upload_root(bucket: str, object_: str) -> str:
    digest = hashlib.sha256(f"{bucket}/{object_}".encode()).hexdigest()[:32]
    return f"multipart/{bucket}/{digest}"


def _upload_dir(bucket: str, object_: str, upload_id: str) -> str:
    return f"{_upload_root(bucket, object_)}/{upload_id}"


def new_multipart_upload(es, bucket: str, object_: str,
                         opts: Optional[PutOptions] = None) -> str:
    from minio_tpu.object import erasure_object as eo
    opts = opts or PutOptions()
    es._check_bucket(bucket)
    n = len(es.disks)
    m = es.default_parity
    if opts.storage_class == "REDUCED_REDUNDANCY" and n > 1:
        m = max(1, min(m, 2))
    k = n - m
    upload_id = new_uuid()
    record = {
        "bucket": bucket, "object": object_, "upload_id": upload_id,
        "k": k, "m": m,
        "distribution": eo.hash_order(f"{bucket}/{object_}", n),
        "user_metadata": {k: v for k, v in opts.user_metadata.items()
                          if not k.startswith("x-internal-")},
        # SSE params, object-lock state, ...: applied to the final
        # object's metadata at complete (the reference persists them in
        # the upload's fileInfo the same way).
        "internal_metadata": dict(opts.internal_metadata),
        "content_type": opts.content_type,
        "versioned": bool(opts.versioned),
        "initiated": now_ns(),
    }
    blob = json.dumps(record).encode()
    path = f"{_upload_dir(bucket, object_, upload_id)}/upload.json"
    _, errors = es._fanout(
        [lambda d=d: d.write_all(eo.SYS_VOL, path, blob) for d in es.disks])
    if sum(e is None for e in errors) < n // 2 + 1:
        raise WriteQuorumError(bucket, object_)
    return upload_id


def _read_upload(es, bucket: str, object_: str, upload_id: str) -> dict:
    from minio_tpu.object import erasure_object as eo
    path = f"{_upload_dir(bucket, object_, upload_id)}/upload.json"
    results, _ = es._fanout(
        [lambda d=d: d.read_all(eo.SYS_VOL, path) for d in es.disks])
    for r in results:
        if r is not None:
            try:
                return json.loads(r)
            except ValueError:
                continue
    raise UploadNotFound(upload_id)


def get_multipart_upload(es, bucket: str, object_: str,
                         upload_id: str) -> dict:
    """The upload's persisted record (metadata, EC layout) — the API
    layer consults it for SSE parameters before encrypting parts."""
    return _read_upload(es, bucket, object_, upload_id)


def put_object_part(es, bucket: str, object_: str, upload_id: str,
                    part_number: int, data,
                    actual_size: Optional[int] = None,
                    nonce: str = "") -> ObjectPartInfo:
    """`actual_size`: logical (pre-transform) part size when `data` is
    a transformed stream (SSE ciphertext); defaults to the stored
    size. `nonce`: the part's DARE base nonce (base64) when encrypted —
    fresh per attempt, persisted with the part so re-uploads never
    reuse an AES-GCM (key, nonce) pair."""
    from minio_tpu.object import erasure_object as eo
    from minio_tpu.utils.streams import Payload
    if not (1 <= part_number <= MAX_PARTS):
        raise InvalidArgument(bucket, object_, "part number out of range")
    rec = _read_upload(es, bucket, object_, upload_id)
    k, m, dist = rec["k"], rec["m"], rec["distribution"]
    n = k + m
    write_quorum = k + (1 if k == m else 0)
    payload = Payload.wrap(data)
    size = payload.size
    logical = actual_size if actual_size is not None else size
    # Each upload attempt gets its own data file; the atomic .meta replace
    # referencing it is the commit point, so a crash or concurrent
    # re-upload of the same part can never pair a torn data file with a
    # .meta that validates (the reference stages parts through tmp +
    # rename, cmd/erasure-multipart.go:570).
    attempt = new_uuid()
    data_file = f"part.{part_number}.{attempt}"
    updir = _upload_dir(bucket, object_, upload_id)

    if size > eo.STREAM_THRESHOLD:
        # O(window) streaming: shard files stream in windows, then the
        # .meta commit fans out to the drives whose data write landed.
        def path_for(i: int):
            return es.disks[i], eo.SYS_VOL, f"{updir}/{data_file}"

        def cleanup_staged():
            es._fanout([lambda d=d: eo._swallow(
                lambda: d.delete(eo.SYS_VOL, f"{updir}/{data_file}"))
                for d in es.disks])

        try:
            etag, werrors = es._stream_framed_writes(payload, k, m, dist,
                                                     path_for)
        except Exception:
            cleanup_staged()
            raise
        staged = [i for i in range(n) if werrors[i] is None]
        if len(staged) < write_quorum:
            cleanup_staged()
            raise WriteQuorumError(bucket, object_)
        meta = {"number": part_number, "size": size,
                "actual_size": logical, "etag": etag, "mod_time": now_ns(),
                "file": data_file, "nonce": nonce}
        blob = json.dumps(meta).encode()
        _, merrors = es._fanout(
            [lambda i=i: es.disks[i].write_all(
                eo.SYS_VOL, f"{updir}/part.{part_number}.meta", blob)
             for i in staged])
        if sum(e2 is None for e2 in merrors) < write_quorum:
            cleanup_staged()
            raise WriteQuorumError(bucket, object_)
        return ObjectPartInfo(number=part_number, size=size,
                              actual_size=logical, etag=etag,
                              mod_time=meta["mod_time"], nonce=nonce)

    body = payload.read_all()
    # Pool-leased fused framing (io/bufpool + native mtpu_put_frame):
    # each drive's writer holds its own lease reference until its shard
    # write truly finishes (_leased_fns), so a deadline-abandoned
    # writer can never read a recycled window buffer.
    framed, frames_lease = es._frame_windows(body, k, m)
    etag = hashlib.md5(body).hexdigest()
    meta = {"number": part_number, "size": size,
            "actual_size": logical, "etag": etag, "mod_time": now_ns(),
            "file": data_file, "nonce": nonce}

    def write_one(disk_idx: int):
        d = es.disks[disk_idx]
        shard_idx = dist[disk_idx] - 1
        d.create_file(eo.SYS_VOL, f"{updir}/{data_file}",
                      list(framed[shard_idx]))
        d.write_all(eo.SYS_VOL, f"{updir}/part.{part_number}.meta",
                    json.dumps(meta).encode())

    try:
        _, errors = es._fanout(eo._leased_fns(
            [lambda i=i: write_one(i) for i in range(n)], frames_lease))
    finally:
        if frames_lease is not None:
            frames_lease.release()
    if sum(e2 is None for e2 in errors) < write_quorum:
        raise WriteQuorumError(bucket, object_)
    return ObjectPartInfo(number=part_number, size=size,
                          actual_size=logical, etag=etag,
                          mod_time=meta["mod_time"], nonce=nonce)


def _read_part_meta(es, updir: str, part_number: int) -> Optional[dict]:
    from minio_tpu.object import erasure_object as eo
    results, _ = es._fanout(
        [lambda d=d: d.read_all(eo.SYS_VOL, f"{updir}/part.{part_number}.meta")
         for d in es.disks])
    votes: dict[bytes, int] = {}
    for r in results:
        if r is not None:
            votes[r] = votes.get(r, 0) + 1
    if not votes:
        return None
    try:
        return json.loads(max(votes, key=lambda b: votes[b]))
    except ValueError:
        return None


def list_parts(es, bucket: str, object_: str, upload_id: str,
               part_marker: int = 0, max_parts: int = 1000) -> list[dict]:
    from minio_tpu.object import erasure_object as eo
    _read_upload(es, bucket, object_, upload_id)  # existence check
    updir = _upload_dir(bucket, object_, upload_id)
    found: dict[int, dict] = {}
    results, _ = es._fanout(
        [lambda d=d: d.list_dir(eo.SYS_VOL, updir) for d in es.disks])
    numbers = set()
    for entries in results:
        for name in entries or ():
            if name.startswith("part.") and name.endswith(".meta"):
                try:
                    numbers.add(int(name[len("part."):-len(".meta")]))
                except ValueError:
                    pass
    for num in sorted(numbers):
        if num <= part_marker:
            continue
        meta = _read_part_meta(es, updir, num)
        if meta:
            found[num] = meta
        if len(found) >= max_parts:
            break
    return [found[n2] for n2 in sorted(found)]


def list_multipart_uploads(es, bucket: str, prefix: str = "") -> list[dict]:
    from minio_tpu.object import erasure_object as eo
    es._check_bucket(bucket)
    out = []
    seen = set()
    for d in es.disks[:len(es.disks) // 2 + 1]:
        try:
            hashes = d.list_dir(eo.SYS_VOL, f"multipart/{bucket}")
        except Exception:  # noqa: BLE001
            continue
        for hdir in hashes:
            hdir = hdir.rstrip("/")
            try:
                uploads = d.list_dir(eo.SYS_VOL, f"multipart/{bucket}/{hdir}")
            except Exception:  # noqa: BLE001
                continue
            for uid in uploads:
                uid = uid.rstrip("/")
                if uid in seen:
                    continue
                try:
                    rec = json.loads(d.read_all(
                        eo.SYS_VOL,
                        f"multipart/{bucket}/{hdir}/{uid}/upload.json"))
                except Exception:  # noqa: BLE001
                    continue
                if rec.get("object", "").startswith(prefix):
                    seen.add(uid)
                    out.append(rec)
    out.sort(key=lambda r: (r.get("object", ""), r.get("initiated", 0)))
    return out


def abort_multipart_upload(es, bucket: str, object_: str,
                           upload_id: str) -> None:
    from minio_tpu.object import erasure_object as eo
    _read_upload(es, bucket, object_, upload_id)
    updir = _upload_dir(bucket, object_, upload_id)
    es._fanout([lambda d=d: _try(lambda: d.delete(eo.SYS_VOL, updir,
                                                  recursive=True))
                for d in es.disks])


def complete_multipart_upload(es, bucket: str, object_: str, upload_id: str,
                              parts: list[tuple[int, str]]) -> ObjectInfo:
    """parts: [(part_number, etag), ...] in the client's declared order."""
    from minio_tpu.object import erasure_object as eo
    rec = _read_upload(es, bucket, object_, upload_id)
    k, m, dist = rec["k"], rec["m"], rec["distribution"]
    n = k + m
    updir = _upload_dir(bucket, object_, upload_id)
    if not parts:
        raise InvalidPart("empty part list")
    if any(parts[i][0] >= parts[i + 1][0] for i in range(len(parts) - 1)):
        raise InvalidPartOrder()

    fi_parts: list[ObjectPartInfo] = []
    part_files: dict[int, str] = {}
    md5_concat = b""
    total = 0
    actual_total = 0
    for idx, (num, etag) in enumerate(parts):
        meta = _read_part_meta(es, updir, num)
        clean = etag.strip('"')
        if meta is None or meta["etag"] != clean:
            raise InvalidPart(f"part {num}")
        if meta["actual_size"] < MIN_PART_SIZE and idx != len(parts) - 1:
            # The S3 minimum is on the CLIENT payload; ciphertext
            # expansion must not let an undersized part slip through.
            raise EntityTooSmall(f"part {num}")
        fi_parts.append(ObjectPartInfo(
            number=num, size=meta["size"], actual_size=meta["actual_size"],
            etag=clean, mod_time=meta["mod_time"],
            nonce=meta.get("nonce", "")))
        part_files[num] = meta.get("file", f"part.{num}")
        md5_concat += bytes.fromhex(clean)
        total += meta["size"]
        actual_total += meta["actual_size"]

    etag = hashlib.md5(md5_concat).hexdigest() + f"-{len(parts)}"
    version_id = new_uuid() if rec.get("versioned") else ""
    mod_time = now_ns()
    data_dir = new_uuid()
    metadata = dict(rec.get("user_metadata") or {})
    metadata.update(rec.get("internal_metadata") or {})
    if metadata.get("x-internal-sse-alg"):
        # The plaintext size is unknowable at initiate; the summed part
        # logical sizes ARE it (the GET path and HEAD report from this
        # key, crypto/sse.py META_SIZE).
        metadata["x-internal-sse-size"] = str(actual_total)
    metadata["etag"] = etag
    if rec.get("content_type"):
        metadata["content-type"] = rec["content_type"]

    def commit_one(disk_idx: int):
        d = es.disks[disk_idx]
        shard_idx = dist[disk_idx] - 1
        staging = eo.new_staging()
        for num, _ in parts:
            d.rename_file(eo.SYS_VOL, f"{updir}/{part_files[num]}",
                          eo.SYS_VOL, f"{staging}/{data_dir}/part.{num}")
        fi = FileInfo(
            volume=bucket, name=object_, version_id=version_id,
            deleted=False, data_dir=data_dir, mod_time=mod_time,
            size=total, metadata=metadata, parts=list(fi_parts),
            erasure=ErasureInfo(
                data_blocks=k, parity_blocks=m,
                block_size=eo.BLOCK_SIZE, index=shard_idx + 1,
                distribution=tuple(dist)))
        d.rename_data(eo.SYS_VOL, staging, fi, bucket, object_)

    # Namespace write lock: the final assembly is an object commit and
    # must serialize with puts/deletes/heals of the same key.
    with es.ns.write(bucket, object_):
        _, errors = es._fanout(
            [lambda i=i: commit_one(i) for i in range(n)])
    ok = sum(e2 is None for e2 in errors)
    write_quorum = k + (1 if k == m else 0)
    if ok < write_quorum:
        raise WriteQuorumError(bucket, object_,
                               f"committed {ok}/{n}")
    if ok < n:
        es.mrf.enqueue(bucket, object_, version_id)
    # Drop the upload dir (part files already moved on the disks that
    # committed; stale copies elsewhere go with the dir).
    es._fanout([lambda d=d: _try(lambda: d.delete(eo.SYS_VOL, updir,
                                                  recursive=True))
                for d in es.disks])
    es.metacache.bump(bucket)
    return ObjectInfo(bucket=bucket, name=object_, mod_time=mod_time,
                      size=total, etag=etag,
                      content_type=rec.get("content_type", ""),
                      version_id=version_id,
                      user_metadata=dict(rec.get("user_metadata") or {}),
                      parts=fi_parts, actual_size=total)


def _try(fn):
    try:
        fn()
    except Exception:  # noqa: BLE001
        pass
