"""GF(2^8) arithmetic and Reed-Solomon matrix construction.

Field: GF(2^8) with the reducing polynomial x^8+x^4+x^3+x^2+1 (0x11D),
generator element 2 — the same field the reference's erasure codec uses
(reference: cmd/erasure-coding.go:63 dispatching to klauspost/reedsolomon,
which ports Backblaze's JavaReedSolomon Galois tables). The encoding matrix
is the classic systematic Vandermonde construction: build V[r][c] = r^c over
GF(2^8) for r in [0, n), invert the top k x k block, and right-multiply so
the first k rows become the identity. Parity rows are then a pure GF matmul
against the data shards. Reproducing this construction exactly is what makes
our shards byte-identical to the reference's (validated by the golden
xxhash64 digests from cmd/erasure-coding.go:163).

Everything here is host-side (numpy) table math: building the (tiny) coding
matrices, inverting sub-matrices for reconstruct, and decomposing GF(2^8)
constant-multiplications into GF(2) bit-matrices for the TPU bitplane-matmul
path (see minio_tpu/ops/rs_device.py).
"""

from __future__ import annotations

import functools

import numpy as np

_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """exp/log tables for GF(2^8) with generator 2."""
    exp = np.zeros(512, dtype=np.uint16)
    log = np.zeros(256, dtype=np.uint16)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    # Duplicate so exp[(log a + log b)] never needs an explicit mod.
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    return exp.astype(np.uint8), log

EXP_TABLE, LOG_TABLE = _build_tables()

def _build_mul_table() -> np.ndarray:
    """Full 256x256 multiplication table (64 KiB) — the workhorse for
    host-side encode/verify paths and per-coefficient lookup tables."""
    nz = np.arange(1, 256, dtype=np.uint16)
    log_sum = (LOG_TABLE[nz][:, None].astype(np.int32)
               + LOG_TABLE[nz][None, :].astype(np.int32))
    mul = np.zeros((256, 256), dtype=np.uint8)
    mul[1:, 1:] = EXP_TABLE[log_sum % 255]
    return mul

MUL_TABLE = _build_mul_table()


def gf_mul(a: int, b: int) -> int:
    return int(MUL_TABLE[a, b])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) - int(LOG_TABLE[b])) % 255])


def gf_exp(a: int, n: int) -> int:
    """a**n in GF(2^8) (matches the reference dependency's galExp)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) * n) % 255])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product of small uint8 matrices (table lookups + XOR)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    assert a.shape[1] == b.shape[0]
    # products[i, k, j] = a[i, k] * b[k, j]; XOR-reduce over k.
    prod = MUL_TABLE[a[:, :, None], b[None, :, :]]
    return np.bitwise_xor.reduce(prod, axis=1)


def gf_inverse(m: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix via Gauss-Jordan elimination.

    Raises ValueError if singular. Mirrors the augmented-matrix elimination
    the reference's dependency uses, so reconstruct picks identical inverses.
    """
    m = np.asarray(m, dtype=np.uint8)
    n = m.shape[0]
    assert m.shape == (n, n)
    work = np.concatenate([m, np.eye(n, dtype=np.uint8)], axis=1)
    for r in range(n):
        if work[r, r] == 0:
            # Find a row below with a non-zero entry in this column and swap.
            for r2 in range(r + 1, n):
                if work[r2, r] != 0:
                    work[[r, r2]] = work[[r2, r]]
                    break
            else:
                raise ValueError("singular matrix")
        # Scale pivot row so the pivot becomes 1.
        pivot = int(work[r, r])
        if pivot != 1:
            inv_pivot = gf_div(1, pivot)
            work[r] = MUL_TABLE[inv_pivot, work[r]]
        # Eliminate this column from every other row.
        for r2 in range(n):
            if r2 != r and work[r2, r] != 0:
                work[r2] ^= MUL_TABLE[int(work[r2, r]), work[r]]
    return work[:, n:].copy()


@functools.lru_cache(maxsize=4096)
def coding_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """The (k+m) x k systematic coding matrix, identical to the reference's.

    Top k rows are the identity; bottom m rows are the parity coefficients.
    Construction: Vandermonde V[r][c] = r^c, right-multiplied by the inverse
    of its top k x k block.
    """
    k, m = data_shards, parity_shards
    n = k + m
    if k <= 0 or m < 0:
        raise ValueError("invalid shard counts")
    if n > 256:
        raise ValueError("too many shards for GF(2^8)")
    vm = np.zeros((n, k), dtype=np.uint8)
    for r in range(n):
        for c in range(k):
            vm[r, c] = gf_exp(r, c)
    top_inv = gf_inverse(vm[:k, :k])
    mat = gf_matmul(vm, top_inv)
    mat.setflags(write=False)
    return mat


def parity_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """Just the m x k parity rows of the coding matrix."""
    return coding_matrix(data_shards, parity_shards)[data_shards:, :]


@functools.lru_cache(maxsize=4096)
def decode_matrix(data_shards: int, parity_shards: int,
                  available: tuple[int, ...]) -> np.ndarray:
    """k x k matrix that maps k surviving shards back to the k data shards.

    `available` is a sorted tuple of exactly k surviving shard indices
    (0..k+m-1). Rows of the coding matrix for those shards are gathered and
    inverted, exactly as the reference's ReconstructData does with the first
    k valid shards.
    """
    k = data_shards
    if len(available) != k:
        raise ValueError(f"need exactly {k} surviving shards")
    full = coding_matrix(data_shards, parity_shards)
    sub = full[list(available), :]
    out = gf_inverse(sub)
    out.setflags(write=False)
    return out


def gf_matvec_bytes(matrix: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """Apply an (r x k) GF matrix to k shards of bytes: out[r] = XOR_j m[r,j]*in[j].

    shards: uint8 array [k, shard_len]. Returns [r, shard_len]. Host (numpy)
    reference path; the device path lives in rs_device.py.
    """
    matrix = np.asarray(matrix, dtype=np.uint8)
    shards = np.asarray(shards, dtype=np.uint8)
    r, k = matrix.shape
    assert shards.shape[0] == k
    out = np.zeros((r, shards.shape[1]), dtype=np.uint8)
    for i in range(r):
        acc = out[i]
        for j in range(k):
            c = int(matrix[i, j])
            if c == 0:
                continue
            if c == 1:
                acc ^= shards[j]
            else:
                acc ^= MUL_TABLE[c][shards[j]]
    return out


# ---------------------------------------------------------------------------
# GF(2) bit-matrix decomposition — the bridge to the TPU MXU path.
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _const_mul_bitmatrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix B such that bits(c*x) = B @ bits(x) mod 2.

    Bit order: index 0 = least-significant bit. Multiplication by a constant
    is GF(2)-linear, so it is fully described by its action on the 8 basis
    bytes 1<<j.
    """
    b = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        y = gf_mul(c, 1 << j)
        for i in range(8):
            b[i, j] = (y >> i) & 1
    return b


def bit_matrix(matrix: np.ndarray) -> np.ndarray:
    """Expand an (r x k) GF(2^8) matrix into an (r*8 x k*8) GF(2) matrix.

    With data bytes unpacked to bitplanes, the whole Reed-Solomon transform
    becomes a binary matmul followed by mod-2 — which is how we feed it to
    the TPU MXU. The device path MUST accumulate in int32
    (preferred_element_type=jnp.int32): dot-product sums reach k*8 ones
    (up to 2048 for the max k=256), which overflows bf16's exact-integer
    range past k=16, but is always exact with int8 operands + int32
    accumulation.
    """
    matrix = np.asarray(matrix, dtype=np.uint8)
    r, k = matrix.shape
    out = np.zeros((r * 8, k * 8), dtype=np.uint8)
    for i in range(r):
        for j in range(k):
            out[i * 8:(i + 1) * 8, j * 8:(j + 1) * 8] = _const_mul_bitmatrix(int(matrix[i, j]))
    return out
