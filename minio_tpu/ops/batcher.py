"""Cross-request stripe batching for the fused PUT pipeline.

The blueprint's most TPU-native idea (BASELINE.json: "shard batches from
parallelWriter ... are coalesced into HBM-resident tensors so a full
erasure set's stripes encode in one pmap"): stripe windows from MANY
concurrent PutObject calls coalesce into ONE device step — the batch
dimension becomes "stripes from many requests" — and completions
demultiplex back to the waiting writers. The reference's analogue is the
opposite trade (each goroutine encodes its own blocks on its own core,
cmd/erasure-encode.go:27 multiWriter); on a TPU the accelerator is one
big shared core, so batching across requests is what fills it.

Dispatch policy is MEASURED, not assumed: a one-time background probe
times the device round trip (host->HBM transfer + fused kernel +
readback) against the host codec for the same bytes. Where the device
link is fast (PCIe-local TPU) batches beat the host and route to the
device; where it is slow (e.g. a tunneled remote chip) everything stays
on the host codec and the batcher degrades to a pass-through. A lone
PUT with no concurrency never waits: frame() bypasses the queue
entirely unless other requests are already in flight.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

# Batch-dim padding buckets: one compiled device shape per bucket, not
# one per distinct concurrency level.
_BUCKETS = (8, 16, 32, 64, 128, 256)
# How long the first window of a burst waits for company.
_MAX_WAIT_S = 0.002
# Cap per dispatched device batch (VMEM/HBM bound upstream anyway).
_MAX_BATCH_BLOCKS = 256


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return _BUCKETS[-1]


class _Pending:
    __slots__ = ("stacked", "rows", "exc", "event")

    def __init__(self, stacked: np.ndarray):
        self.stacked = stacked
        self.rows = None
        self.exc: Optional[BaseException] = None
        self.event = threading.Event()


class StripeBatcher:
    """Coalesces concurrent frame() calls of one EC config.

    device_fn(stacked [B, k, L] u8) -> per-drive rows (the
    make_encode_framer run() contract); host_fn(stacked) -> same rows
    via the host codec. Both must be thread-safe.
    """

    def __init__(self, device_fn: Callable, host_fn: Callable,
                 probe_fn: Optional[Callable] = None,
                 min_device_blocks: int = 8,
                 max_wait_s: float = _MAX_WAIT_S):
        self._device_fn = device_fn
        self._host_fn = host_fn
        self._min_device_blocks = min_device_blocks
        self._max_wait = max_wait_s
        self._mu = threading.Condition()
        self._pending: list[_Pending] = []
        self._deadline = 0.0
        self._inflight = 0          # frame() calls currently active
        self._dispatcher: Optional[threading.Thread] = None
        self._closed = False
        # Calibration: None = unknown (host until probed), True/False.
        self._device_ok: Optional[bool] = None
        self._probe_fn = probe_fn
        self._probe_started = False

    # -- calibration ----------------------------------------------------

    def _default_probe(self, sample: np.ndarray) -> bool:
        """Time device vs host on one representative batch (the first
        request's config, widened to a device-worthy block count);
        True when the device round trip wins."""
        stacked = np.zeros(
            (_bucket(self._min_device_blocks),) + sample.shape[1:],
            dtype=np.uint8)
        try:
            self._device_fn(stacked)           # compile
            t0 = time.perf_counter()
            self._device_fn(stacked)
            t_dev = time.perf_counter() - t0
        except Exception:  # noqa: BLE001 - no device -> host
            return False
        t0 = time.perf_counter()
        self._host_fn(stacked)
        t_host = time.perf_counter() - t0
        return t_dev < t_host

    def _ensure_probe(self, sample: np.ndarray) -> None:
        with self._mu:
            # Check-and-set under the lock: two first-users racing here
            # would otherwise run two probes whose device/host timings
            # pollute each other.
            if self._probe_started:
                return
            self._probe_started = True

        def probe():
            try:
                if self._probe_fn is not None:
                    ok = bool(self._probe_fn())
                else:
                    ok = self._default_probe(sample)
            except Exception:  # noqa: BLE001 - probe failure -> host
                ok = False
            with self._mu:
                self._device_ok = ok

        # Non-daemon: a daemon probe mid-device-call at interpreter
        # exit aborts the process from inside the runtime (terminate
        # without rethrow); joining at exit costs at most one compile.
        threading.Thread(target=probe, daemon=False,
                         name="stripe-batcher-probe").start()

    def wants_device(self) -> bool:
        """False only once calibration has RESOLVED to host — the
        caller can then skip the batcher entirely (its own host path is
        at least as good, without the queue/lock hop). Unprobed (None)
        answers True so traffic keeps flowing through frame() until the
        probe settles."""
        return self._device_ok is not False

    def force(self, device_ok: bool) -> None:
        """Pin the calibration verdict (bench/tests): no probe runs,
        dispatch follows `device_ok` unconditionally."""
        with self._mu:
            self._probe_started = True
            self._device_ok = bool(device_ok)

    def reset_calibration(self) -> None:
        """Back to unprobed (bench/tests cleanup after force())."""
        with self._mu:
            self._probe_started = False
            self._device_ok = None

    # -- submission -----------------------------------------------------

    def frame(self, stacked: np.ndarray):
        """Frame one request's stripe window [B, k, L]; blocks until
        the (possibly coalesced) result is ready. Returns per-drive
        rows for exactly this window's blocks."""
        if self._device_ok is False:
            # Calibration resolved to host: genuinely free pass-through
            # — no lock, no inflight bookkeeping, no condition-variable
            # hop, just the host codec (the unlocked read is safe: the
            # verdict transitions once, None -> True/False).
            return self._host_fn(stacked)
        big = stacked.shape[0] >= self._min_device_blocks
        with self._mu:
            self._inflight += 1
            solo = self._inflight == 1 and not self._pending
        try:
            if big or not solo:
                # Worth calibrating: either this window alone is
                # device-sized, or there is company to coalesce with.
                # (A lone small PUT never probes — the probe's device
                # compile would steal host CPU from a workload that is
                # not even a batching candidate.)
                self._ensure_probe(stacked)
            if solo:
                if big and self._device_ok:
                    # A single device-sized window (e.g. a streaming
                    # PUT's 32-block window) needs no queue — dispatch
                    # straight to the fused pipeline, padded to the
                    # same fixed buckets as coalesced batches so a
                    # ragged tail window can't compile a fresh shape.
                    b = stacked.shape[0]
                    pad = _bucket(b) - b
                    if pad > 0:
                        stacked = np.concatenate(
                            [stacked,
                             np.zeros((pad,) + stacked.shape[1:],
                                      dtype=stacked.dtype)])
                    rows = self._device_fn(stacked)
                    return [drive[:b] for drive in rows] if pad > 0 \
                        else rows
                return self._host_fn(stacked)
            if self._device_ok is not True:
                return self._host_fn(stacked)
            return self._enqueue(stacked)
        finally:
            with self._mu:
                self._inflight -= 1

    def _enqueue(self, stacked: np.ndarray):
        p = _Pending(stacked)
        with self._mu:
            if not self._pending:
                self._deadline = time.monotonic() + self._max_wait
            self._pending.append(p)
            # _dispatcher is cleared (under this lock) by the loop
            # BEFORE it exits, so is_alive() can never claim a thread
            # that has already decided to die with our entry unseen.
            if self._dispatcher is None or not self._dispatcher.is_alive():
                self._dispatcher = threading.Thread(
                    target=self._dispatch_loop, daemon=True,
                    name="stripe-batcher")
                self._dispatcher.start()
            # Always wake the dispatcher: if it is parked in its idle
            # 0.2 s poll, an un-notified append would stretch the 2 ms
            # coalescing window into a 200 ms latency spike.
            self._mu.notify_all()
        p.event.wait()
        if p.exc is not None:
            raise p.exc
        return p.rows

    # -- dispatch -------------------------------------------------------

    def _dispatch_loop(self) -> None:
        while True:
            with self._mu:
                while not self._pending and not self._closed:
                    self._mu.wait(timeout=0.2)
                    if not self._pending and self._inflight == 0:
                        # Idle: clear the handle BEFORE dying (still
                        # under the lock) so a racing _enqueue starts
                        # a fresh dispatcher instead of trusting a
                        # thread that will never look again.
                        self._dispatcher = None
                        return
                if self._closed and not self._pending:
                    self._dispatcher = None
                    return
                now = time.monotonic()
                total = sum(e.stacked.shape[0] for e in self._pending)
                if total < _MAX_BATCH_BLOCKS and now < self._deadline \
                        and not self._closed:
                    self._mu.wait(timeout=self._deadline - now)
                    continue
                # Drain at most one bucket's worth per dispatch; the
                # remainder keeps its place for the next round (an
                # unbounded drain could exceed the largest pad bucket).
                batch, rest = [], []
                taken = 0
                for p in self._pending:
                    c = p.stacked.shape[0]
                    if batch and taken + c > _MAX_BATCH_BLOCKS:
                        rest.append(p)
                    else:
                        batch.append(p)
                        taken += c
                self._pending = rest
                if rest:
                    self._deadline = now      # no extra wait for them
            self._run_batch(batch)

    def _run_batch(self, batch: list[_Pending]) -> None:
        counts = [p.stacked.shape[0] for p in batch]
        total = sum(counts)
        try:
            if total >= self._min_device_blocks and self._device_ok:
                stacked = np.concatenate([p.stacked for p in batch]) \
                    if len(batch) > 1 else batch[0].stacked
                pad = max(0, _bucket(total) - total)
                if pad:
                    stacked = np.concatenate(
                        [stacked, np.zeros((pad,) + stacked.shape[1:],
                                           dtype=stacked.dtype)])
                rows_all = self._device_fn(stacked)
                off = 0
                for p, c in zip(batch, counts):
                    p.rows = [drive[off:off + c] for drive in rows_all]
                    off += c
            else:
                for p in batch:
                    p.rows = self._host_fn(p.stacked)
        except BaseException as e:  # noqa: BLE001 - deliver to waiters
            for p in batch:
                p.exc = e
        finally:
            for p in batch:
                p.event.set()

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._mu.notify_all()
