"""Device-resident cross-request stripe batching on the sharded codec.

The blueprint's most TPU-native idea (BASELINE.json: "shard batches from
parallelWriter ... are coalesced into HBM-resident tensors so a full
erasure set's stripes encode in one pmap"): stripe windows from MANY
concurrent PutObject calls coalesce into ONE device step — the batch
dimension becomes "stripes from many requests" — and completions
demultiplex back to the waiting writers, whose per-drive shard writes
then ride the io/engine drive queues exactly like solo PUTs. The
reference's analogue is the opposite trade (each goroutine encodes its
own blocks on its own core, cmd/erasure-encode.go:27 multiWriter); on a
TPU the accelerator is one big shared mesh, so batching across requests
is what fills it.

What makes the batch DEVICE-resident (ops/hh_device.make_mesh_framer):
the coalesced window is staged into ONE pooled bufpool buffer, padded to
a fixed power-of-two bucket, and dispatched as a pjit-style sharded step
— NamedSharding(mesh, P("stripe")) splits the batch dim over every
available chip and `donate_argnums` hands the staged HBM buffer to the
kernel so data flows host -> HBM -> parity with no defensive copy. One
compiled executable exists per (bucket, EC config), never per
concurrency level. All device dispatches in the process serialize
through the shared io/engine kernel lane (the chip is one resource, like
a drive), which also yields wait-vs-service attribution for free.

Dispatch policy is MEASURED, not assumed: a one-time background probe
times the device round trip (host->HBM transfer + fused kernel +
readback) against the host codec for the same bytes. Where the device
link is fast (PCIe-local TPU) batches beat the host and route to the
device; where it is slow (e.g. a tunneled remote chip) everything stays
on the host codec and the batcher degrades to a pass-through. A lone
PUT with no concurrency never waits: frame() bypasses the queue
entirely unless other requests are already in flight. The accumulation
window is ADAPTIVE: it opens at the measured base wait, stretches while
bursts keep filling whole buckets, shrinks toward zero while traffic is
sparse, dispatches early the moment one mesh-filling batch is pending,
and never holds a member past its request deadline (members whose
budget is already spent fail alone — they are culled before dispatch
and cannot poison batch-mates).

Every batched dispatch is also one `kernel` span FANNED into each
member request's span tree (utils/tracing.record_into): a traced PUT
shows the shared dispatch it rode — batch size, bucket, mesh width,
its own coalescing wait — not a gap.

The same machinery runs the READ path in reverse (the decode mirror,
PR "device-resident read path"): a batcher carries a `route` —
  * "put"          — encode+frame windows (the original),
  * "get"          — framed-window bitrot verification (the device
                     de-framer, hh_device.make_mesh_deframer; members
                     are [B, k, 32+shard] stacked on-disk frames),
  * "reconstruct"  — batched GF decode-matrix application for degraded
                     reads / heal rebuilds (rs_device.make_mesh_matrix;
                     members are [B, k, shard] survivor stripes).
  * "transform"    — the fused single-pass data plane's frame stage
                     (object/transform.py): stored windows that already
                     ran digest/compress/DARE through the native
                     transform kernel coalesce here, calibrated and
                     forceable independently of raw PUT windows.
Routes calibrate INDEPENDENTLY (one batcher instance per route and
config): a host whose device link wins on encode but loses on decode —
or vice versa — routes each direction on its own measurement, and
MTPU_BATCH_FORCE accepts per-route pins. Non-put routes plug in a
`split_fn` that demultiplexes the shared dispatch result back to
member-sized results (the PUT-specific digest/block re-pointing stays
the default), and a `concat_fn` that splices oversized windows'
chunked results. Members whose trailing shapes differ (e.g. heal
verify batches from objects of different EC configs through one
verifier) never share a staging buffer: the dispatcher drains
same-shape runs per batch.

Environment:
  MTPU_BATCH_FORCE    device|host|auto (default auto): pin the
                      calibration verdict — reproducible benches/CI
                      instead of a silent probe-dependent route.
                      Accepts per-route pins as a comma list, e.g.
                      "put=device,get=host" (unnamed routes stay auto).
  MTPU_BATCH_WAIT_MS  base accumulation window in ms (default 2).
  MTPU_GET_BATCH_WAIT_MS
                      base window for the get/reconstruct routes
                      (default: MTPU_BATCH_WAIT_MS) — read latency
                      budgets are tighter than write ones, so the
                      decode coalescing window tunes separately.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Callable, Optional

import numpy as np

from minio_tpu.io.engine import EngineSaturated, kernel_lane
from minio_tpu.utils import deadline as deadline_mod
from minio_tpu.utils import tracing
from minio_tpu.utils.deadline import DeadlineExceeded
from minio_tpu.utils.latency import Histogram

# Batch-dim padding buckets: one compiled device shape per bucket, not
# one per distinct concurrency level. Powers of two so every bucket
# divides evenly across a power-of-two chip mesh (hh_device
# mesh_batch_devices) with zero per-chip remainder shapes.
_BUCKETS = (8, 16, 32, 64, 128, 256)
# Base accumulation window (first window of a burst); the adaptive
# controller moves the live value between _MIN_WAIT_S and this.
_MAX_WAIT_S = 0.002
_MIN_WAIT_S = 0.00025
# Cap per dispatched device batch (VMEM/HBM bound upstream anyway).
_MAX_BATCH_BLOCKS = 256
# Stripe blocks per chip that saturate one chip's fused pipeline: the
# accumulation window stops waiting the moment the pending total can
# feed the whole mesh at this depth.
_PER_CHIP_BLOCKS = 32
# A member must dispatch at least this long before its deadline — the
# device round trip plus demux must fit in what remains.
_DEADLINE_SLACK_S = 0.005


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return _BUCKETS[-1]


def _env_wait_s(route: str = "put") -> float:
    raw = os.environ.get("MTPU_BATCH_WAIT_MS", "")
    if route in ("get", "reconstruct"):
        raw = os.environ.get("MTPU_GET_BATCH_WAIT_MS", "") or raw
    try:
        return max(0.0, float(raw or 2.0)) / 1000.0
    except ValueError:
        return _MAX_WAIT_S


def batch_force_mode(route: str = "put") -> str:
    """The MTPU_BATCH_FORCE verdict for `route`: "device", "host", or
    "auto". A bare value pins every route; a comma list of
    `route=value` pairs pins each independently (the encode/decode
    small-fix: a host that wins on encode but loses on decode — or the
    reverse — must be forceable per direction, and the auto
    calibration already measures each route's own device_fn/host_fn
    rivalry)."""
    v = os.environ.get("MTPU_BATCH_FORCE", "auto").strip().lower()
    if "=" in v:
        out = "auto"
        for part in v.split(","):
            r, _, m = part.partition("=")
            if r.strip() == route and m.strip() in ("device", "host",
                                                    "auto"):
                out = m.strip()
        return out
    return v if v in ("device", "host") else "auto"


def _default_concat(rows, chunk):
    """Oversized-window splice for the PUT rows contract: per-drive
    lists of per-block piece tuples concatenate drive-wise."""
    return [r + c for r, c in zip(rows, chunk)]


class _Pending:
    __slots__ = ("stacked", "count", "rows", "exc", "event", "expires_at",
                 "tctx", "tparent", "t_enq", "route_taken")

    def __init__(self, stacked: np.ndarray,
                 dl: Optional[deadline_mod.Deadline]):
        self.stacked = stacked
        self.count = stacked.shape[0]
        self.rows = None
        self.exc: Optional[BaseException] = None
        self.event = threading.Event()
        self.expires_at = dl.expires_at if dl is not None else None
        self.tctx, self.tparent = tracing.capture() if tracing.ACTIVE \
            else (None, 0)
        self.t_enq = time.perf_counter()
        self.route_taken = "host"      # resolved by _run_batch


# Live batchers, for fleet-wide occupancy metrics (s3/metrics.py
# renders minio_tpu_batcher_* from aggregate_stats()).
_REGISTRY: "weakref.WeakSet[StripeBatcher]" = weakref.WeakSet()


ROUTES = ("put", "get", "reconstruct", "transform")


def _route_zero() -> dict:
    return {
        "dispatches": {"device": 0, "host": 0},
        "requests": {"device": 0, "host": 0, "bypass": 0},
        "buckets": {},
        "batched_blocks": 0,
        "capacity_blocks": 0,
        "deadline_failures": 0,
        "wait_hist": None,
        "fill_ratio": 0.0,
    }


def aggregate_stats() -> dict:
    """Occupancy stats across every live batcher, summed PER ROUTE
    (put|get|reconstruct): dispatch/path/bucket counters, fill
    accounting, the coalescing wait histogram, deadline culls — plus
    the decode-route kernel-lane service histogram (the get/
    reconstruct dispatches' share of the shared accelerator lane)."""
    out = {
        "routes": {r: _route_zero() for r in ROUTES},
        "mesh_devices": 0,
        "forced": {r: batch_force_mode(r) for r in ROUTES},
        "decode_lane_hist": None,
    }
    hists: dict[str, list] = {r: [] for r in ROUTES}
    decode_lane = []
    for sb in list(_REGISTRY):
        st = sb.stats()
        route = st.get("route", "put")
        agg = out["routes"].setdefault(route, _route_zero())
        for key in ("device", "host"):
            agg["dispatches"][key] += st["dispatches"][key]
        for key in ("device", "host", "bypass"):
            agg["requests"][key] += st["requests"][key]
        for b, v in st["buckets"].items():
            agg["buckets"][b] = agg["buckets"].get(b, 0) + v
        agg["batched_blocks"] += st["batched_blocks"]
        agg["capacity_blocks"] += st["capacity_blocks"]
        agg["deadline_failures"] += st["deadline_failures"]
        out["mesh_devices"] = max(out["mesh_devices"], st["mesh_devices"])
        hists.setdefault(route, []).append(st["wait_hist"])
        if route in ("get", "reconstruct"):
            decode_lane.append(st["lane_hist"])
    for r, agg in out["routes"].items():
        hs = hists.get(r, [])
        agg["wait_hist"] = Histogram.merge(hs) if hs \
            else Histogram().state()
        cap = agg["capacity_blocks"]
        agg["fill_ratio"] = (agg["batched_blocks"] / cap) if cap else 0.0
    out["decode_lane_hist"] = Histogram.merge(decode_lane) \
        if decode_lane else Histogram().state()
    return out


class StripeBatcher:
    """Coalesces concurrent frame() calls of one EC config.

    device_fn(stacked [B, k, L] u8) -> per-drive rows (the
    make_mesh_framer / make_encode_framer run() contract);
    host_fn(stacked) -> same rows via the host codec. Both must be
    thread-safe. `pool` (io/bufpool.BufferPool) backs the coalesced
    staging buffer — its lease is RETAINED for the whole dispatch, so a
    donated host buffer can never be recycled under an in-flight
    host->HBM transfer.
    """

    def __init__(self, device_fn: Callable, host_fn: Callable,
                 probe_fn: Optional[Callable] = None,
                 min_device_blocks: int = 8,
                 max_wait_s: Optional[float] = None,
                 pool=None, name: str = "", route: str = "put",
                 split_fn: Optional[Callable] = None,
                 concat_fn: Optional[Callable] = None):
        self._device_fn = device_fn
        self._host_fn = host_fn
        self._min_device_blocks = min_device_blocks
        self._max_wait = _env_wait_s(route) if max_wait_s is None \
            else max_wait_s
        self._cur_wait = self._max_wait
        self._pool = pool
        self.name = name
        self.route = route
        # split_fn(result, off, count, member_stacked) -> member result:
        # how one coalesced dispatch's output demultiplexes back to a
        # member (None = the PUT per-drive rows contract). concat_fn
        # splices chunked oversized-window results back together.
        self._split_fn = split_fn
        self._concat = concat_fn if concat_fn is not None \
            else _default_concat
        self.mesh_devices = max(1, int(getattr(device_fn, "mesh_devices",
                                               1) or 1))
        self._mu = threading.Condition()
        self._pending: list[_Pending] = []
        self._deadline = 0.0            # current window's dispatch-by time
        self._inflight = 0              # frame() calls currently active
        self._dispatcher: Optional[threading.Thread] = None
        self._closed = False
        # Calibration: None = unknown (host until probed), True/False.
        self._device_ok: Optional[bool] = None
        self._probe_fn = probe_fn
        self._probe_started = False
        forced = batch_force_mode(route)
        if forced != "auto":
            self._probe_started = True
            self._device_ok = forced == "device"
        # Occupancy stats (own lock: the dispatcher holds _mu at the
        # moments hot paths want to count).
        self._stat_mu = threading.Lock()
        self._dispatches = {"device": 0, "host": 0}
        self._requests = {"device": 0, "host": 0, "bypass": 0}
        # Calibrated-host bypass count: bumped WITHOUT _stat_mu on the
        # zero-overhead pass-through, folded into stats() reads.
        self._bypass_approx = 0
        self._bucket_dispatches: dict[int, int] = {}
        self._batched_blocks = 0
        self._capacity_blocks = 0
        self._deadline_failures = 0
        self._wait_hist = Histogram()
        # Per-calling-thread record of the last frame() dispatch path
        # (device|host|bypass): callers with their own fused host
        # kernel read last_route() to keep path metrics honest — a
        # coalesced batch below min_device_blocks resolves to the host
        # fallback even under a device calibration, and that must not
        # be counted as a device window.
        self._local = threading.local()
        # Kernel-lane service time of this batcher's device dispatches
        # (submit-to-result through io/engine.kernel_lane). For decode
        # routes this is the read path's share of the shared
        # accelerator — exported as the decode-route lane histogram.
        self._lane_hist = Histogram()
        _REGISTRY.add(self)

    # -- calibration ----------------------------------------------------

    def _default_probe(self, sample: np.ndarray) -> bool:
        """Time device vs host on one representative batch (the first
        request's config, widened to a device-worthy block count);
        True when the device round trip wins."""
        stacked = np.zeros(
            (_bucket(max(self._min_device_blocks, self.mesh_devices)),)
            + sample.shape[1:], dtype=np.uint8)
        try:
            self._device_fn(stacked)           # compile
            t0 = time.perf_counter()
            self._device_fn(stacked)
            t_dev = time.perf_counter() - t0
        except Exception:  # noqa: BLE001 - no device -> host
            return False
        t0 = time.perf_counter()
        self._host_fn(stacked)
        t_host = time.perf_counter() - t0
        return t_dev < t_host

    def _ensure_probe(self, sample: np.ndarray) -> None:
        with self._mu:
            # Check-and-set under the lock: two first-users racing here
            # would otherwise run two probes whose device/host timings
            # pollute each other.
            if self._probe_started:
                return
            self._probe_started = True

        def probe():
            try:
                if self._probe_fn is not None:
                    ok = bool(self._probe_fn())
                else:
                    ok = self._default_probe(sample)
            except Exception:  # noqa: BLE001 - probe failure -> host
                ok = False
            with self._mu:
                self._device_ok = ok

        # Non-daemon: a daemon probe mid-device-call at interpreter
        # exit aborts the process from inside the runtime (terminate
        # without rethrow); joining at exit costs at most one compile.
        threading.Thread(target=probe, daemon=False,
                         name="stripe-batcher-probe").start()

    def wants_device(self) -> bool:
        """False only once calibration has RESOLVED to host — the
        caller can then skip the batcher entirely (its own host path is
        at least as good, without the queue/lock hop). Unprobed (None)
        answers True so traffic keeps flowing through frame() until the
        probe settles."""
        return self._device_ok is not False

    def worth_batching(self, blocks: int) -> bool:
        """True when frame(`blocks`) could plausibly take the device
        route RIGHT NOW: calibration has not resolved to host, and
        either the window alone is device-sized or other requests are
        in flight to coalesce with. Callers with a fused native host
        kernel of their own (the GET window's mtpu_get_frame) consult
        this before stacking a member — a solo sub-threshold window
        should ride the native kernel, not the batcher's generic host
        fallback."""
        if self._device_ok is False:
            return False
        return blocks >= self._min_device_blocks or self._inflight > 0 \
            or bool(self._pending)

    def force(self, device_ok: bool) -> None:
        """Pin the calibration verdict (bench/tests): no probe runs,
        dispatch follows `device_ok` unconditionally. The env knob
        MTPU_BATCH_FORCE=device|host applies the same pin at
        construction (CI/bench reproducibility: a slow-link probe must
        not silently degrade a measured run to pass-through)."""
        with self._mu:
            self._probe_started = True
            self._device_ok = bool(device_ok)

    def reset_calibration(self) -> None:
        """Back to the configured default (bench/tests cleanup after
        force()): unprobed under auto, re-pinned under a
        MTPU_BATCH_FORCE override."""
        with self._mu:
            forced = batch_force_mode(self.route)
            if forced != "auto":
                self._probe_started = True
                self._device_ok = forced == "device"
            else:
                self._probe_started = False
                self._device_ok = None

    # -- observability --------------------------------------------------

    def stats(self) -> dict:
        with self._stat_mu:
            requests = dict(self._requests)
            requests["bypass"] += self._bypass_approx
            return {
                "name": self.name,
                "route": self.route,
                "mesh_devices": self.mesh_devices,
                "dispatches": dict(self._dispatches),
                "requests": requests,
                "buckets": dict(self._bucket_dispatches),
                "batched_blocks": self._batched_blocks,
                "capacity_blocks": self._capacity_blocks,
                "deadline_failures": self._deadline_failures,
                "wait_hist": self._wait_hist.state(),
                "lane_hist": self._lane_hist.state(),
                "window_s": self._cur_wait,
            }

    def _note_request(self, route: str, n: int = 1) -> None:
        with self._stat_mu:
            self._requests[route] += n

    # -- submission -----------------------------------------------------

    def frame(self, stacked: np.ndarray):
        """Frame one request's stripe window [B, k, L]; blocks until
        the (possibly coalesced) result is ready. Returns per-drive
        rows for exactly this window's blocks. Raises DeadlineExceeded
        without touching the device when the caller's budget is
        already spent."""
        if self._device_ok is False:
            # Calibration resolved to host: genuinely free pass-through
            # — no lock, no inflight bookkeeping, no condition-variable
            # hop, just the host codec (the unlocked read is safe: the
            # verdict transitions once, None -> True/False). The counter
            # bump is unlocked too — approximate under races, and the
            # only shared state this path touches.
            self._bypass_approx += 1
            self._local.route = "bypass"
            return self._host_fn(stacked)
        if stacked.shape[0] > _MAX_BATCH_BLOCKS:
            # An oversized window (whole-part framing of a huge
            # multipart/copy part can exceed the largest padding
            # bucket) must never reach _stage as one pending — the
            # staging buffer is at most _BUCKETS[-1] rows, and a mesh
            # dispatch needs a divisible batch. Dispatch bucket-sized
            # chunks through the same path (each rides the device or
            # host route on its own merits) and splice the per-drive
            # rows back together.
            rows = None
            routes = set()
            for off in range(0, stacked.shape[0], _MAX_BATCH_BLOCKS):
                chunk = self.frame(stacked[off:off + _MAX_BATCH_BLOCKS])
                routes.add(self.last_route())
                rows = chunk if rows is None else self._concat(rows, chunk)
            self._local.route = "device" if "device" in routes \
                else routes.pop()
            return rows
        dl = deadline_mod.current()
        if dl is not None and dl.expired():
            with self._stat_mu:
                self._deadline_failures += 1
            raise DeadlineExceeded("request deadline exceeded")
        big = stacked.shape[0] >= self._min_device_blocks
        with self._mu:
            self._inflight += 1
            solo = self._inflight == 1 and not self._pending
        try:
            if big or not solo:
                # Worth calibrating: either this window alone is
                # device-sized, or there is company to coalesce with.
                # (A lone small PUT never probes — the probe's device
                # compile would steal host CPU from a workload that is
                # not even a batching candidate.)
                self._ensure_probe(stacked)
            if solo:
                if big and self._device_ok:
                    # A single device-sized window (e.g. a streaming
                    # PUT's 32-block window) needs no queue — dispatch
                    # straight through the shared batch path (same
                    # staging, padding buckets, kernel lane, tracing).
                    p = _Pending(stacked, dl)
                    self._run_batch([p])
                    self._local.route = p.route_taken
                    if p.exc is not None:
                        raise p.exc
                    return p.rows
                self._note_request("bypass")
                self._local.route = "bypass"
                return self._host_fn(stacked)
            if self._device_ok is not True:
                self._note_request("host")
                self._local.route = "host"
                return self._host_fn(stacked)
            return self._enqueue(stacked, dl)
        finally:
            with self._mu:
                self._inflight -= 1

    def _enqueue(self, stacked: np.ndarray, dl):
        p = _Pending(stacked, dl)
        with self._mu:
            if not self._pending:
                self._deadline = time.monotonic() + self._cur_wait
            self._pending.append(p)
            # _dispatcher is cleared (under this lock) by the loop
            # BEFORE it exits, so is_alive() can never claim a thread
            # that has already decided to die with our entry unseen.
            if self._dispatcher is None or not self._dispatcher.is_alive():
                self._dispatcher = threading.Thread(
                    target=self._dispatch_loop, daemon=True,
                    name="stripe-batcher")
                self._dispatcher.start()
            # Always wake the dispatcher: if it is parked in its idle
            # 0.2 s poll, an un-notified append would stretch the
            # coalescing window into a 200 ms latency spike.
            self._mu.notify_all()
        p.event.wait()
        self._local.route = p.route_taken
        if p.exc is not None:
            raise p.exc
        return p.rows

    def last_route(self) -> str:
        """The dispatch path the CALLING thread's last frame() took:
        "device" (rode a device dispatch), "host" (served by the host
        fallback — calibration unresolved, or a coalesced batch below
        min_device_blocks), or "bypass" (calibrated-host pass-through
        / lone small window). Callers with a fused native kernel of
        their own use this to label path metrics honestly."""
        return getattr(self._local, "route", "host")

    # -- dispatch -------------------------------------------------------

    def _fill_target(self) -> int:
        """Pending blocks that saturate the mesh: stop accumulating
        the moment one dispatch can feed every chip at working depth."""
        return min(_MAX_BATCH_BLOCKS,
                   max(self._min_device_blocks,
                       self.mesh_devices * _PER_CHIP_BLOCKS))

    def _adapt_window(self, fill_ratio: float) -> None:
        """Depth-aware accumulation: buckets dispatching full mean the
        burst outruns the window — stretch it (more coalescing per
        compile is paying for itself); sparse dispatches mean waiting
        only adds latency — shrink toward pass-through."""
        if fill_ratio >= 0.75:
            self._cur_wait = min(self._max_wait, self._cur_wait * 1.5)
        elif fill_ratio < 0.25:
            self._cur_wait = max(_MIN_WAIT_S, self._cur_wait * 0.5)

    def _dispatch_loop(self) -> None:
        while True:
            with self._mu:
                while not self._pending and not self._closed:
                    self._mu.wait(timeout=0.2)
                    if not self._pending and self._inflight == 0:
                        # Idle: clear the handle BEFORE dying (still
                        # under the lock) so a racing _enqueue starts
                        # a fresh dispatcher instead of trusting a
                        # thread that will never look again.
                        self._dispatcher = None
                        return
                if self._closed and not self._pending:
                    self._dispatcher = None
                    return
                now = time.monotonic()
                total = sum(p.count for p in self._pending)
                # The window closes at the adaptive deadline, when the
                # mesh can be fed at full depth, or in time for the
                # EARLIEST member deadline — a coalesced batch must
                # respect the most impatient request riding it.
                bound = self._deadline
                expiries = [p.expires_at for p in self._pending
                            if p.expires_at is not None]
                if expiries:
                    bound = min(bound, min(expiries) - _DEADLINE_SLACK_S)
                if total < self._fill_target() and now < bound \
                        and not self._closed:
                    self._mu.wait(timeout=bound - now)
                    continue
                # Drain at most one bucket's worth per dispatch; the
                # remainder keeps its place for the next round (an
                # unbounded drain could exceed the largest pad bucket).
                batch, rest = [], []
                taken = 0
                for p in self._pending:
                    c = p.count
                    if batch and (taken + c > _MAX_BATCH_BLOCKS
                                  or p.stacked.shape[1:]
                                  != batch[0].stacked.shape[1:]):
                        # Over the bucket cap, or a DIFFERENT member
                        # geometry (heal verifies of mixed EC configs
                        # share one route batcher): staging copies
                        # members into one [bucket, *trail] buffer, so
                        # a batch is one trailing shape — the rest
                        # keeps its place for the next round.
                        rest.append(p)
                    else:
                        batch.append(p)
                        taken += c
                self._pending = rest
                if rest:
                    self._deadline = now      # no extra wait for them
            self._run_batch(batch)

    def _stage(self, live: list[_Pending], bucket: int):
        """(lease, stacked [bucket, k, L]): members copied into ONE
        pooled staging buffer, zero-padded to the bucket. The lease is
        held by the caller for the whole dispatch — donation safety:
        the buffer the device is still reading can never be recycled
        into a new lease mid-transfer. Returns (None, member array)
        when a lone member already fills the bucket exactly."""
        if len(live) == 1 and live[0].count == bucket:
            return None, live[0].stacked
        shape = (bucket,) + live[0].stacked.shape[1:]
        lease = None
        stacked = None
        if self._pool is not None:
            try:
                lease = self._pool.lease(int(np.prod(shape)))
                stacked = lease.ndarray(shape)
            except Exception:  # noqa: BLE001 - pool pressure -> fresh
                lease = None
        if stacked is None:
            stacked = np.empty(shape, dtype=np.uint8)
        off = 0
        for p in live:
            stacked[off:off + p.count] = p.stacked
            off += p.count
        if off < bucket:
            # Zero the pad rows: a recycled pool buffer carries stale
            # bytes, and deterministic pads keep batched output
            # byte-stable run to run (the pad rows' parity/digests are
            # sliced off either way).
            stacked[off:] = 0
        return lease, stacked

    def _lane_dispatch(self, stacked: np.ndarray):
        """Run the device framer through the process-wide kernel lane
        (serialized device access + wait/service attribution); falls
        back to a direct call if the lane is saturated or closed."""
        try:
            fut = kernel_lane().submit(lambda: self._device_fn(stacked))
        except EngineSaturated:
            return self._device_fn(stacked)
        return fut.result()

    def _run_batch(self, batch: list[_Pending]) -> None:
        # Cull members whose budget is already spent: they fail ALONE
        # (DeadlineExceeded, counted) and never poison batch-mates —
        # the dispatch proceeds without them.
        now = time.monotonic()
        live, dead = [], []
        for p in batch:
            if p.expires_at is not None \
                    and now >= p.expires_at - 1e-9:
                dead.append(p)
            else:
                live.append(p)
        if dead:
            with self._stat_mu:
                self._deadline_failures += len(dead)
            for p in dead:
                p.exc = DeadlineExceeded(
                    "request deadline exceeded before batch dispatch")
                p.event.set()
        if not live:
            return
        counts = [p.count for p in live]
        total = sum(counts)
        # Never pick a bucket narrower than the mesh: the device run()
        # requires batch % mesh_devices == 0, and small dispatches on a
        # wide mesh (e.g. 8 blocks across 16 chips) would otherwise
        # fail every batch member.
        bucket = _bucket(max(total, self.mesh_devices))
        route = "host"
        t_wall = time.time()
        t0 = time.perf_counter()
        try:
            if total >= self._min_device_blocks and self._device_ok:
                route = "device"
                lease, stacked = self._stage(live, bucket)
                t_lane = time.perf_counter()
                try:
                    rows_all = self._lane_dispatch(stacked)
                finally:
                    # The dispatch is synchronous through the readback
                    # (the framer returns host numpy), so the staging
                    # buffer is done feeding HBM here — and not before.
                    self._lane_hist.observe(time.perf_counter() - t_lane)
                    if lease is not None:
                        lease.release()
                if self._split_fn is not None:
                    # Route-specific demux (get: verdict slices + data
                    # views of the member's OWN window; reconstruct:
                    # rebuilt-row slices).
                    off = 0
                    for p, c in zip(live, counts):
                        p.rows = self._split_fn(rows_all, off, c,
                                                p.stacked)
                        off += c
                else:
                    k = live[0].stacked.shape[1]
                    staged = lease is not None or len(live) > 1
                    off = 0
                    for p, c in zip(live, counts):
                        rows = [drive[off:off + c] for drive in rows_all]
                        if staged:
                            # Demultiplex data drives back onto each
                            # member's OWN window: device rows view the
                            # shared staging buffer whose lease just
                            # returned to the pool; digests/parity are
                            # fresh device output and stay as-is.
                            for i in range(k):
                                rows[i] = [(dig, p.stacked[bi, i])
                                           for bi, (dig, _blk)
                                           in enumerate(rows[i])]
                        p.rows = rows
                        off += c
                with self._stat_mu:
                    self._dispatches["device"] += 1
                    self._requests["device"] += len(live)
                    self._bucket_dispatches[bucket] = \
                        self._bucket_dispatches.get(bucket, 0) + 1
                    self._batched_blocks += total
                    self._capacity_blocks += bucket
                self._adapt_window(total / bucket)
            else:
                for p in live:
                    p.rows = self._host_fn(p.stacked)
                with self._stat_mu:
                    self._dispatches["host"] += 1
                    self._requests["host"] += len(live)
                # Host-routed dispatches are the sparse case (total
                # below min_device_blocks) — adapt here too, or light
                # steady traffic pins _cur_wait at whatever a past
                # burst stretched it to and every small PUT pays the
                # full window forever.
                self._adapt_window(total / bucket)
        except BaseException as e:  # noqa: BLE001 - deliver to waiters
            for p in live:
                p.exc = e
        finally:
            dur_ms = (time.perf_counter() - t0) * 1000.0
            for p in live:
                p.route_taken = route
                wait_s = max(0.0, t0 - p.t_enq)
                self._wait_hist.observe(wait_s)
                if p.tctx is not None:
                    # ONE kernel span fanned into each member's tree.
                    tracing.record_into(
                        p.tctx, p.tparent, "kernel", "batcher.dispatch",
                        t_wall, dur_ms,
                        tags={"blocks": p.count, "batch_blocks": total,
                              "bucket": bucket, "members": len(live),
                              "route": route,
                              "mesh_devices": self.mesh_devices,
                              "wait_ms": round(wait_s * 1000.0, 3)})
                p.event.set()

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._mu.notify_all()
