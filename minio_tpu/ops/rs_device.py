"""TPU device path for the Reed-Solomon GF(2^8) transform.

This is the north-star kernel (BASELINE.json): the reference runs its
erasure math through hand-written AVX2/AVX512/GFNI Galois kernels inside
github.com/klauspost/reedsolomon (reference: cmd/erasure-coding.go:59-71);
we run the *same* linear transform on the TPU MXU instead.

Formulation — bitplane decomposition to GF(2):
  GF(2^8) multiplication by a constant is GF(2)-linear on the 8 bits of the
  input byte, so an (r x k) GF(2^8) coding matrix expands to an
  (r*8 x k*8) 0/1 matrix over GF(2) (minio_tpu/ops/gf256.bit_matrix). With
  data bytes unpacked into bitplanes, the whole Reed-Solomon transform
  becomes ONE int8 matmul (contraction length k*8 <= 128 for k <= 16 — a
  perfect fit for one MXU pass) followed by `& 1` (the mod-2) and a
  shift-sum repack to bytes. Accumulation must be int32
  (preferred_element_type): dot sums reach k*8 ones, exact in int32, NOT
  exact in bf16 past k=16. This mirrors how GFNI expresses GF(2^8) ops as
  8x8 bit-matrix affine transforms, mapped onto a 128x128 systolic array.

Two implementations behind one `DeviceBackend`:
  * `_xla_apply` — pure jax.numpy, runs anywhere (CPU tests, the virtual
    8-device mesh) and lets XLA fuse unpack/pack. Materialises the 8x
    bitplane expansion in HBM, so it is bandwidth-bound at ~1/17 of peak.
  * `_pallas_apply` — fused Pallas kernel: unpack -> matmul -> mod2 -> pack
    all inside VMEM per tile, so HBM traffic is just bytes-in + parity-out
    (~(1 + r/k) x). Bit rows/cols are PLANE-major (row = plane*width + byte)
    so in-kernel unpack is a static concatenate of 8 shifted views and the
    repack is 8 static sublane slices — no strided sublane access, which
    Mosaic does not support.

Both produce bytes identical to the host numpy backend and therefore to the
reference's shards (golden digests, cmd/erasure-coding.go:163).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from minio_tpu.ops import gf256

# Lane width of the TPU vector unit; tiles are sized in multiples of this.
_LANES = 128
# Lane-tile ceiling and per-cell VMEM budget for the Pallas kernel. Measured
# on v5e (axon): large tiles win decisively — grid-cell overhead dominates
# below ~32k lanes (5.6 GB/s at 1k-lane tiles vs 120 GB/s at 128k-lane
# tiles with two batch rows per cell for EC 8+4 on 1 MiB blocks).
_TILE_L_MAX = 131072
# Starting budget for the _choose_tile guess (v5e scoped VMEM caps cells
# at 16 MiB, but Mosaic's padding/double-buffering makes real usage
# opaque — the compile-retry loop in apply_matrix_device is the actual
# enforcement; this just sets where the probe starts).
_VMEM_BUDGET = 32 * 1024 * 1024


def _choose_tile(k: int, r: int, l: int, b: int) -> tuple[int, int]:
    """(lane_tile, batch_rows_per_cell) subject to the VMEM budget.

    Per-cell VMEM ~ bits[k*8, T] int8 + acc[r*8, T] int32 + data/out tiles.
    The tile is a power of two, so padding l up to a tile multiple and then
    re-deriving the tile from the padded l is a fixed point — the wrapper
    and the jitted body always agree.
    """
    # bits int8 [k8,T] + unpack temps + acc int32 [r8,T] + data/out tiles.
    # This is only the STARTING guess: the scoped-VMEM ceiling on v5e is
    # 16 MiB and Mosaic's real allocation (padding of small sublane dims,
    # double-buffered grid cells) is opaque, so apply_matrix_device
    # halves the tile and retries whenever the compile overflows VMEM,
    # caching what worked (see _working_tile).
    per_lane = k * 8 + r * 8 * 4 + 2 * (k + r)
    tile = _LANES
    while tile < _TILE_L_MAX and tile * 2 * per_lane <= _VMEM_BUDGET and tile < l:
        tile *= 2
    bb = 2 if b % 2 == 0 else 1
    return tile, bb


# (k, r, bb) -> lane-tile cap learned from VMEM compile failures.
_tile_cap: dict[tuple[int, int, int], int] = {}
# (k, r, bb, tile) combos that compiled successfully (skip the probe sync).
_tile_ok: set[tuple[int, int, int, int]] = set()


def _is_vmem_error(e: Exception) -> bool:
    # Only the actual scoped-VMEM overflow signature — a transient
    # compile-service error or unrelated Mosaic failure must surface
    # immediately, not trigger halve-and-retry (which would poison
    # _tile_cap at the minimum tile).
    return "vmem" in str(e).lower()


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# Matrix preprocessing (host side, cached)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=4096)
def _prep_cached(key: bytes, r: int, k: int) -> tuple[np.ndarray, np.ndarray]:
    """(byte-major bitmatrix [r8,k8], plane-major bitmatrix [r8,k8]) int8."""
    matrix = np.frombuffer(key, dtype=np.uint8).reshape(r, k)
    bm = gf256.bit_matrix(matrix).astype(np.int8)  # rows j*8+c, cols i*8+b
    col_perm = np.arange(k * 8).reshape(k, 8).T.reshape(-1)  # b*k+i <- i*8+b
    row_perm = np.arange(r * 8).reshape(r, 8).T.reshape(-1)  # c*r+j <- j*8+c
    bm_plane = bm[row_perm][:, col_perm]
    return bm, bm_plane


@functools.lru_cache(maxsize=64)
def _repack_weights(r: int) -> np.ndarray:
    """int8 [r, r8] weights matmul that packs plane-major mod-2 planes
    back to bytes on the MXU: out[j] = sum_c acc[c*r+j] * 2^c. The 2^7
    weight stores as int8 -128; consumers mask the product with & 0xFF,
    which recovers the byte exactly under two's complement."""
    w = np.zeros((r, r * 8), dtype=np.uint8)
    for c in range(8):
        for j in range(r):
            w[j, c * r + j] = 1 << c
    return w.view(np.int8)


def _prep(matrix: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    return _prep_cached(matrix.tobytes(), matrix.shape[0], matrix.shape[1])


# ---------------------------------------------------------------------------
# Pure-XLA path (portable)
# ---------------------------------------------------------------------------

@jax.jit
def _xla_apply(bmat: jax.Array, data: jax.Array) -> jax.Array:
    """bmat int8 [r8, k8] (byte-major), data uint8 [B, k, L] -> uint8 [B, r, L]."""
    b, k, l = data.shape
    r = bmat.shape[0] // 8
    x = data.astype(jnp.int32)
    shifts = jnp.arange(8, dtype=jnp.int32)
    bits = ((x[:, :, None, :] >> shifts[None, None, :, None]) & 1)  # [B,k,8,L]
    bits = bits.reshape(b, k * 8, l).astype(jnp.int8)
    acc = jnp.einsum("rk,bkl->brl", bmat, bits,
                     preferred_element_type=jnp.int32)
    outbits = (acc & 1).reshape(b, r, 8, l)
    weights = (jnp.int32(1) << shifts)[None, None, :, None]
    out = jnp.sum(outbits * weights, axis=2)
    return out.astype(jnp.uint8)


# ---------------------------------------------------------------------------
# Fused Pallas kernel
# ---------------------------------------------------------------------------

def _rs_kernel(bmat_ref, wrep_ref, data_ref, out_ref):
    """One (batch, lane-tile) cell: fused unpack -> GF(2) matmul -> pack.

    bmat_ref: int8 [r8, k8] PLANE-major both axes (row c*r+j, col b*k+i).
    wrep_ref: int8 [r, r8] repack weights (_repack_weights).
    data_ref: uint8 [bb, k, TL]; out_ref: uint8 [bb, r, TL].

    Two measured v5e rules shape this kernel: (a) int8 arrays tile as
    (32, 128) per vreg, so concatenating 8-row int8 pieces forces
    sublane shuffles — build the bitplanes in int32 (natural (8, 128)
    tiles) and cast ONCE; (b) the mod-2 repack as shift/or loops is
    ~25% of kernel time — one tiny weights matmul does it on the MXU
    instead (0.92 ms vs 1.38 ms for EC 8+4 on 128 MiB).
    """
    k = data_ref.shape[1]
    r = out_ref.shape[1]
    for i in range(data_ref.shape[0]):
        x = data_ref[i].astype(jnp.int32)  # [k, TL]
        # Plane-major unpack: row b*k+i holds bit b of shard i. Static
        # concat — no sublane interleaving needed. (Shifts must be int32:
        # Mosaic cannot legalize arith.shrui on 8-bit vectors.)
        bits = jnp.concatenate(
            [(x >> b) & 1 for b in range(8)], axis=0).astype(jnp.int8)
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)  # [r8, TL]
        accb = (acc & 1).astype(jnp.int8)
        packed = jax.lax.dot_general(
            wrep_ref[:], accb,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)  # [r, TL] byte values
        out_ref[i] = (packed & 0xFF).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("tile", "bb", "interpret"))
def _pallas_apply(bmat_plane: jax.Array, data: jax.Array, tile: int,
                  bb: int, interpret: bool = False) -> jax.Array:
    """bmat_plane int8 [r8, k8] (plane-major), data uint8 [B, k, L_padded]."""
    b, k, l = data.shape
    r8 = bmat_plane.shape[0]
    r = r8 // 8
    # Loud failure beats silently-unwritten output tails: callers must pad
    # (DeviceBackend.apply_matrix_device / make_encoder do).
    assert l % tile == 0, f"lane dim {l} not a multiple of tile {tile}"
    assert b % bb == 0, f"batch dim {b} not a multiple of {bb}"
    grid = (b // bb, l // tile)
    wrep = jnp.asarray(_repack_weights(r))
    return pl.pallas_call(
        _rs_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((r8, k * 8), lambda ib, il: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r, r8), lambda ib, il: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, k, tile), lambda ib, il: (ib, 0, il),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bb, r, tile), lambda ib, il: (ib, 0, il),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, r, l), jnp.uint8),
        interpret=interpret,
    )(bmat_plane, wrep, data)


# ---------------------------------------------------------------------------
# u32-lane variant (for the fused encode+bitrot pipeline)
# ---------------------------------------------------------------------------
# Byte-level device arrays pay a hidden tax: TPU tiles uint8 along
# sublanes, so bitcasting u8 shards to the u32 words HighwayHash needs
# is a ~35 GiB/s relayout — slower than the hash itself. This variant
# keeps the WHOLE pipeline in u32 lanes: each lane holds 4 consecutive
# shard bytes and the output is directly the word layout the hash
# kernel consumes. Byte-identical to the u8 path.
#
# The kernel unpacks bits in the i8 DOMAIN: pltpu.bitcast reinterprets
# the u32 tile as u8 rows in-register (row = 4*shard + byte_slot,
# measured v5e layout), where each bit extraction is and/cmp/select on
# (32, 128)-dense i8 vregs — 4x the elements per op of the old
# u32-domain shift+mask unpack (which cost 64 VPU ops per word, the
# kernel's former governor). The byte slots ride the ROW axis, so the
# GF(2) matrix expands block-diagonally per slot (_prep8), and the
# mod-2 repack is a slice/or tree straight out of the i32 accumulator —
# measured faster than the weights-matmul repack here because it skips
# the [r8, lanes] i32->i8 cast relayout. 131 -> ~170 GiB/s on v5e for
# EC 8+4 on 1 MiB blocks.

@functools.lru_cache(maxsize=4096)
def _prep8_cached(key: bytes, r: int, k: int) -> np.ndarray:
    """Plane-PAIR-packed block-diagonal bit matrix int8 [16*rp, 32k]
    for the i8-row layout (rp = r rounded up to even so byte rows tile
    in 8s): row a = p*4rp + 4*jr + slot carries bit planes 2p (weight
    +1) and 2p+1 (weight -128) of output byte row 4*jr + slot; col =
    b*4k + 4*i + slot. Packing two GF(2) planes per accumulator row —
    recoverable because the +1 part sums to < 128 for k <= 15 — halves
    the [rows, lanes] i32 accumulator, whose VMEM round-trip is the
    kernel's real cost on v5e."""
    matrix = np.frombuffer(key, dtype=np.uint8).reshape(r, k)
    assert k <= 15, "plane-pair packing requires k <= 15"
    bm = gf256.bit_matrix(matrix)          # [r8, k8]: row jr*8+c, col i*8+b
    rp = r + (r & 1)
    planes = np.zeros((8, 4 * rp, 32 * k), dtype=np.int32)
    for c in range(8):
        for jr in range(r):
            for j in range(4):
                for b in range(8):
                    for i in range(k):
                        planes[c, 4 * jr + j, b * 4 * k + 4 * i + j] = \
                            bm[jr * 8 + c, i * 8 + b]
    out = np.zeros((16 * rp, 32 * k), dtype=np.int32)
    for p in range(4):
        out[p * 4 * rp:(p + 1) * 4 * rp] = \
            planes[2 * p] - 128 * planes[2 * p + 1]
    return out.astype(np.int8)


def _rs_kernel32(bmat_ref, data_ref, out_ref):
    """One (batch, lane-tile) cell on u32 lanes.

    bmat_ref: int8 [16*rp, 32k] pair-packed bit matrix (_prep8_cached).
    data_ref: uint32 [bb, k, TL4]; out_ref: uint32 [bb, r, TL4].

    acc row (p, row4) = lo - 128*hi where lo/hi are the GF(2) dot sums
    of planes 2p / 2p+1 (each in [0, 120]): lo parity = acc & 1 (the
    -128*hi part is even), hi = (127 - acc) >> 7 exactly.
    """
    r = out_ref.shape[1]
    rp = bmat_ref.shape[0] // 16
    r4 = 4 * rp
    for i in range(data_ref.shape[0]):
        xb = pltpu.bitcast(data_ref[i], jnp.uint8)       # [4k, TL4]
        bits = jnp.concatenate(
            [jnp.where((xb & jnp.uint8(1 << b)) != 0,
                       jnp.int8(1), jnp.int8(0)) for b in range(8)],
            axis=0)                                      # [32k, TL4]
        acc = jax.lax.dot_general(
            bmat_ref[:], bits,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)            # [16rp, TL4]
        packed = None
        for p in range(4):
            t = acc[p * r4:(p + 1) * r4]
            lo = (t & 1) << (2 * p)
            hi = (((127 - t) >> 7) & 1) << (2 * p + 1)
            contrib = lo | hi
            packed = contrib if packed is None else (packed | contrib)
        words = pltpu.bitcast(packed.astype(jnp.uint8),
                              jnp.uint32)                # [rp, TL4]
        out_ref[i] = words[0:r]


@functools.partial(jax.jit,
                   static_argnames=("r", "tile4", "bb", "interpret"))
def _pallas_apply32(bmat8: jax.Array, data: jax.Array, r: int, tile4: int,
                    bb: int, interpret: bool = False) -> jax.Array:
    """bmat8 int8 [16*rp, 32k] pair-packed (_prep8_cached), data uint32
    [B, k, L4_padded]."""
    b, k, l4 = data.shape
    assert l4 % tile4 == 0, f"lane dim {l4} not a multiple of tile {tile4}"
    assert b % bb == 0, f"batch dim {b} not a multiple of {bb}"
    grid = (b // bb, l4 // tile4)
    return pl.pallas_call(
        _rs_kernel32,
        grid=grid,
        in_specs=[
            pl.BlockSpec(tuple(bmat8.shape), lambda ib, il: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, k, tile4), lambda ib, il: (ib, 0, il),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bb, r, tile4), lambda ib, il: (ib, 0, il),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, r, l4), jnp.uint32),
        interpret=interpret,
    )(bmat8, data)


def make_encoder32(matrix: np.ndarray, mode: str = "auto"):
    """u32-lane encoder: fn(data uint32 [B, k, L4]) -> uint32 [B, r, L4].

    Lane t of shard i holds bytes 4t..4t+3 (little-endian), i.e. the
    same bytes as the u8 path's lanes 4t..4t+3 — outputs bitcast-equal.
    Pads lanes to a tile multiple internally (zeros are a fixed point).
    """
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    r, k = matrix.shape
    backend = DeviceBackend(mode)
    if backend.mode == "xla" or k > 15:
        # k > 15 would break the pair-packing overflow bound (never hit
        # in practice: erasure sets cap at 16 drives with m >= 1).
        def run_xla(data):
            # Portable fallback: via the byte path.
            b, kk, l4 = data.shape
            bytes_ = jax.lax.bitcast_convert_type(data, jnp.uint8) \
                .reshape(b, kk, l4 * 4)
            out = _xla_apply(jnp.asarray(_prep(matrix)[0]), bytes_)
            return jax.lax.bitcast_convert_type(
                out.reshape(b, r, l4, 4), jnp.uint32)
        return run_xla
    interpret = backend._interpret
    bmat = jnp.asarray(_prep8_cached(matrix.tobytes(), r, k))
    rp = r + (r & 1)

    def run(data):
        b, kk, l4 = data.shape
        # VMEM per cell ~ bits i8 [32k, T] + acc i32 [16rp, T] + io u32
        # (no double-buffer factor: the probe/retry loop below is the
        # real enforcement and measured-best tiles sit near the cap).
        tile4 = 128
        per_lane4 = 32 * k + 16 * rp * 4 + (k + r) * 4 + 4 * rp
        # Cap at 16k lanes: measured best for the pair-packed kernel on
        # v5e (32k-lane cells run ~8% slower — the acc no longer
        # double-buffers cleanly against the next cell's bits).
        while tile4 < _TILE_L_MAX // 8 and tile4 * per_lane4 <= _VMEM_BUDGET \
                and tile4 < l4:
            tile4 *= 2
        bb = 1
        key = ("u32", k, r, bb)
        tile4 = min(tile4, _tile_cap.get(key, tile4))
        pad = (-l4) % tile4
        padded = jnp.pad(data, ((0, 0), (0, 0), (0, pad))) if pad else data
        if isinstance(data, jax.core.Tracer):
            out = _pallas_apply32(bmat, padded, r=r, tile4=tile4, bb=bb,
                                  interpret=interpret)
            return out[..., :l4] if pad else out
        while True:
            try:
                out = _pallas_apply32(bmat, padded, r=r, tile4=tile4, bb=bb,
                                      interpret=interpret)
                if key + (tile4,) not in _tile_ok:
                    out.block_until_ready()
                    _tile_ok.add(key + (tile4,))
                return out[..., :l4] if pad else out
            except Exception as e:  # noqa: BLE001 - inspect & retry
                if tile4 > 128 and _is_vmem_error(e):
                    tile4 //= 2
                    _tile_cap[key] = min(_tile_cap.get(key, tile4), tile4)
                    pad = (-l4) % tile4
                    padded = jnp.pad(data, ((0, 0), (0, 0), (0, pad))) if pad else data
                    continue
                raise
    return run


# ---------------------------------------------------------------------------
# Backend
# ---------------------------------------------------------------------------

class DeviceBackend:
    """ECBackend that runs the GF(2^8) transform on the default JAX device.

    mode: "pallas" (fused kernel; interpreted off-TPU), "xla" (portable
    einsum path), or "auto" (pallas on TPU, xla elsewhere).
    """

    def __init__(self, mode: str = "auto", host_cutover: int | None = None):
        if mode not in ("auto", "pallas", "xla"):
            raise ValueError(f"unknown mode {mode!r}")
        if mode == "auto":
            mode = "pallas" if _on_tpu() else "xla"
        self.mode = mode
        self._interpret = mode == "pallas" and not _on_tpu()
        if host_cutover is not None:
            self.HOST_CUTOVER_BYTES = host_cutover

    # -- device-array API (stays on device; used by batched/jit callers) ----

    def apply_matrix_device(self, matrix: np.ndarray, data: jax.Array) -> jax.Array:
        """data uint8 [B, k, L] on device -> [B, r, L] on device.

        Pads lanes to a whole number of tiles (zero bytes are a fixed
        point of the linear transform so the tail slices back out
        exactly). If the Pallas compile overflows the chip's scoped VMEM
        at the heuristic tile size, halves the tile and retries; the
        working size is cached per (k, r, bb) so the probe cost is paid
        once per config.
        """
        bm_byte, bm_plane = _prep(matrix)
        if self.mode == "xla":
            return _xla_apply(jnp.asarray(bm_byte), data)
        b, k, l = data.shape
        r = matrix.shape[0]
        tile, bb = _choose_tile(k, r, l, b)
        key = (k, r, bb)
        tile = min(tile, _tile_cap.get(key, tile))
        bmat = jnp.asarray(bm_plane)
        if isinstance(data, jax.core.Tracer):
            # Under an outer jit/shard_map trace there is no way to probe
            # (no concrete values, failures surface at the caller's
            # compile); use the capped heuristic directly.
            pad = (-l) % tile
            padded = jnp.pad(data, ((0, 0), (0, 0), (0, pad))) if pad else data
            out = _pallas_apply(bmat, padded, tile=tile, bb=bb,
                                interpret=self._interpret)
            return out[..., :l] if pad else out
        while True:
            pad = (-l) % tile
            padded = jnp.pad(data, ((0, 0), (0, 0), (0, pad))) if pad else data
            try:
                out = _pallas_apply(bmat, padded, tile=tile, bb=bb,
                                    interpret=self._interpret)
                if key + (tile,) not in _tile_ok:
                    # Force the (possibly async) compile to surface VMEM
                    # overflows now, while we can still retry smaller.
                    out.block_until_ready()
                    _tile_ok.add(key + (tile,))
                return out[..., :l] if pad else out
            except Exception as e:  # noqa: BLE001 - inspect & retry
                if tile > _LANES and _is_vmem_error(e):
                    tile //= 2
                    _tile_cap[key] = min(_tile_cap.get(key, tile), tile)
                    continue
                raise

    # -- ECBackend protocol (numpy in / numpy out) --------------------------

    # Below this many input bytes a host->device->host round trip costs
    # more than the transform itself (and the batch cannot fill the
    # kernel's vector tiles): small PUT/GET/reconstruct calls run the
    # host GF core instead, keeping p50 latency of 1 MiB objects at
    # host-codec level while large batches ride the MXU.
    HOST_CUTOVER_BYTES = 8 << 20

    def apply_matrix(self, matrix: np.ndarray, shards: np.ndarray) -> np.ndarray:
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        if shards.nbytes < self.HOST_CUTOVER_BYTES:
            # Same host core the pure-host codec uses (native C++ nibble
            # kernel when built) — small objects must not regress vs the
            # host backend.
            from minio_tpu.erasure.codec import _HOST
            return _HOST.apply_matrix(matrix, shards)
        out = self.apply_matrix_device(matrix, jnp.asarray(shards[None]))
        return np.asarray(jax.device_get(out))[0]


def make_encoder(matrix: np.ndarray, mode: str = "auto"):
    """Public jittable entry: fn(data uint8 [B, k, L]) -> uint8 [B, r, L].

    The GF matrix is baked in host-side (prep + padding handled); the
    returned closure is safe to wrap in jax.jit or call inside jitted
    code. This is the single dispatch point — bench.py, __graft_entry__
    and the sharded stripe steps all go through it.
    """
    backend = DeviceBackend(mode)
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    return lambda data: backend.apply_matrix_device(matrix, data)


def make_mesh_matrix(matrix: np.ndarray, mode: str = "auto", devices=None):
    """Mesh-sharded batched GF(2^8) matrix application — the decode
    mirror of hh_device.make_mesh_framer's parity step: stacked u8
    [B, k, L] -> u8 [B, r, L] with the batch dim ("stripes from MANY
    degraded GetObject / heal calls", coalesced by ops/batcher's
    reconstruct route) sharded over the chips via
    NamedSharding(mesh, P("stripe")).

    `matrix` is any (r x k) GF matrix: decode-matrix rows
    (gf256.decode_matrix gathered for the missing data shards — one
    compiled route per surviving-shard set, the common case being ONE
    set per dead drive) for degraded reads, parity rows for heal's
    re-derive. `donate_argnums=(0,)` on TPU donates the staged survivor
    batch. On one device this degrades to the single-chip encoder —
    same bytes (gf256 bitplane transform, byte-identical to the host
    codec by the rs_device contract).
    """
    from minio_tpu.ops.hh_device import _shard_map_compat, mesh_batch_devices
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    devs = mesh_batch_devices(devices)
    ndev = len(devs)
    encode = make_encoder(matrix, mode=mode)
    if ndev <= 1:
        def run_solo(stacked) -> np.ndarray:
            stacked = np.ascontiguousarray(stacked, dtype=np.uint8)
            return np.asarray(encode(jnp.asarray(stacked)))
        run_solo.mesh_devices = 1
        return run_solo
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    shard_map = _shard_map_compat()
    mesh = Mesh(np.asarray(devs), ("stripe",))
    sharding = NamedSharding(mesh, P("stripe"))
    donate = (0,) if _on_tpu() else ()

    @functools.partial(jax.jit, donate_argnums=donate)
    def mesh_apply(data):
        return shard_map(lambda d: encode(d), mesh=mesh,
                         in_specs=(P("stripe"),),
                         out_specs=P("stripe"))(data)

    def run(stacked) -> np.ndarray:
        stacked = np.ascontiguousarray(stacked, dtype=np.uint8)
        assert stacked.shape[0] % ndev == 0, \
            f"batch {stacked.shape[0]} not divisible by {ndev}-chip mesh"
        d = jax.device_put(stacked, sharding)
        return np.asarray(mesh_apply(d))

    run.mesh_devices = ndev
    return run


def mesh_info() -> dict:
    """Accelerator-mesh summary for bench/admin surfaces: the resolved
    JAX backend, total visible devices, and the power-of-two mesh width
    the cross-request stripe batching shards over (the prefix
    hh_device.mesh_batch_devices resolves, honoring MTPU_MESH_DEVICES).
    Importing here (not at module top) keeps rs_device importable
    before JAX platform selection is final."""
    from minio_tpu.ops.hh_device import mesh_batch_devices
    devs = jax.devices()
    return {"backend": jax.default_backend(),
            "devices": len(devs),
            "mesh_devices": len(mesh_batch_devices(devs))}
