"""Device-side HighwayHash-256 and the fused encode+bitrot pipeline.

The reference's PutObject hot loop interleaves Reed-Solomon encode with
per-shard-block HighwayHash-256 framing (`hash || block`, reference:
cmd/erasure-encode.go:69 feeding streamingBitrotWriter.Write,
cmd/bitrot-streaming.go:44-75, AVX2/AVX512 lane kernels in
github.com/minio/highwayhash). This module puts BOTH on the TPU:

  * `hash_blocks_device` — keyed HighwayHash-256 of S equal-length
    blocks as one XLA computation. 64-bit lane math is emulated with
    uint32 pairs (the TPU VPU is 32-bit): adds via explicit carries,
    the 32x32->64 multiplies via 16-bit limb products, the zipper
    merges as byte extract/deposit masks. The per-packet recurrence is
    sequential by construction, so parallelism comes from hashing many
    independent shard blocks in lockstep — one vector lane per stream,
    the same trick as the host numpy path (utils/highwayhash.py) but on
    the VPU and without leaving HBM.
  * `make_encode_framer` — the fused PUT pipeline: stripe batch in,
    parity via the RS bitplane matmul (ops/rs_device.py), HighwayHash
    of every shard block, and the framed per-drive byte layout
    assembled on device. One host<->device round trip per batch.

State layout: each of v0/v1/mul0/mul1 is (lo, hi) uint32 arrays of
shape [2 pairs, 2 lanes, S streams] — lane pairs (0,1) and (2,3) are
the zipper/finalize grouping, S rides the minor (vector) axis.

Byte-identical to utils/highwayhash.py and therefore to the reference's
golden digests (cmd/bitrot.go:225-230) — enforced by tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from minio_tpu.utils.highwayhash import MAGIC_KEY

_U32 = jnp.uint32


# ---------------------------------------------------------------------------
# 64-bit primitives on (lo, hi) uint32 pairs
# ---------------------------------------------------------------------------

def _add64(alo, ahi, blo, bhi):
    lo = alo + blo
    carry = (lo < alo).astype(_U32)
    return lo, ahi + bhi + carry


def _mul_32x32(a, b):
    """Full 64-bit product of two uint32 vectors, via 16-bit limbs."""
    al = a & 0xFFFF
    ah = a >> 16
    bl = b & 0xFFFF
    bh = b >> 16
    p0 = al * bl
    p1 = al * bh
    p2 = ah * bl
    p3 = ah * bh
    mid = (p0 >> 16) + (p1 & 0xFFFF) + (p2 & 0xFFFF)
    lo = (p0 & 0xFFFF) | (mid << 16)
    hi = p3 + (p1 >> 16) + (p2 >> 16) + (mid >> 16)
    return lo, hi


def _shl64(lo, hi, c: int):
    return lo << c, (hi << c) | (lo >> (32 - c))


def _byte(x, k: int):
    """Byte k (0..3) of a uint32 vector, as a uint32 in bits 0-7."""
    if k == 0:
        return x & 0xFF
    if k == 3:
        return x >> 24
    return (x >> (8 * k)) & 0xFF


def _zipper(elo, ehi, olo, ohi):
    """Zipper-merge of one lane pair (even, odd) -> (even', odd').

    Output byte maps (derived from the reference scalar masks;
    utils/highwayhash.py _zipper_merge_add):
      even' = [e3, o4, e2, e5, o6, e1, o7, e0]
      odd'  = [o3, e4, o2, o5, o1, e6, o0, e7]
    where eN/oN = byte N of the even/odd 64-bit lane (0 = LSB).
    """
    ze_lo = (_byte(elo, 3) | (_byte(ohi, 0) << 8)
             | (_byte(elo, 2) << 16) | (_byte(ehi, 1) << 24))
    ze_hi = (_byte(ohi, 2) | (_byte(elo, 1) << 8)
             | (_byte(ohi, 3) << 16) | (_byte(elo, 0) << 24))
    zo_lo = (_byte(olo, 3) | (_byte(ehi, 0) << 8)
             | (_byte(olo, 2) << 16) | (_byte(ohi, 1) << 24))
    zo_hi = (_byte(olo, 1) | (_byte(ehi, 2) << 8)
             | (_byte(olo, 0) << 16) | (_byte(ehi, 3) << 24))
    return ze_lo, ze_hi, zo_lo, zo_hi


# ---------------------------------------------------------------------------
# Core permutation
# ---------------------------------------------------------------------------
# State: tuple of 8 uint32 arrays [2, 2, S]:
#   (v0lo, v0hi, v1lo, v1hi, m0lo, m0hi, m1lo, m1hi)

def _update(st, plo, phi):
    v0lo, v0hi, v1lo, v1hi, m0lo, m0hi, m1lo, m1hi = st
    tlo, thi = _add64(m0lo, m0hi, plo, phi)
    v1lo, v1hi = _add64(v1lo, v1hi, tlo, thi)
    xlo, xhi = _mul_32x32(v1lo, v0hi)          # (v1 & M32) * (v0 >> 32)
    m0lo, m0hi = m0lo ^ xlo, m0hi ^ xhi
    v0lo, v0hi = _add64(v0lo, v0hi, m1lo, m1hi)
    ylo, yhi = _mul_32x32(v0lo, v1hi)          # (v0 & M32) * (v1 >> 32)
    m1lo, m1hi = m1lo ^ ylo, m1hi ^ yhi
    # v0 += zipper(v1), then v1 += zipper(updated v0) — per lane pair,
    # even/odd = index 0/1 on axis 1.
    ze_lo, ze_hi, zo_lo, zo_hi = _zipper(
        v1lo[:, 0], v1hi[:, 0], v1lo[:, 1], v1hi[:, 1])
    zlo = jnp.stack([ze_lo, zo_lo], axis=1)
    zhi = jnp.stack([ze_hi, zo_hi], axis=1)
    v0lo, v0hi = _add64(v0lo, v0hi, zlo, zhi)
    ze_lo, ze_hi, zo_lo, zo_hi = _zipper(
        v0lo[:, 0], v0hi[:, 0], v0lo[:, 1], v0hi[:, 1])
    zlo = jnp.stack([ze_lo, zo_lo], axis=1)
    zhi = jnp.stack([ze_hi, zo_hi], axis=1)
    v1lo, v1hi = _add64(v1lo, v1hi, zlo, zhi)
    return (v0lo, v0hi, v1lo, v1hi, m0lo, m0hi, m1lo, m1hi)


def _permute_and_update(st):
    v0lo, v0hi = st[0], st[1]
    # permuted lane i = rot32(v0 lane (i+2) mod 4): pair axis flips,
    # parity is preserved; rot32 = swap (lo, hi).
    plo = v0hi[::-1]
    phi = v0lo[::-1]
    return _update(st, plo, phi)


@functools.lru_cache(maxsize=16)
def _init_state_np(key: bytes) -> np.ndarray:
    """Initial state as one uint32 array [8, 2, 2] (statevec, pair, parity)."""
    init0 = np.array([0xDBE6D5D5FE4CCE2F, 0xA4093822299F31D0,
                      0x13198A2E03707344, 0x243F6A8885A308D3], dtype=np.uint64)
    init1 = np.array([0x3BD39E10CB0EF593, 0xC0ACF169B5F18A8C,
                      0xBE5466CF34E90C6C, 0x452821E638D01377], dtype=np.uint64)
    k = np.frombuffer(key, dtype="<u8").astype(np.uint64)
    rot = (k >> np.uint64(32)) | (k << np.uint64(32))
    v0, v1, m0, m1 = init0 ^ k, init1 ^ rot, init0, init1
    out = np.empty((8, 4), dtype=np.uint32)
    for i, v in enumerate((v0, v1, m0, m1)):
        out[2 * i] = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        out[2 * i + 1] = (v >> np.uint64(32)).astype(np.uint32)
    # [statevec, lane] -> [statevec, pair, parity]
    return out.reshape(8, 2, 2)


def _words_from_bytes(blocks):
    """uint8 [S, L] -> little-endian uint32 words [S, L//4]."""
    s, l = blocks.shape
    r = blocks.reshape(s, l // 4, 4).astype(_U32)
    return r[..., 0] | (r[..., 1] << 8) | (r[..., 2] << 16) | (r[..., 3] << 24)


def _hash_impl(blocks, init, length: int):
    """blocks uint8 [S, L] (L static), init [8,2,2] -> digests uint8 [S, 32]."""
    s = blocks.shape[0]
    n_packets = length // 32
    mod = length % 32
    st = tuple(jnp.broadcast_to(init[i][:, :, None], (2, 2, s)).astype(_U32)
               for i in range(8))

    if n_packets:
        words = _words_from_bytes(blocks[:, :n_packets * 32])
        # [S, P*8] -> [P, 8, S]: packet p's 8 words on the leading axis so
        # the loop body is one dynamic slice; S stays minor (vectorized).
        words = words.reshape(s, n_packets, 8).transpose(1, 2, 0)

        def body(p, st):
            pk = jax.lax.dynamic_slice(words, (p, 0, 0), (1, 8, s))
            pk = pk.reshape(4, 2, s)          # [lane, lo/hi, S]
            plo = pk[:, 0].reshape(2, 2, s)   # [pair, parity, S]
            phi = pk[:, 1].reshape(2, 2, s)
            return _update(st, plo, phi)

        st = jax.lax.fori_loop(0, n_packets, body, st)

    if mod:
        st = _remainder(st, blocks[:, n_packets * 32:], mod)

    for _ in range(10):
        st = _permute_and_update(st)
    return _finalize(st)


def _remainder(st, tail, mod: int):
    """Final partial packet; `mod` = len mod 32 is static (compile-time)."""
    s = tail.shape[0]
    mod4 = mod & 3
    rem = mod & ~3
    packet = jnp.zeros((s, 32), dtype=jnp.uint8)
    if rem:
        packet = packet.at[:, :rem].set(tail[:, :rem])
    # v0 += (mod << 32) + mod
    v0lo, v0hi = _add64(st[0], st[1], _U32(mod), _U32(mod))
    # Rotate each 32-bit half of every v1 lane left by `mod` bits.
    v1lo, v1hi = st[2], st[3]
    if mod:
        v1lo = (v1lo << mod) | (v1lo >> (32 - mod))
        v1hi = (v1hi << mod) | (v1hi >> (32 - mod))
    st = (v0lo, v0hi, v1lo, v1hi) + st[4:]
    if mod & 16:
        for i in range(4):
            packet = packet.at[:, 28 + i].set(tail[:, rem + i + mod4 - 4])
    elif mod4:
        packet = packet.at[:, 16].set(tail[:, rem])
        packet = packet.at[:, 17].set(tail[:, rem + (mod4 >> 1)])
        packet = packet.at[:, 18].set(tail[:, rem + mod4 - 1])
    w = _words_from_bytes(packet)              # [S, 8]
    w = w.reshape(s, 4, 2).transpose(1, 2, 0)  # [lane, lo/hi, S]
    plo = w[:, 0].reshape(2, 2, s)
    phi = w[:, 1].reshape(2, 2, s)
    return _update(st, plo, phi)


def _finalize(st):
    """Modular reduction -> digests uint8 [S, 32]."""
    v0lo, v0hi, v1lo, v1hi, m0lo, m0hi, m1lo, m1hi = st
    # Per pair p: a3 = v1odd+mul1odd, a2 = v1even+mul1even,
    #             a1 = v0odd+mul0odd, a0 = v0even+mul0even.
    a3lo, a3hi = _add64(v1lo[:, 1], v1hi[:, 1], m1lo[:, 1], m1hi[:, 1])
    a2lo, a2hi = _add64(v1lo[:, 0], v1hi[:, 0], m1lo[:, 0], m1hi[:, 0])
    a1lo, a1hi = _add64(v0lo[:, 1], v0hi[:, 1], m0lo[:, 1], m0hi[:, 1])
    a0lo, a0hi = _add64(v0lo[:, 0], v0hi[:, 0], m0lo[:, 0], m0hi[:, 0])
    a3hi = a3hi & 0x3FFFFFFF                   # a3 &= 2^62 - 1
    s1lo, s1hi = _shl64(a3lo, a3hi, 1)
    s1lo = s1lo | (a2hi >> 31)                 # | (a2 >> 63)
    s2lo, s2hi = _shl64(a3lo, a3hi, 2)
    s2lo = s2lo | (a2hi >> 30)                 # | (a2 >> 62)
    odd_lo = a1lo ^ s1lo ^ s2lo
    odd_hi = a1hi ^ s1hi ^ s2hi
    t1lo, t1hi = _shl64(a2lo, a2hi, 1)
    t2lo, t2hi = _shl64(a2lo, a2hi, 2)
    even_lo = a0lo ^ t1lo ^ t2lo
    even_hi = a0hi ^ t1hi ^ t2hi
    # Assemble [S, 8] words in lane order (l0lo, l0hi, l1lo, l1hi, ...),
    # pairs stacked: lanes (0,1) from pair 0, (2,3) from pair 1.
    words = jnp.stack([even_lo[0], even_hi[0], odd_lo[0], odd_hi[0],
                       even_lo[1], even_hi[1], odd_lo[1], odd_hi[1]],
                      axis=1)                  # [S, 8]
    b = jnp.stack([(words & 0xFF), (words >> 8) & 0xFF,
                   (words >> 16) & 0xFF, (words >> 24) & 0xFF],
                  axis=2)                      # [S, 8, 4]
    return b.reshape(words.shape[0], 32).astype(jnp.uint8)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("length",))
def _hash_jit(blocks, init, length: int):
    return _hash_impl(blocks, init, length)


def hash_blocks_device(key: bytes, blocks) -> np.ndarray:
    """Keyed HighwayHash-256 of S equal-length blocks on device.

    blocks: uint8 [S, L] (numpy or device array) -> uint8 [S, 32] numpy.
    """
    if len(key) != 32:
        raise ValueError("HighwayHash-256 requires a 32-byte key")
    blocks = jnp.asarray(blocks, dtype=jnp.uint8)
    s, l = blocks.shape
    init = jnp.asarray(_init_state_np(key))
    return np.asarray(_hash_jit(blocks, init, l))


# ---------------------------------------------------------------------------
# Fused encode + bitrot framing
# ---------------------------------------------------------------------------

def make_encode_framer(matrix: np.ndarray, mode: str = "auto"):
    """Fused PUT pipeline on device, one call per stripe batch.

    Returns fn(data uint8 [B, k, L]) -> framed uint8 [n, B*(32+L)]:
    Reed-Solomon parity (ops/rs_device), HighwayHash-256 of each of the
    B*n shard blocks, and the on-disk frame layout `hash || block`
    concatenated per shard (reference: cmd/bitrot-streaming.go:44-75 —
    each erasure block contributes one framed segment per shard file).
    Row i of the result IS the bytes of drive i's shard file for these
    B blocks. Digest algorithm is the bitrot default HighwayHash-256S
    under the magic key (cmd/bitrot.go:37,105-110).
    """
    from minio_tpu.ops.rs_device import make_encoder
    encode = make_encoder(matrix, mode=mode)
    init_np = _init_state_np(MAGIC_KEY)

    @functools.partial(jax.jit, static_argnames=())
    def fused(data, init):
        b, k, l = data.shape
        parity = encode(data)                      # [B, m, L]
        shards = jnp.concatenate([data, parity], axis=1)  # [B, n, L]
        n = shards.shape[1]
        digests = _hash_impl(shards.reshape(b * n, l), init, l)
        framed = jnp.concatenate(
            [digests.reshape(b, n, 32), shards], axis=2)  # [B, n, 32+L]
        # Per-drive layout: shard i's file is the concat over blocks.
        return framed.transpose(1, 0, 2).reshape(n, b * (32 + l))

    def run(data) -> jax.Array:
        return fused(jnp.asarray(data, dtype=jnp.uint8),
                     jnp.asarray(init_np))

    return run
