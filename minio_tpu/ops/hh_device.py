"""Device-side HighwayHash-256 and the fused encode+bitrot pipeline.

The reference's PutObject hot loop interleaves Reed-Solomon encode with
per-shard-block HighwayHash-256 framing (`hash || block`, reference:
cmd/erasure-encode.go:69 feeding streamingBitrotWriter.Write,
cmd/bitrot-streaming.go:44-75, AVX2/AVX512 lane kernels in
github.com/minio/highwayhash). This module puts BOTH on the TPU:

  * `hash_blocks_device` — keyed HighwayHash-256 of S equal-length
    blocks as one XLA computation. 64-bit lane math is emulated with
    uint32 pairs (the TPU VPU is 32-bit): adds via explicit carries,
    the 32x32->64 multiplies via 16-bit limb products, the zipper
    merges as byte extract/deposit masks. The per-packet recurrence is
    sequential by construction, so parallelism comes from hashing many
    independent shard blocks in lockstep — one vector lane per stream,
    the same trick as the host numpy path (utils/highwayhash.py) but on
    the VPU and without leaving HBM.
  * `make_encode_framer` — the fused PUT pipeline: stripe batch in,
    parity via the RS bitplane matmul (ops/rs_device.py) and the
    HighwayHash of every shard block, one host<->device round trip per
    batch. The on-disk `hash || block` frame is assembled by the shard
    writers from (digest, block) pieces at write time, like the
    reference's streaming bitrot writer — no interleaved frame buffer
    exists anywhere.

State layout: each of v0/v1/mul0/mul1 is (lo, hi) uint32 arrays of
shape [2 pairs, 2 lanes, S streams] — lane pairs (0,1) and (2,3) are
the zipper/finalize grouping, S rides the minor (vector) axis.

Byte-identical to utils/highwayhash.py and therefore to the reference's
golden digests (cmd/bitrot.go:225-230) — enforced by tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from minio_tpu.utils.highwayhash import MAGIC_KEY

_U32 = jnp.uint32


# ---------------------------------------------------------------------------
# 64-bit primitives on (lo, hi) uint32 pairs
# ---------------------------------------------------------------------------

def _add64(alo, ahi, blo, bhi):
    lo = alo + blo
    carry = (lo < alo).astype(_U32)
    return lo, ahi + bhi + carry


def _mul_32x32(a, b):
    """Full 64-bit product of two uint32 vectors, via 16-bit limbs."""
    al = a & 0xFFFF
    ah = a >> 16
    bl = b & 0xFFFF
    bh = b >> 16
    p0 = al * bl
    p1 = al * bh
    p2 = ah * bl
    p3 = ah * bh
    mid = (p0 >> 16) + (p1 & 0xFFFF) + (p2 & 0xFFFF)
    lo = (p0 & 0xFFFF) | (mid << 16)
    hi = p3 + (p1 >> 16) + (p2 >> 16) + (mid >> 16)
    return lo, hi


def _shl64(lo, hi, c: int):
    return lo << c, (hi << c) | (lo >> (32 - c))


def _byte(x, k: int):
    """Byte k (0..3) of a uint32 vector, as a uint32 in bits 0-7."""
    if k == 0:
        return x & 0xFF
    if k == 3:
        return x >> 24
    return (x >> (8 * k)) & 0xFF


def _zipper(elo, ehi, olo, ohi):
    """Zipper-merge of one lane pair (even, odd) -> (even', odd').

    Output byte maps (derived from the reference scalar masks;
    utils/highwayhash.py _zipper_merge_add):
      even' = [e3, o4, e2, e5, o6, e1, o7, e0]
      odd'  = [o3, e4, o2, o5, o1, e6, o0, e7]
    where eN/oN = byte N of the even/odd 64-bit lane (0 = LSB).
    """
    ze_lo = (_byte(elo, 3) | (_byte(ohi, 0) << 8)
             | (_byte(elo, 2) << 16) | (_byte(ehi, 1) << 24))
    ze_hi = (_byte(ohi, 2) | (_byte(elo, 1) << 8)
             | (_byte(ohi, 3) << 16) | (_byte(elo, 0) << 24))
    zo_lo = (_byte(olo, 3) | (_byte(ehi, 0) << 8)
             | (_byte(olo, 2) << 16) | (_byte(ohi, 1) << 24))
    zo_hi = (_byte(olo, 1) | (_byte(ehi, 2) << 8)
             | (_byte(olo, 0) << 16) | (_byte(ehi, 3) << 24))
    return ze_lo, ze_hi, zo_lo, zo_hi


# ---------------------------------------------------------------------------
# Core permutation
# ---------------------------------------------------------------------------
# State: tuple of 8 uint32 arrays [2, 2, S]:
#   (v0lo, v0hi, v1lo, v1hi, m0lo, m0hi, m1lo, m1hi)

def _update(st, plo, phi):
    v0lo, v0hi, v1lo, v1hi, m0lo, m0hi, m1lo, m1hi = st
    tlo, thi = _add64(m0lo, m0hi, plo, phi)
    v1lo, v1hi = _add64(v1lo, v1hi, tlo, thi)
    xlo, xhi = _mul_32x32(v1lo, v0hi)          # (v1 & M32) * (v0 >> 32)
    m0lo, m0hi = m0lo ^ xlo, m0hi ^ xhi
    v0lo, v0hi = _add64(v0lo, v0hi, m1lo, m1hi)
    ylo, yhi = _mul_32x32(v0lo, v1hi)          # (v0 & M32) * (v1 >> 32)
    m1lo, m1hi = m1lo ^ ylo, m1hi ^ yhi
    # v0 += zipper(v1), then v1 += zipper(updated v0) — per lane pair,
    # even/odd = index 0/1 on axis 1.
    ze_lo, ze_hi, zo_lo, zo_hi = _zipper(
        v1lo[:, 0], v1hi[:, 0], v1lo[:, 1], v1hi[:, 1])
    zlo = jnp.stack([ze_lo, zo_lo], axis=1)
    zhi = jnp.stack([ze_hi, zo_hi], axis=1)
    v0lo, v0hi = _add64(v0lo, v0hi, zlo, zhi)
    ze_lo, ze_hi, zo_lo, zo_hi = _zipper(
        v0lo[:, 0], v0hi[:, 0], v0lo[:, 1], v0hi[:, 1])
    zlo = jnp.stack([ze_lo, zo_lo], axis=1)
    zhi = jnp.stack([ze_hi, zo_hi], axis=1)
    v1lo, v1hi = _add64(v1lo, v1hi, zlo, zhi)
    return (v0lo, v0hi, v1lo, v1hi, m0lo, m0hi, m1lo, m1hi)


def _permute_and_update(st):
    v0lo, v0hi = st[0], st[1]
    # permuted lane i = rot32(v0 lane (i+2) mod 4): pair axis flips,
    # parity is preserved; rot32 = swap (lo, hi).
    plo = v0hi[::-1]
    phi = v0lo[::-1]
    return _update(st, plo, phi)


@functools.lru_cache(maxsize=16)
def _init_state_np(key: bytes) -> np.ndarray:
    """Initial state as one uint32 array [8, 2, 2] (statevec, pair, parity)."""
    init0 = np.array([0xDBE6D5D5FE4CCE2F, 0xA4093822299F31D0,
                      0x13198A2E03707344, 0x243F6A8885A308D3], dtype=np.uint64)
    init1 = np.array([0x3BD39E10CB0EF593, 0xC0ACF169B5F18A8C,
                      0xBE5466CF34E90C6C, 0x452821E638D01377], dtype=np.uint64)
    k = np.frombuffer(key, dtype="<u8").astype(np.uint64)
    rot = (k >> np.uint64(32)) | (k << np.uint64(32))
    v0, v1, m0, m1 = init0 ^ k, init1 ^ rot, init0, init1
    out = np.empty((8, 4), dtype=np.uint32)
    for i, v in enumerate((v0, v1, m0, m1)):
        out[2 * i] = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        out[2 * i + 1] = (v >> np.uint64(32)).astype(np.uint32)
    # [statevec, lane] -> [statevec, pair, parity]
    return out.reshape(8, 2, 2)


def _words_from_bytes(blocks):
    """uint8 [S, L] -> little-endian uint32 words [S, L//4]."""
    s, l = blocks.shape
    r = blocks.reshape(s, l // 4, 4).astype(_U32)
    return r[..., 0] | (r[..., 1] << 8) | (r[..., 2] << 16) | (r[..., 3] << 24)


def _hash_impl(blocks, init, length: int):
    """blocks uint8 [S, L] (L static), init [8,2,2] -> digests uint8 [S, 32]."""
    s = blocks.shape[0]
    n_packets = length // 32
    mod = length % 32
    st = tuple(jnp.broadcast_to(init[i][:, :, None], (2, 2, s)).astype(_U32)
               for i in range(8))

    if n_packets:
        words = _words_from_bytes(blocks[:, :n_packets * 32])
        # [S, P*8] -> [P, 8, S]: packet p's 8 words on the leading axis so
        # the loop body is one dynamic slice; S stays minor (vectorized).
        words = words.reshape(s, n_packets, 8).transpose(1, 2, 0)

        def body(p, st):
            pk = jax.lax.dynamic_slice(words, (p, 0, 0), (1, 8, s))
            pk = pk.reshape(4, 2, s)          # [lane, lo/hi, S]
            plo = pk[:, 0].reshape(2, 2, s)   # [pair, parity, S]
            phi = pk[:, 1].reshape(2, 2, s)
            return _update(st, plo, phi)

        st = jax.lax.fori_loop(0, n_packets, body, st)

    if mod:
        st = _remainder(st, blocks[:, n_packets * 32:], mod)

    # Rolled loop: unrolling the 10 permute rounds balloons the traced
    # graph ~4x and makes CPU (LLVM) compiles take minutes.
    st = jax.lax.fori_loop(0, 10, lambda _, s: _permute_and_update(s), st)
    return _finalize(st)


def _remainder(st, tail, mod: int):
    """Final partial packet; `mod` = len mod 32 is static (compile-time)."""
    s = tail.shape[0]
    mod4 = mod & 3
    rem = mod & ~3
    packet = jnp.zeros((s, 32), dtype=jnp.uint8)
    if rem:
        packet = packet.at[:, :rem].set(tail[:, :rem])
    # v0 += (mod << 32) + mod
    v0lo, v0hi = _add64(st[0], st[1], _U32(mod), _U32(mod))
    # Rotate each 32-bit half of every v1 lane left by `mod` bits.
    v1lo, v1hi = st[2], st[3]
    if mod:
        v1lo = (v1lo << mod) | (v1lo >> (32 - mod))
        v1hi = (v1hi << mod) | (v1hi >> (32 - mod))
    st = (v0lo, v0hi, v1lo, v1hi) + st[4:]
    if mod & 16:
        for i in range(4):
            packet = packet.at[:, 28 + i].set(tail[:, rem + i + mod4 - 4])
    elif mod4:
        packet = packet.at[:, 16].set(tail[:, rem])
        packet = packet.at[:, 17].set(tail[:, rem + (mod4 >> 1)])
        packet = packet.at[:, 18].set(tail[:, rem + mod4 - 1])
    w = _words_from_bytes(packet)              # [S, 8]
    w = w.reshape(s, 4, 2).transpose(1, 2, 0)  # [lane, lo/hi, S]
    plo = w[:, 0].reshape(2, 2, s)
    phi = w[:, 1].reshape(2, 2, s)
    return _update(st, plo, phi)


def _finalize(st):
    """Modular reduction -> digests uint8 [S, 32]."""
    v0lo, v0hi, v1lo, v1hi, m0lo, m0hi, m1lo, m1hi = st
    # Per pair p: a3 = v1odd+mul1odd, a2 = v1even+mul1even,
    #             a1 = v0odd+mul0odd, a0 = v0even+mul0even.
    a3lo, a3hi = _add64(v1lo[:, 1], v1hi[:, 1], m1lo[:, 1], m1hi[:, 1])
    a2lo, a2hi = _add64(v1lo[:, 0], v1hi[:, 0], m1lo[:, 0], m1hi[:, 0])
    a1lo, a1hi = _add64(v0lo[:, 1], v0hi[:, 1], m0lo[:, 1], m0hi[:, 1])
    a0lo, a0hi = _add64(v0lo[:, 0], v0hi[:, 0], m0lo[:, 0], m0hi[:, 0])
    a3hi = a3hi & 0x3FFFFFFF                   # a3 &= 2^62 - 1
    s1lo, s1hi = _shl64(a3lo, a3hi, 1)
    s1lo = s1lo | (a2hi >> 31)                 # | (a2 >> 63)
    s2lo, s2hi = _shl64(a3lo, a3hi, 2)
    s2lo = s2lo | (a2hi >> 30)                 # | (a2 >> 62)
    odd_lo = a1lo ^ s1lo ^ s2lo
    odd_hi = a1hi ^ s1hi ^ s2hi
    t1lo, t1hi = _shl64(a2lo, a2hi, 1)
    t2lo, t2hi = _shl64(a2lo, a2hi, 2)
    even_lo = a0lo ^ t1lo ^ t2lo
    even_hi = a0hi ^ t1hi ^ t2hi
    # Assemble [S, 8] words in lane order (l0lo, l0hi, l1lo, l1hi, ...),
    # pairs stacked: lanes (0,1) from pair 0, (2,3) from pair 1.
    words = jnp.stack([even_lo[0], even_hi[0], odd_lo[0], odd_hi[0],
                       even_lo[1], even_hi[1], odd_lo[1], odd_hi[1]],
                      axis=1)                  # [S, 8]
    b = jnp.stack([(words & 0xFF), (words >> 8) & 0xFF,
                   (words >> 16) & 0xFF, (words >> 24) & 0xFF],
                  axis=2)                      # [S, 8, 4]
    return b.reshape(words.shape[0], 32).astype(jnp.uint8)


# ---------------------------------------------------------------------------
# Pallas kernel: the VPU-saturating HighwayHash path
# ---------------------------------------------------------------------------
# The jnp path above lays state out as [2, 2, S]: only 4 of 8 sublanes
# carry data and every elementwise op covers 4 HH lanes of S streams —
# XLA's fusions top out ~14 GiB/s on v5e. The kernel below instead makes
# the HH lane index an UNROLLED leading dim and packs 1024 streams per
# grid cell as full (8 sublane, 128 lane) vector tiles, so every VPU op
# is 100% dense. The packet recurrence runs inside the kernel (state in
# VMEM scratch, carried across the packet-chunk grid dim), so there is
# no per-packet dispatch overhead and data streams HBM -> VMEM once.
#
# State representation: each of v0/v1/mul0/mul1 is an (lo, hi) pair of
# uint32 [4, 8, 128] arrays — axis 0 is the HH 64-bit lane, (8, 128) is
# 1024 streams (stream = su*128 + ln).

_STREAM_TILE = 1024   # streams per grid cell: one (8, 128) tile set
_PCHUNK_MAX = 64      # packets per grid step (measured best on v5e:
                      # 64 beats 128 by ~3-10% across stream shapes)


def _k_add64(a, b):
    """(lo, hi) + (lo, hi) with explicit carry; any matching shapes."""
    lo = a[0] + b[0]
    carry = (lo < a[0]).astype(_U32)
    return lo, a[1] + b[1] + carry


def _k_mul64(a, b):
    """Full 64-bit product of uint32 arrays a*b via 16-bit limbs."""
    al, ah = a & 0xFFFF, a >> 16
    bl, bh = b & 0xFFFF, b >> 16
    p0 = al * bl
    p1 = al * bh
    p2 = ah * bl
    mid = (p0 >> 16) + (p1 & 0xFFFF) + (p2 & 0xFFFF)
    lo = (p0 & 0xFFFF) | (mid << 16)
    hi = ah * bh + (p1 >> 16) + (p2 >> 16) + (mid >> 16)
    return lo, hi


def _k_zipper(vlo, vhi):
    """Zipper-merge of [4, ...] lane arrays, both pairs at once.

    Same byte maps as _zipper (even' = [e3,o4,e2,e5,o6,e1,o7,e0],
    odd' = [o3,e4,o2,o5,o1,e6,o0,e7]) but in fused mask form: each
    output word is 4 mask/shift terms instead of per-byte extracts.
    """
    # Static leading-dim selection (strided slices lower to gathers,
    # which Mosaic does not support — stack register views instead).
    elo = jnp.stack([vlo[0], vlo[2]])   # lanes 0, 2  [2, ...]
    ehi = jnp.stack([vhi[0], vhi[2]])
    olo = jnp.stack([vlo[1], vlo[3]])   # lanes 1, 3
    ohi = jnp.stack([vhi[1], vhi[3]])
    ze_lo = ((elo >> 24) | ((ohi & 0xFF) << 8)
             | (elo & 0x00FF0000) | ((ehi & 0x0000FF00) << 16))
    ze_hi = (((ohi >> 16) & 0xFF) | (elo & 0xFF00)
             | ((ohi >> 8) & 0x00FF0000) | (elo << 24))
    zo_lo = ((olo >> 24) | ((ehi & 0xFF) << 8)
             | (olo & 0x00FF0000) | ((ohi & 0x0000FF00) << 16))
    zo_hi = (((olo >> 8) & 0xFF) | ((ehi >> 8) & 0xFF00)
             | ((olo & 0xFF) << 16) | (ehi & _U32(0xFF000000)))
    zlo = jnp.stack([ze_lo[0], zo_lo[0], ze_lo[1], zo_lo[1]])
    zhi = jnp.stack([ze_hi[0], zo_hi[0], ze_hi[1], zo_hi[1]])
    return zlo, zhi


def _k_update(st, plo, phi):
    """One packet: st = 8-tuple of [4, 8, 128] u32, p{lo,hi} [4, 8, 128]."""
    v0lo, v0hi, v1lo, v1hi, m0lo, m0hi, m1lo, m1hi = st
    tlo, thi = _k_add64((m0lo, m0hi), (plo, phi))
    v1lo, v1hi = _k_add64((v1lo, v1hi), (tlo, thi))
    xlo, xhi = _k_mul64(v1lo, v0hi)            # (v1 & M32) * (v0 >> 32)
    m0lo, m0hi = m0lo ^ xlo, m0hi ^ xhi
    v0lo, v0hi = _k_add64((v0lo, v0hi), (m1lo, m1hi))
    ylo, yhi = _k_mul64(v0lo, v1hi)            # (v0 & M32) * (v1 >> 32)
    m1lo, m1hi = m1lo ^ ylo, m1hi ^ yhi
    zlo, zhi = _k_zipper(v1lo, v1hi)
    v0lo, v0hi = _k_add64((v0lo, v0hi), (zlo, zhi))
    zlo, zhi = _k_zipper(v0lo, v0hi)
    v1lo, v1hi = _k_add64((v1lo, v1hi), (zlo, zhi))
    return (v0lo, v0hi, v1lo, v1hi, m0lo, m0hi, m1lo, m1hi)


def _k_permute_update(st):
    # permuted lane i = rot32(v0 lane (i+2) mod 4); rot32 = swap halves.
    plo = jnp.stack([st[1][2], st[1][3], st[1][0], st[1][1]])
    phi = jnp.stack([st[0][2], st[0][3], st[0][0], st[0][1]])
    return _k_update(st, plo, phi)


def _k_shl64(lo, hi, c: int):
    return lo << c, (hi << c) | (lo >> (32 - c))


def _hh_kernel(init_ref, w_ref, out_ref, st_ref, *, unroll: bool = True):
    """Grid cell (stream-tile is, packet-chunk ip); ip is innermost.

    init_ref: SMEM u32 [8, 4]  (statevec sv = 2*var + lo/hi, HH lane)
    w_ref:    VMEM u32 [1, 8su, 1, PCHUNK, 4, 2, 128]  (packet words,
              su-major so the feeding transpose kernel writes each
              sublane group contiguously)
    out_ref:  VMEM u32 [1, 8, 8, 128]  (digest words per stream)
    st_ref:   VMEM u32 [8, 4, 8, 128]  scratch, carried across ip
    """
    ip = pl.program_id(1)
    n_ip = pl.num_programs(1)
    pchunk = w_ref.shape[3]
    su = 8

    @pl.when(ip == 0)
    def _init():
        for sv in range(8):
            st_ref[sv] = jnp.stack(
                [jnp.full((su, 128), init_ref[sv, l], dtype=_U32)
                 for l in range(4)])

    st = tuple(st_ref[sv] for sv in range(8))

    def body(p, st):
        w = w_ref[0, :, 0, p]                 # [8su, 4, 2, 128]
        plo = jnp.stack([w[:, l, 0] for l in range(4)])   # [4, 8, 128]
        phi = jnp.stack([w[:, l, 1] for l in range(4)])
        return _k_update(st, plo, phi)

    # Full unroll (the only unroll factor Mosaic's for-loop lowering
    # supports besides 1): exposes the whole chunk to the scheduler so
    # w_ref loads pipeline ahead of the serial state chain. Interpret
    # mode (CPU tests) keeps the rolled loop — the unrolled trace is
    # minutes-slow under the Python interpreter.
    st = jax.lax.fori_loop(0, pchunk, body, st,
                           unroll=pchunk if unroll else 1)

    for sv in range(8):
        st_ref[sv] = st[sv]

    @pl.when(ip == n_ip - 1)
    def _finalize():
        # Digest words per stream, in byte order:
        # pair 0: even lo/hi, odd lo/hi; then pair 1.
        _hh_finalize_tail(st, out_ref)


def _hh_kernel_nt(init_ref, w_ref, out_ref, st_ref, wt_ref,
                  *, unroll: bool = True):
    """Transpose-fused variant of _hh_kernel: reads the NATURAL stream
    layout and transposes in VMEM, so packet words never round-trip
    through HBM twice (the standalone _t7_kernel pass is pure HBM
    bandwidth — ~0.4 ms per 128 MiB on v5e — and this kernel replaces
    it for free).

    init_ref: SMEM u32 [8, 4]
    w_ref:    VMEM u32 [1024, CT] or [BSUB, X, CT] with BSUB*X == 1024
              (CT = 8 * pchunk words; stream-major natural layout,
              stream = su*128 + ln within the tile — leading dims
              collapse for free, which is the whole point: a pallas
              operand fed through an XLA reshape is MATERIALISED (a full
              HBM copy), so 3-D [B, shard, W] arrays hash directly)
    out_ref:  VMEM u32 [1, 8, 8, 128]
    st_ref:   VMEM u32 [8, 4, 8, 128] scratch, carried across ip
    wt_ref:   VMEM u32 [8, PCHUNK, 4, 2, 128] scratch (transposed words)
    """
    ip = pl.program_id(1)
    n_ip = pl.num_programs(1)
    pchunk = wt_ref.shape[1]
    su = 8

    @pl.when(ip == 0)
    def _init():
        for sv in range(8):
            st_ref[sv] = jnp.stack(
                [jnp.full((su, 128), init_ref[sv, l], dtype=_U32)
                 for l in range(4)])

    w2 = w_ref[:].reshape(1024, w_ref.shape[-1])
    # In-VMEM transpose, same sub-tile decomposition as _t7_kernel.
    for g in range(su):
        t = w2[g * 128:(g + 1) * 128, :].T             # [CT, 128]
        wt_ref[g] = t.reshape(pchunk, 4, 2, 128)

    st = tuple(st_ref[sv] for sv in range(8))

    def body(p, st):
        w = wt_ref[:, p]                               # [8su, 4, 2, 128]
        plo = jnp.stack([w[:, l, 0] for l in range(4)])
        phi = jnp.stack([w[:, l, 1] for l in range(4)])
        return _k_update(st, plo, phi)

    st = jax.lax.fori_loop(0, pchunk, body, st,
                           unroll=pchunk if unroll else 1)

    for sv in range(8):
        st_ref[sv] = st[sv]

    @pl.when(ip == n_ip - 1)
    def _finalize():
        _hh_finalize_tail(st, out_ref)


def _hh_finalize_tail(st, out_ref):
    """Shared 10-round permute + modular reduction tail (see _hh_kernel)."""
    s = st
    for _ in range(10):
        s = _k_permute_update(s)
    v0lo, v0hi, v1lo, v1hi, m0lo, m0hi, m1lo, m1hi = s
    odd = lambda x: jnp.stack([x[1], x[3]])    # noqa: E731
    even = lambda x: jnp.stack([x[0], x[2]])   # noqa: E731
    a3 = _k_add64((odd(v1lo), odd(v1hi)), (odd(m1lo), odd(m1hi)))
    a2 = _k_add64((even(v1lo), even(v1hi)), (even(m1lo), even(m1hi)))
    a1 = _k_add64((odd(v0lo), odd(v0hi)), (odd(m0lo), odd(m0hi)))
    a0 = _k_add64((even(v0lo), even(v0hi)), (even(m0lo), even(m0hi)))
    a3lo, a3hi = a3[0], a3[1] & 0x3FFFFFFF           # a3 &= 2^62 - 1
    s1lo, s1hi = _k_shl64(a3lo, a3hi, 1)
    s1lo = s1lo | (a2[1] >> 31)
    s2lo, s2hi = _k_shl64(a3lo, a3hi, 2)
    s2lo = s2lo | (a2[1] >> 30)
    odd_lo, odd_hi = a1[0] ^ s1lo ^ s2lo, a1[1] ^ s1hi ^ s2hi
    t1lo, t1hi = _k_shl64(a2[0], a2[1], 1)
    t2lo, t2hi = _k_shl64(a2[0], a2[1], 2)
    even_lo, even_hi = a0[0] ^ t1lo ^ t2lo, a0[1] ^ t1hi ^ t2hi
    out_ref[0] = jnp.stack([even_lo[0], even_hi[0], odd_lo[0], odd_hi[0],
                            even_lo[1], even_hi[1], odd_lo[1], odd_hi[1]])


def _hash_words_pallas(words, init, pchunk: int,
                       interpret: bool = False):
    """Core u32 path: words u32 [S, W] or [B, X, W] (S = B*X streams;
    lane w = bytes 4w..4w+3 LE of the stream, W % (8*pchunk) == 0),
    init u32 [8, 4] -> digest words u32 [S, 8].

    A u32 shard array from make_encoder32 IS this word layout already —
    no byte bitcast (a ~35 GiB/s relayout on v5e) anywhere on the path.
    3-D inputs hash as-is: reshaping a pallas operand in XLA would
    MATERIALISE the reshape (a full HBM copy — measured 2x slowdown),
    so the block spec carves 1024-stream tiles out of the leading dims
    instead and the kernel collapses them for free.
    """
    n_words = words.shape[-1]
    x3 = words.shape[1] if words.ndim == 3 else None
    if words.ndim == 3 and (1024 % x3 != 0 or pchunk < 1):
        words = words.reshape(-1, n_words)       # rare shapes: pay the copy
        x3 = None
    s = int(np.prod(words.shape[:-1]))
    stile = 1024
    spad = -(-s // stile) * stile
    st_tiles = spad // stile
    pc = n_words // 8 // pchunk
    if (8 * pchunk) % 128 == 0 and n_words % (8 * pchunk) == 0:
        # Fast path: the kernel reads the NATURAL stream-major layout
        # and transposes in VMEM (_hh_kernel_nt) — no standalone
        # transpose pass over HBM. Stream padding comes free from OOB
        # edge-block reads (pad streams hash garbage; digests sliced).
        ct = 8 * pchunk
        if x3 is not None:
            bsub = 1024 // x3
            in_spec = pl.BlockSpec((bsub, x3, ct), lambda i, p: (i, 0, p),
                                   memory_space=pltpu.VMEM)
        else:
            in_spec = pl.BlockSpec((1024, ct), lambda i, p: (i, p),
                                   memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            functools.partial(_hh_kernel_nt, unroll=not interpret),
            grid=(st_tiles, pc),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), in_spec],
            out_specs=pl.BlockSpec((1, 8, 8, 128), lambda i, p: (i, 0, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((st_tiles, 8, 8, 128), jnp.uint32),
            scratch_shapes=[pltpu.VMEM((8, 4, 8, 128), jnp.uint32),
                            pltpu.VMEM((8, pchunk, 4, 2, 128), jnp.uint32)],
            interpret=interpret,
        )(init, words)
    else:
        words = words.reshape(s, n_words)
        wt = words.T
        if spad != s:
            wt = jnp.pad(wt, ((0, 0), (0, spad - s)))
        wt = wt.reshape(pc, pchunk, 4, 2, st_tiles, 8, 128) \
            .transpose(4, 5, 0, 1, 2, 3, 6)
        out = pl.pallas_call(
            functools.partial(_hh_kernel, unroll=not interpret),
            grid=(st_tiles, pc),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 8, 1, pchunk, 4, 2, 128),
                             lambda i, p: (i, 0, p, 0, 0, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, 8, 8, 128), lambda i, p: (i, 0, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((st_tiles, 8, 8, 128), jnp.uint32),
            scratch_shapes=[pltpu.VMEM((8, 4, 8, 128), jnp.uint32)],
            interpret=interpret,
        )(init, wt)
    # [ST, word, su, ln] -> [S, 8] digest words.
    out = out.transpose(0, 2, 3, 1).reshape(spad, 8)
    return out[:s] if spad != s else out


@functools.partial(jax.jit, static_argnames=("pchunk", "interpret"))
def _hash_pallas(blocks, init, pchunk: int, interpret: bool = False):
    """Byte-API wrapper: blocks uint8 [S, L] -> digests uint8 [S, 32].
    The u8 -> u32 bitcast here is itself a device relayout; hot callers
    (the fused framer) use _hash_words_pallas on u32 arrays directly."""
    s, l = blocks.shape
    w = jax.lax.bitcast_convert_type(
        blocks.reshape(s, l // 4, 4), jnp.uint32)         # [S, W]
    out = _hash_words_pallas(w, init, pchunk, interpret)
    return jax.lax.bitcast_convert_type(out, jnp.uint8).reshape(s, 32)


def _init_smem_np(key: bytes) -> np.ndarray:
    """Initial state as u32 [8, 4]: rows 2*var + (0 lo, 1 hi), cols lane."""
    return _init_state_np(key).reshape(8, 4)


def _pick_pchunk(n_packets: int) -> int:
    """Largest divisor of n_packets <= _PCHUNK_MAX (1 if prime-ish)."""
    for c in range(min(_PCHUNK_MAX, n_packets), 0, -1):
        if n_packets % c == 0:
            return c
    return 1


def _pallas_eligible(s: int, l: int) -> bool:
    """The kernel needs whole packets and enough streams to fill tiles
    without the zero-padding overhead dominating."""
    return l > 0 and l % 32 == 0 and s >= _STREAM_TILE // 2 \
        and _pick_pchunk(l // 32) >= 8


def hash_blocks_pallas(blocks, init, interpret: bool = False) -> jax.Array:
    """Pallas HH-256 of S blocks: uint8 [S, L] (device or host) ->
    uint8 [S, 32] device array. Requires L % 32 == 0; stream padding is
    handled internally."""
    blocks = jnp.asarray(blocks, dtype=jnp.uint8)
    s, l = blocks.shape
    return _hash_pallas(blocks, init, pchunk=_pick_pchunk(l // 32),
                        interpret=interpret)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("length",))
def _hash_jit(blocks, init, length: int):
    return _hash_impl(blocks, init, length)


def hash_blocks_device(key: bytes, blocks, mode: str = "auto") -> np.ndarray:
    """Keyed HighwayHash-256 of S equal-length blocks on device.

    blocks: uint8 [S, L] (numpy or device array) -> uint8 [S, 32] numpy.
    mode: "auto" (Pallas kernel on TPU when eligible, else the portable
    jnp path), "pallas" (forced; interpreted off-TPU), or "xla".
    """
    if len(key) != 32:
        raise ValueError("HighwayHash-256 requires a 32-byte key")
    blocks = jnp.asarray(blocks, dtype=jnp.uint8)
    s, l = blocks.shape
    on_tpu = jax.default_backend() == "tpu"
    if mode == "pallas" and l % 32 != 0:
        raise ValueError(
            f"pallas HH kernel requires whole 32-byte packets (L % 32 == 0), "
            f"got L={l}; use mode='auto' or 'xla' for ragged lengths")
    if mode == "pallas" or (mode == "auto" and on_tpu
                            and _pallas_eligible(s, l)):
        init = jnp.asarray(_init_smem_np(key))
        return np.asarray(hash_blocks_pallas(blocks, init,
                                             interpret=not on_tpu))
    init = jnp.asarray(_init_state_np(key))
    return np.asarray(_hash_jit(blocks, init, l))


# ---------------------------------------------------------------------------
# Device digests of bitrot-framed shard windows (the GET/heal read path)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("pchunk", "interpret"))
def _framed_digests_jit(blobs, init, pchunk: int, interpret: bool = False):
    """blobs: tuple of u32 [nb_i, fw] framed-frame arrays (fw = 8 digest
    words + block words). One concat + slice on device (HBM-speed), then
    the Pallas hash over all blocks as one stream set."""
    stacked = jnp.concatenate(blobs, axis=0) if len(blobs) > 1 else blobs[0]
    words = stacked[:, 8:]
    return _hash_words_pallas(words, init, pchunk, interpret=interpret)


# Device rows per hash dispatch: exactly one 1024-stream tile. Bounds
# HBM use per call (~3x 128 MiB at 128 KiB blocks) so multi-GiB heal
# reads can never OOM the chip, and keeps the jit cache to a handful of
# keys per frame width: (1024, fw) for full chunks plus (pad, fw) with
# pad a multiple of _FRAMED_PAD for the combined remainder.
_FRAMED_CHUNK = 1024
_FRAMED_PAD = 256


def framed_digests_device(blobs: list[np.ndarray],
                          interpret: bool = False) -> np.ndarray:
    """HighwayHash-256 digests of every framed block across shard blobs.

    blobs: u32 arrays [nb_i, fw], each row one on-disk frame
    (`digest || block`, reference cmd/bitrot-streaming.go:44-75) — pass
    zero-copy views of the raw shard-file bytes. Returns uint8
    [sum(nb_i), 32] recomputed digests of the block payloads, hashed on
    device in batched kernel passes (the read-side counterpart of the
    fused PUT pipeline: GETs dominate object-store traffic, so per-block
    host hashing is the wrong place to spend CPU).

    Dispatch shape discipline: whole _FRAMED_CHUNK-row slices of each
    blob go to the device as zero-copy views; the sub-chunk remainders
    of all blobs are packed into ONE host-padded array (rounded up to a
    _FRAMED_PAD multiple — pad rows hash garbage, sliced off). Every
    compiled shape is therefore from a small fixed set, not one per
    distinct shard-file size."""
    fw = blobs[0].shape[1]
    w = fw - 8
    pchunk = _pick_pchunk(w // 8)
    init = jnp.asarray(_init_smem_np(MAGIC_KEY))
    parts: list[tuple[int, int, np.ndarray]] = []  # (out_off, rows, view)
    rem: list[tuple[int, np.ndarray]] = []         # (out_off, view)
    off = 0
    for b in blobs:
        nb = b.shape[0]
        whole = (nb // _FRAMED_CHUNK) * _FRAMED_CHUNK
        for lo in range(0, whole, _FRAMED_CHUNK):
            parts.append((off + lo, _FRAMED_CHUNK,
                          b[lo:lo + _FRAMED_CHUNK]))
        if whole < nb:
            rem.append((off + whole, b[whole:]))
        off += nb
    out = np.empty((off, 32), dtype=np.uint8)
    for out_off, rows, view in parts:
        d = _framed_digests_jit((jnp.asarray(view),), init, pchunk,
                                interpret=interpret)
        out[out_off:out_off + rows] = \
            np.ascontiguousarray(np.asarray(d)).view(np.uint8)
    if rem:
        total = sum(v.shape[0] for _, v in rem)
        pad = -(-total // _FRAMED_PAD) * _FRAMED_PAD
        packed = np.zeros((pad, fw), dtype=np.uint32)
        pos = 0
        for _, v in rem:
            packed[pos:pos + v.shape[0]] = v
            pos += v.shape[0]
        d = np.ascontiguousarray(np.asarray(_framed_digests_jit(
            (jnp.asarray(packed),), init, pchunk,
            interpret=interpret))).view(np.uint8)
        pos = 0
        for out_off, v in rem:
            out[out_off:out_off + v.shape[0]] = d[pos:pos + v.shape[0]]
            pos += v.shape[0]
    return out                                    # [S, 32]


def framed_digests_eligible(n_blocks: int, shard_size: int) -> bool:
    """Worth dispatching to the device: enough streams to fill vector
    tiles and a whole-packet block length."""
    return (jax.default_backend() == "tpu" and shard_size % 1024 == 0
            and n_blocks >= 256 and _pick_pchunk(shard_size // 4 // 8) >= 8)


# ---------------------------------------------------------------------------
# Fused encode + bitrot digests
# ---------------------------------------------------------------------------

def make_encode_framer(matrix: np.ndarray, mode: str = "auto"):
    """Fused PUT pipeline on device, one call per stripe batch.

    Returns fn(data uint8 [B, k, L]) -> per-drive lists of per-block
    piece tuples: Reed-Solomon parity (ops/rs_device) plus the
    HighwayHash-256 bitrot digest of each of the B*n shard blocks. Like
    the reference's streaming bitrot writer (cmd/bitrot-streaming.go:
    44-75 writes the hash, then the block, per erasure block), the
    `hash || block` frame is assembled AT WRITE TIME from the pieces —
    the device never materialises interleaved frames (that copy is pure
    HBM bandwidth, ~0.75 ms per 128 MiB on v5e), data blocks are served
    as zero-copy views of the caller's buffer, and only parity +
    digests ride the device->host link. Digest algorithm is the bitrot
    default HighwayHash-256S under the magic key (cmd/bitrot.go:37,
    105-110).
    """
    from minio_tpu.ops.rs_device import make_encoder, make_encoder32
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    n = matrix.shape[1] + matrix.shape[0]
    encode = make_encoder(matrix, mode=mode)
    encode32 = make_encoder32(matrix, mode=mode)
    on_tpu = jax.default_backend() == "tpu"

    @functools.partial(jax.jit, static_argnames=("pchunk",))
    def fused32(data32, init, pchunk: int):
        """u32 hot path: data [B, k, L4] u32 -> (parity [B, m, L4],
        dig_d [B, k, 8], dig_p [B, m, 8]) u32.

        Everything stays in u32 lanes (lane t = shard bytes 4t..4t+3 LE):
        the encoder's output IS the word layout the hash wants, the hash
        kernel transposes in VMEM (no standalone transpose pass), and
        data and parity hash as two separate stream sets (no shards
        concatenate). No u8<->u32 relayouts and no XLA copies anywhere.
        """
        b, k, l4 = data32.shape
        m = n - k
        parity = encode32(data32)                  # [B, m, L4]
        dig_d = _hash_words_pallas(data32, init,
                                   pchunk=pchunk).reshape(b, k, 8)
        dig_p = _hash_words_pallas(parity, init,
                                   pchunk=pchunk).reshape(b, m, 8)
        return parity, dig_d, dig_p

    @functools.partial(jax.jit, static_argnames=())
    def fused8(data, init):
        """Portable byte path (off-TPU / ineligible shapes)."""
        b, k, l = data.shape
        parity = encode(data)                      # [B, m, L]
        shards = jnp.concatenate([data, parity], axis=1)  # [B, n, L]
        digests = _hash_impl(shards.reshape(b * n, l), init, l)
        return parity, digests.reshape(b, n, 32)

    def run(data) -> list[list[tuple]]:
        """data uint8 [B, k, L] numpy -> n per-drive lists; entry i is
        [(digest32, block_bytes), ...] per erasure block, concatenation
        of which is drive i's framed shard-file bytes. Data-block pieces
        are views of `data` (zero copy)."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        b, k, l = data.shape
        pchunk = _pick_pchunk(l // 32) if l and l % 32 == 0 else 0
        if on_tpu and l % 1024 == 0 and pchunk >= 8:
            data32 = jnp.asarray(data.view(np.uint32))
            parity, dig_d, dig_p = fused32(
                data32, jnp.asarray(_init_smem_np(MAGIC_KEY)), pchunk)
            # ascontiguousarray: device arrays can come back with a
            # non-contiguous minor axis for some batch shapes, and
            # .view of a wider dtype requires contiguity.
            parity = np.ascontiguousarray(np.asarray(parity)) \
                .view(np.uint8)                          # [B, m, L]
            dig_d = np.ascontiguousarray(np.asarray(dig_d)) \
                .view(np.uint8)                          # [B, k, 32]
            dig_p = np.ascontiguousarray(np.asarray(dig_p)) \
                .view(np.uint8)                          # [B, m, 32]
            return ([[(dig_d[bi, i], data[bi, i]) for bi in range(b)]
                     for i in range(k)]
                    + [[(dig_p[bi, j], parity[bi, j]) for bi in range(b)]
                       for j in range(parity.shape[1])])
        parity, digests = fused8(jnp.asarray(data, dtype=jnp.uint8),
                                 jnp.asarray(_init_state_np(MAGIC_KEY)))
        parity = np.asarray(parity)
        digests = np.asarray(digests)                    # [B, n, 32]
        shards = [data[:, i] for i in range(k)] \
            + [parity[:, j] for j in range(parity.shape[1])]
        return [[(digests[bi, i], shards[i][bi]) for bi in range(b)]
                for i in range(n)]

    def device_step(data32):
        """Device-resident fused pipeline: u32 [B, k, L4] -> (parity,
        data digests, parity digests) device arrays. The exact jitted
        graph the PUT hot path runs — exposed so bench.py measures
        production code rather than a hand copy."""
        l4 = data32.shape[2]
        return fused32(data32, jnp.asarray(_init_smem_np(MAGIC_KEY)),
                       _pick_pchunk(l4 // 8))

    run.device_step = device_step
    run.mesh_devices = 1
    return run


# ---------------------------------------------------------------------------
# Fused GET verify (the device de-framer)
# ---------------------------------------------------------------------------
# The read-side mirror of make_encode_framer: the GET hot loop's cost on
# the host is HighwayHashing every fetched framed shard block
# (native.cc mtpu_get_frame does it GIL-free; the numpy path in
# storage/bitrot.read_framed_blocks_many does it vectorized). The
# de-framer moves that hashing onto the accelerator: ONE dispatch takes
# a stacked window of on-disk frames (`digest || block`,
# cmd/bitrot-streaming.go:44-75) across the k data shards, recomputes
# every block digest on device, and returns the per-(block, shard)
# verification verdicts. The interleaved plaintext is then served as
# zero-copy views of the caller's own framed bytes at demux time
# (ops/batcher split_fn) — the payload never rides the device->host
# link back (the digests are 32 bytes/block; the blocks are 128 KiB),
# which is strictly less PCIe than the PUT direction pays. Byte
# identity with the host kernels is therefore exactly the question
# "does the device hash agree", asserted by tests/test_decode_route.py.


def make_deframer(k: int, mode: str = "auto"):
    """Single-chip fused GET verifier for k-data-shard stripes.

    Returns fn(framed uint8 [B, k, F]) -> ok bool numpy [B, k], where
    F = 32 + shard_size and row b holds erasure block b's k on-disk
    frames. ok[b, i] is True when shard i's block b digest verifies —
    the same verdict mtpu_get_frame's bad-mask encodes, batched.
    """
    del k  # shape-generic: the stream count is B*k either way
    on_tpu = jax.default_backend() == "tpu"

    @functools.partial(jax.jit, static_argnames=("pchunk",))
    def verify32(framed32, init, pchunk: int):
        """u32 hot path: framed [B, k, F4] u32 -> ok bool [B, k]."""
        b, kk, f4 = framed32.shape
        words = framed32[:, :, 8:].reshape(b * kk, f4 - 8)
        digs = _hash_words_pallas(words, init, pchunk=pchunk)  # [B*k, 8]
        stored = framed32[:, :, :8].reshape(b * kk, 8)
        return jnp.all(digs == stored, axis=1).reshape(b, kk)

    @jax.jit
    def verify8(framed, init):
        """Portable byte path: framed [B, k, F] u8 -> ok bool [B, k]."""
        b, kk, f = framed.shape
        blocks = framed[:, :, 32:].reshape(b * kk, f - 32)
        digs = _hash_impl(blocks, init, f - 32)                # [B*k, 32]
        stored = framed[:, :, :32].reshape(b * kk, 32)
        return jnp.all(digs == stored, axis=1).reshape(b, kk)

    def run(framed) -> np.ndarray:
        framed = np.ascontiguousarray(framed, dtype=np.uint8)
        b, kk, f = framed.shape
        s = f - 32
        pchunk = _pick_pchunk(s // 32) if s and s % 32 == 0 else 0
        if on_tpu and f % 4 == 0 and s % 1024 == 0 and pchunk >= 8:
            f32 = jnp.asarray(framed.view(np.uint32))
            ok = verify32(f32, jnp.asarray(_init_smem_np(MAGIC_KEY)),
                          _pick_pchunk(s // 4 // 8))
        else:
            ok = verify8(jnp.asarray(framed),
                         jnp.asarray(_init_state_np(MAGIC_KEY)))
        return np.asarray(ok)

    run.mesh_devices = 1
    return run


# ---------------------------------------------------------------------------
# Mesh-sharded cross-request framer
# ---------------------------------------------------------------------------

def mesh_batch_devices(devices=None) -> list:
    """The largest power-of-two prefix of the visible devices: padding
    buckets are powers of two (ops/batcher._BUCKETS), so a power-of-two
    mesh keeps every bucketed batch evenly divisible across chips with
    zero per-chip remainder shapes (one compile per bucket, not per
    (bucket, remainder) pair). MTPU_MESH_DEVICES caps the prefix — the
    chip-count scaling sweep (bench.py put_scaling) uses it to measure
    1/2/4/8-chip aggregates on one host."""
    import os as _os
    devs = list(devices if devices is not None else jax.devices())
    try:
        cap = int(_os.environ.get("MTPU_MESH_DEVICES", "") or len(devs))
    except ValueError:
        cap = len(devs)
    devs = devs[:max(1, cap)]
    p = 1
    # Cap at the largest padding bucket (ops/batcher._BUCKETS[-1]): a
    # mesh wider than the biggest batch shape could never be fed a
    # divisible batch.
    while p * 2 <= len(devs) and p * 2 <= 256:
        p *= 2
    return devs[:p]


def _shard_map_compat():
    """shard_map under its jax 0.6 top-level or 0.4 experimental home,
    with the replication check disabled under whichever kwarg name
    (check_rep -> check_vma rename) this jax spells."""
    try:                                       # jax >= 0.6 top-level
        from jax import shard_map as _shard_map
    except ImportError:                        # 0.4.x experimental home
        from jax.experimental.shard_map import shard_map as _shard_map
    import inspect as _inspect
    _sm_params = _inspect.signature(_shard_map).parameters
    _sm_kw = {"check_vma": False} if "check_vma" in _sm_params \
        else ({"check_rep": False} if "check_rep" in _sm_params else {})

    def shard_map(body, mesh, in_specs, out_specs):
        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **_sm_kw)
    return shard_map


def make_mesh_framer(matrix: np.ndarray, mode: str = "auto", devices=None):
    """The cross-request device framer: make_encode_framer's run()
    contract — stacked u8 [B, k, L] -> k+m per-drive lists of
    (digest, block) piece tuples — with the batch dimension ("stripes
    from MANY concurrent PutObject requests", coalesced by
    ops/batcher.StripeBatcher) sharded over every available chip.

    pjit-style dispatch (SNIPPETS [1][2][3]): the jitted step carries a
    NamedSharding(mesh, P("stripe")) on the batch axis — each chip runs
    the fused GF(2^8)+HighwayHash pipeline on its local stripe slice,
    no cross-chip traffic inside the hot loop (stripes are independent,
    the same property the reference exploits with per-goroutine encode,
    cmd/erasure-encode.go:27) — and `donate_argnums=(0,)` donates the
    input HBM buffer so the pooled host staging (io/bufpool) flows
    host->HBM->parity without XLA's defensive copy. One compile per
    (padding bucket, EC config): callers pad the batch dim to the fixed
    buckets, never to raw concurrency levels.

    On one device (CPU tests, MTPU_MESH_DEVICES=1) this degrades to the
    single-chip fused framer — same bytes, no mesh machinery.
    """
    devs = mesh_batch_devices(devices)
    ndev = len(devs)
    if ndev <= 1:
        return make_encode_framer(matrix, mode=mode)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    shard_map = _shard_map_compat()
    from minio_tpu.ops.rs_device import make_encoder, make_encoder32
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    m, k = matrix.shape
    n = k + m
    mesh = Mesh(np.asarray(devs), ("stripe",))
    sharding = NamedSharding(mesh, P("stripe"))
    on_tpu = jax.default_backend() == "tpu"
    # Donation is a TPU-memory contract; the CPU backend ignores it
    # with a compile warning, so only declare it where it buys the copy.
    donate = (0,) if on_tpu else ()
    encode = make_encoder(matrix, mode=mode)
    encode32 = make_encoder32(matrix, mode=mode)

    @functools.partial(jax.jit, static_argnames=("pchunk",),
                       donate_argnums=donate)
    def mesh32(data32, init, pchunk: int):
        """u32 hot path, batch sharded over the mesh (see fused32)."""
        def body(d, ini):
            b = d.shape[0]
            parity = encode32(d)
            dig_d = _hash_words_pallas(d, ini,
                                       pchunk=pchunk).reshape(b, k, 8)
            dig_p = _hash_words_pallas(parity, ini,
                                       pchunk=pchunk).reshape(b, m, 8)
            return parity, dig_d, dig_p
        return shard_map(
            body, mesh=mesh, in_specs=(P("stripe"), P()),
            out_specs=(P("stripe"), P("stripe"), P("stripe")))(data32, init)

    @functools.partial(jax.jit, donate_argnums=donate)
    def mesh8(data, init):
        """Portable byte path, batch sharded over the mesh."""
        def body(d, ini):
            b, _, l = d.shape
            parity = encode(d)
            shards = jnp.concatenate([d, parity], axis=1)
            digests = _hash_impl(shards.reshape(b * n, l), ini, l)
            return parity, digests.reshape(b, n, 32)
        return shard_map(
            body, mesh=mesh, in_specs=(P("stripe"), P()),
            out_specs=(P("stripe"), P("stripe")))(data, init)

    def run(data) -> list[list[tuple]]:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        b, kk, l = data.shape
        assert b % ndev == 0, \
            f"batch {b} not divisible by {ndev}-chip mesh (pad buckets)"
        pchunk = _pick_pchunk(l // 32) if l and l % 32 == 0 else 0
        if on_tpu and l % 1024 == 0 and pchunk >= 8:
            d32 = jax.device_put(data.view(np.uint32), sharding)
            parity, dig_d, dig_p = mesh32(
                d32, jnp.asarray(_init_smem_np(MAGIC_KEY)), pchunk)
            parity = np.ascontiguousarray(np.asarray(parity)) \
                .view(np.uint8)
            dig_d = np.ascontiguousarray(np.asarray(dig_d)).view(np.uint8)
            dig_p = np.ascontiguousarray(np.asarray(dig_p)).view(np.uint8)
            return ([[(dig_d[bi, i], data[bi, i]) for bi in range(b)]
                     for i in range(k)]
                    + [[(dig_p[bi, j], parity[bi, j]) for bi in range(b)]
                       for j in range(m)])
        d8 = jax.device_put(data, sharding)
        parity, digests = mesh8(d8,
                                jnp.asarray(_init_state_np(MAGIC_KEY)))
        parity = np.asarray(parity)
        digests = np.asarray(digests)
        shards = [data[:, i] for i in range(k)] \
            + [parity[:, j] for j in range(m)]
        return [[(digests[bi, i], shards[i][bi]) for bi in range(b)]
                for i in range(n)]

    run.mesh_devices = ndev
    return run


def make_mesh_deframer(k: int, mode: str = "auto", devices=None):
    """The cross-request device de-framer: make_deframer's run()
    contract — framed u8 [B, k, F] -> ok bool [B, k] — with the batch
    dimension ("erasure blocks from MANY concurrent GetObject windows",
    coalesced by ops/batcher's get route) sharded over every available
    chip via NamedSharding(mesh, P("stripe")), exactly the encode
    framer's dispatch shape mirrored.

    `donate_argnums=(0,)` on TPU donates the staged framed window (one
    pooled bufpool lease, ops/batcher._stage) into HBM so the read-side
    batch flows host->HBM copy-free; only the B*k verdicts ride back.
    One compile per (padding bucket, k, frame width). On one device
    (CPU tests, MTPU_MESH_DEVICES=1) this degrades to the single-chip
    fused verifier — same verdicts, no mesh machinery.
    """
    devs = mesh_batch_devices(devices)
    ndev = len(devs)
    if ndev <= 1:
        return make_deframer(k, mode=mode)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    shard_map = _shard_map_compat()
    mesh = Mesh(np.asarray(devs), ("stripe",))
    sharding = NamedSharding(mesh, P("stripe"))
    on_tpu = jax.default_backend() == "tpu"
    donate = (0,) if on_tpu else ()

    @functools.partial(jax.jit, static_argnames=("pchunk",),
                       donate_argnums=donate)
    def mesh_verify32(framed32, init, pchunk: int):
        def body(fr, ini):
            b, kk, f4 = fr.shape
            words = fr[:, :, 8:].reshape(b * kk, f4 - 8)
            digs = _hash_words_pallas(words, ini, pchunk=pchunk)
            stored = fr[:, :, :8].reshape(b * kk, 8)
            return jnp.all(digs == stored, axis=1).reshape(b, kk)
        return shard_map(body, mesh=mesh, in_specs=(P("stripe"), P()),
                         out_specs=P("stripe"))(framed32, init)

    @functools.partial(jax.jit, donate_argnums=donate)
    def mesh_verify8(framed, init):
        def body(fr, ini):
            b, kk, f = fr.shape
            blocks = fr[:, :, 32:].reshape(b * kk, f - 32)
            digs = _hash_impl(blocks, ini, f - 32)
            stored = fr[:, :, :32].reshape(b * kk, 32)
            return jnp.all(digs == stored, axis=1).reshape(b, kk)
        return shard_map(body, mesh=mesh, in_specs=(P("stripe"), P()),
                         out_specs=P("stripe"))(framed, init)

    def run(framed) -> np.ndarray:
        framed = np.ascontiguousarray(framed, dtype=np.uint8)
        b, kk, f = framed.shape
        assert b % ndev == 0, \
            f"batch {b} not divisible by {ndev}-chip mesh (pad buckets)"
        s = f - 32
        pchunk = _pick_pchunk(s // 32) if s and s % 32 == 0 else 0
        if on_tpu and f % 4 == 0 and s % 1024 == 0 and pchunk >= 8:
            f32 = jax.device_put(framed.view(np.uint32), sharding)
            ok = mesh_verify32(f32, jnp.asarray(_init_smem_np(MAGIC_KEY)),
                               _pick_pchunk(s // 4 // 8))
        else:
            f8 = jax.device_put(framed, sharding)
            ok = mesh_verify8(f8, jnp.asarray(_init_state_np(MAGIC_KEY)))
        return np.asarray(ok)

    run.mesh_devices = ndev
    return run
