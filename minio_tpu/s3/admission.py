"""API admission control: bounded in-flight requests with load shedding.

The analogue of the reference's maxClients middleware
(cmd/generic-handlers.go + cmd/handler-api.go apiConfig: requests_max /
requests_deadline): at most N API requests run concurrently; excess
requests wait in a BOUNDED queue for a slot and are shed with
503 + Retry-After when the queue is full or the wait deadline passes.
Request classes get independent gates so admin/health/metrics traffic
is never starved behind saturating data traffic (the reference exempts
its admin and health routers from the throttle for the same reason).

Environment:
  MTPU_API_REQUESTS_MAX       max in-flight data-path requests
                              (0 = unlimited, the default)
  MTPU_API_REQUESTS_DEADLINE  max time a request may wait for a slot
                              (duration: "10s", "500ms", "1m"; default 10s)
  MTPU_API_ADMIN_REQUESTS_MAX independent cap for the admin/health class
                              (0 = unlimited, the default)
  MTPU_API_REQUEST_TIMEOUT    per-request deadline budget granted at
                              admission and propagated through the stack
                              (utils/deadline.py); 0 = no budget (default)
"""

from __future__ import annotations

import math
import os
import threading
from typing import Optional

# Shed reasons (label values on the shed counter).
QUEUE_FULL = "queue_full"
DEADLINE = "deadline"

# Request classes.
CLASS_S3 = "s3"
CLASS_ADMIN = "admin"

# The operator health endpoints, enumerated ONCE: the router, the
# metrics labeler, and the admission classifier all consult this — a
# new health endpoint added here is automatically exempt from data-
# path gating and labeled correctly.
HEALTH_PATHS = ("/minio/health/live", "/minio/health/ready")


def path_class(raw_path: str) -> str:
    """'admin' | 'health' | 'metrics' | 's3' — the single source of
    truth for operator-endpoint path patterns, matching the router's
    dispatch exactly. A path the router serves as ordinary S3 data
    (e.g. a bucket named "minio" with key "healthfiles/x") must
    classify as 's3'."""
    if raw_path == "/minio/admin" or raw_path.startswith("/minio/admin/"):
        return "admin"
    if raw_path in HEALTH_PATHS:
        return "health"
    if raw_path.startswith("/minio/v2/metrics"):
        return "metrics"
    return CLASS_S3


def class_for(pc: str) -> str:
    """Gate class for an already-computed path_class — the serve hot
    loop classifies each request's path ONCE and shares the result
    between admission, routing, and metrics labeling."""
    return CLASS_ADMIN if pc != CLASS_S3 else CLASS_S3


class AdmissionShed(Exception):
    """Request shed by admission control -> 503 SlowDown + Retry-After."""

    def __init__(self, klass: str, reason: str, retry_after: int):
        self.klass = klass
        self.reason = reason
        self.retry_after = retry_after
        super().__init__(f"{klass} request shed ({reason})")


def parse_duration(text: str, default: float) -> float:
    """Parse "10s" / "500ms" / "1m" / bare seconds; fall back on junk
    (a typo in an env var must not take the server down)."""
    t = (text or "").strip().lower()
    if not t:
        return default
    mult = 1.0
    for suffix, m in (("ms", 1e-3), ("us", 1e-6), ("s", 1.0), ("m", 60.0),
                      ("h", 3600.0)):
        if t.endswith(suffix):
            t, mult = t[:-len(suffix)], m
            break
    try:
        return float(t) * mult
    except ValueError:
        return default


class _Gate:
    """One request class: a semaphore of `limit` slots plus a bounded
    wait queue of `queue_limit` (overflow sheds immediately, a queued
    wait sheds at the deadline). limit=0 disables gating entirely."""

    def __init__(self, name: str, limit: int, wait_deadline: float,
                 queue_limit: Optional[int] = None):
        self.name = name
        self.limit = max(0, limit)
        self.wait_deadline = max(0.0, wait_deadline)
        # Queue bound defaults to the slot count: at saturation at most
        # 2*limit requests occupy threads (running + queued); the rest
        # shed instantly instead of accumulating unbounded waiters.
        self.queue_limit = self.limit if queue_limit is None \
            else max(0, queue_limit)
        self._sem = threading.Semaphore(self.limit) if self.limit else None
        self._mu = threading.Lock()
        self.in_flight = 0
        self.waiting = 0
        self.peak_in_flight = 0
        self.admitted_total = 0
        self.shed_total: dict[str, int] = {QUEUE_FULL: 0, DEADLINE: 0}

    def _shed(self, reason: str) -> None:
        with self._mu:
            self.shed_total[reason] += 1
        raise AdmissionShed(self.name, reason, self.retry_after())

    def retry_after(self) -> int:
        """Advisory Retry-After: the wait deadline rounded up — a
        client retrying sooner would likely just queue again."""
        return max(1, int(math.ceil(self.wait_deadline)))

    def _admitted(self) -> None:
        with self._mu:
            self.in_flight += 1
            self.peak_in_flight = max(self.peak_in_flight, self.in_flight)
            self.admitted_total += 1

    def enter(self) -> None:
        if self._sem is None:
            self._admitted()
            return
        # Fast path: a free slot admits without ever touching the
        # queue (and without racing the in_flight bookkeeping).
        if self._sem.acquire(blocking=False):
            self._admitted()
            return
        with self._mu:
            if self.waiting >= self.queue_limit:
                # Counter bumped inline (we hold the lock already).
                self.shed_total[QUEUE_FULL] += 1
                raise AdmissionShed(self.name, QUEUE_FULL,
                                    self.retry_after())
            self.waiting += 1
        try:
            ok = self._sem.acquire(timeout=self.wait_deadline)
        finally:
            with self._mu:
                self.waiting -= 1
        if not ok:
            self._shed(DEADLINE)
        self._admitted()

    def leave(self) -> None:
        with self._mu:
            self.in_flight -= 1
        if self._sem is not None:
            self._sem.release()

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "limit": self.limit,
                "queue_limit": self.queue_limit,
                "wait_deadline_seconds": self.wait_deadline,
                "in_flight": self.in_flight,
                "waiting": self.waiting,
                "peak_in_flight": self.peak_in_flight,
                "admitted_total": self.admitted_total,
                "shed_queue_full_total": self.shed_total[QUEUE_FULL],
                "shed_deadline_total": self.shed_total[DEADLINE],
            }


class AdmissionController:
    """Per-class gates plus the per-request deadline budget config."""

    def __init__(self, max_requests: int = 0, wait_deadline: float = 10.0,
                 admin_max_requests: int = 0,
                 request_timeout: float = 0.0):
        self.gates = {
            CLASS_S3: _Gate(CLASS_S3, max_requests, wait_deadline),
            CLASS_ADMIN: _Gate(CLASS_ADMIN, admin_max_requests,
                               wait_deadline),
        }
        # Seconds granted to each admitted request as its deadline
        # budget (utils/deadline.py); 0 = requests get no budget.
        self.request_timeout = max(0.0, request_timeout)
        self._mu = threading.Lock()
        self.deadline_exceeded_total = 0

    @classmethod
    def from_env(cls, env=os.environ) -> "AdmissionController":
        def intenv(key):
            try:
                return int(env.get(key, "0") or 0)
            except ValueError:
                return 0
        return cls(
            max_requests=intenv("MTPU_API_REQUESTS_MAX"),
            wait_deadline=parse_duration(
                env.get("MTPU_API_REQUESTS_DEADLINE", ""), 10.0),
            admin_max_requests=intenv("MTPU_API_ADMIN_REQUESTS_MAX"),
            request_timeout=parse_duration(
                env.get("MTPU_API_REQUEST_TIMEOUT", ""), 0.0),
        )

    def divided(self, workers: int) -> "AdmissionController":
        """This controller's budgets split across `workers` pre-forked
        processes (io/workers.py): per-worker limit = ceil(limit / n),
        so the fleet-wide in-flight bound stays what the operator
        configured (within rounding). 0 (unlimited) stays 0; the
        per-request deadline budget is per request, not per fleet, and
        passes through unchanged."""
        if workers <= 1:
            return self
        def split(limit: int) -> int:
            return math.ceil(limit / workers) if limit > 0 else 0
        s3 = self.gates[CLASS_S3]
        admin = self.gates[CLASS_ADMIN]
        return AdmissionController(
            max_requests=split(s3.limit),
            wait_deadline=s3.wait_deadline,
            admin_max_requests=split(admin.limit),
            request_timeout=self.request_timeout)

    def classify(self, raw_path: str) -> str:
        """Admin, health, and metrics endpoints ride the admin gate —
        an operator diagnosing an overloaded server must not queue
        behind the very traffic that overloaded it (path_class is the
        single shared pattern source, so router and gate cannot
        drift)."""
        return class_for(path_class(raw_path))

    def enter(self, klass: str) -> _Gate:
        """Admit or raise AdmissionShed; caller must leave() the
        returned gate when the request finishes."""
        gate = self.gates[klass]
        gate.enter()
        return gate

    def record_deadline_exceeded(self) -> None:
        with self._mu:
            self.deadline_exceeded_total += 1

    def snapshot(self) -> dict:
        out = {name: g.snapshot() for name, g in self.gates.items()}
        out["request_timeout_seconds"] = self.request_timeout
        with self._mu:
            out["deadline_exceeded_total"] = self.deadline_exceeded_total
        return out
