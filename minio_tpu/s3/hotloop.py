"""Serve hot loop: native request framing + pooled connection buffers.

The per-request cost of the stdlib front end is readline-per-header,
an email.Message build, and a fresh BufferedReader per connection. This
module replaces that hot path for the S3 handler (s3/server.py):

  * ConnReader — one pooled recv buffer per connection (io/bufpool
    lease, held hot across keep-alive requests). It serves the rfile
    surface (read/readline/readinto) for EVERY parser, and exposes its
    buffer to the native head framer so request heads are scanned
    GIL-free straight out of the recv buffer (native/native.cc
    mtpu_http_head) with header names lowercased in place.
  * FastHeaders — the flat lowercase dict the native parse produces,
    quacking like the email.Message the handlers index. Header-name
    strings are interned per CONNECTION, so a keep-alive client's
    repeated header sets reuse the same str objects request after
    request (the "header parse memoized per connection" fast path).
  * send_gathered — writev-style response writes: socket.sendmsg of
    [header block, body view, ...] in ONE syscall, pooled GET window
    memoryviews going to the wire with no Python-level bytes joins.

Anything the native framer rejects (obs-fold, exotic framing, heads
larger than the recv buffer) falls back to the stdlib Python parser on
the SAME buffered bytes — a per-request decision, counted in
`minio_tpu_http_parse_fallbacks_total`.

MTPU_HTTP_NATIVE=off disables the native framer entirely (the stock
BaseHTTPRequestHandler parse path, byte-for-byte).
"""

from __future__ import annotations

import ctypes
import os
import socket

MAX_HEADERS = 100
# Matches http.client's per-line bound; heads that exceed the recv
# buffer take the Python fallback (which enforces stock limits).
RECV_BUF = 64 << 10
# Native head parse result codes (mtpu_http_head).
_INCOMPLETE = 0
_MALFORMED = -1
_TOO_MANY = -2


def native_enabled(env=os.environ) -> bool:
    return env.get("MTPU_HTTP_NATIVE", "").lower() not in ("off", "0",
                                                           "false")


_LIB = None
_LIB_TRIED = False


def lib():
    """The native library handle, or None (pure-Python fallback)."""
    global _LIB, _LIB_TRIED
    if not _LIB_TRIED:
        from minio_tpu import native as native_mod
        _LIB = native_mod.load()
        _LIB_TRIED = True
    return _LIB


class FastHeaders:
    """Case-insensitive header view over the native parse's flat
    lowercase dict — the subset of email.Message the handlers use."""

    __slots__ = ("d",)

    def __init__(self, d: dict):
        self.d = d

    def get(self, name, default=None):
        return self.d.get(name.lower(), default)

    def __getitem__(self, name):
        return self.d.get(name.lower())

    def __contains__(self, name):
        return name.lower() in self.d

    def items(self):
        return self.d.items()

    def keys(self):
        return self.d.keys()

    def values(self):
        return self.d.values()


class ConnReader:
    """Pooled-buffer connection reader, persistent across keep-alive
    requests. File-like for every body/fallback consumer (BufferedReader
    semantics: read(n) blocks for n bytes or EOF), while the native head
    parser works on the underlying buffer directly between requests.

    The recv buffer is LAZY and hibernatable: no pool lease is held
    until the first byte arrives, and `hibernate()` returns the lease
    whenever the buffer is empty (the event loop parks idle keep-alive
    connections with ZERO pooled bytes held — 10k idle connections cost
    file descriptors and small Python objects, not 10k recv buffers).
    The next fill re-leases transparently (steady-state pool hit)."""

    def __init__(self, sock: socket.socket, pool=None):
        from minio_tpu.io.bufpool import global_pool
        self._sock = sock
        self._pool = pool or global_pool()
        self._lease = None
        self._raw = None
        self._cap = RECV_BUF
        self._mv = None
        self._arr = None
        self._out = (ctypes.c_int32 * (6 + 4 * MAX_HEADERS))()
        self._start = 0
        self._end = 0
        self._closed = False
        # Per-connection header-name interning: bytes -> str survives
        # across this connection's requests.
        self.name_cache: dict[bytes, str] = {}

    # -- buffer plumbing -------------------------------------------------

    def _ensure(self) -> None:
        """Lease the recv buffer (first use, or re-arm after
        hibernate())."""
        if self._raw is not None:
            return
        if self._closed:
            # A re-lease after close() would never be released again.
            raise ValueError("read on closed ConnReader")
        self._lease = self._pool.lease(RECV_BUF)
        self._raw = self._lease.raw
        self._cap = len(self._raw)
        self._mv = memoryview(self._raw)
        # ctypes view for the native framer (dropped before the lease
        # returns — an exported buffer must never reach the free list).
        self._arr = (ctypes.c_uint8 * self._cap).from_buffer(self._raw)
        self._start = self._end = 0

    def hibernate(self) -> bool:
        """Release the pooled recv buffer if nothing is buffered.
        Returns True when the reader now holds no lease (already
        hibernated counts); False when buffered bytes pin it."""
        if self._raw is None:
            return True
        if self._end - self._start:
            return False
        self._arr = None
        self._mv.release()
        self._mv = None
        self._raw = None
        lease, self._lease = self._lease, None
        lease.release()
        return True

    def _compact(self) -> None:
        if self._start:
            n = self._end - self._start
            self._mv[:n] = self._mv[self._start:self._end]
            self._start, self._end = 0, n

    def _fill(self) -> int:
        """recv into the buffer tail; returns bytes added (0 = EOF or
        buffer full)."""
        self._ensure()
        if self._end == self._cap:
            self._compact()
            if self._end == self._cap:
                return 0
        n = self._sock.recv_into(self._mv[self._end:], self._cap - self._end)
        self._end += n
        return n

    def fill_nb(self):
        """Non-blocking fill for the event loop (socket must be in
        non-blocking mode): bytes added (> 0), 0 at EOF, or None when
        the read would block (spurious wakeup) or the buffer is full."""
        self._ensure()
        if self._end == self._cap:
            self._compact()
            if self._end == self._cap:
                return None
        try:
            n = self._sock.recv_into(self._mv[self._end:],
                                     self._cap - self._end)
        except (BlockingIOError, InterruptedError):
            return None
        self._end += n
        return n

    @property
    def buffered(self) -> int:
        return self._end - self._start

    # -- rfile surface ---------------------------------------------------

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            # Read-to-EOF: nothing on the serve path does this (bodies
            # are Content-Length or chunk framed), but be correct.
            parts = [bytes(self._mv[self._start:self._end])
                     if self._mv is not None else b""]
            self._start = self._end = 0
            while True:
                chunk = self._sock.recv(65536)
                if not chunk:
                    break
                parts.append(chunk)
            return b"".join(parts)
        if n == 0:
            return b""
        have = self.buffered
        if have >= n:
            out = bytes(self._mv[self._start:self._start + n])
            self._start += n
            if self._start == self._end:
                self._start = self._end = 0
            return out
        parts = []
        if have:
            parts.append(bytes(self._mv[self._start:self._end]))
            self._start = self._end = 0
            n -= have
        # Large remainders recv straight into caller-sized chunks —
        # no bounce through the 64 KiB buffer.
        while n > 0:
            chunk = self._sock.recv(min(n, 1 << 20))
            if not chunk:
                break
            parts.append(chunk)
            n -= len(chunk)
        return b"".join(parts)

    def readinto(self, b) -> int:
        mv = memoryview(b).cast("B")
        want = len(mv)
        done = 0
        have = min(self.buffered, want)
        if have:
            mv[:have] = self._mv[self._start:self._start + have]
            self._start += have
            if self._start == self._end:
                self._start = self._end = 0
            done = have
        while done < want:
            n = self._sock.recv_into(mv[done:], want - done)
            if not n:
                break
            done += n
        return done

    def readline(self, limit: int = 65537) -> bytes:
        self._ensure()
        while True:
            nl = self._raw.find(b"\n", self._start, self._end)
            if nl >= 0:
                take = min(nl + 1 - self._start, limit)
                out = bytes(self._mv[self._start:self._start + take])
                self._start += take
                if self._start == self._end:
                    self._start = self._end = 0
                return out
            if self.buffered >= limit:
                out = bytes(self._mv[self._start:self._start + limit])
                self._start += limit
                return out
            if not self._fill():
                out = bytes(self._mv[self._start:self._end])
                self._start = self._end = 0
                return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._raw is None:          # hibernated / never leased
            return
        # Exported views go first: a ctypes array or memoryview still
        # attached would alias a recycled pool buffer.
        self._arr = None
        self._mv.release()
        self._mv = None
        self._raw = None
        lease, self._lease = self._lease, None
        lease.release()

    # -- native head parse ----------------------------------------------

    def parse_head(self, native_lib):
        """Frame one request head out of the connection buffer.

        Returns (headers_dict, method, target, version, keep_default)
        on success (head bytes consumed), None at a clean EOF before
        any byte of a request, or raises _Fallback when the Python
        parser should take this request (bytes left buffered)."""
        while True:
            if self.buffered:
                self._ensure()
                self._compact()
                n = native_lib.mtpu_http_head(self._arr, self._end,
                                              self._out, MAX_HEADERS)
                if n > 0:
                    return self._build_head(int(n))
                if n != _INCOMPLETE:
                    raise _Fallback()
                if self._end == self._cap:
                    raise _Fallback()      # head larger than the buffer
            got = self._fill()
            if not got:
                if self.buffered:
                    raise _Fallback()      # EOF mid-head: stock error path
                return None                # clean close between requests

    def _build_head(self, head_len: int):
        out = self._out
        mv = self._mv
        method = bytes(mv[out[0]:out[0] + out[1]]).decode("latin-1")
        target = bytes(mv[out[2]:out[2] + out[3]]).decode("latin-1")
        version = "HTTP/1.1" if out[4] == 11 else "HTTP/1.0"
        cache = self.name_cache
        d: dict[str, str] = {}
        for i in range(out[5]):
            base = 6 + 4 * i
            nb = bytes(mv[out[base]:out[base] + out[base + 1]])
            name = cache.get(nb)
            if name is None:
                if len(cache) < 256:
                    name = cache.setdefault(nb, nb.decode("latin-1"))
                else:
                    name = nb.decode("latin-1")
            val = bytes(mv[out[base + 2]:out[base + 2] + out[base + 3]]) \
                .decode("latin-1")
            if name in d:
                # SigV4 canonicalization folds repeats with a comma;
                # match what signing clients produced.
                d[name] = d[name] + "," + val
            else:
                d[name] = val
        self._start += head_len
        if self._start == self._end:
            self._start = self._end = 0
        return d, method, target, version, out[4] == 11


    def try_parse_head(self, native_lib):
        """Frame one request head from ALREADY-buffered bytes only —
        the event loop's non-blocking probe (never touches the socket).

        Returns ("head", head_tuple) on a complete head (consumed),
        ("more", None) when more bytes are needed, or ("fallback",
        None) when the Python parser must take this request (malformed
        / oversized head; bytes stay buffered)."""
        if not self.buffered:
            return ("more", None)
        self._ensure()
        self._compact()
        n = native_lib.mtpu_http_head(self._arr, self._end,
                                      self._out, MAX_HEADERS)
        if n > 0:
            return ("head", self._build_head(int(n)))
        if n != _INCOMPLETE:
            return ("fallback", None)
        if self._end == self._cap:
            return ("fallback", None)      # head larger than the buffer
        return ("more", None)


class _Fallback(Exception):
    """Native framer declined this request; run the Python parser."""


def send_gathered(sock: socket.socket, bufs) -> int:
    """writev-style send of several buffers in as few syscalls as the
    kernel allows; returns bytes sent. Raises on a dead peer like
    sendall. Pooled memoryviews go straight to the socket — no joins."""
    bufs = [b if isinstance(b, memoryview) else memoryview(b)
            for b in bufs if len(b)]
    total = sum(len(b) for b in bufs)
    if not bufs:
        return 0
    done = 0
    try:
        sent = sock.sendmsg(bufs)
        done = sent
        while done < total:
            skip = sent              # last call's progress within bufs
            rest = []
            for b in bufs:
                if skip >= len(b):
                    skip -= len(b)
                    continue
                rest.append(b[skip:] if skip else b)
                skip = 0
            bufs = rest
            sent = sock.sendmsg(bufs)
            done += sent
    except Exception as e:           # noqa: BLE001 - annotate progress
        # Callers deciding between "send a clean error response" and
        # "cut the connection" need to know whether ANY bytes hit the
        # wire before this raise (a resume sendmsg can fail after a
        # partial first call).
        e.mtpu_sent = done
        raise
    return total


def send_nb(sock: socket.socket, bufs) -> tuple[int, list]:
    """EAGAIN-aware gathered send on a NON-blocking socket: sendmsg
    until done or the kernel buffer fills. Returns (bytes_sent,
    remaining_views) — remaining empty when everything went out. Raises
    (with .mtpu_sent progress) on a dead peer, like send_gathered."""
    bufs = [b if isinstance(b, memoryview) else memoryview(b)
            for b in bufs if len(b)]
    done = 0
    try:
        while bufs:
            try:
                sent = sock.sendmsg(bufs)
            except (BlockingIOError, InterruptedError):
                return done, bufs
            done += sent
            skip = sent
            rest = []
            for b in bufs:
                if skip >= len(b):
                    skip -= len(b)
                    continue
                rest.append(b[skip:] if skip else b)
                skip = 0
            bufs = rest
    except Exception as e:           # noqa: BLE001 - annotate progress
        e.mtpu_sent = done
        raise
    return done, []
