"""S3 HTTP front-end: router + handlers over the object layer.

The analogue of the reference's api-router + object/bucket handlers
(cmd/api-router.go:253, cmd/object-handlers.go, cmd/bucket-handlers.go):
SigV4-authenticated REST mapping onto the ObjectLayer-equivalent
(ErasureSet / server pools). Stdlib threading HTTP server — one OS
thread per request, the Python shape of the reference's
goroutine-per-request model.
"""

from __future__ import annotations

import datetime
import email.utils
import hashlib
import time as _time_mod
import os
import queue as _queue_mod
import socket as socket_mod
import threading
import urllib.parse
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from minio_tpu.object.types import (DeleteOptions, GetOptions, InvalidArgument,
                                    ObjectNotFound, PutOptions)
from minio_tpu.s3 import hotloop, sigv4
from minio_tpu.s3.admission import AdmissionController, AdmissionShed
from minio_tpu.s3.admission import class_for as admission_class_for
from minio_tpu.s3.admission import path_class as admission_path_class
from minio_tpu.s3.errors import S3Error, from_exception
from minio_tpu.utils import deadline as deadline_mod
from minio_tpu.utils import tracing as tracing_mod
from minio_tpu.s3.metrics import Metrics, layer_sets as _layer_sets, \
    node_info, probe_disks as _probe_disks
from minio_tpu.utils.streams import (HashingReader, HttpChunkedReader,
                                     LimitedReader, Payload)

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
MAX_OBJECT_SIZE = 5 * (1 << 40)


def _rfc1123(ns: int) -> str:
    return email.utils.formatdate(ns / 1e9, usegmt=True)


def _iso8601(ns: int) -> str:
    return datetime.datetime.fromtimestamp(
        ns / 1e9, tz=datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


def _xml(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)


def _el(parent, tag, text=None):
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = str(text)
    return e


# Sentinel: a bucket policy exists on disk but cannot be compiled; the
# authorizer fails closed on it (distinct from None = no policy).
_BAD_POLICY = object()


class Credentials:
    """Root credentials + optional IAM store behind one resolver.

    With an IAMSys attached, non-root access keys resolve through the
    store (users and service accounts) and per-request authorization
    runs against their policies; without one, only root exists."""

    def __init__(self, access_key: str = "", secret_key: str = "",
                 iam=None):
        self.access_key = access_key or os.environ.get(
            "MTPU_ROOT_USER", "minioadmin")
        self.secret_key = secret_key or os.environ.get(
            "MTPU_ROOT_PASSWORD", "minioadmin")
        self.iam = iam

    def secret_for(self, access_key: str):
        if access_key == self.access_key:
            return self.secret_key
        if self.iam is not None:
            return self.iam.secret_for(access_key)
        return None

    def is_allowed(self, access_key: str, action: str, resource: str) -> bool:
        if access_key == self.access_key:
            return True
        if self.iam is not None:
            return self.iam.is_allowed(access_key, action, resource)
        return False

    def decide(self, access_key: str, action: str, resource: str,
               context=None):
        """Tri-state identity decision; without an IAM store every
        non-root signed identity is unknown -> None (not Deny), so a
        bucket policy may still grant it."""
        if access_key == self.access_key:
            return "Allow"
        if self.iam is not None:
            return self.iam.decide(access_key, action, resource, context)
        return None


class _ReusePortHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that joins an SO_REUSEPORT group: every
    pre-forked worker (io/workers.py) binds the same (host, port) and
    the kernel spreads accepted connections across their independent
    accept queues — no proxy hop, no shared listener lock."""

    def server_bind(self):
        self.socket.setsockopt(socket_mod.SOL_SOCKET,
                               socket_mod.SO_REUSEPORT, 1)
        super().server_bind()


class S3Server:
    def __init__(self, object_layer, address: str = "127.0.0.1:9000",
                 credentials: Credentials | None = None,
                 reuse_port: bool | None = None):
        self.object_layer = object_layer
        self.credentials = credentials or Credentials()
        # Hot-object read tier (object/hotcache.py): frequency-admitted
        # whole-object RAM cache. Hits are served straight off the epoll
        # loop (the handler class exports loop_hot_probe below) or from
        # the handler GET path; invalidation rides the metacache bump /
        # coherence funnel the layer already maintains. MTPU_HOT_CACHE=off
        # disables it wholesale.
        from minio_tpu.object.hotcache import HotObjectCache
        self.hot_cache = HotObjectCache()
        self.hot_cache.attach_layer(object_layer)
        host, _, port = address.rpartition(":")
        handler = _make_handler(self)
        if reuse_port is None:
            reuse_port = os.environ.get("MTPU_REUSE_PORT", "") \
                in ("1", "on", "true")
        # Event-loop connection plane (s3/eventloop.py): epoll accept/
        # dispatch, idle connections parked fd-cheap, bounded executor.
        # MTPU_HTTP_EVENTLOOP=off reverts wholesale to thread-per-
        # connection (and non-Linux platforms take it automatically).
        from minio_tpu.s3 import eventloop as eventloop_mod
        if eventloop_mod.loop_enabled():
            self.httpd = eventloop_mod.EventLoopServer(
                (host or "127.0.0.1", int(port)), handler,
                reuse_port=reuse_port,
                keepalive_s=handler.loop_keepalive_s)
        else:
            server_cls = _ReusePortHTTPServer if reuse_port \
                else ThreadingHTTPServer
            self.httpd = server_cls((host or "127.0.0.1", int(port)),
                                    handler)
        self.httpd.daemon_threads = True
        # Pre-forked worker identity (io/workers.py attaches these;
        # single-process mode is worker 0 of 1). cluster_stats, when
        # set, answers every worker's control-plane snapshot so
        # metrics/admin info aggregate across the fleet.
        self.worker_id = 0
        self.worker_total = 1
        self.cluster_stats = None
        # Self-declared node identity (distributed boot sets it; empty
        # on single-node deployments). Labels cluster-merged telemetry.
        self.node_id = ""
        # Fleet-wide trace subscription hub (io/workers.WorkerContext);
        # None = single-process mode, admin trace subscribes locally.
        self.cluster_trace = None
        self._thread: threading.Thread | None = None
        # Serializes read-modify-write of bucket metadata (policy /
        # tagging / versioning toggles) within this process; cross-node
        # serialization would ride the dsync namespace lock.
        self.bucket_meta_lock = threading.Lock()
        self.metrics = Metrics()
        # Continuous SLO engine (utils/slo.py): declared objectives
        # evaluated against the rolling windows above; None when
        # MTPU_SLO=off.
        from minio_tpu.utils.slo import SLOEngine
        self.slo = SLOEngine.from_env()
        if self.slo is not None:
            self.slo.start(metrics=self.metrics)
        # Admission control: bounded in-flight requests with per-class
        # gates and the per-request deadline budget
        # (MTPU_API_REQUESTS_MAX / _DEADLINE / _TIMEOUT; s3/admission.py).
        self.admission = AdmissionController.from_env()
        # Admin-triggered heal sweeps run in this background slot.
        self.heal_status: dict = {"state": "idle"}
        self._heal_thread: threading.Thread | None = None
        self._heal_lock = threading.Lock()
        # Drive lifecycle manager (object/drive_heal.DriveHealManager):
        # hot-replacement detection + checkpointed bulk heals. Wired by
        # minio_tpu.server boot; None = feature idle (tests, bare sets).
        self.drive_heal = None
        # Event notifier (events.EventNotifier); None = no targets.
        self.notifier = None
        # KMS for SSE-S3 (None until configured via MTPU_KMS_SECRET_KEY).
        from minio_tpu.crypto.kms import KMS
        self.kms = KMS.from_env()
        # Live request tracing + optional audit webhook. Background
        # spans (scanner/heal) and slow-op records publish through the
        # module hook straight into this broadcaster.
        from minio_tpu.s3.trace import TraceBroadcaster
        self.tracer = TraceBroadcaster()
        tracing_mod.set_publisher(self.tracer.publish)
        self.audit = None
        # Async bucket replication engine (replication.ReplicationEngine).
        self.replicator = None
        # Transparent compression for eligible content (off by default;
        # --compression enables).
        self.compression = False
        # Peer control plane fan-out: callable(kind, bucket="") set by
        # the distributed boot (grid.peers.PeerNotifier.broadcast);
        # None on single-node deployments.
        self.peer_notify = None
        # Warm-tier registry (object/tier.TierRegistry), created on
        # first admin use or at boot.
        self.tiers = None
        # OpenID validator for AssumeRoleWithWebIdentity; built lazily
        # from the config subsystem, reset on config change.
        self.oidc = None
        # Admin profiling (s3/profiling.py); peer grid clients are set
        # by the distributed boot so bundles cover every node.
        from minio_tpu.s3.profiling import Profiler
        self.profiler = Profiler()
        self.profile_peers = []            # [(name, grid client)]
        # Batch-job manager (object/batch.BatchJobs), ditto.
        self.batch = None
        # Site replicator (replication/site.SiteReplicator); None until
        # sites are registered.
        self.site = None
        # In-flight request count (stop() drains to zero before
        # closing the layer). Guarded: bare += across handler threads
        # can lose updates and either close the layer under a live
        # request or burn the full drain deadline.
        self._inflight = 0
        self._inflight_mu = threading.Lock()
        # Bucket-quota usage cache: bucket -> [stamp, bytes]. Seeded by
        # a live walk (TTL'd), advanced by committed writes so quota
        # enforcement reacts between scanner cycles (reference:
        # cmd/bucket-quota.go enforces from the data-usage cache).
        self.scanner = None
        self._quota_usage: dict = {}
        self._quota_mu = threading.Lock()

    @property
    def address(self) -> str:
        h, p = self.httpd.server_address[:2]
        return f"{h}:{p}"

    def eventloop_stats(self):
        """Connection-plane snapshot of the epoll front end, or None
        under the thread-per-connection path (metrics/admin surface)."""
        stats = getattr(self.httpd, "stats", None)
        return stats() if stats is not None else None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        # Drain in-flight requests before tearing down anything they
        # use (shutdown() only stops the accept loop; an accepted large
        # PUT must finish cleanly, not 500 on a closed executor).
        # Counted explicitly: socketserver does NOT track daemon
        # handler threads (_Threads.append returns early for them).
        deadline = _time_mod.monotonic() + 10
        while self._inflight > 0 and _time_mod.monotonic() < deadline:
            _time_mod.sleep(0.05)
        # Workers that consume the object layer stop BEFORE the layer
        # closes — a replication/notification worker mid-delivery must
        # not hit a shut-down executor (and their threads must not
        # outlive the server: the leak harness counts them).
        if self.slo is not None:
            self.slo.stop()
        if self.site is not None:
            self.site.stop()
        if self.replicator is not None:
            self.replicator.stop()
        if self.notifier is not None:
            stop = getattr(self.notifier, "stop", None)
            if stop is not None:
                stop()
        if self.batch is not None:
            self.batch.shutdown()
        close = getattr(self.object_layer, "close", None)
        if close is not None:
            close()


def _keepalive_seconds():
    """MTPU_HTTP_KEEPALIVE_S: idle keep-alive deadline, shared by the
    thread path (settimeout around the head parse) and the event
    loop's parked-connection reaper. None = no idle timeout."""
    try:
        keepalive_s = float(
            os.environ.get("MTPU_HTTP_KEEPALIVE_S", "") or 75.0)
    except ValueError:
        keepalive_s = 75.0
    if keepalive_s <= 0:
        # <= 0 means "no idle timeout" — settimeout(0) would flip the
        # socket non-blocking and drop every slow-arriving head.
        return None
    return keepalive_s


def _make_handler(server: S3Server):
    # Native serve hot loop (s3/hotloop.py): request heads framed
    # GIL-free out of a pooled per-connection recv buffer, kept hot
    # across keep-alive requests. MTPU_HTTP_NATIVE=off (or a missing
    # native lib) keeps the stock BaseHTTPRequestHandler parse path.
    native_lib = hotloop.lib() if hotloop.native_enabled() else None
    keepalive_s = _keepalive_seconds()
    from minio_tpu.object import hotcache as hotcache_mod

    # Hot-cache short circuit (object/hotcache.py), run ON the event
    # loop thread before dispatch: a plain signed whole-object GET whose
    # object is resident in the hot read tier is answered from the
    # entry's captured header template (Date re-spliced) + pinned body —
    # no executor thread, no object-layer call, no erasure fan-out, no
    # journal read. Anything the probe declines dispatches to the full
    # handler unchanged, so declined requests are byte-identical to a
    # cache-off server. Admission gates are deliberately bypassed: a hit
    # is a RAM copy on the loop thread with none of the drive/CPU
    # fan-out the per-class admission slots exist to bound.
    _HOT_DECLINE = ("transfer-encoding", "expect",
                    "range", "if-match", "if-none-match",
                    "if-modified-since", "if-unmodified-since",
                    "x-amz-checksum-mode", "x-amz-security-token",
                    "x-amz-server-side-encryption-customer-algorithm",
                    "x-amz-server-side-encryption-customer-key")

    def _hot_probe(handler, head):
        """(bufs, close_connection) for a servable hot GET, else None.

        Only the root credential short-circuits: root bypasses policy
        evaluation legitimately (see _authorize); any other identity
        needs the bucket/IAM policy walk, so the full handler runs.
        Auth failures also decline — the handler then produces the
        exact error a cache-off server would."""
        hc = server.hot_cache
        if hc is None or not hc.enabled:
            return None
        d, method, target, version, http11 = head
        if method != "GET" or "?" in target:
            return None
        if "authorization" not in d:
            return None
        # A GET carrying a body would desynchronize the framed stream
        # (we never read bodies here); an explicit zero length is fine.
        if d.get("content-length", "0").strip() not in ("", "0"):
            return None
        for hk in _HOT_DECLINE:
            if hk in d:
                return None
        t0 = _time_mod.perf_counter()
        parts = urllib.parse.unquote(target).lstrip("/").split("/", 1)
        if len(parts) < 2 or not parts[0] or not parts[1]:
            return None
        bucket, key = parts[0], parts[1]
        entry = hc.get(bucket, key)
        if entry is None or entry.head_prefix is None:
            return None
        try:
            auth = sigv4.verify_request("GET", target, {}, d,
                                        server.credentials.secret_for)
        except Exception:  # noqa: BLE001 - any auth failure: full handler
            return None
        if auth.anonymous or auth.credential is None \
                or auth.credential.access_key \
                != server.credentials.access_key:
            return None
        body = entry.body
        bufs = [entry.head_prefix, hotcache_mod.date_bytes(),
                entry.head_suffix, body]
        conntype = d.get("connection", "").lower()
        if conntype == "close":
            close = True
        elif http11:
            close = False
        else:
            close = conntype != "keep-alive"
        # The loop path never enters _route: replicate its per-request
        # accounting (metrics, path split, keep-alive reuse, trace and
        # audit) so hot hits are observable like every other response.
        handler._count_request()
        dt = _time_mod.perf_counter() - t0
        server.metrics.record("GET:object", 200, dt, rx=0, tx=len(body))
        server.metrics.response_path("hotcache")
        if server.tracer.active or server.audit is not None:
            from minio_tpu.s3.trace import make_entry
            te = make_entry(
                "GET:object", "GET", target, bucket, key, 200, dt,
                handler.client_address[0] if handler.client_address
                else "", auth.credential.access_key, rx=0, tx=len(body))
            te["worker"] = server.worker_id
            server.tracer.publish(te)
            if server.audit is not None:
                server.audit.submit(te)
        return bufs, close

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "MinIO-TPU"
        # Event-loop dispatcher hooks (s3/eventloop.py): the loop frames
        # heads with the same native lib and enforces the same idle
        # deadline the thread path applies via settimeout.
        loop_native_lib = native_lib
        loop_keepalive_s = keepalive_s
        loop_hot_probe = staticmethod(_hot_probe)

        # -- plumbing ---------------------------------------------------

        def log_message(self, fmt, *args):  # quiet; tracing subsystem logs
            pass

        def setup(self):
            super().setup()
            self._requests_served = 0
            self._h_lower = None
            self._conn = None
            self._body_reader = None
            self._defer_head = False
            self._deferred_head = None
            # Per-response override for the response-path counter
            # ("hotcache" when _get_object served from the hot tier);
            # None = the transport default (pooled/legacy/sendfile).
            self._path_kind = None
            # Set by the event-loop dispatcher (s3/eventloop.py _Conn);
            # None under the thread-per-connection front end.
            self._loop_conn = None
            if native_lib is not None:
                # The pooled ConnReader replaces the per-connection
                # BufferedReader for EVERY parser (the Python fallback
                # reads lines from the same buffer), so fast path and
                # fallback see one byte stream.
                try:
                    conn = hotloop.ConnReader(self.connection)
                except Exception:  # noqa: BLE001 - pool/alloc failure
                    conn = None
                if conn is not None:
                    try:
                        self.rfile.close()
                    except OSError:
                        pass
                    self.rfile = conn
                    self._conn = conn
            server.metrics.conn_open()

        def finish(self):
            try:
                super().finish()
            finally:
                if self._conn is not None:
                    self._conn.close()
                server.metrics.conn_close()

        def handle_one_request(self):
            """Native fast path: frame the head out of the connection
            buffer in one GIL-free scan; dispatch straight to do_*.
            Anything the framer rejects is re-parsed by the stock
            Python path from the SAME buffered bytes (counted)."""
            self._h_lower = None
            conn = self._conn
            if conn is None:
                return self._stock_request()
            try:
                # Idle keep-alive connections time out between requests
                # (stock behavior blocks forever); mid-head timeouts
                # close too — the deadline budget governs the rest of
                # the request, not the socket.
                self.connection.settimeout(keepalive_s)
                try:
                    head = conn.parse_head(native_lib)
                finally:
                    self.connection.settimeout(None)
            except hotloop._Fallback:
                server.metrics.parse_fallback()
                return self._stock_request()
            except (socket_mod.timeout, ConnectionError):
                self.close_connection = True
                return
            except OSError:
                self.close_connection = True
                return
            if head is None:                  # clean close between requests
                self.close_connection = True
                return
            self._dispatch_head(head)

        def _dispatch_head(self, head):
            """Serve ONE natively-framed request head: shared by the
            thread path above and the event-loop dispatcher
            (s3/eventloop.py), which frames heads on the loop and hands
            them here on an executor thread."""
            self._h_lower = None
            d, method, target, version, http11 = head
            self.command = method
            self.path = target
            self.request_version = version
            self.requestline = f"{method} {target} {version}"
            self.headers = hotloop.FastHeaders(d)
            conntype = d.get("connection", "").lower()
            if conntype == "close":
                self.close_connection = True
            elif http11:
                self.close_connection = False
            else:
                self.close_connection = conntype != "keep-alive"
            if http11 and d.get("expect", "").lower() == "100-continue":
                self.wfile.write(b"HTTP/1.1 100 Continue\r\n\r\n")
            self._count_request()
            mname = "do_" + method
            if not hasattr(self, mname):
                self.send_error(501, f"Unsupported method ({method!r})")
                return
            try:
                getattr(self, mname)()
                self.wfile.flush()
            except (socket_mod.timeout, ConnectionError):
                self.close_connection = True

        def _count_request(self):
            self._requests_served += 1
            if self._requests_served > 1:
                server.metrics.keepalive_reuse()

        def _stock_request(self):
            """Stock parse path (MTPU_HTTP_NATIVE=off or native-framer
            fallback) with the same connection accounting as the fast
            path: a non-empty request line means the connection served
            one more request, so keepalive_reuses_total stays truthful
            with the native framer disabled."""
            self.raw_requestline = b""
            rv = super().handle_one_request()
            if getattr(self, "raw_requestline", b""):
                self._count_request()
            return rv

        def flush_headers(self):
            """Deferred-head hook for gathered writes: while
            _defer_head is set the formatted header block is stashed so
            the response path can sendmsg it WITH the first body bytes
            in one syscall instead of a separate write."""
            buf = b"".join(getattr(self, "_headers_buffer", []))
            self._headers_buffer = []
            if self._defer_head:
                self._deferred_head = buf
                self._defer_head = False
            else:
                self.wfile.write(buf)

        def _take_head(self) -> bytes:
            head, self._deferred_head = self._deferred_head, None
            self._defer_head = False
            return head or b""

        def _send_bufs(self, bufs, final: bool = False) -> None:
            """Gathered zero-copy write: one sendmsg for head + body
            views (pooled GET windows go to the wire as memoryviews,
            no Python-level joins). Falls back to wfile on platforms
            without sendmsg.

            `final` marks a response's LAST write: under the event loop
            a full socket buffer then hands the remainder to the loop's
            EPOLLOUT drain (the executor thread goes back to the pool
            instead of blocking on a slow reader); it also stamps the
            per-response path-split counter exactly once."""
            lc = self._loop_conn
            if final and lc is not None:
                self.server.offload_final(lc, bufs)
                server.metrics.response_path(self._path_kind or "pooled")
                return
            try:
                hotloop.send_gathered(self.connection, bufs)
                if final:
                    server.metrics.response_path(self._path_kind
                                                 or "pooled")
            except (AttributeError, NotImplementedError):
                sent = 0
                try:
                    for b in bufs:
                        if len(b):
                            self.wfile.write(b)
                            sent += len(b)
                    if final:
                        server.metrics.response_path(self._path_kind
                                                     or "legacy")
                except Exception as e:  # noqa: BLE001 - annotate progress
                    e.mtpu_sent = sent
                    raise

        def _sendfile_body(self, head: bytes, fd: int, offset: int,
                           length: int) -> None:
            """Whole-object zero-copy GET body: the header block goes
            out via the gathered write, then the body moves file->socket
            entirely in-kernel (os.sendfile) — no userspace byte, no
            pooled window. Blocking-socket context only (the event
            loop's executor and the thread path both hold the socket
            blocking while a handler runs); the caller's finally owns
            the fd."""
            sent = 0
            try:
                # Span the in-kernel copy so the short-circuit shows up
                # in internal traces and the slow-op log like every
                # other response path (it never touches the pooled
                # windows the engine spans cover).
                with tracing_mod.span("http", "sendfile",
                                      {"bytes": length}) \
                        if tracing_mod.ACTIVE else tracing_mod.NOOP:
                    self._send_bufs([head])
                    sfd = self.connection.fileno()
                    while sent < length:
                        n = os.sendfile(sfd, fd, offset + sent,
                                        min(length - sent, 1 << 24))
                        if n == 0:      # truncated source: cut short
                            break
                        sent += n
                self._sent_bytes = getattr(self, "_sent_bytes", 0) + sent
            except OSError:
                # Headers (a 200) may already be on the wire: all we
                # can do is cut the connection so the client sees a
                # truncated transfer, never a silently short body.
                sent = -1
            if sent == length:
                server.metrics.response_path("sendfile")
            else:
                self.close_connection = True

        def _headers_lower(self) -> dict[str, str]:
            h = self.headers
            d = getattr(h, "d", None)      # FastHeaders: already lowercase
            if d is not None:
                return d
            if self._h_lower is None:
                low: dict[str, str] = {}
                for k, v in h.items():
                    k = k.lower()
                    # Repeats fold with a comma, matching both the
                    # native framer and SigV4 canonicalization — the
                    # two parse paths must verify identically.
                    low[k] = low[k] + "," + v if k in low else v
                self._h_lower = low
            return self._h_lower

        def _parse(self):
            parsed = urllib.parse.urlsplit(self.path)
            raw_path = parsed.path          # still percent-encoded: signed
            path = urllib.parse.unquote(raw_path)
            query = urllib.parse.parse_qs(parsed.query,
                                          keep_blank_values=True)
            parts = path.lstrip("/").split("/", 1)
            bucket = parts[0] if parts[0] else ""
            key = parts[1] if len(parts) > 1 else ""
            return raw_path, query, bucket, key

        def _read_body(self) -> bytes:
            te = self._headers_lower().get("transfer-encoding", "")
            if "chunked" in te.lower():
                out = bytearray()
                while True:
                    line = self.rfile.readline().strip()
                    try:
                        size = int(line.split(b";")[0], 16)
                    except ValueError:
                        raise S3Error("IncompleteBody") from None
                    if size == 0:
                        self.rfile.readline()
                        break
                    if len(out) + size > MAX_OBJECT_SIZE:
                        raise S3Error("EntityTooLarge")
                    out += self.rfile.read(size)
                    self.rfile.readline()
                return bytes(out)
            length = int(self.headers.get("Content-Length") or 0)
            if length > MAX_OBJECT_SIZE:
                raise S3Error("EntityTooLarge")
            return self.rfile.read(length) if length else b""

        def _auth(self, method, path, query) -> sigv4.ParsedAuth:
            return sigv4.verify_request(
                method, path, query, self._headers_lower(),
                server.credentials.secret_for)

        def _auth_context(self, access_key: str, query: dict,
                          h: dict) -> dict:
            """Condition-key context for policy evaluation (reference:
            cmd/auth-handler.go getConditionValues). Keys are stored
            lowercase; Statement.conditions_met folds case."""
            ctx = {
                "aws:sourceip": self.client_address[0]
                if self.client_address else "",
                "aws:securetransport": "false",
                "aws:useragent": h.get("user-agent", ""),
                "aws:referer": h.get("referer", ""),
                "aws:username": access_key,
                "aws:userid": access_key,
            }
            for qk in ("prefix", "delimiter", "max-keys", "versionId"):
                v = query.get(qk, [""])[0]
                if v:
                    ctx[f"s3:{qk.lower()}"] = v
            for hk, hv in h.items():
                if hk.startswith("x-amz-"):
                    ctx[f"s3:{hk}"] = hv
            return ctx

        def _bucket_policy(self, bucket: str):
            """Compiled bucket policy, None when absent, or _BAD_POLICY
            when a stored document fails to compile — the caller must
            fail CLOSED on that (returning None would silently drop the
            document's Deny statements)."""
            if not bucket or bucket == "*":
                return None
            import json as _json
            try:
                stored = server.object_layer.get_bucket_meta(bucket).get(
                    "config:policy")
            except Exception:  # noqa: BLE001 - bucket missing / offline
                return None
            if not stored:
                return None
            try:
                from minio_tpu.iam.policy import compile_policy
                return compile_policy(_json.loads(stored))
            except Exception:  # noqa: BLE001 - legacy/corrupt document
                return _BAD_POLICY

        def _authorize(self, ak: str, anonymous: bool, action: str,
                       resource: str, ctx: dict) -> bool:
            """Merge identity and bucket-policy decisions, deny-wins
            (reference: cmd/auth-handler.go:433-449,758): root always
            passes; anonymous requires an explicit bucket-policy Allow;
            signed identities pass if either side allows and neither
            explicitly denies."""
            if ak == server.credentials.access_key:
                return True
            from minio_tpu.iam.policy import decide
            bp = self._bucket_policy(resource.split("/", 1)[0])
            if bp is _BAD_POLICY:
                # A policy exists but cannot be evaluated: every
                # non-owner request to the bucket is refused rather
                # than guessing what it said.
                return False
            bp_decision = None if bp is None else decide(
                [bp], action, resource, ctx,
                ak if not anonymous else None, require_principal=True)
            if bp_decision == "Deny":
                return False
            if anonymous:
                return bp_decision == "Allow"
            id_decision = server.credentials.decide(ak, action, resource,
                                                    ctx)
            if id_decision == "Deny":
                return False
            return id_decision == "Allow" or bp_decision == "Allow"

        def _make_payload(self, auth) -> Payload:
            """Sized streaming payload for object-data PUTs: the body is
            never materialized; content verification (sha256 or chunk
            signatures) runs incrementally and rejects before commit."""
            h = self._headers_lower()
            te = h.get("transfer-encoding", "")
            if auth.payload_hash in (sigv4.STREAMING_PAYLOAD,
                                     sigv4.STREAMING_PAYLOAD_TRAILER,
                                     sigv4.STREAMING_UNSIGNED_TRAILER):
                declared = h.get("x-amz-decoded-content-length")
                if declared is None:
                    raise S3Error("MissingContentLength")
                declared = int(declared)
                if declared > MAX_OBJECT_SIZE:
                    raise S3Error("EntityTooLarge")
                if "chunked" in te.lower():
                    # aws-chunked inside HTTP TE-chunked (SDK pattern
                    # for unknown-length streams): strip the transfer
                    # framing incrementally first.
                    raw = HttpChunkedReader(self.rfile)
                else:
                    encoded_len = int(h.get("content-length") or 0)
                    raw = LimitedReader(self.rfile, encoded_len)
                secret = server.credentials.secret_for(
                    auth.credential.access_key)
                # Native-scan pooled decoder when available (byte-
                # identical to ChunkedPayloadReader, golden-tested);
                # tracked on the handler so its recv-buffer lease
                # returns deterministically even on error paths.
                reader = sigv4.chunked_reader(
                    raw, auth, secret,
                    verify_signatures=auth.payload_hash
                    != sigv4.STREAMING_UNSIGNED_TRAILER)
                self._body_reader = reader
                return Payload(reader, declared, finish=reader.finalize)
            if "chunked" in te.lower():
                # Plain HTTP chunked TE (no declared size): buffer it —
                # rare for S3 clients; bounded by MAX_OBJECT_SIZE.
                body = self._read_body()
                if auth.payload_hash != sigv4.UNSIGNED_PAYLOAD and \
                        hashlib.sha256(body).hexdigest() != auth.payload_hash:
                    raise S3Error("XAmzContentSHA256Mismatch")
                return Payload.wrap(body)
            length = int(h.get("content-length") or 0)
            if length > MAX_OBJECT_SIZE:
                raise S3Error("EntityTooLarge")
            raw = LimitedReader(self.rfile, length)
            if auth.payload_hash == sigv4.UNSIGNED_PAYLOAD:
                return Payload(raw, length)
            hasher = HashingReader(raw)
            want = auth.payload_hash

            def fin():
                if hasher.hexdigest() != want:
                    raise S3Error("XAmzContentSHA256Mismatch")
            return Payload(hasher, length, finish=fin)

        def _send(self, status: int, body: bytes = b"",
                  headers: dict | None = None, content_type="application/xml"):
            self._defer_head = True
            self.send_response(status)
            self.send_header("x-amz-request-id", "0")
            if body or status not in (204, 304):
                self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            head = self._take_head()
            if body and self.command != "HEAD":
                self._send_bufs([head, body], final=True)
                self._sent_bytes = getattr(self, "_sent_bytes", 0) \
                    + len(body)
            else:
                self._send_bufs([head], final=True)

        # Shed-path body drain cap: reading the remnant is cheap
        # network receive (the resource being protected is CPU/disk,
        # not the NIC), but it must stay bounded — a multi-GiB upload
        # is closed on instead (SDKs retry on connection errors).
        _DRAIN_CAP = 8 << 20

        def _drain_unread_body(self) -> None:
            """Discard the request body AFTER an early error response,
            bounded by _DRAIN_CAP and a read timeout. Only safe where
            NOTHING of the body has been consumed yet (the admission
            path runs before any body read); Content-Length framing
            only — chunked bodies just close (framing-position
            unknown). The shape of Go http.Server's pre-close drain."""
            try:
                h = self._headers_lower()
                if "chunked" in h.get("transfer-encoding", "").lower():
                    return
                remaining = int(h.get("content-length") or 0)
            except ValueError:
                return
            if remaining <= 0 or remaining > self._DRAIN_CAP:
                return
            try:
                self.connection.settimeout(2.0)
                while remaining > 0:
                    chunk = self.rfile.read(min(65536, remaining))
                    if not chunk:
                        return
                    remaining -= len(chunk)
            except OSError:
                pass        # stalled/gone client; the close handles it

        def _send_error(self, e: Exception, bucket="", key=""):
            # The request body may be partially or fully unread (auth runs
            # before body consumption): close the connection rather than
            # letting keep-alive parse leftover body bytes as a request.
            self.close_connection = True
            err = from_exception(e)
            if err.code == "RequestTimeout":
                server.admission.record_deadline_exceeded()
            root = ET.Element("Error")
            _el(root, "Code", err.code)
            _el(root, "Message", err.message)
            _el(root, "BucketName", err.bucket or bucket)
            _el(root, "Key", err.key or key)
            _el(root, "Resource", self.path)
            _el(root, "RequestId", "0")
            self._send(err.status, _xml(root),
                       headers=getattr(err, "headers", None))

        # -- dispatch ---------------------------------------------------

        def send_response(self, code, message=None):
            self._last_status = code
            super().send_response(code, message)

        def _api_label(self, method, raw_path, bucket, key,
                       pc=None) -> str:
            if pc is None:
                pc = admission_path_class(raw_path)
            if pc != "s3":
                return f"{method}:{pc}"
            scope = "object" if key else ("bucket" if bucket else "service")
            return f"{method}:{scope}"

        def _route(self, method: str):
            raw_path, query, bucket, key = self._parse()
            # Classify the path ONCE per request; admission gating,
            # dispatch, and the metrics label all consume this instead
            # of re-running the pattern checks (the hot loop's
            # "admission without re-entering the router slow path").
            pc = admission_path_class(raw_path)
            self._last_status = 0
            self._sent_bytes = 0
            self._auth_key = ""
            self._path_kind = None
            t0 = _time_mod.perf_counter()
            with server._inflight_mu:
                server._inflight += 1
            gate = None
            tctx = None
            try:
                # Admission: bounded in-flight slots per request class
                # BEFORE any auth/body work — a saturated server sheds
                # with 503 + Retry-After instead of queueing unbounded
                # (reference: maxClients, cmd/generic-handlers.go).
                try:
                    gate = server.admission.enter(
                        admission_class_for(pc))
                except AdmissionShed as shed:
                    err = S3Error("SlowDown", str(shed))
                    err.headers = {"Retry-After": str(shed.retry_after)}
                    self._send_error(err, bucket, key)
                    # A shed PUT's client is mid-upload: discard its
                    # body (bounded) so it can finish sending and READ
                    # the 503 + Retry-After instead of dying on a
                    # connection reset when we close under its write.
                    self._drain_unread_body()
                    return
                # Per-request deadline budget: every layer below (fan-
                # outs, drive deadlines, grid calls) consumes from it,
                # so one hung drive bounds the request, not the stack
                # of per-layer timeouts.
                dl = None
                if server.admission.request_timeout > 0:
                    dl = deadline_mod.Deadline(
                        server.admission.request_timeout)
                # Span context: armed only while somebody watches (a
                # trace subscriber wanting internal types, a remote
                # worker relay, or a slow-op threshold) — disarmed,
                # requests pay one attribute check. It rides the same
                # binding channel the deadline budget rides.
                if tracing_mod.ACTIVE:
                    tctx = tracing_mod.TraceContext()
                with deadline_mod.bind(dl), tracing_mod.bind(tctx), \
                        server.profiler.request_profile():
                    self._route_inner(method, raw_path, query, bucket, key,
                                      pc)
            finally:
                if gate is not None:
                    gate.leave()
                reader = getattr(self, "_body_reader", None)
                if reader is not None:
                    self._body_reader = None
                    close = getattr(reader, "close", None)
                    if close is not None:
                        close()
                with server._inflight_mu:
                    server._inflight -= 1
                try:
                    rx = int(self.headers.get("Content-Length") or 0)
                except ValueError:
                    rx = 0
                dt = _time_mod.perf_counter() - t0
                api = self._api_label(method, raw_path, bucket, key, pc)
                status = self._last_status or 500
                server.metrics.record(api, status, dt,
                                      rx=rx, tx=self._sent_bytes)
                if server.slo is not None:
                    server.slo.observe(api, status)
                if server.tracer.active or server.audit is not None:
                    from minio_tpu.s3.trace import make_entry
                    entry = make_entry(
                        api, method, raw_path, bucket, key, status, dt,
                        self.client_address[0] if self.client_address
                        else "", self._auth_key, rx=rx,
                        tx=self._sent_bytes)
                    entry["worker"] = server.worker_id
                    if server.node_id:
                        entry["node"] = server.node_id
                    if tctx is not None:
                        # The request record IS the trace root: span 0,
                        # every internal span parents (transitively)
                        # under it.
                        entry["trace_type"] = "s3"
                        entry["trace"] = tctx.trace_id
                        entry["span"] = 0
                        server.tracer.publish(entry)
                        if server.tracer.wants_internal():
                            for se in tracing_mod.entries_from(
                                    tctx, worker=server.worker_id):
                                server.tracer.publish(se)
                    else:
                        server.tracer.publish(entry)
                    if server.audit is not None:
                        server.audit.submit(entry)

        def _route_inner(self, method, raw_path, query, bucket, key,
                         pc=None):
            if pc is None:
                pc = admission_path_class(raw_path)
            try:
                # Unauthenticated endpoints: health probes and metrics
                # (reference: cmd/healthcheck-handler.go is authless;
                # metrics here follow suit for scrape simplicity).
                # (path_class in s3/admission.py is the shared pattern
                # source for these operator endpoints; keep dispatch
                # and classification in lockstep.)
                if raw_path == "/minio/health/live":
                    return self._send(200)
                if raw_path == "/minio/health/ready":
                    return self._health_ready()
                if pc == "metrics":
                    # Worker mode: whichever worker the kernel handed
                    # this scrape to aggregates the whole fleet via
                    # the parent control pipe (io/workers.py).
                    peers = None
                    if server.cluster_stats is not None:
                        try:
                            peers = server.cluster_stats()
                        except Exception:  # noqa: BLE001 - serve own
                            peers = None
                    # Cluster federation: pull every peer NODE's
                    # telemetry over the grid (peer.metrics verb) so a
                    # scrape of any node reports the whole cluster
                    # with per-node labels. ?cluster=false opts out
                    # (per-node scrape configs avoiding N^2 fan-out).
                    nodes = None
                    want_cluster = (query.get("cluster", [""])[0]
                                    or "").lower() not in (
                        "false", "0", "off", "no")
                    if server.profile_peers and want_cluster:
                        nodes = self._cluster_metrics_states()
                    text = server.metrics.render(
                        object_layer=server.object_layer,
                        scanner=getattr(server.object_layer, "scanner",
                                        None),
                        server=server, peer_states=peers,
                        node_states=nodes)
                    return self._send(200, text.encode(),
                                      content_type="text/plain; "
                                      "version=0.0.4")
                ctype = self._headers_lower().get("content-type", "")
                if method == "POST" and bucket and not key \
                        and "multipart/form-data" in ctype:
                    # Browser POST-policy upload: credentials live in
                    # the form fields, not the Authorization header.
                    return self._post_object(bucket, self._read_body(),
                                             ctype)
                # Verify the signature from headers first; the declared
                # payload hash is part of the signed canonical request, so
                # the body is only hashed afterwards when the mode calls
                # for it (streaming modes verify per chunk instead). The
                # RAW request path is signed — never a re-encoding of it.
                # Requests with no credentials at all are anonymous and
                # authorized purely by bucket policy (reference:
                # cmd/auth-handler.go:433-449 authTypeAnonymous ->
                # globalPolicySys.IsAllowed).
                h = self._headers_lower()
                if "authorization" not in h \
                        and "X-Amz-Signature" not in query \
                        and "Signature" not in query:
                    auth = sigv4.anonymous_auth()
                else:
                    auth = self._auth(method, raw_path, query)
                self._auth_key = auth.credential.access_key
                # STS credentials must present their session token on
                # every request (reference: cmd/auth-handler.go's
                # getSessionToken check); permanent keys have none.
                if not auth.anonymous and \
                        server.credentials.iam is not None:
                    tok = server.credentials.iam.session_token_for(
                        auth.credential.access_key)
                    if tok is not None:
                        presented = h.get("x-amz-security-token", "") or \
                            query.get("X-Amz-Security-Token", [""])[0]
                        if presented != tok:
                            raise S3Error("AccessDenied",
                                          "invalid session token")
                if pc == "admin":
                    if auth.anonymous:
                        raise S3Error("AccessDenied")
                    return self._admin_op(method, raw_path, query, auth)
                # Per-request policy authorization (reference:
                # checkRequestAuthType -> IsAllowed): root passes, IAM
                # identities evaluate their policies merged deny-wins
                # with the bucket policy; anonymous identities need an
                # explicit bucket-policy Allow.
                ak = auth.credential.access_key
                ctx = self._auth_context(ak, query, h)
                for action, resource in _required_permissions(
                        method, bucket, key, query, h):
                    if not self._authorize(ak, auth.anonymous, action,
                                           resource, ctx):
                        raise S3Error("AccessDenied", bucket=bucket,
                                      key=key)
                body = b""
                payload = None
                # Object-data PUTs stream O(window); every other body
                # (bucket XML, multipart-complete XML, ...) is small and
                # buffered with upfront content verification.
                data_put = method == "PUT" and bool(key)
                if data_put:
                    payload = self._make_payload(auth)
                elif method in ("PUT", "POST"):
                    body = self._read_body()
                    if auth.payload_hash in (
                            sigv4.STREAMING_PAYLOAD,
                            sigv4.STREAMING_PAYLOAD_TRAILER,
                            sigv4.STREAMING_UNSIGNED_TRAILER):
                        secret = server.credentials.secret_for(
                            auth.credential.access_key)
                        body = sigv4.decode_chunked_payload(body, auth, secret)
                    elif auth.payload_hash != sigv4.UNSIGNED_PAYLOAD:
                        if hashlib.sha256(body).hexdigest() != auth.payload_hash:
                            raise S3Error("XAmzContentSHA256Mismatch")

                if not bucket:
                    if method == "GET":
                        return self._list_buckets()
                    if method == "POST":
                        # STS rides POST / with a form body (reference:
                        # cmd/sts-handlers.go router).
                        return self._sts_op(auth, body)
                    raise S3Error("MethodNotAllowed")
                try:
                    if not key:
                        return self._bucket_op(method, bucket, query, body)
                    return self._object_op(method, bucket, key, query, body,
                                           payload)
                finally:
                    # A handler that did not drain the request body (copy
                    # object, errors) leaves bytes on the socket: close
                    # rather than let keep-alive misparse them.
                    if payload is not None and payload.remaining:
                        self.close_connection = True
            except Exception as e:  # noqa: BLE001 - rendered as S3 error XML
                self._send_error(e, bucket, key)

        def do_GET(self):
            self._route("GET")

        def do_PUT(self):
            self._route("PUT")

        def do_POST(self):
            self._route("POST")

        def do_DELETE(self):
            self._route("DELETE")

        def do_HEAD(self):
            self._route("HEAD")

        # -- service / bucket ops --------------------------------------

        def _sts_op(self, auth, body: bytes):
            """POST / — STS (reference: cmd/sts-handlers.go:61-65):
            AssumeRole (any authenticated USER identity mints temporary
            credentials scoped to its own permissions, optionally
            narrowed by a session policy) and
            AssumeRoleWithWebIdentity (an OIDC JWT from a configured
            IdP mints credentials mapped from its policy claim — no
            local user needed, no SigV4 on the request)."""
            import json as _json
            form = dict(urllib.parse.parse_qsl(
                body.decode("utf-8", "replace")))
            action = form.get("Action", "")
            if action not in ("AssumeRole", "AssumeRoleWithWebIdentity"):
                raise S3Error("NotImplemented", f"STS action {action!r}")
            iam = server.credentials.iam
            if iam is None:
                raise S3Error("NotImplemented", "no IAM store")
            duration = None
            if form.get("DurationSeconds"):
                try:
                    duration = int(form["DurationSeconds"])
                except ValueError:
                    raise S3Error("InvalidArgument",
                                  "bad DurationSeconds") from None
            from minio_tpu.iam import IAMError
            from minio_tpu.iam.policy import PolicyError
            if action == "AssumeRoleWithWebIdentity":
                rec = self._sts_web_identity(iam, form, duration)
            else:
                if auth.anonymous:
                    raise S3Error("AccessDenied")
                policy = None
                if form.get("Policy"):
                    try:
                        policy = _json.loads(form["Policy"])
                    except ValueError:
                        raise S3Error("MalformedPolicy") from None
                try:
                    rec = iam.assume_role(auth.credential.access_key,
                                          duration, policy)
                except PolicyError as e:
                    raise S3Error("MalformedPolicy", str(e)) from None
                except IAMError as e:
                    raise S3Error("AccessDenied", str(e)) from None
            root = ET.Element(
                f"{action}Response",
                xmlns="https://sts.amazonaws.com/doc/2011-06-15/")
            res = _el(root, f"{action}Result")
            if action == "AssumeRoleWithWebIdentity" and rec.get("subject"):
                _el(res, "SubjectFromWebIdentityToken", rec["subject"])
            creds = _el(res, "Credentials")
            _el(creds, "AccessKeyId", rec["access_key"])
            _el(creds, "SecretAccessKey", rec["secret_key"])
            _el(creds, "SessionToken", rec["session_token"])
            _el(creds, "Expiration", _iso8601(rec["expiry_ns"]))
            self._send(200, _xml(root))

        def _sts_web_identity(self, iam, form: dict, duration):
            """Validate the WebIdentityToken against the configured
            OIDC provider and mint claim-mapped credentials."""
            from minio_tpu.iam import IAMError
            from minio_tpu.iam.oidc import OIDCError, OpenIDValidator
            token = form.get("WebIdentityToken", "")
            if not token:
                raise S3Error("InvalidArgument",
                              "WebIdentityToken is required")
            validator = server.oidc
            if validator is None:
                from minio_tpu.s3 import config as cfg_mod
                cfg = cfg_mod.load_config(server.object_layer)
                try:
                    validator = OpenIDValidator.from_config(cfg)
                except OIDCError as e:
                    raise S3Error("NotImplemented", str(e)) from None
                if validator is None:
                    raise S3Error("NotImplemented",
                                  "no OpenID provider configured")
                server.oidc = validator
            session_policy = None
            if form.get("Policy"):
                import json as _json
                try:
                    session_policy = _json.loads(form["Policy"])
                except ValueError:
                    raise S3Error("MalformedPolicy") from None
            try:
                claims = validator.validate(token)
                names = validator.policies_from(claims)
                rec = iam.assume_role_web_identity(
                    claims.get("sub", ""), names, duration,
                    session_policy)
            except OIDCError as e:
                raise S3Error("AccessDenied", str(e)) from None
            except IAMError as e:
                raise S3Error("AccessDenied", str(e)) from None
            rec["subject"] = claims.get("sub", "")
            return rec

        def _list_buckets(self):
            buckets = server.object_layer.list_buckets()
            root = ET.Element("ListAllMyBucketsResult", xmlns=XMLNS)
            owner = _el(root, "Owner")
            _el(owner, "ID", "minio-tpu")
            _el(owner, "DisplayName", "minio-tpu")
            bl = _el(root, "Buckets")
            for b in buckets:
                be = _el(bl, "Bucket")
                _el(be, "Name", b.name)
                _el(be, "CreationDate", _iso8601(b.created))
            self._send(200, _xml(root))

        # Bucket sub-configurations persisted in bucket metadata
        # (reference: cmd/bucket-metadata-sys.go keeps policy/lifecycle/
        # tagging/... documents in one quorum-replicated record):
        # meta key -> (absent-error, validator).
        # meta key -> (absent-error or None for empty-doc GET, validator).
        _BUCKET_CONFIGS = {
            "policy": ("NoSuchBucketPolicy", "_validate_policy_json"),
            "lifecycle": ("NoSuchLifecycleConfiguration",
                          "_validate_lifecycle_xml"),
            "tagging": ("NoSuchTagSet", "_validate_xml_doc"),
            "cors": ("NoSuchCORSConfiguration", "_validate_xml_doc"),
            "encryption": ("ServerSideEncryptionConfigurationNotFoundError",
                           "_validate_xml_doc"),
            "notification": (None, "_validate_notification_xml"),
            "replication": ("ReplicationConfigurationNotFoundError",
                            "_validate_replication_xml"),
        }

        def _validate_policy_json(self, body: bytes) -> None:
            import json as _json
            try:
                doc = _json.loads(body)
            except ValueError:
                raise S3Error("MalformedPolicy") from None
            if not isinstance(doc, dict) or "Statement" not in doc:
                raise S3Error("MalformedPolicy")
            # Full compile: unsupported condition operators and bad
            # principals are rejected HERE, not silently ignored at
            # evaluation time (ignoring a condition would over-grant).
            from minio_tpu.iam.policy import Policy, PolicyError
            try:
                pol = Policy.from_json(doc)
            except PolicyError as e:
                raise S3Error("MalformedPolicy", str(e)) from None
            # Bucket policies are principal-scoped by definition; a
            # statement without one is an identity-policy document
            # pasted in the wrong place (AWS rejects these too).
            if any(s.principals is None for s in pol.statements):
                raise S3Error("MalformedPolicy",
                              "bucket policy statements need a Principal")

        def _validate_xml_doc(self, body: bytes) -> None:
            try:
                ET.fromstring(body)
            except ET.ParseError:
                raise S3Error("MalformedXML") from None

        def _validate_lifecycle_xml(self, body: bytes) -> None:
            """Semantic validation, not just well-formedness: a config
            the scanner cannot evaluate must be rejected at PUT, never
            accepted and silently ignored."""
            from minio_tpu.object.lifecycle import (LifecycleError,
                                                    parse_lifecycle)
            try:
                parse_lifecycle(body)
            except LifecycleError as e:
                raise S3Error("MalformedXML", str(e)) from None

        def _validate_notification_xml(self, body: bytes) -> None:
            from minio_tpu.events import parse_notification_xml
            from minio_tpu.events.notify import EventError
            try:
                parse_notification_xml(body)
            except EventError as e:
                raise S3Error("MalformedXML", str(e)) from None

        def _validate_replication_xml(self, body: bytes) -> None:
            from minio_tpu.replication import (ReplicationError,
                                               parse_replication_xml)
            try:
                parse_replication_xml(body)
            except ReplicationError as e:
                raise S3Error("MalformedXML", str(e)) from None

        def _bucket_config(self, method, bucket, name, query, body):
            ol = server.object_layer
            ol.get_bucket_info(bucket)
            absent_err, validator = self._BUCKET_CONFIGS[name]
            meta_key = f"config:{name}"
            if method == "PUT":
                getattr(self, validator)(body)
                with server.bucket_meta_lock:
                    meta = ol.get_bucket_meta(bucket)
                    meta[meta_key] = body.decode("utf-8", "replace")
                    ol.set_bucket_meta(bucket, meta)
                self._site_enqueue("bucket-meta", bucket)
                return self._send(200)
            if method == "DELETE":
                with server.bucket_meta_lock:
                    meta = ol.get_bucket_meta(bucket)
                    if meta.pop(meta_key, None) is not None:
                        ol.set_bucket_meta(bucket, meta)
                self._site_enqueue("bucket-meta", bucket)
                return self._send(204)
            stored = ol.get_bucket_meta(bucket).get(meta_key)
            if stored is None:
                if absent_err is None:
                    # Unset notification config answers an empty
                    # document, per S3.
                    root = ET.Element("NotificationConfiguration",
                                      xmlns=XMLNS)
                    return self._send(200, _xml(root))
                raise S3Error(absent_err, bucket=bucket)
            ctype = "application/json" if name == "policy" \
                else "application/xml"
            return self._send(200, stored.encode(), content_type=ctype)

        def _notify(self, event_name, bucket, key, size=0, etag="",
                    version_id=""):
            if server.notifier is not None:
                server.notifier.notify(event_name, bucket, key, size=size,
                                       etag=etag, version_id=version_id)

        def _bucket_op(self, method, bucket, query, body):
            ol = server.object_layer
            for name in self._BUCKET_CONFIGS:
                if name in query:
                    return self._bucket_config(method, bucket, name, query,
                                               body)
            if "object-lock" in query:
                return self._object_lock_config(method, bucket, body)
            if "acl" in query:
                return self._acl(method, bucket, "", body)
            if method == "PUT":
                if "versioning" in query:
                    return self._put_versioning(bucket, body)
                _validate_bucket_name(bucket)
                ol.make_bucket(bucket)
                self._site_enqueue("bucket-make", bucket)
                if self._headers_lower().get(
                        "x-amz-bucket-object-lock-enabled", "").lower() \
                        == "true":
                    # Lock-enabled buckets are born versioned with the
                    # lock flag set atomically-enough (no objects can
                    # exist yet) — reference: cmd/bucket-handlers.go
                    # PutBucketHandler's objectLockEnabled path.
                    from minio_tpu.object import objectlock as olock
                    with server.bucket_meta_lock:
                        meta = ol.get_bucket_meta(bucket)
                        meta["versioning"] = True
                        meta[olock.BUCKET_META_KEY] = {"enabled": True}
                        ol.set_bucket_meta(bucket, meta)
                    self._site_enqueue("bucket-meta", bucket)
                return self._send(200, headers={"Location": f"/{bucket}"})
            if method == "HEAD":
                ol.get_bucket_info(bucket)
                return self._send(200)
            if method == "DELETE":
                ol.delete_bucket(bucket)
                self._site_enqueue("bucket-delete", bucket)
                return self._send(204)
            if method == "POST" and "delete" in query:
                return self._delete_objects(bucket, body)
            if method == "GET" and "uploads" in query:
                return self._list_uploads(bucket, query)
            if method == "GET":
                if "location" in query:
                    root = ET.Element("LocationConstraint", xmlns=XMLNS)
                    return self._send(200, _xml(root))
                if "versioning" in query:
                    return self._get_versioning(bucket)
                if "versions" in query:
                    return self._list_versions(bucket, query)
                return self._list_objects(bucket, query)
            raise S3Error("MethodNotAllowed")

        def _lock_config(self, bucket) -> dict:
            """The bucket's object-lock config ({} when lock-less).
            Read failures PROPAGATE: returning {} on a transient error
            would fail every lock check open (new versions without
            default retention, versioning suspendable mid-outage)."""
            from minio_tpu.object import objectlock as olock
            return server.object_layer.get_bucket_meta(bucket).get(
                olock.BUCKET_META_KEY) or {}

        def _object_attributes(self, bucket, key, query):
            """GET ?attributes — GetObjectAttributes (reference:
            cmd/object-handlers.go GetObjectAttributesHandler): the
            caller names the attributes it wants in
            x-amz-object-attributes."""
            h = self._headers_lower()
            wanted = {w.strip() for w in
                      h.get("x-amz-object-attributes", "").split(",")
                      if w.strip()}
            if not wanted:
                raise S3Error("InvalidArgument",
                              "x-amz-object-attributes is required")
            vid = query.get("versionId", [""])[0]
            info = server.object_layer.get_object_info(
                bucket, key, GetOptions(version_id=vid))
            root = ET.Element("GetObjectAttributesOutput", xmlns=XMLNS)
            if "ETag" in wanted:
                _el(root, "ETag", info.etag)
            if "Checksum" in wanted:
                from minio_tpu.s3 import checksum as ck
                stored = ck.response_headers(info.internal_metadata)
                if stored:
                    ce = _el(root, "Checksum")
                    for hname, v in stored.items():
                        algo = hname[len(ck.H_PREFIX):]
                        _el(ce, f"Checksum{algo.upper()}", v)
            if "ObjectParts" in wanted and info.parts and \
                    len(info.parts) > 1:
                pe = _el(root, "ObjectParts")
                _el(pe, "TotalPartsCount", len(info.parts))
                _el(pe, "IsTruncated", "false")
                for p in info.parts:
                    part = _el(pe, "Part")
                    _el(part, "PartNumber", p.number)
                    _el(part, "Size", p.actual_size)
            if "StorageClass" in wanted:
                _el(root, "StorageClass", info.storage_class or "STANDARD")
            if "ObjectSize" in wanted:
                _el(root, "ObjectSize", info.size)
            headers = {"Last-Modified": _rfc1123(info.mod_time)}
            if info.version_id:
                headers["x-amz-version-id"] = info.version_id
            return self._send(200, _xml(root), headers=headers)

        def _acl(self, method, bucket, key, body):
            """GET/PUT ?acl — the MinIO-parity ACL surface (reference:
            cmd/acl-handlers.go): ACLs are a legacy AWS mechanism; only
            'private' exists, GET always answers the owner's
            FULL_CONTROL, and any attempt to grant something else is
            refused (policies are the real authorization surface)."""
            if not key:
                server.object_layer.get_bucket_info(bucket)
            if method == "GET":
                root = ET.Element("AccessControlPolicy", xmlns=XMLNS)
                owner = _el(root, "Owner")
                _el(owner, "ID", "minio-tpu")
                _el(owner, "DisplayName", "minio-tpu")
                grants = _el(root, "AccessControlList")
                g = _el(grants, "Grant")
                grantee = _el(g, "Grantee")
                grantee.set("xmlns:xsi",
                            "http://www.w3.org/2001/XMLSchema-instance")
                grantee.set("xsi:type", "CanonicalUser")
                _el(grantee, "ID", "minio-tpu")
                _el(g, "Permission", "FULL_CONTROL")
                return self._send(200, _xml(root))
            if method != "PUT":
                raise S3Error("MethodNotAllowed")
            h = self._headers_lower()
            canned = h.get("x-amz-acl", "")
            if canned and canned != "private":
                raise S3Error("NotImplemented",
                              "only the 'private' canned ACL exists; "
                              "use bucket policies")
            if body:
                try:
                    root = ET.fromstring(body)
                except ET.ParseError:
                    raise S3Error("MalformedACLError") from None
                perms = [e.text for e in root.iter()
                         if e.tag.endswith("Permission")]
                if any(p != "FULL_CONTROL" for p in perms):
                    raise S3Error("NotImplemented",
                                  "only FULL_CONTROL grants exist; use "
                                  "bucket policies")
            return self._send(200)

        def _object_lock_config(self, method, bucket, body):
            """GET/PUT ?object-lock (reference: cmd/bucket-handlers.go
            GetBucketObjectLockConfigHandler /
            PutBucketObjectLockConfigHandler)."""
            from minio_tpu.object import objectlock as olock
            ol = server.object_layer
            ol.get_bucket_info(bucket)
            if method == "GET":
                cfg = self._lock_config(bucket)
                if not cfg.get("enabled"):
                    raise S3Error("ObjectLockConfigurationNotFoundError",
                                  bucket=bucket)
                return self._send(200, olock.lock_config_xml(cfg))
            if method != "PUT":
                raise S3Error("MethodNotAllowed")
            try:
                cfg = olock.parse_lock_config_xml(body)
            except olock.ObjectLockError as e:
                raise S3Error(e.code, str(e)) from None
            with server.bucket_meta_lock:
                meta = ol.get_bucket_meta(bucket)
                # Enabling lock on an existing bucket requires (and
                # then pins) versioning.
                meta["versioning"] = True
                meta[olock.BUCKET_META_KEY] = cfg
                ol.set_bucket_meta(bucket, meta)
            self._site_enqueue("bucket-meta", bucket)
            return self._send(200)

        def _list_versions(self, bucket, query):
            """GET ?versions — ListObjectVersions (reference:
            cmd/bucket-listobjects-handlers.go ListObjectVersionsHandler).

            A version-id-marker resumes WITHIN the marker key: its
            remaining (older) versions are emitted first, then the
            listing continues past the key."""
            def q(name, default=""):
                return query.get(name, [default])[0]
            prefix = q("prefix")
            delimiter = q("delimiter")
            key_marker = q("key-marker")
            vid_marker = q("version-id-marker")
            max_keys = int(q("max-keys", "1000") or 1000)
            entries = []
            if key_marker and vid_marker:
                from minio_tpu.object.erasure_object import ErasureSet
                try:
                    versions = server.object_layer.list_versions_all(
                        bucket, key_marker)
                except Exception:  # noqa: BLE001 - marker key deleted
                    versions = []
                emit = False
                for v in versions:           # latest-first journal order
                    if emit:
                        entries.append(ErasureSet._to_object_info(
                            bucket, key_marker, v))
                    elif (v.version_id or "null") == vid_marker:
                        emit = True
            info = server.object_layer.list_objects(
                bucket, prefix=prefix, marker=key_marker,
                delimiter=delimiter, max_keys=max_keys,
                include_versions=True)
            combined = entries + info.objects
            truncated = info.is_truncated
            if len(combined) > max_keys:
                combined = combined[:max_keys]
                truncated = True
            root = ET.Element("ListVersionsResult", xmlns=XMLNS)
            _el(root, "Name", bucket)
            _el(root, "Prefix", prefix)
            _el(root, "KeyMarker", key_marker)
            if vid_marker:
                _el(root, "VersionIdMarker", vid_marker)
            _el(root, "MaxKeys", max_keys)
            _el(root, "IsTruncated", "true" if truncated else "false")
            if truncated and combined:
                _el(root, "NextKeyMarker", combined[-1].name)
                _el(root, "NextVersionIdMarker",
                    combined[-1].version_id or "null")
            for o in combined:
                tag = "DeleteMarker" if o.delete_marker else "Version"
                ve = _el(root, tag)
                _el(ve, "Key", o.name)
                _el(ve, "VersionId", o.version_id or "null")
                _el(ve, "IsLatest", "true" if o.is_latest else "false")
                _el(ve, "LastModified", _iso8601(o.mod_time))
                if not o.delete_marker:
                    _el(ve, "ETag", f'"{o.etag}"')
                    _el(ve, "Size", o.size)
                    _el(ve, "StorageClass", o.storage_class)
            for p in info.prefixes:
                ce = _el(root, "CommonPrefixes")
                _el(ce, "Prefix", p)
            self._send(200, _xml(root))

        def _get_versioning(self, bucket):
            ol = server.object_layer
            ol.get_bucket_info(bucket)
            state = _versioning_state(ol, bucket)
            root = ET.Element("VersioningConfiguration", xmlns=XMLNS)
            if state:
                _el(root, "Status", state)
            self._send(200, _xml(root))

        def _put_versioning(self, bucket, body):
            ol = server.object_layer
            ol.get_bucket_info(bucket)
            try:
                status = ET.fromstring(body).findtext(
                    f"{{{XMLNS}}}Status") or ET.fromstring(body).findtext("Status")
            except ET.ParseError:
                raise S3Error("MalformedXML") from None
            if status not in ("Enabled", "Suspended"):
                raise S3Error("MalformedXML",
                              "Status must be Enabled or Suspended")
            with server.bucket_meta_lock:
                # Lock-config check INSIDE the metadata lock: checked
                # outside, a concurrent PutObjectLockConfiguration could
                # commit between check and write, leaving a WORM bucket
                # unversioned. WORM guarantee: a lock-enabled bucket can
                # never stop versioning (reference:
                # cmd/bucket-handlers.go PutBucketVersioningHandler).
                if status != "Enabled" and self._lock_config(bucket).get(
                        "enabled"):
                    raise S3Error("InvalidBucketState",
                                  "object lock requires versioning",
                                  bucket=bucket)
                # Suspension is a distinct state, not versioning-off:
                # null-versionId writes replace the null version while
                # older real versions survive (reference:
                # internal/bucket/versioning/versioning.go:36,76). The
                # layer setter manages both meta keys consistently.
                setter = getattr(ol, "set_bucket_versioning", None)
                if setter is None:
                    raise S3Error("NotImplemented")
                setter(bucket, status)
            self._site_enqueue("bucket-meta", bucket)
            self._send(200)

        def _list_objects(self, bucket, query):
            def q(name, default=""):
                return query.get(name, [default])[0]
            v2 = q("list-type") == "2"
            prefix = q("prefix")
            delimiter = q("delimiter")
            max_keys = int(q("max-keys", "1000") or 1000)
            if v2:
                marker = q("start-after")
                token = q("continuation-token")
                if token:
                    marker = _b64d(token)
            else:
                marker = q("marker")
            info = server.object_layer.list_objects(
                bucket, prefix=prefix, marker=marker, delimiter=delimiter,
                max_keys=max_keys)
            root = ET.Element("ListBucketResult", xmlns=XMLNS)
            _el(root, "Name", bucket)
            _el(root, "Prefix", prefix)
            if delimiter:
                _el(root, "Delimiter", delimiter)
            _el(root, "MaxKeys", max_keys)
            _el(root, "IsTruncated", "true" if info.is_truncated else "false")
            if v2:
                _el(root, "KeyCount", len(info.objects) + len(info.prefixes))
                if info.is_truncated:
                    _el(root, "NextContinuationToken", _b64e(info.next_marker))
            else:
                _el(root, "Marker", marker)
                if info.is_truncated:
                    _el(root, "NextMarker", info.next_marker)
            for o in info.objects:
                c = _el(root, "Contents")
                _el(c, "Key", o.name)
                _el(c, "LastModified", _iso8601(o.mod_time))
                _el(c, "ETag", f'"{o.etag}"')
                _el(c, "Size", o.size)
                _el(c, "StorageClass", o.storage_class)
            for p in info.prefixes:
                cp = _el(root, "CommonPrefixes")
                _el(cp, "Prefix", p)
            self._send(200, _xml(root))

        def _list_uploads(self, bucket, query):
            prefix = query.get("prefix", [""])[0]
            uploads = server.object_layer.list_multipart_uploads(bucket,
                                                                 prefix)
            root = ET.Element("ListMultipartUploadsResult", xmlns=XMLNS)
            _el(root, "Bucket", bucket)
            _el(root, "Prefix", prefix)
            _el(root, "IsTruncated", "false")
            for rec in uploads:
                ue = _el(root, "Upload")
                _el(ue, "Key", rec.get("object", ""))
                _el(ue, "UploadId", rec.get("upload_id", ""))
                _el(ue, "Initiated", _iso8601(rec.get("initiated", 0)))
                _el(ue, "StorageClass", "STANDARD")
            self._send(200, _xml(root))

        def _delete_objects(self, bucket, body):
            try:
                tree = ET.fromstring(body)
            except ET.ParseError:
                raise S3Error("MalformedXML") from None
            ns = f"{{{XMLNS}}}"
            objs = tree.findall(f"{ns}Object") or tree.findall("Object")
            quiet = (tree.findtext(f"{ns}Quiet") or
                     tree.findtext("Quiet") or "") == "true"
            root = ET.Element("DeleteResult", xmlns=XMLNS)
            state = _versioning_state(server.object_layer, bucket)
            h = self._headers_lower()
            for obj in objs[:1000]:
                key = obj.findtext(f"{ns}Key") or obj.findtext("Key") or ""
                vid = obj.findtext(f"{ns}VersionId") or obj.findtext("VersionId") or ""
                try:
                    self._check_version_deletable(bucket, key, vid, h)
                    deleted = server.object_layer.delete_object(
                        bucket, key,
                        DeleteOptions(version_id=vid,
                                      versioned=state == "Enabled",
                                      null_marker=state == "Suspended"
                                      and not vid))
                    if not vid:
                        # Bulk deletes mirror to peer sites like single
                        # DELETEs (version-targeted prunes stay local).
                        self._site_enqueue("delete", bucket, key)
                    self._notify(
                        "s3:ObjectRemoved:DeleteMarkerCreated"
                        if deleted.delete_marker
                        else "s3:ObjectRemoved:Delete", bucket, key,
                        version_id=deleted.delete_marker_version_id
                        if deleted.delete_marker else vid)
                    if not quiet:
                        de = _el(root, "Deleted")
                        _el(de, "Key", key)
                        if vid:
                            _el(de, "VersionId", vid)
                        if deleted.delete_marker:
                            _el(de, "DeleteMarker", "true")
                            _el(de, "DeleteMarkerVersionId",
                                deleted.delete_marker_version_id)
                except Exception as e:  # noqa: BLE001 - per-key result
                    err = from_exception(e)
                    ee = _el(root, "Error")
                    _el(ee, "Key", key)
                    _el(ee, "Code", err.code)
                    _el(ee, "Message", err.message)
            self._send(200, _xml(root))

        # -- object ops -------------------------------------------------

        def _object_op(self, method, bucket, key, query, body, payload=None):
            _validate_object_name(key)
            if method == "POST" and "select" in query:
                return self._select_object(bucket, key, query, body)
            if method == "POST" and "uploads" in query:
                return self._initiate_multipart(bucket, key)
            if method == "POST" and "uploadId" in query:
                return self._complete_multipart(bucket, key, query, body)
            if method == "PUT" and "partNumber" in query:
                return self._put_part(bucket, key, query, payload,
                                      self._headers_lower())
            if method == "DELETE" and "uploadId" in query:
                server.object_layer.abort_multipart_upload(
                    bucket, key, query["uploadId"][0])
                return self._send(204)
            if method == "GET" and "uploadId" in query:
                return self._list_parts(bucket, key, query)
            if "tagging" in query:
                return self._object_tagging(method, bucket, key, query,
                                            payload)
            if method == "GET" and "attributes" in query:
                return self._object_attributes(bucket, key, query)
            if "acl" in query:
                body_acl = payload.read_all() if method == "PUT" and \
                    payload is not None else b""
                server.object_layer.get_object_info(
                    bucket, key,
                    GetOptions(version_id=query.get("versionId",
                                                    [""])[0]))
                return self._acl(method, bucket, key, body_acl)
            if "retention" in query:
                return self._object_retention(method, bucket, key, query,
                                              payload)
            if "legal-hold" in query:
                return self._object_legal_hold(method, bucket, key, query,
                                               payload)
            if method == "PUT":
                return self._put_object(bucket, key, query, payload)
            if method in ("GET", "HEAD"):
                return self._get_object(method, bucket, key, query)
            if method == "DELETE":
                return self._delete_object(bucket, key, query)
            raise S3Error("MethodNotAllowed")

        def _select_object(self, bucket, key, query, body):
            """POST ?select&select-type=2 — SQL over one object
            (reference: internal/s3select; the SelectObjectContent API).
            Records STREAM through the engine in O(record) memory; the
            SSE/compression transforms reuse the GET path's plaintext
            chunk generators, version-pinned so params and data come
            from one snapshot."""
            from minio_tpu.s3select import SelectError, run_select
            h = self._headers_lower()
            vid = query.get("versionId", [""])[0]
            # ONE open: the stream's own info decides the transform
            # branch. Version-pinned buckets are fully race-free; on
            # unversioned buckets the transform re-open below keeps
            # the same small overwrite window the plain GET path has
            # (and the reference shares).
            info, chunks = server.object_layer.get_object_stream(
                bucket, key, GetOptions(version_id=vid))
            imeta = info.internal_metadata
            if imeta.get("x-internal-sse-alg"):
                chunks.close()
                self._sse_check_head(h, info)
                info, chunks, _, _ = self._get_encrypted(
                    bucket, key, vid or info.version_id, None, h, info)
            elif imeta.get("x-internal-comp"):
                chunks.close()
                info, chunks, _, _ = self._get_compressed(
                    bucket, key, vid or info.version_id, None, info)
            try:
                resp = run_select(chunks, body)
            except SelectError as e:
                raise S3Error("InvalidArgument", str(e)) from None
            self._send(200, resp,
                       content_type="application/octet-stream")

        def _object_tagging(self, method, bucket, key, query, payload):
            """GET/PUT/DELETE ?tagging on an object (reference:
            cmd/object-handlers.go PutObjectTagsHandler et al.)."""
            vid = query.get("versionId", [""])[0]
            if method == "GET":
                info = server.object_layer.get_object_info(
                    bucket, key, GetOptions(version_id=vid))
                root = ET.Element("Tagging", xmlns=XMLNS)
                ts = _el(root, "TagSet")
                for kv in urllib.parse.parse_qsl(info.user_tags):
                    te = _el(ts, "Tag")
                    _el(te, "Key", kv[0])
                    _el(te, "Value", kv[1])
                return self._send(200, _xml(root))
            if method == "PUT":
                body = payload.read_all() if payload is not None else b""
                tags = _parse_tagging_xml(body)
                server.object_layer.update_object_tags(bucket, key, vid,
                                                       tags)
                return self._send(200)
            if method == "DELETE":
                server.object_layer.update_object_tags(bucket, key, vid,
                                                       None)
                return self._send(204)
            raise S3Error("MethodNotAllowed")

        def _object_lock_put_meta(self, bucket, h) -> dict:
            """Lock metadata for a new version: explicit request
            headers win; otherwise the bucket's default-retention rule
            applies (reference: cmd/api-headers.go +
            cmd/bucket-object-lock.go defaults at PutObject)."""
            from minio_tpu.object import objectlock as olock
            cfg = self._lock_config(bucket)
            now = _time_mod.time_ns()
            try:
                explicit = olock.headers_to_meta(h, cfg.get("enabled", False),
                                                 now)
            except olock.ObjectLockError as e:
                raise S3Error(e.code, str(e)) from None
            # Merge: the bucket default supplies retention unless the
            # request set its own mode — a legal-hold-only header must
            # not suppress the default-retention rule.
            out = olock.default_retention_meta(cfg, now)
            out.update(explicit)
            return out

        def _site_enqueue(self, kind, bucket, key="", vid=""):
            """Mirror a change to peer sites — unless the change ITSELF
            arrived from a site (replica markers break the ping-pong)."""
            if server.site is None:
                return
            from minio_tpu.replication.site import H_SITE_REPLICA
            h = self._headers_lower()
            if h.get(H_SITE_REPLICA) or "x-amz-meta-mtpu-replica" in h:
                return
            server.site.enqueue(kind, bucket, key, vid)

        def _layer_sets(self):
            ol = server.object_layer
            if hasattr(ol, "pools"):
                return ol.pools[0].sets
            if hasattr(ol, "sets"):
                return ol.sets
            return [ol]

        def _batch_jobs(self):
            if server.batch is None:
                from minio_tpu.object.batch import BatchJobs
                ol = server.object_layer
                if hasattr(ol, "pools"):
                    sets = ol.pools[0].sets
                elif hasattr(ol, "sets"):
                    sets = ol.sets
                else:
                    sets = [ol]
                server.batch = BatchJobs(ol, sets)
                server.batch.kms = server.kms
            return server.batch

        def _tier_registry(self):
            """The server's tier registry, created on first use and
            attached to every erasure set (the read/transition paths
            resolve backends through set.tiers)."""
            if server.tiers is None:
                from minio_tpu.object.tier import TierRegistry
                ol = server.object_layer
                if hasattr(ol, "pools"):
                    reg_sets = ol.pools[0].sets
                    all_sets = [s for p in ol.pools for s in p.sets]
                elif hasattr(ol, "sets"):
                    reg_sets = all_sets = ol.sets
                else:
                    reg_sets = all_sets = [ol]
                server.tiers = TierRegistry(reg_sets)
                for s in all_sets:
                    s.tiers = server.tiers
            return server.tiers

        def _can_bypass_governance(self, bucket, key, h) -> bool:
            """Governance bypass needs BOTH the explicit header and the
            s3:BypassGovernanceRetention permission (reference:
            cmd/bucket-object-lock.go enforceRetentionBypassForDelete)."""
            if h.get(
                    "x-amz-bypass-governance-retention", "").lower() != "true":
                return False
            ak = self._auth_key
            return self._authorize(ak, ak == "",
                                   "s3:BypassGovernanceRetention",
                                   f"{bucket}/{key}",
                                   self._auth_context(ak, {}, h))

        def _object_retention(self, method, bucket, key, query, payload):
            """GET/PUT ?retention (reference: cmd/object-handlers.go
            GetObjectRetentionHandler / PutObjectRetentionHandler:2705)."""
            from minio_tpu.object import objectlock as olock
            vid = query.get("versionId", [""])[0]
            # Consistent gate for every verb: retention APIs only exist
            # on lock-enabled buckets (checked before any object read).
            if not self._lock_config(bucket).get("enabled"):
                raise S3Error("InvalidRequest", "bucket is missing "
                              "ObjectLockConfiguration", bucket=bucket)
            if method == "GET":
                info = server.object_layer.get_object_info(
                    bucket, key, GetOptions(version_id=vid))
                if not info.internal_metadata.get(olock.META_MODE):
                    raise S3Error("NoSuchObjectLockConfiguration",
                                  bucket=bucket, key=key)
                return self._send(200, olock.retention_xml(
                    info.internal_metadata))
            if method != "PUT":
                raise S3Error("MethodNotAllowed")
            body = payload.read_all() if payload is not None else b""
            h = self._headers_lower()
            try:
                mode, until = olock.parse_retention_xml(body)
                now = _time_mod.time_ns()
                if until and olock.parse_iso8601(until) <= now:
                    raise S3Error("InvalidArgument",
                                  "RetainUntilDate must be in the future")
                info = server.object_layer.get_object_info(
                    bucket, key, GetOptions(version_id=vid))
                denial = olock.check_retention_change(
                    info.internal_metadata, mode, until, now,
                    self._can_bypass_governance(bucket, key, h))
            except olock.ObjectLockError as e:
                raise S3Error(e.code, str(e)) from None
            if denial:
                raise S3Error(denial, "existing retention forbids this "
                              "change", bucket=bucket, key=key)

            def mutate(meta):
                if mode:
                    meta[olock.META_MODE] = mode
                    meta[olock.META_UNTIL] = until
                else:
                    meta.pop(olock.META_MODE, None)
                    meta.pop(olock.META_UNTIL, None)
            server.object_layer.update_version_metadata(bucket, key, vid,
                                                        mutate)
            return self._send(200)

        def _object_legal_hold(self, method, bucket, key, query, payload):
            """GET/PUT ?legal-hold (reference: cmd/object-handlers.go
            GetObjectLegalHoldHandler / PutObjectLegalHoldHandler:2862)."""
            from minio_tpu.object import objectlock as olock
            vid = query.get("versionId", [""])[0]
            if not self._lock_config(bucket).get("enabled"):
                raise S3Error("InvalidRequest", "bucket is missing "
                              "ObjectLockConfiguration", bucket=bucket)
            if method == "GET":
                info = server.object_layer.get_object_info(
                    bucket, key, GetOptions(version_id=vid))
                return self._send(200, olock.legal_hold_xml(
                    info.internal_metadata))
            if method != "PUT":
                raise S3Error("MethodNotAllowed")
            body = payload.read_all() if payload is not None else b""
            try:
                status = olock.parse_legal_hold_xml(body)
            except olock.ObjectLockError as e:
                raise S3Error(e.code, str(e)) from None
            server.object_layer.update_version_metadata(
                bucket, key, vid,
                lambda meta: meta.__setitem__(olock.META_HOLD, status))
            return self._send(200)

        def _check_version_deletable(self, bucket, key, vid, h):
            """Refuse destroying a retained/held version (reference:
            enforceRetentionForDeletion via DeleteObjectHandler). Only
            version-targeted deletes destroy data; marker stacking is
            always allowed."""
            if not vid:
                return
            # Lock can never be disabled once enabled, so a bucket whose
            # (TTL-cached) config lacks it holds no retained versions —
            # skip the per-version quorum metadata read on the common
            # path (bulk version deletes would otherwise double their
            # metadata I/O).
            if not self._lock_config(bucket).get("enabled"):
                return
            from minio_tpu.object import objectlock as olock
            from minio_tpu.object.types import (MethodNotAllowed as _MNA,
                                                ObjectNotFound as _ONF,
                                                VersionNotFound as _VNF)
            try:
                info = server.object_layer.get_object_info(
                    bucket, key, GetOptions(version_id=vid))
            except (_ONF, _VNF, _MNA):
                return          # absent or a delete marker: nothing held
            imeta = info.internal_metadata
            if not (imeta.get(olock.META_MODE) or imeta.get(olock.META_HOLD)):
                return
            denial = olock.check_version_deletable(
                imeta, _time_mod.time_ns(),
                self._can_bypass_governance(bucket, key, h))
            if denial:
                raise S3Error(denial, "object version is WORM-protected",
                              bucket=bucket, key=key)

        # -- multipart --------------------------------------------------

        def _initiate_multipart(self, bucket, key):
            h = self._headers_lower()
            from minio_tpu.crypto import sse as sse_mod
            meta = {k[len("x-amz-meta-"):]: v for k, v in h.items()
                    if k.startswith("x-amz-meta-")}
            opts = PutOptions(
                versioned=_versioned(server.object_layer, bucket),
                user_metadata=meta,
                content_type=h.get("content-type", ""),
                storage_class=h.get("x-amz-storage-class", "STANDARD"))
            opts.internal_metadata.update(
                self._object_lock_put_meta(bucket, h))
            # SSE multipart: choose/seal the object data key NOW and
            # persist the params with the upload; each part becomes its
            # own DARE stream under a per-part derived key (reference:
            # cmd/encryption-v1.go:643 part-boundary crypto).
            sse_headers = {}
            try:
                customer = sse_mod.parse_sse_c(h)
                enc_cfg = None
                if customer is None:
                    # A metadata read failure PROPAGATES: guessing "no
                    # default encryption" on a transient error would
                    # silently store the whole object as plaintext.
                    enc_cfg = server.object_layer.get_bucket_meta(
                        bucket).get("config:encryption")
                if customer is not None or sse_mod.wants_sse_s3(h, enc_cfg):
                    _, _, imeta = sse_mod.encrypt_metadata(
                        bucket, key, 0, server.kms, customer)
                    imeta[sse_mod.META_MULTIPART] = "1"
                    opts.internal_metadata.update(imeta)
                    if customer is not None:
                        sse_headers = {sse_mod.H_C_ALG: "AES256",
                                       sse_mod.H_C_MD5: customer[1]}
                    else:
                        sse_headers = {sse_mod.H_SSE: "AES256"}
            except sse_mod.SSEError as e:
                raise S3Error(e.code, str(e)) from None
            uid = server.object_layer.new_multipart_upload(bucket, key, opts)
            root = ET.Element("InitiateMultipartUploadResult", xmlns=XMLNS)
            _el(root, "Bucket", bucket)
            _el(root, "Key", key)
            _el(root, "UploadId", uid)
            self._send(200, _xml(root), headers=sse_headers)

        def _part_sse_wrap(self, bucket, key, uid, part_num, payload, h):
            """Encrypt one part's payload when the upload was initiated
            with SSE: an independent DARE stream under the per-part
            derived key (crypto/sse.part_key) and a FRESH random base
            nonce persisted with the part — a re-uploaded part number
            must never reuse an AES-GCM (key, nonce, seq) tuple on
            different plaintext. Returns (payload, actual_size|None,
            part nonce b64, response headers). Errors reading the
            upload record PROPAGATE: silently storing an SSE part as
            plaintext is the one unacceptable failure mode."""
            from minio_tpu.crypto import (EncryptingPayload,
                                          encrypt_stream_size)
            from minio_tpu.crypto import sse as sse_mod
            rec = server.object_layer.get_multipart_upload(bucket, key, uid)
            imeta = rec.get("internal_metadata") or {}
            if not imeta.get(sse_mod.META_ALG):
                return payload, None, "", {}
            try:
                customer = sse_mod.parse_sse_c(h)
                data_key, _ = sse_mod.decrypt_params(
                    bucket, key, imeta, server.kms, customer)
            except sse_mod.SSEError as e:
                raise S3Error(e.code, str(e)) from None
            part_nonce = os.urandom(12)
            plain = payload.size
            enc = EncryptingPayload(payload,
                                    sse_mod.part_key(data_key, part_num),
                                    part_nonce)
            # The inner payload runs its own finish (signature/trailer
            # verification) as the encryptor drains its last byte.
            out = Payload(enc, encrypt_stream_size(plain))
            if customer is not None:
                hdrs = {sse_mod.H_C_ALG: "AES256",
                        sse_mod.H_C_MD5: customer[1]}
            else:
                hdrs = {sse_mod.H_SSE: "AES256"}
            import base64 as _b64
            return out, plain, _b64.b64encode(part_nonce).decode(), hdrs

        def _put_part(self, bucket, key, query, payload, h):
            try:
                part_num = int(query["partNumber"][0])
            except (ValueError, KeyError):
                raise S3Error("InvalidArgument") from None
            uid = query.get("uploadId", [""])[0]
            if payload is not None:
                self._check_quota(bucket, payload.size)
            if "x-amz-copy-source" in h:
                # UploadPartCopy: source bytes become the part payload.
                src = urllib.parse.unquote(h["x-amz-copy-source"]).lstrip("/")
                src_vid = ""
                if "?versionId=" in src:
                    src, _, src_vid = src.partition("?versionId=")
                if "/" not in src:
                    raise S3Error("InvalidArgument", "bad copy source")
                sbucket, skey = src.split("/", 1)
                spec = _range_spec(h.get("x-amz-copy-source-range", "")
                                   .replace("bytes=", "bytes=")
                                   ) if h.get("x-amz-copy-source-range") else None
                # Decrypting fetch: an SSE source must contribute
                # PLAINTEXT part bytes (range in plaintext space too).
                _, body = self._read_source_plain(sbucket, skey, src_vid,
                                                  spec, h)
                cpay, actual, pnonce, sse_hdrs = self._part_sse_wrap(
                    bucket, key, uid, part_num, Payload.wrap(body), h)
                part = server.object_layer.put_object_part(
                    bucket, key, uid, part_num, cpay, actual_size=actual,
                    nonce=pnonce)
                root = ET.Element("CopyPartResult", xmlns=XMLNS)
                _el(root, "ETag", f'"{part.etag}"')
                _el(root, "LastModified", _iso8601(part.mod_time))
                return self._send(200, _xml(root), headers=sse_hdrs)
            # Per-part checksums (boto3 >= 1.36 declares one on every
            # UploadPart by default): verified before commit; composite
            # object-level checksums are not assembled in v1.
            ck_opts = PutOptions()
            payload, ck_hdrs = self._apply_checksums(payload, h, ck_opts)
            payload, actual, pnonce, sse_hdrs = self._part_sse_wrap(
                bucket, key, uid, part_num, payload, h)
            part = server.object_layer.put_object_part(
                bucket, key, uid, part_num, payload, actual_size=actual,
                nonce=pnonce)
            self._send(200, headers={"ETag": f'"{part.etag}"', **sse_hdrs,
                                     **ck_hdrs})

        def _complete_multipart(self, bucket, key, query, body):
            uid = query["uploadId"][0]
            try:
                tree = ET.fromstring(body)
            except ET.ParseError:
                raise S3Error("MalformedXML") from None
            ns = f"{{{XMLNS}}}"
            parts = []
            for pe in tree.findall(f"{ns}Part") or tree.findall("Part"):
                num = pe.findtext(f"{ns}PartNumber") or pe.findtext("PartNumber")
                etag = pe.findtext(f"{ns}ETag") or pe.findtext("ETag") or ""
                try:
                    parts.append((int(num), etag))
                except (TypeError, ValueError):
                    raise S3Error("MalformedXML") from None
            info = server.object_layer.complete_multipart_upload(
                bucket, key, uid, parts)
            self._replicate_after_write(bucket, key, info.version_id,
                                        self._headers_lower())
            self._site_enqueue("put", bucket, key, info.version_id)
            self._notify("s3:ObjectCreated:CompleteMultipartUpload",
                         bucket, key, size=info.size, etag=info.etag,
                         version_id=info.version_id)
            root = ET.Element("CompleteMultipartUploadResult", xmlns=XMLNS)
            _el(root, "Location", f"/{bucket}/{key}")
            _el(root, "Bucket", bucket)
            _el(root, "Key", key)
            _el(root, "ETag", f'"{info.etag}"')
            headers = {}
            if info.version_id:
                headers["x-amz-version-id"] = info.version_id
            self._send(200, _xml(root), headers=headers)

        def _list_parts(self, bucket, key, query):
            uid = query["uploadId"][0]
            try:
                marker = int(query.get("part-number-marker", ["0"])[0] or 0)
                max_parts = int(query.get("max-parts", ["1000"])[0] or 1000)
            except ValueError:
                raise S3Error("InvalidArgument") from None
            parts = server.object_layer.list_parts(bucket, key, uid,
                                                   marker, max_parts)
            root = ET.Element("ListPartsResult", xmlns=XMLNS)
            _el(root, "Bucket", bucket)
            _el(root, "Key", key)
            _el(root, "UploadId", uid)
            _el(root, "PartNumberMarker", marker)
            _el(root, "MaxParts", max_parts)
            _el(root, "IsTruncated", "false")
            for p in parts:
                pe = _el(root, "Part")
                _el(pe, "PartNumber", p["number"])
                _el(pe, "ETag", f'"{p["etag"]}"')
                _el(pe, "Size", p["size"])
                _el(pe, "LastModified", _iso8601(p["mod_time"]))
            self._send(200, _xml(root))

        def _copy_object(self, bucket, key, h):
            src = urllib.parse.unquote(h["x-amz-copy-source"])
            src_vid = ""
            if "?versionId=" in src:
                src, _, src_vid = src.partition("?versionId=")
            src = src.lstrip("/")
            if "/" not in src:
                raise S3Error("InvalidArgument", "bad copy source")
            sbucket, skey = src.split("/", 1)
            sinfo, payload = self._read_source_plain(sbucket, skey,
                                                     src_vid, None, h)
            if any(c in h for c in ("x-amz-copy-source-if-match",
                                    "x-amz-copy-source-if-none-match",
                                    "x-amz-copy-source-if-modified-since",
                                    "x-amz-copy-source-if-unmodified-since")):
                self._check_conditions(h, sinfo, for_read=False,
                                       prefix="x-amz-copy-source-")
            directive = h.get("x-amz-metadata-directive", "COPY").upper()
            if directive == "REPLACE":
                meta = {k2[len("x-amz-meta-"):]: v for k2, v in h.items()
                        if k2.startswith("x-amz-meta-")}
                ctype = h.get("content-type", sinfo.content_type)
            else:
                meta = dict(sinfo.user_metadata)
                ctype = sinfo.content_type
            tag_directive = h.get("x-amz-tagging-directive", "COPY").upper()
            tags = h.get("x-amz-tagging", "") if tag_directive == "REPLACE" \
                else sinfo.user_tags
            opts = PutOptions(
                versioned=_versioned(server.object_layer, bucket),
                user_metadata=meta, content_type=ctype, tags=tags)
            # Copies into a lock-enabled bucket honor lock headers and
            # the default-retention rule like any other new version.
            opts.internal_metadata.update(
                self._object_lock_put_meta(bucket, h))
            self._check_quota(bucket, len(payload))
            out_payload, sse_headers = self._apply_sse(
                bucket, key, Payload.wrap(payload), h, opts)
            info = server.object_layer.put_object(
                bucket, key, out_payload, opts)
            self._note_quota_write(bucket, len(payload))
            self._replicate_after_write(bucket, key, info.version_id, h)
            self._site_enqueue("put", bucket, key, info.version_id)
            self._notify("s3:ObjectCreated:Copy", bucket, key,
                         size=len(payload), etag=info.etag,
                         version_id=info.version_id)
            root = ET.Element("CopyObjectResult", xmlns=XMLNS)
            _el(root, "ETag", f'"{info.etag}"')
            _el(root, "LastModified", _iso8601(info.mod_time))
            headers = dict(sse_headers)
            if info.version_id:
                headers["x-amz-version-id"] = info.version_id
            self._send(200, _xml(root), headers=headers)

        def _put_object(self, bucket, key, query, payload):
            h = self._headers_lower()
            if "x-amz-copy-source" in h:
                return self._copy_object(bucket, key, h)
            if "if-match" in h or "if-none-match" in h:
                # Conditional write (create-only / replace-exact): check
                # the current version before accepting the body. Only a
                # definitive not-found counts as absent — a transient
                # read failure must NOT let a create-only PUT overwrite.
                from minio_tpu.object.types import (MethodNotAllowed as _MNA,
                                                    ObjectNotFound as _ONF,
                                                    VersionNotFound as _VNF)
                try:
                    cur = server.object_layer.get_object_info(
                        bucket, key, GetOptions())
                except (_ONF, _VNF, _MNA):
                    cur = None
                if cur is None:
                    if "if-match" in h:
                        raise S3Error("NoSuchKey", bucket=bucket, key=key)
                else:
                    self._check_conditions(h, cur, for_read=False)
            meta = {k[len("x-amz-meta-"):]: v for k, v in h.items()
                    if k.startswith("x-amz-meta-")}
            opts = PutOptions(
                versioned=_versioned(server.object_layer, bucket),
                user_metadata=meta,
                content_type=h.get("content-type", ""),
                storage_class=h.get("x-amz-storage-class", "STANDARD"),
                tags=h.get("x-amz-tagging", ""))
            opts.internal_metadata.update(
                self._object_lock_put_meta(bucket, h))
            self._check_quota(bucket, payload.size)
            fused = self._fused_put_prepare(bucket, key, payload, h, opts)
            if fused is not None:
                # Fused single-pass plane: the raw LOGICAL body goes to
                # the object layer with a TransformSpec — etag md5,
                # declared checksums, compression, and DARE all run as
                # ONE native pass next to the framer
                # (object/transform.py). Checksum verification runs
                # pre-commit via the spec's verify hook.
                payload, sse_headers, checksum_hdrs, plain_size = fused
            else:
                from minio_tpu.object import transform as _tf
                from minio_tpu.object.erasure_object import \
                    STREAM_THRESHOLD as _ST
                if payload.size <= _ST:
                    _tf.note_put("legacy", payload.size)
                payload, checksum_hdrs = self._apply_checksums(payload, h,
                                                               opts)
                plain_size = payload.size
                # Compression BEFORE encryption: the block scheme sees
                # plaintext (ciphertext is incompressible), so
                # compressed+encrypted objects store DARE(compressed)
                # — the same layering the fused pass produces.
                payload = self._apply_compression(key, payload, opts)
                payload, sse_headers = self._apply_sse(bucket, key,
                                                       payload, h, opts)
            # Replicate only after the SSE decision: encrypted objects
            # do not replicate in v1 (their keys bind to this cluster),
            # and an incoming REPLICA must not ping-pong back in
            # active-active setups (the mtpu-replica marker).
            replicate = (server.replicator is not None
                         and "x-amz-meta-mtpu-replica" not in h
                         and not opts.internal_metadata.get(
                             "x-internal-sse-alg")
                         and server.replicator.should_replicate(bucket,
                                                                key))
            if replicate:
                from minio_tpu.replication import REPL_STATUS_KEY
                opts.internal_metadata[REPL_STATUS_KEY] = "PENDING"
            info = server.object_layer.put_object(bucket, key, payload, opts)
            self._note_quota_write(bucket, plain_size)
            if replicate:
                server.replicator.enqueue(bucket, key, info.version_id,
                                          "put",
                                          mod_time=getattr(info,
                                                           "mod_time", 0))
            self._site_enqueue("put", bucket, key, info.version_id)
            self._notify("s3:ObjectCreated:Put", bucket, key,
                         size=plain_size, etag=info.etag,
                         version_id=info.version_id)
            headers = {"ETag": f'"{info.etag}"', **sse_headers,
                       **checksum_hdrs}
            if info.version_id:
                headers["x-amz-version-id"] = info.version_id
            self._send(200, headers=headers)

        def _replicate_after_write(self, bucket, key, version_id, h):
            """Post-hoc replication marking for write paths that cannot
            stamp PENDING before commit (multipart complete, copy): one
            metadata update, then enqueue — so the scanner resync also
            covers them after a crash."""
            r = server.replicator
            if r is None or "x-amz-meta-mtpu-replica" in h \
                    or not r.should_replicate(bucket, key):
                return
            from minio_tpu.replication import REPL_STATUS_KEY
            mod_time = 0
            try:
                info = server.object_layer.update_version_metadata(
                    bucket, key, version_id,
                    lambda m: None if m.get("x-internal-sse-alg")
                    else m.__setitem__(REPL_STATUS_KEY, "PENDING"))
                if info.internal_metadata.get("x-internal-sse-alg"):
                    return            # SSE objects do not replicate (v1)
                mod_time = getattr(info, "mod_time", 0)
            except Exception:  # noqa: BLE001 - stamping is advisory
                pass
            r.enqueue(bucket, key, version_id, "put", mod_time=mod_time)

        _QUOTA_TTL = 5.0

        def _bucket_quota(self, bucket) -> int:
            """Configured hard quota bytes (0 = none)."""
            import json as _json
            raw = server.object_layer.get_bucket_meta(bucket) \
                .get("config:quota")
            if not raw:
                return 0
            try:
                cfg = _json.loads(raw) if isinstance(raw, str) else raw
            except ValueError:
                return 0
            if cfg.get("quotatype", "hard") != "hard":
                return 0
            return int(cfg.get("quota") or 0)

        def _bucket_usage_bytes(self, bucket) -> float:
            """Current bucket size: the scanner's accounting when
            available, else a TTL'd live walk; committed writes advance
            the cached figure between refreshes (_note_quota_write).
            Single-flight: exactly one thread refreshes an expired
            entry — concurrent PUTs after TTL expiry must not each
            repeat the O(objects) walk."""
            now = _time_mod.monotonic()
            with server._quota_mu:
                ent = server._quota_usage.get(bucket)
                if ent is not None and (now - ent[0] < self._QUOTA_TTL
                                        or len(ent) > 2):
                    return ent[1]       # fresh, or someone refreshing
                if ent is None:
                    ent = server._quota_usage[bucket] = [now, 0]
                ent.append("refreshing")
            size = ent[1]               # prior figure if refresh fails
            try:
                sc = server.scanner
                if sc is not None and bucket in getattr(
                        sc.usage, "buckets", {}):
                    size = sc.usage.buckets[bucket].size
                else:
                    from minio_tpu.object.rebalance import \
                        bucket_used_bytes
                    size = bucket_used_bytes(server.object_layer, bucket)
            finally:
                with server._quota_mu:
                    server._quota_usage[bucket] = [
                        _time_mod.monotonic(), size]
            return size

        def _check_quota(self, bucket, incoming: int) -> None:
            """Hard-quota gate for every write path (reference:
            cmd/bucket-quota.go:32 enforceBucketQuotaHard on PutObject,
            parts and copies)."""
            quota = self._bucket_quota(bucket)
            if not quota:
                return
            if self._bucket_usage_bytes(bucket) + incoming > quota:
                raise S3Error("XMinioAdminBucketQuotaExceeded",
                              bucket=bucket)

        def _note_quota_write(self, bucket, nbytes: int) -> None:
            with server._quota_mu:
                ent = server._quota_usage.get(bucket)
                if ent is not None:
                    ent[1] += nbytes

        def _fused_put_prepare(self, bucket, key, payload, h, opts):
            """Plan the fused single-pass data plane for a buffered
            PUT: returns (logical bytes, sse response headers, checksum
            response headers, plain size) with opts.transform set — or
            None when the fused plane cannot take this request (kill
            switch, no native kernel, streaming-size body) and the
            layered pipeline should run instead."""
            from minio_tpu.crypto import sse as sse_mod
            from minio_tpu.object import transform as _tf
            from minio_tpu.object.erasure_object import STREAM_THRESHOLD
            from minio_tpu.s3 import checksum as ck
            if not _tf.fused_put_enabled() \
                    or payload.size > STREAM_THRESHOLD:
                return None
            try:
                declared = dict(ck.declared_algos(h))
                t_algos = ck.trailer_algos(h)
                algos = ck.single_algo(declared, t_algos)
            except ck.ChecksumError as e:
                raise S3Error(e.code, str(e)) from None
            # SSE decision (same gates as transform.sse_payload, minus
            # the payload wrap — the erasure layer seals in-pass).
            try:
                customer = sse_mod.parse_sse_c(h)
                enc_key = enc_nonce = b""
                sse_headers = {}
                if customer is not None or sse_mod.wants_sse_s3(
                        h, server.object_layer.get_bucket_meta(bucket)
                        .get("config:encryption")):
                    enc_key, enc_nonce, imeta = sse_mod.encrypt_metadata(
                        bucket, key, payload.size, server.kms, customer)
                    opts.internal_metadata.update(imeta)
                    sse_headers = ({sse_mod.H_C_ALG: "AES256",
                                    sse_mod.H_C_MD5: customer[1]}
                                   if customer is not None
                                   else {sse_mod.H_SSE: "AES256"})
            except sse_mod.SSEError as e:
                raise S3Error(e.code, str(e)) from None
            from minio_tpu.crypto import compress as comp
            compress = bool(server.compression and payload.size
                            and comp.eligible(key, opts.content_type))
            raw = getattr(payload, "_reader", None)   # trailer source
            # Reading the body drives the SigV4/chunk-signature checks
            # and the trailer parse — the single ingest walk the
            # layered path also pays; every digest after this point
            # comes out of the ONE fused native pass.
            data = payload.read_all()
            checksum_hdrs: dict = {}

            def verify(sp):
                expected = dict(declared)
                trailers = getattr(raw, "trailers", {}) or {}
                for a in t_algos:
                    expected.setdefault(a,
                                        trailers.get(ck.H_PREFIX + a))
                if not expected:
                    return
                try:
                    meta = ck.verify_and_meta(
                        ck.DigestValues(sp.digests), expected)
                except ck.ChecksumError as e:
                    raise S3Error(e.code, str(e)) from None
                opts.internal_metadata.update(meta)
                checksum_hdrs.update(ck.response_headers(meta))

            opts.transform = _tf.TransformSpec(
                algos=tuple(algos), compress=compress, enc_key=enc_key,
                enc_nonce=enc_nonce, verify=verify)
            return data, sse_headers, checksum_hdrs, len(data)

        def _apply_checksums(self, payload, h, opts):
            """Wrap the LOGICAL payload in checksum computation when
            the request declares x-amz-checksum-* values (headers, or
            aws-chunked trailers — the SDK default). Verification runs
            in the payload's finish hook, i.e. before commit; verified
            values land in internal metadata. Returns (payload,
            response-header dict that fills in post-verify)."""
            from minio_tpu.s3 import checksum as ck
            try:
                declared = dict(ck.declared_algos(h))
                t_algos = ck.trailer_algos(h)
                algos = ck.single_algo(declared, t_algos)
            except ck.ChecksumError as e:
                raise S3Error(e.code, str(e)) from None
            if not algos:
                return payload, {}
            raw = getattr(payload, "_reader", None)   # trailer source
            reader = ck.ChecksumingReader(payload, algos)
            hdrs: dict = {}

            def fin():
                # Zero-byte bodies: the outer payload finishes without
                # ever pulling the inner one, whose own finish parses
                # the trailers — drive it explicitly (idempotent for
                # non-empty bodies, whose finish already ran).
                payload.read(1)
                expected = dict(declared)
                trailers = getattr(raw, "trailers", {}) or {}
                for a in t_algos:
                    expected.setdefault(a,
                                        trailers.get(ck.H_PREFIX + a))
                try:
                    meta = ck.verify_and_meta(reader, expected)
                except ck.ChecksumError as e:
                    raise S3Error(e.code, str(e)) from None
                opts.internal_metadata.update(meta)
                hdrs.update(ck.response_headers(meta))

            return Payload(reader, payload.size, finish=fin), hdrs

        def _apply_sse(self, bucket, key, payload, h, opts):
            """Wrap a put payload in DARE encryption when the request
            (SSE-C / SSE-S3 headers) or the bucket's default encryption
            config asks for it (shared put-side seam:
            object/transform.py). Returns (payload, response headers)."""
            from minio_tpu.crypto import sse as sse_mod
            from minio_tpu.object import transform
            try:
                return transform.sse_payload(server.object_layer,
                                             server.kms, bucket, key,
                                             payload, opts, h)
            except sse_mod.SSEError as e:
                raise S3Error(e.code, str(e)) from None

        def _apply_compression(self, key, payload, opts):
            """Compress eligible buffered-size plaintext objects
            (reference: cmd/object-api-utils.go compression gate —
            never for incompressible payloads). Runs BEFORE the SSE
            wrap, so encrypted eligible objects store DARE over the
            compressed block stream — the fused pass's layering."""
            from minio_tpu.crypto import compress as comp
            from minio_tpu.object.erasure_object import STREAM_THRESHOLD
            if not server.compression \
                    or payload.size == 0 \
                    or payload.size > STREAM_THRESHOLD \
                    or not comp.eligible(key, opts.content_type):
                return payload
            data = payload.read_all()
            result = comp.compress(data)
            if result is None:           # incompressible: store as-is
                return Payload.wrap(data)
            stored, meta = result
            opts.internal_metadata.update(meta)
            # ETag must hash the LOGICAL bytes (single-PUT clients
            # verify ETag == md5(body)), not the compressed stream.
            opts.etag = hashlib.md5(data).hexdigest()
            return Payload.wrap(stored)

        def _get_compressed(self, bucket, key, vid, spec, info):
            """Ranged read of a compressed object (shared transform
            seam: object/transform.py)."""
            from minio_tpu.crypto import compress as comp
            from minio_tpu.object import transform
            try:
                return transform.get_compressed(server.object_layer,
                                                bucket, key, vid, spec,
                                                info)
            except comp.CompressionError as e:
                raise S3Error("InternalError", str(e)) from None

        def _sse_response_headers(self, h, info) -> dict:
            from minio_tpu.crypto import sse as sse_mod
            alg = info.internal_metadata.get(sse_mod.META_ALG, "")
            if alg == sse_mod.ALG_SSE_S3:
                return {sse_mod.H_SSE: "AES256"}
            if alg == sse_mod.ALG_SSE_C:
                return {sse_mod.H_C_ALG: "AES256",
                        sse_mod.H_C_MD5:
                        info.internal_metadata.get(sse_mod.META_KEY_MD5,
                                                   "")}
            return {}

        def _sse_check_head(self, h, info):
            """HEAD/GET of an SSE-C object requires the matching key."""
            from minio_tpu.crypto import sse as sse_mod
            from minio_tpu.object import transform
            try:
                transform.sse_check_head(h, info)
            except sse_mod.SSEError as e:
                raise S3Error(e.code, str(e)) from None

        def _read_source_plain(self, sbucket, skey, src_vid, spec, h):
            """Copy-source fetch in PLAINTEXT space: decrypts SSE
            sources (using x-amz-copy-source-...-customer-* headers for
            SSE-C) and resolves ranges against the logical size."""
            sinfo = server.object_layer.get_object_info(
                sbucket, skey, GetOptions(version_id=src_vid))
            # SSE first: a compressed+encrypted source must decrypt
            # before inflating (get_encrypted handles the combined
            # layering; the comp branch alone would inflate ciphertext).
            if sinfo.internal_metadata.get("x-internal-comp") \
                    and not sinfo.internal_metadata.get(
                        "x-internal-sse-alg"):
                sinfo, chunks, _, _ = self._get_compressed(
                    sbucket, skey, src_vid or sinfo.version_id, spec,
                    sinfo)
                return sinfo, b"".join(chunks)
            if not sinfo.internal_metadata.get("x-internal-sse-alg"):
                return server.object_layer.get_object(
                    sbucket, skey, GetOptions(version_id=src_vid,
                                              range_spec=spec))
            from minio_tpu.crypto import sse as sse_mod
            src_h = {}
            pfx = "x-amz-copy-source-server-side-encryption-customer-"
            for tail, name in (("algorithm", sse_mod.H_C_ALG),
                               ("key", sse_mod.H_C_KEY),
                               ("key-md5", sse_mod.H_C_MD5)):
                v = h.get(pfx + tail)
                if v is not None:
                    src_h[name] = v
            # The GET-side decryptor handles both single-stream and
            # per-part multipart DARE layouts.
            sinfo, chunks, _, _ = self._get_encrypted(
                sbucket, skey, src_vid or sinfo.version_id, spec, src_h,
                sinfo)
            return sinfo, b"".join(chunks)

        def _get_encrypted(self, bucket, key, vid, spec, h, info):
            """Ranged decrypting GET (shared transform seam:
            object/transform.py; reference: cmd/encryption-v1.go:643)."""
            from minio_tpu.crypto import sse as sse_mod
            from minio_tpu.object import transform
            try:
                return transform.get_encrypted(server.object_layer,
                                               server.kms, bucket, key,
                                               vid, spec, h, info)
            except sse_mod.SSEError as e:
                raise S3Error(e.code, str(e)) from None

        def _check_conditions(self, h, info, for_read: bool,
                              prefix: str = "") -> bool:
            """RFC 7232 / S3 conditional requests. Returns True when a
            read should answer 304 Not Modified; raises
            PreconditionFailed for failed write/read preconditions.
            prefix selects copy-source variants (x-amz-copy-source-if-*).
            """
            def g(name):
                return h.get(prefix + name)

            def etag_matches(val):
                vals = [v.strip().strip('"') for v in val.split(",")]
                return "*" in vals or info.etag in vals

            def parse_http_date(val):
                try:
                    dt = email.utils.parsedate_to_datetime(val)
                    return dt.timestamp()
                except (TypeError, ValueError):
                    return None

            # Whole-second comparison: Last-Modified is served at second
            # granularity, so sub-second mod times must truncate or
            # revalidation (If-Modified-Since echoing our own header)
            # could never match (RFC 7232).
            mod_secs = info.mod_time // 1_000_000_000
            im, inm = g("if-match"), g("if-none-match")
            ims = parse_http_date(g("if-modified-since") or "")
            ius = parse_http_date(g("if-unmodified-since") or "")
            if im is not None:
                if not etag_matches(im):
                    raise S3Error("PreconditionFailed", bucket=info.bucket,
                                  key=info.name)
            elif ius is not None and mod_secs > ius:
                raise S3Error("PreconditionFailed", bucket=info.bucket,
                              key=info.name)
            if inm is not None:
                if etag_matches(inm):
                    if for_read:
                        return True          # 304
                    raise S3Error("PreconditionFailed", bucket=info.bucket,
                                  key=info.name)
            elif ims is not None and mod_secs <= ims:
                if for_read:
                    return True
                # Copy-source semantics: "only copy if modified since"
                # fails hard when the source has not changed.
                raise S3Error("PreconditionFailed", bucket=info.bucket,
                              key=info.name)
            return False

        def _send_not_modified(self, info):
            self.send_response(304)
            self.send_header("ETag", f'"{info.etag}"')
            self.send_header("Last-Modified", _rfc1123(info.mod_time))
            self.end_headers()

        def _get_object(self, method, bucket, key, query):
            h = self._headers_lower()
            vid = query.get("versionId", [""])[0]
            rng = h.get("range", "")
            spec = _range_spec(rng)
            chunks = None
            send_fd = None
            if any(c in h for c in ("if-match", "if-none-match",
                                    "if-modified-since",
                                    "if-unmodified-since")):
                pre = server.object_layer.get_object_info(
                    bucket, key, GetOptions(version_id=vid))
                if self._check_conditions(h, pre, for_read=True):
                    return self._send_not_modified(pre)
            hot = getattr(server, "hot_cache", None)
            hot_entry = None
            hot_token = None
            hot_admit = False
            hot_head = None
            if method == "HEAD":
                # HEAD: metadata fan-out only, no shard reads.
                info = server.object_layer.get_object_info(
                    bucket, key, GetOptions(version_id=vid))
                self._sse_check_head(h, info)
                start, length = (_resolve_head_range(spec, info.size)
                                 if spec else (0, info.size))
            elif hot is not None and not vid \
                    and (hot_entry := hot.get(bucket, key)) is not None:
                # Hot-tier RAM hit (object/hotcache.py): serve the
                # pinned plaintext body with ZERO object-layer work.
                # The shared header-assembly + send code below runs
                # unchanged on the cached ObjectInfo, so the response
                # is byte-identical to a miss (and to a
                # MTPU_HOT_CACHE=off server). Cheap ranges resolve
                # against the resident whole object.
                info = hot_entry.info
                start, length = (_resolve_head_range(spec, info.size)
                                 if spec else (0, info.size))
                chunks = (w for w in
                          (memoryview(hot_entry.body)
                           [start:start + length],))
                self._path_kind = "hotcache"
            else:
                # One streaming read, rerouted on the returned info when
                # the object carries a transform (SSE grows the offset
                # space, compression shrinks it). A plaintext range
                # exceeding a COMPRESSED stored size raises InvalidRange
                # at the open — only then fall back to an info-first
                # read; spec=None can never take that path. Version
                # pinning keeps params and data from the same generation
                # (unversioned buckets keep a small overwrite race, as
                # does the reference).
                from minio_tpu.object.types import InvalidRange as _IR
                if hot is not None and hot.enabled and not vid:
                    # Hot-tier token BEFORE the read fan-out (the
                    # fi_cache contract): a mutation racing this read
                    # bumps the bucket generation, and put() below
                    # refuses the stale insert.
                    hot_token = hot.token(bucket)
                info = chunks = None
                try:
                    info, chunks = \
                        server.object_layer.get_object_stream(
                            bucket, key, GetOptions(version_id=vid,
                                                    range_spec=spec))
                except _IR:
                    info = server.object_layer.get_object_info(
                        bucket, key, GetOptions(version_id=vid))
                    if not info.internal_metadata.get("x-internal-comp"):
                        raise      # genuinely out of range
                imeta = info.internal_metadata
                if imeta.get("x-internal-sse-alg"):
                    if chunks is not None:
                        chunks.close()
                    self._sse_check_head(h, info)
                    info, chunks, start, length = self._get_encrypted(
                        bucket, key, vid or info.version_id, spec, h,
                        info)
                elif imeta.get("x-internal-comp"):
                    if chunks is not None:
                        chunks.close()
                    info, chunks, start, length = self._get_compressed(
                        bucket, key, vid or info.version_id, spec, info)
                else:
                    start, length = info.range_start, info.range_length
                    # Hot-tier admission (tinyLFU): only plaintext
                    # whole-object reads under the size cap are
                    # candidates; the sketch decides whether buffering
                    # this body beats the would-be eviction victim.
                    if hot_token is not None and spec is None and length:
                        hot_admit = hot.admit(bucket, key, length)
                    # Whole-object plaintext sendfile short-circuit:
                    # a tier-resident (FS-warm) version's stored bytes
                    # live contiguously in one local file, so the body
                    # can go socket-ward entirely in-kernel. Erasure-
                    # resident objects never qualify (shard files are
                    # bitrot-framed). The probe is gated on the tier
                    # marker so the hot erasure GET path pays nothing.
                    if spec is None and length \
                            and imeta.get("x-internal-tier-name"):
                        gof = getattr(server.object_layer,
                                      "get_object_file", None)
                        sf = None
                        if gof is not None:
                            # The stream's read lock is still held and
                            # `info` is resolved for this exact version:
                            # the probe skips a second quorum fan-out.
                            try:
                                sf = gof(bucket, key, GetOptions(
                                    version_id=vid or info.version_id),
                                    info=info)
                            except Exception:  # noqa: BLE001 - fall back
                                sf = None
                        if sf is not None:
                            chunks.close()
                            chunks = None
                            info, send_fd, start, length = sf
            if spec and info.size == 0 and spec[0] is None:
                spec = None  # suffix range on empty object: plain 200 (AWS)
            headers = {
                "ETag": f'"{info.etag}"',
                "Last-Modified": _rfc1123(info.mod_time),
                "Accept-Ranges": "bytes",
            }
            headers.update(self._sse_response_headers(h, info))
            from minio_tpu.object import objectlock as olock
            headers.update(olock.meta_to_headers(info.internal_metadata))
            if h.get("x-amz-checksum-mode", "").upper() == "ENABLED":
                from minio_tpu.s3 import checksum as ck
                headers.update(ck.response_headers(
                    info.internal_metadata))
            repl = info.internal_metadata.get("x-internal-repl-status")
            if repl:
                headers["x-amz-replication-status"] = repl
            if info.version_id:
                headers["x-amz-version-id"] = info.version_id
            for mk, mv in info.user_metadata.items():
                headers[f"x-amz-meta-{mk}"] = mv
            ctype = info.content_type or "application/octet-stream"
            status = 206 if spec else 200
            if spec:
                headers["Content-Range"] = \
                    f"bytes {start}-{start + length - 1}/{info.size}"
            try:
                self._defer_head = True
                self.send_response(status)
                self.send_header("x-amz-request-id", "0")
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(length))
                for k2, v2 in headers.items():
                    self.send_header(k2, v2)
                self.end_headers()
                head = self._take_head()
                if method == "HEAD":
                    return self._send_bufs([head], final=True)
                if hot is not None and spec is None \
                        and h.get("x-amz-checksum-mode",
                                  "").upper() != "ENABLED":
                    # A plain whole-object GET's header block is the
                    # canonical response every later hit must replay
                    # byte-identically; checksum-mode requests shape
                    # extra headers, so their head never becomes the
                    # template (their body may still be admitted).
                    if hot_entry is not None:
                        hot.set_head(bucket, key, info.etag,
                                     info.version_id or "", head)
                    elif hot_admit:
                        hot_head = head
                if send_fd is not None:
                    self._sendfile_body(head, send_fd, start, length)
                    if hot_admit and not self.close_connection:
                        # Tier-resident hit went out in-kernel; admit
                        # the same bytes from the already-open fd.
                        try:
                            hbody = os.pread(send_fd, length, start)
                        except OSError:
                            hbody = b""
                        if len(hbody) == length:
                            hot.put(bucket, key, info, hbody, hot_head,
                                    hot_token)
                    return
                sent = 0
                hot_buf = bytearray() if hot_admit else None
                try:
                    # Gathered zero-copy streaming: the header block
                    # rides the FIRST window's sendmsg; every window is
                    # a pooled-buffer memoryview straight from the
                    # engine's readahead (released when the generator
                    # advances) — no Python-level joins or re-buffering.
                    # The LAST window is the response's final write:
                    # under the event loop an EAGAIN remainder there is
                    # handed to the loop instead of blocking the
                    # executor on a slow reader.
                    for chunk in chunks:
                        last = sent + len(chunk) >= length
                        if hot_buf is not None:
                            # Copy BEFORE the send: pooled windows are
                            # recycled when the generator advances.
                            hot_buf += chunk
                        if head is not None:
                            self._send_bufs([head, chunk], final=last)
                            head = None
                        else:
                            self._send_bufs([chunk], final=last)
                        sent += len(chunk)
                        self._sent_bytes = getattr(
                            self, "_sent_bytes", 0) + len(chunk)
                    if head is not None:      # zero-length body
                        self._send_bufs([head], final=True)
                        head = None
                except Exception as exc:  # noqa: BLE001 - headers may be sent
                    if head is not None and \
                            not getattr(exc, "mtpu_sent", 0):
                        # Nothing hit the wire yet (the FIRST window's
                        # produce failed, or its send died before any
                        # byte went out): surface a proper S3 error
                        # instead of a truncated 200. A partially-sent
                        # first window (mtpu_sent > 0) must NOT re-raise
                        # — a second full response after partial 200
                        # bytes is protocol corruption; cut instead.
                        raise
                    # Mid-stream failure (quorum loss, drive death) after
                    # the status line went out: all we can do is cut the
                    # connection short so the client sees a failed
                    # (truncated) transfer, never a silently short 200.
                    sent = -1
                if sent != length:
                    self.close_connection = True
                elif hot_buf is not None:
                    hot.put(bucket, key, info, bytes(hot_buf), hot_head,
                            hot_token)
            finally:
                if chunks is not None:
                    chunks.close()
                if send_fd is not None:
                    os.close(send_fd)

        def _post_object(self, bucket, body, ctype):
            """Browser-form POST-policy upload (reference:
            cmd/post-policy.go PostPolicyBucketHandler): multipart form
            with a base64 policy document signed by the uploader's key;
            the object is the `file` part."""
            import base64
            import email.parser as _ep
            import email.policy as _epol
            import hmac as _hmac
            import json as _json
            import re as _re

            raw = b"Content-Type: " + ctype.encode() + b"\r\n\r\n" + body
            msg = _ep.BytesParser(policy=_epol.default).parsebytes(raw)
            if not msg.is_multipart():
                raise S3Error("MalformedPOSTRequest")
            fields: dict[str, str] = {}
            file_data = None
            file_name = ""
            for part in msg.iter_parts():
                cd = part.get("Content-Disposition", "")
                m = _re.search(r'name="([^"]*)"', cd)
                if not m:
                    continue
                name = m.group(1).lower()
                data = part.get_payload(decode=True) or b""
                if name == "file":
                    file_data = data
                    fm = _re.search(r'filename="([^"]*)"', cd)
                    file_name = fm.group(1) if fm else ""
                    fields.setdefault("content-type",
                                      part.get_content_type())
                else:
                    fields[name] = data.decode("utf-8", "replace")
            if file_data is None:
                raise S3Error("InvalidArgument", "POST form missing file")
            policy_b64 = fields.get("policy", "")
            sig = fields.get("x-amz-signature", "")
            cred_str = fields.get("x-amz-credential", "")
            # A form with no credentials at all is an anonymous upload,
            # authorized purely by bucket policy below (reference:
            # cmd/post-policy.go treats a missing policy as anonymous).
            anonymous = not policy_b64 and not sig and not cred_str
            if anonymous:
                access_key = ""
                pol = {}
            else:
                if not policy_b64 or not sig or not cred_str:
                    raise S3Error("AccessDenied")
                cred = sigv4.Credential.parse(cred_str)
                access_key = cred.access_key
                self._auth_key = access_key   # audit/trace attribution
                secret = server.credentials.secret_for(access_key)
                if secret is None:
                    raise S3Error("InvalidAccessKeyId")
                skey = sigv4.signing_key(secret, cred.date, cred.region,
                                         cred.service)
                want = _hmac.new(skey, policy_b64.encode(),
                                 hashlib.sha256).hexdigest()
                if not _hmac.compare_digest(want, sig):
                    raise S3Error("SignatureDoesNotMatch")
                # STS keys must present their session token in the form
                # (same invariant as header-authorized requests).
                if server.credentials.iam is not None:
                    tok = server.credentials.iam.session_token_for(
                        access_key)
                    if tok is not None and \
                            fields.get("x-amz-security-token", "") != tok:
                        raise S3Error("AccessDenied",
                                      "invalid session token")
                try:
                    pol = _json.loads(base64.b64decode(policy_b64))
                except ValueError:
                    raise S3Error("MalformedPOSTRequest") from None
            exp = pol.get("expiration", "")
            if exp:
                try:
                    exp_dt = datetime.datetime.fromisoformat(
                        exp.replace("Z", "+00:00"))
                    if exp_dt.tzinfo is None:
                        exp_dt = exp_dt.replace(
                            tzinfo=datetime.timezone.utc)
                except (ValueError, TypeError):
                    raise S3Error("MalformedPOSTRequest") from None
                if exp_dt < datetime.datetime.now(datetime.timezone.utc):
                    raise S3Error("AccessDenied", "policy expired")
            key = fields.get("key", "")
            if not key:
                raise S3Error("InvalidArgument", "POST form missing key")
            key = key.replace("${filename}", file_name)
            # Enforce the policy's own conditions (eq / starts-with /
            # content-length-range) against the submitted form.
            form_view = dict(fields)
            form_view["bucket"] = bucket
            form_view["key"] = key
            for cond in pol.get("conditions", []):
                if isinstance(cond, dict):
                    items = [("eq", f"${k}", v) for k, v in cond.items()]
                elif isinstance(cond, list) and len(cond) == 3:
                    items = [tuple(cond)]
                else:
                    continue
                for op, field, val in items:
                    op = str(op).lower()
                    if op == "content-length-range":
                        continue
                    fname = str(field).lstrip("$").lower()
                    got = form_view.get(fname, "")
                    if op == "eq" and got != val:
                        raise S3Error("AccessDenied",
                                      f"policy condition failed: {fname}")
                    if op == "starts-with" and not got.startswith(val):
                        raise S3Error("AccessDenied",
                                      f"policy condition failed: {fname}")
                if isinstance(cond, list) and \
                        str(cond[0]).lower() == "content-length-range":
                    lo, hi = int(cond[1]), int(cond[2])
                    if not lo <= len(file_data) <= hi:
                        raise S3Error("EntityTooLarge"
                                      if len(file_data) > hi
                                      else "EntityTooSmall")
            # Same deny-wins identity + bucket-policy merge as every
            # header-authorized request (was a plain IAM check, which
            # bypassed bucket-policy Deny statements).
            ctx = self._auth_context(access_key, {}, self._headers_lower())
            if not self._authorize(access_key, anonymous, "s3:PutObject",
                                   f"{bucket}/{key}", ctx):
                raise S3Error("AccessDenied", bucket=bucket, key=key)
            meta = {k[len("x-amz-meta-"):]: v for k, v in fields.items()
                    if k.startswith("x-amz-meta-")}
            opts = PutOptions(
                versioned=_versioned(server.object_layer, bucket),
                user_metadata=meta,
                content_type=fields.get("content-type", ""),
                tags=fields.get("tagging", ""))
            # Form fields carry the same x-amz-object-lock-* names as
            # headers; lock metadata and bucket defaults apply equally.
            opts.internal_metadata.update(
                self._object_lock_put_meta(bucket, fields))
            # Bucket default encryption applies to form uploads too
            # (explicit SSE form fields ride the same header names).
            self._check_quota(bucket, len(file_data))
            post_payload, _ = self._apply_sse(
                bucket, key, Payload.wrap(file_data),
                {sse_key: v for sse_key, v in fields.items()
                 if sse_key.startswith("x-amz-server-side-encryption")},
                opts)
            info = server.object_layer.put_object(bucket, key,
                                                  post_payload, opts)
            self._note_quota_write(bucket, len(file_data))
            self._site_enqueue("put", bucket, key, info.version_id)
            self._notify("s3:ObjectCreated:Post", bucket, key,
                         size=len(file_data), etag=info.etag,
                         version_id=info.version_id)
            status = fields.get("success_action_status", "204")
            if status == "201":
                root = ET.Element("PostResponse")
                _el(root, "Location", f"/{bucket}/{key}")
                _el(root, "Bucket", bucket)
                _el(root, "Key", key)
                _el(root, "ETag", f'"{info.etag}"')
                return self._send(201, _xml(root))
            return self._send(200 if status == "200" else 204)

        def _health_ready(self):
            """Readiness: honest about degradation. 503 with a JSON
            body NAMING the degraded sets when any erasure set is below
            write quorum or still bulk-healing a replaced drive —
            orchestrators keep traffic off a node that would fail or
            slow-path writes (reference: ClusterCheckHandler,
            cmd/healthcheck-handler.go, plus the maintenance probe's
            healing awareness)."""
            import json as _json
            sets = _layer_sets(server.object_layer)
            if not sets:
                return self._send(503, _json.dumps(
                    {"ready": False, "reason": "no erasure sets"}
                ).encode(), content_type="application/json")
            probes = _probe_disks(server.object_layer)
            degraded = []
            for si, s in enumerate(sets):
                infos = [di for psi, _, di in probes if psi == si]
                ok = sum(1 for di in infos if di is not None)
                healing = sum(1 for di in infos
                              if di is not None
                              and getattr(di, "healing", False))
                n = len(s.disks)
                parity = getattr(s, "default_parity", 0)
                k = n - parity
                write_quorum = max(k + (1 if k == parity else 0),
                                   n // 2 + (1 if n > 1 else 0))
                if ok < write_quorum or healing:
                    degraded.append({
                        "set": si, "drives_online": ok, "drives": n,
                        "write_quorum": write_quorum,
                        "healing_drives": healing,
                    })
            if degraded:
                return self._send(503, _json.dumps(
                    {"ready": False, "degraded_sets": degraded}
                ).encode(), content_type="application/json")
            return self._send(200, _json.dumps({"ready": True}).encode(),
                              content_type="application/json")

        def _admin_speedtest(self, q1):
            """Self-measured object throughput (reference: `mc admin
            speedtest`, cmd/perf-tests.go): timed PUTs then GETs of
            synthetic objects through the full object layer, cleaned up
            afterwards."""
            import json as _json
            import os as _os
            import time as _time
            try:
                size = int(q1.get("size", str(4 << 20)))
                count = int(q1.get("count", "8"))
            except ValueError:
                raise S3Error("InvalidArgument") from None
            size = max(1 << 10, min(size, 256 << 20))
            count = max(1, min(count, 64))
            ol = server.object_layer
            bucket = "mtpu-speedtest-tmp"
            from minio_tpu.object.types import BucketExists
            try:
                ol.make_bucket(bucket)
            except BucketExists:
                pass        # shared across runs; keys are run-unique
            body = _os.urandom(size)
            run = _os.urandom(6).hex()    # concurrent runs never collide
            keys = [f"obj-{run}-{i}" for i in range(count)]
            try:
                t0 = _time.perf_counter()
                for k2 in keys:
                    ol.put_object(bucket, k2, body, PutOptions())
                put_s = _time.perf_counter() - t0
                t0 = _time.perf_counter()
                for k2 in keys:
                    ol.get_object(bucket, k2)
                get_s = _time.perf_counter() - t0
            finally:
                # Mid-run failures must not strand synthetic data.
                for k2 in keys:
                    try:
                        ol.delete_object(bucket, k2)
                    except Exception:  # noqa: BLE001 - best effort
                        pass
                try:
                    ol.delete_bucket(bucket)
                except Exception:  # noqa: BLE001 - other runs active
                    pass
            total = size * count
            result = {
                "object_size": size,
                "objects": count,
                "put_seconds": round(put_s, 4),
                "get_seconds": round(get_s, 4),
                "put_mibps": round(total / put_s / (1 << 20), 2),
                "get_mibps": round(total / get_s / (1 << 20), 2),
            }
            self._send(200, _json.dumps(result).encode(),
                       content_type="application/json")

        def _cluster_metrics_states(self):
            """Fleet-federated telemetry: the local node's merged
            snapshot (all pre-forked workers, one level down) plus one
            grid `peer.metrics` call per peer node — the same merge
            shape io/workers.py applies to workers, lifted to nodes.
            Down peers yield an `unreachable` stub so the scrape still
            reports them (as minio_tpu_cluster_node_up 0)."""
            from minio_tpu.s3.metrics import peer_metrics_state
            local = peer_metrics_state(server)
            local["local"] = True
            nodes = [local]
            mu = threading.Lock()

            def _fetch(name, client):
                try:
                    st = client.call("peer.metrics", {}, timeout=3)
                    if not isinstance(st, dict):
                        raise ValueError("bad peer snapshot")
                except Exception:  # noqa: BLE001 - peer down
                    st = {"node": name, "states": [],
                          "unreachable": True}
                st.setdefault("node", name)
                with mu:
                    nodes.append(st)

            # Concurrent fan-out: serial calls would stack one timeout
            # per DOWN peer onto every scrape.
            ts = [threading.Thread(target=_fetch, args=(n, c),
                                   daemon=True)
                  for n, c in server.profile_peers]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=4)
            return nodes

        def _admin_trace(self, query):
            """Live trace stream: chunked JSON lines until the client
            disconnects (reference: TraceHandler + pubsub; the `mc
            admin trace` shape). ?count=N stops after N entries;
            ?types=storage,grid,... filters (default `s3` — the
            top-level request records; `all` = every type including
            internal storage/grid/kernel/scanner/heal spans).

            In pre-forked worker mode this request lands on ONE worker
            while requests spread over ALL of them: the handler
            subscribes fleet-wide through the parent control pipe
            (io/workers.py trace pump) instead of its local
            broadcaster, so entries from every sibling stream here.

            ?cluster=true lifts the same merge one level up: the
            subscription fans out over every peer NODE as a grid
            `trace.stream` and the relays funnel into this response,
            so one connection tails the whole deployment (entries
            carry their origin `node`)."""
            import json as _json
            import queue as _queue
            limit = 0
            try:
                limit = int(query.get("count", ["0"])[0] or 0)
            except ValueError:
                pass
            raw = (query.get("types", [""])[0] or "").strip()
            if not raw:
                types = {"s3"}
            elif raw == "all":
                types = set(tracing_mod.TRACE_TYPES)
            else:
                types = {t.strip() for t in raw.split(",") if t.strip()} \
                    & set(tracing_mod.TRACE_TYPES)
                if not types:
                    types = {"s3"}

            relay_q = relay_stop = None
            if server.profile_peers and \
                    (query.get("cluster", [""])[0] or "").lower() in (
                        "true", "1", "yes", "on"):
                relay_q = _queue.Queue(maxsize=4096)
                relay_stop = threading.Event()
                for name, client in server.profile_peers:
                    threading.Thread(
                        target=self._trace_relay,
                        args=(name, client, sorted(types), relay_q,
                              relay_stop),
                        daemon=True).start()

            hub = getattr(server, "cluster_trace", None)
            sub = sub_id = None
            if hub is not None:
                try:
                    sub_id = hub.trace_sub(sorted(types))
                except Exception:  # noqa: BLE001 - control plane down
                    hub = None
            if hub is None:
                sub = server.tracer.subscribe(types)
            try:
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                sent = 0
                idle_since = _time_mod.monotonic()
                while not limit or sent < limit:
                    entries = []
                    if hub is not None:
                        entries = hub.trace_poll(sub_id)
                    else:
                        try:
                            entries = [sub.get(timeout=0.2)]
                        except _queue.Empty:
                            pass
                    if relay_q is not None:
                        try:
                            while len(entries) < 1024:
                                entries.append(relay_q.get_nowait())
                        except _queue.Empty:
                            pass
                    if not entries:
                        if _time_mod.monotonic() - idle_since > 1.0:
                            # Heartbeat chunk: on an idle server this
                            # is the only way a disconnected client
                            # surfaces (EPIPE) — without it the thread
                            # and subscriptions leak.
                            self.wfile.write(b"1\r\n\n\r\n")
                            self.wfile.flush()
                            idle_since = _time_mod.monotonic()
                        if hub is not None:
                            _time_mod.sleep(0.2)
                        continue
                    idle_since = _time_mod.monotonic()
                    for entry in entries:
                        line = _json.dumps(entry).encode() + b"\n"
                        self.wfile.write(b"%x\r\n" % len(line) + line
                                         + b"\r\n")
                        sent += 1
                        if limit and sent >= limit:
                            break
                    self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass        # client went away
            finally:
                if relay_stop is not None:
                    relay_stop.set()
                if hub is not None:
                    try:
                        hub.trace_unsub(sub_id)
                    except Exception:  # noqa: BLE001 - best effort
                        pass
                else:
                    server.tracer.unsubscribe(sub)
                self.close_connection = True

        def _trace_relay(self, name, client, types, out_q, stop):
            """?cluster=true peer relay: one grid trace.stream per peer
            node, batches funneled into the merge queue. Dies with its
            stream on peer failure — the merged response keeps serving
            the surviving nodes. Backpressure drops (full queue) are
            acceptable for a diagnostics tail."""
            try:
                for batch in client.stream("trace.stream",
                                           {"types": types},
                                           timeout=10.0):
                    if stop.is_set():
                        break
                    for entry in batch or []:
                        if isinstance(entry, dict):
                            entry.setdefault("node", name)
                        try:
                            out_q.put_nowait(entry)
                        except _queue_mod.Full:
                            pass
            except Exception:  # noqa: BLE001 - peer gone / stream cut
                pass

        def _admin_info(self):
            import json as _json
            info = node_info(server)
            # Cluster view: each peer contributes its own node summary
            # over the grid (reference: cmd/notification.go ServerInfo
            # fan-out) — admin info reports the whole deployment, not
            # just the node that answered the HTTP call.
            if server.profile_peers:
                nodes = {"local": dict(info)}

                def _fetch(name, client):
                    try:
                        nodes[name] = client.call("peer.info", {},
                                                  timeout=3)
                    except Exception:  # noqa: BLE001 - peer down
                        nodes[name] = {"mode": "offline"}

                # Concurrent fan-out: serial calls would stack one
                # timeout per DOWN peer onto every info request.
                ts = [threading.Thread(target=_fetch, args=(n, c),
                                       daemon=True)
                      for n, c in server.profile_peers]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=4)
                info["nodes"] = nodes
                info["nodes_online"] = sum(
                    1 for n in nodes.values()
                    if n.get("mode") == "online")
                info["nodes_offline"] = len(nodes) - info["nodes_online"]
            self._send(200, _json.dumps(info).encode(),
                       content_type="application/json")

        def _admin_heal(self, query):
            """Trigger a global heal sweep in the background; poll with
            GET heal (reference: cmd/admin-heal-ops.go heal sequences)."""
            import json as _json
            deep = query.get("deep", [""])[0] in ("true", "1")

            def run():
                from minio_tpu.object.scanner import heal_set
                total = {"buckets": 0, "objects": 0, "healed": 0,
                         "failures": 0}
                try:
                    for s in _layer_sets(server.object_layer):
                        r = heal_set(s, deep=deep)
                        for k2 in total:
                            total[k2] += r.get(k2, 0)
                    server.heal_status = {"state": "done", **total}
                except Exception as e:  # noqa: BLE001 - surfaced in status
                    server.heal_status = {"state": "failed",
                                          "error": str(e)[:300]}

            with server._heal_lock:
                if server._heal_thread is None or \
                        not server._heal_thread.is_alive():
                    server.heal_status = {"state": "running", "deep": deep}
                    server._heal_thread = threading.Thread(target=run,
                                                           daemon=True)
                    server._heal_thread.start()
            return self._send(200, _json.dumps(
                self._heal_payload()).encode(),
                content_type="application/json")

        def _heal_payload(self):
            """Admin heal status: the sweep slot plus, when the drive
            lifecycle manager is wired, per-drive bulk-heal progress
            (scanned/healed/failed/bytes/ETA + checkpoint). In
            pre-forked mode the bulk heal lives in worker 0 while this
            request may land on any worker, so the fleet's snapshots
            are merged when the control plane is up."""
            payload = dict(server.heal_status)
            merged = None
            if server.cluster_stats is not None:
                try:
                    agg = {"formats_restored": 0, "drives": []}
                    found = False
                    for p in server.cluster_stats():
                        pst = p.get("drive_heal")
                        if isinstance(pst, dict):
                            found = True
                            agg["formats_restored"] += \
                                pst.get("formats_restored", 0)
                            agg["drives"].extend(pst.get("drives", []))
                    if found:
                        merged = agg
                except Exception:  # noqa: BLE001 - control plane down
                    merged = None
            if merged is None and server.drive_heal is not None:
                try:
                    merged = server.drive_heal.status()
                except Exception:  # noqa: BLE001 - status best effort
                    merged = None
            if merged is not None:
                payload["drive_heal"] = merged
            return payload

        # -- admin API (/minio/admin/v3/...) ---------------------------

        def _admin_op(self, method, raw_path, query, auth):
            """IAM management endpoints, root-only (reference:
            cmd/admin-handlers-users.go; bodies are plain JSON rather
            than the reference's madmin-encrypted payloads)."""
            import json as _json
            ak = auth.credential.access_key
            if not server.credentials.is_allowed(ak, "admin:*", "*"):
                raise S3Error("AccessDenied")
            op = raw_path[len("/minio/admin/v3/"):] \
                if raw_path.startswith("/minio/admin/v3/") else ""
            if op == "info" and method == "GET":
                return self._admin_info()
            if op == "trace" and method == "GET":
                return self._admin_trace(query)
            if op == "heal" and method == "POST":
                return self._admin_heal(query)
            if op == "heal" and method == "GET":
                return self._send(200,
                                  _json.dumps(self._heal_payload()).encode(),
                                  content_type="application/json")
            body = self._read_body()
            q1 = {k: v[0] for k, v in query.items()}

            def ok(payload=None):
                blob = _json.dumps(payload).encode() \
                    if payload is not None else b""
                self._send(200, blob, content_type="application/json")

            if op == "speedtest" and method == "POST":
                return self._admin_speedtest(q1)

            # Config subsystem: persisted KV with hot apply (reference:
            # admin SetConfigKV/GetConfigKV over internal/config).
            if op == "get-config" and method == "GET":
                from minio_tpu.s3 import config as cfg_mod
                return ok(cfg_mod.load_config(server.object_layer))
            if op == "set-config" and method == "PUT":
                from minio_tpu.s3 import config as cfg_mod
                try:
                    updates = _json.loads(body)
                    if not isinstance(updates, dict):
                        raise ValueError("config must be an object")
                    cfg_mod.validate(updates)
                except ValueError as e:
                    raise S3Error("InvalidArgument", str(e)) from None
                except cfg_mod.ConfigError as e:
                    raise S3Error("InvalidArgument", str(e)) from None
                try:
                    # Lock the read-modify-write so two concurrent
                    # set-configs cannot drop each other's keys. Hot
                    # apply reaches THIS node; peers reload over the
                    # control plane (TTL/reboot as the fallback).
                    with server.bucket_meta_lock:
                        prev = cfg_mod.load_config(server.object_layer)
                        cfg = dict(prev)
                        cfg.update(updates)
                        cfg_mod.save_config(server.object_layer, cfg,
                                            prev=prev)
                except cfg_mod.ConfigError as e:
                    # Persistence failure is a SERVICE error, not a bad
                    # request.
                    raise S3Error("InternalError", str(e)) from None
                # Apply only what THIS request changed.
                applied = cfg_mod.apply_config(server, updates)
                if server.peer_notify is not None:
                    server.peer_notify("config")
                return ok({"applied": applied})

            # Site replication (reference: cmd/site-replication.go).
            if op in ("site-replication-add", "site-replication-info",
                      "site-replication-remove",
                      "site-import-bucket-meta", "site-import-iam"):
                from minio_tpu.replication.site import (SiteError,
                                                        SiteReplicator,
                                                        hook_iam_changes)
                try:
                    if op == "site-replication-add" and method == "POST":
                        cfg = SiteReplicator.validate(_json.loads(body))
                        new_site = SiteReplicator(
                            server.object_layer, self._layer_sets(), cfg,
                            iam=server.credentials.iam)
                        try:
                            # Persist BEFORE arming: a failed save must
                            # not leave an active replicator running a
                            # config a restart will silently drop.
                            new_site.save()
                        except SiteError:
                            new_site.stop()
                            raise
                        if server.site is not None:
                            server.site.stop()
                        server.site = new_site
                        hook_iam_changes(server)
                        server.site.bootstrap()
                        return ok()
                    if op == "site-replication-info" and method == "GET":
                        return ok(server.site.info()
                                  if server.site else None)
                    if op == "site-replication-remove" and \
                            method == "POST":
                        if server.site is not None:
                            # Persist the removal BEFORE stopping: if
                            # the save fails quorum, the replicator
                            # keeps running its (intact) config rather
                            # than leaving a dead replicator armed and
                            # an on-disk config that re-arms at boot.
                            old_cfg = dict(server.site.config)
                            server.site.config = {"peers": []}
                            try:
                                server.site.save()
                            except SiteError:
                                server.site.config = old_cfg
                                raise
                            server.site.stop()
                            server.site = None
                        return ok()
                    if op == "site-import-bucket-meta" and method == "PUT":
                        # Receiving side of a peer's bucket-meta push:
                        # applied directly (never re-broadcast).
                        bkt = q1.get("bucket", "")
                        meta = _json.loads(body)
                        if not isinstance(meta, dict):
                            raise S3Error("InvalidArgument", "bad meta")
                        from minio_tpu.object.types import BucketExists
                        try:
                            server.object_layer.make_bucket(bkt)
                        except BucketExists:
                            pass
                        with server.bucket_meta_lock:
                            server.object_layer.set_bucket_meta(bkt, meta)
                        return ok()
                    if op == "site-import-iam" and method == "PUT":
                        # Receiving side of a peer's IAM mirror: applied
                        # directly; import_doc never fires on_change, so
                        # the change cannot ping-pong back.
                        doc = _json.loads(body)
                        if not isinstance(doc, dict):
                            raise S3Error("InvalidArgument", "bad doc")
                        iam = server.credentials.iam
                        if iam is None:
                            raise S3Error("NotImplemented",
                                          "no IAM store")
                        iam.import_doc(doc)
                        return ok()
                except SiteError as e:
                    raise S3Error("InvalidArgument", str(e)) from None
                except ValueError:
                    raise S3Error("MalformedXML") from None
                raise S3Error("MethodNotAllowed")

            # Batch jobs (reference: cmd/batch-handlers.go).
            if op in ("start-batch-job", "batch-job-status",
                      "list-batch-jobs", "cancel-batch-job"):
                from minio_tpu.object.batch import BatchError
                mgr = self._batch_jobs()
                try:
                    if op == "start-batch-job" and method == "POST":
                        return ok({"id": mgr.start(_json.loads(body))})
                    if op == "batch-job-status" and method == "GET":
                        st2 = mgr.status(q1.get("id", ""))
                        if st2 is None:
                            raise S3Error("InvalidArgument",
                                          "no such job")
                        return ok(st2)
                    if op == "list-batch-jobs" and method == "GET":
                        return ok(mgr.list_jobs())
                    if op == "cancel-batch-job" and method == "POST":
                        mgr.cancel(q1.get("id", ""))
                        return ok()
                except BatchError as e:
                    raise S3Error("InvalidArgument", str(e)) from None
                except ValueError:
                    raise S3Error("MalformedXML") from None
                raise S3Error("MethodNotAllowed")

            # Warm-tier management (reference: cmd/admin-handlers-tiers).
            if op in ("add-tier", "remove-tier", "list-tiers"):
                from minio_tpu.object.tier import TierError
                reg = self._tier_registry()
                try:
                    if op == "add-tier" and method == "PUT":
                        doc = _json.loads(body)
                        reg.add(doc.get("name", ""), doc.get("config", {}))
                        return ok()
                    if op == "remove-tier" and method == "DELETE":
                        name = q1.get("name", "")
                        # In-use guard: a lifecycle rule referencing
                        # the tier means transitions (and transitioned
                        # versions) depend on it; removal would make
                        # their data unreachable in one call. Parsed,
                        # not substring-matched — namespaced or
                        # whitespace-styled XML must not slip past.
                        from minio_tpu.object.lifecycle import (
                            LifecycleError, parse_lifecycle)
                        for bi in server.object_layer.list_buckets():
                            doc = server.object_layer.get_bucket_meta(
                                bi.name).get("config:lifecycle", "")
                            if not doc:
                                continue
                            try:
                                rules = parse_lifecycle(doc)
                            except LifecycleError:
                                continue
                            if any(name in (r.transition_tier,
                                            r.noncurrent_transition_tier)
                                   for r in rules):
                                raise S3Error(
                                    "InvalidArgument",
                                    f"tier {name!r} is referenced by "
                                    f"bucket {bi.name!r}'s lifecycle")
                        reg.remove(name)
                        return ok()
                    if op == "list-tiers" and method == "GET":
                        return ok(reg.list())
                except TierError as e:
                    raise S3Error("InvalidArgument", str(e)) from None
                except ValueError:
                    raise S3Error("MalformedXML") from None
                raise S3Error("MethodNotAllowed")

            # Pool decommission / rebalance admin verbs — served from
            # ANY node (reference: cmd/admin-handlers-pools.go).
            # Starts work everywhere because the checkpoint doc lives
            # on cluster-readable drives and the dsync coordinator
            # lease keeps a single driver; status fans IN a live
            # coordinator's counters (fresher than the checkpoint);
            # stop fans OUT so it reaches whichever node drives the
            # walk (grid elastic.status/elastic.stop, wired at boot).
            def _elastic_live_peer(kind):
                for _n, cli in getattr(server, "profile_peers",
                                       None) or []:
                    try:
                        r = cli.call("elastic.status", None, timeout=3.0)
                    except Exception:  # noqa: BLE001 - peer down
                        continue
                    if isinstance(r, dict) and r.get(f"{kind}_live") \
                            and r.get(kind):
                        # At most one live driver exists (the lease),
                        # so the first live answer is THE coordinator.
                        return r[kind]
                return None

            def _elastic_stop_peers(kind):
                for _n, cli in getattr(server, "profile_peers",
                                       None) or []:
                    try:
                        cli.call("elastic.stop", {"kind": kind},
                                 timeout=5.0)
                    except Exception:  # noqa: BLE001 - peer down
                        continue

            if op == "decommission" and method == "POST":
                ol = server.object_layer
                if not hasattr(ol, "start_decommission"):
                    raise S3Error("NotImplemented", "single-pool layout")
                from minio_tpu.object.decom import DecomError
                try:
                    ol.start_decommission(int(q1.get("pool", "-1")))
                except (DecomError, ValueError) as e:
                    raise S3Error("InvalidArgument", str(e)) from None
                return ok()
            if op == "decommission-status" and method == "GET":
                ol = server.object_layer
                fn = getattr(ol, "decommission_status", None)
                st = fn() if fn else None
                d = getattr(ol, "_decom", None)
                if d is None or d.wait(timeout=0):
                    peer = _elastic_live_peer("decommission")
                    if peer is not None:
                        st = peer
                return ok(st)
            if op == "decommission-cancel" and method == "POST":
                fn = getattr(server.object_layer, "cancel_decommission",
                             None)
                if fn:
                    fn()
                _elastic_stop_peers("decommission")
                return ok()

            if op == "rebalance-start" and method == "POST":
                ol = server.object_layer
                if not hasattr(ol, "start_rebalance"):
                    raise S3Error("NotImplemented", "single-pool layout")
                from minio_tpu.object.rebalance import (LeaseHeld,
                                                        RebalanceError)
                try:
                    ol.start_rebalance()
                except (LeaseHeld, RebalanceError) as e:
                    raise S3Error("InvalidArgument", str(e)) from None
                return ok()
            if op == "rebalance-status" and method == "GET":
                ol = server.object_layer
                fn = getattr(ol, "rebalance_status", None)
                st = fn() if fn else None
                rb = getattr(ol, "_rebalance", None)
                if rb is None or rb.wait(timeout=0):
                    peer = _elastic_live_peer("rebalance")
                    if peer is not None:
                        st = peer
                return ok(st)
            if op == "rebalance-stop" and method == "POST":
                fn = getattr(server.object_layer, "stop_rebalance", None)
                if fn:
                    fn()
                _elastic_stop_peers("rebalance")
                return ok()

            # KMS key management (reference: cmd/kms-handlers.go
            # KMSCreateKey / KMSListKeys / KMSKeyStatus).
            if op in ("kms-key-create", "kms-key-list", "kms-key-status"):
                from minio_tpu.crypto.kms import KeyStore, KMSError
                try:
                    ks = getattr(server, "_kms_keystore", None)
                    if ks is None:
                        disks = [d for s in self._layer_sets()
                                 for d in s.disks]
                        ks = server._kms_keystore = KeyStore(
                            server.kms, disks)
                    ks.reload()
                    if op == "kms-key-create" and method == "POST":
                        ks.create(q1.get("key-id", ""))
                        return ok()
                    if op == "kms-key-list" and method == "GET":
                        return ok(ks.list())
                    if op == "kms-key-status" and method == "GET":
                        return ok(ks.status(q1.get("key-id", "")))
                except KMSError as e:
                    raise S3Error("InvalidRequest", str(e)) from None
                raise S3Error("MethodNotAllowed")

            # Profiling (reference: cmd/admin-handlers.go:1021
            # StartProfilingHandler / DownloadProfilingDataHandler).
            if op == "start-profiling" and method == "POST":
                from minio_tpu.s3.profiling import ProfileError
                try:
                    server.profiler.start()
                except ProfileError as e:
                    raise S3Error("InvalidRequest", str(e)) from None
                for _name, client in server.profile_peers:
                    try:
                        client.call("peer.profile", {"action": "start"},
                                    timeout=5)
                    except Exception:  # noqa: BLE001 - peer down
                        pass
                return ok({"started": True})
            if op == "download-profiling" and method == "GET":
                import base64 as _b64

                from minio_tpu.s3 import profiling as prof_mod
                from minio_tpu.s3.profiling import ProfileError
                per_node = {}
                try:
                    per_node["local"] = server.profiler.stop()
                except ProfileError as e:
                    raise S3Error("InvalidRequest", str(e)) from None
                for name, client in server.profile_peers:
                    try:
                        rec = client.call("peer.profile",
                                          {"action": "stop"}, timeout=10)
                        if rec.get("ok"):
                            per_node[name] = {
                                "stats": _b64.b64decode(
                                    rec.get("stats_b64", "")),
                                "text": rec.get("text", "")}
                    except Exception:  # noqa: BLE001 - peer down
                        pass
                return self._send(200, prof_mod.bundle(per_node),
                                  content_type="application/zip")

            # Bucket quotas (reference: cmd/admin-bucket-handlers.go
            # SetBucketQuotaConfigHandler / GetBucketQuotaConfigHandler,
            # enforced by cmd/bucket-quota.go).
            if op == "set-bucket-quota" and method == "PUT":
                bkt = q1.get("bucket", "")
                server.object_layer.get_bucket_info(bkt)
                try:
                    cfg = _json.loads(body) if body else {}
                    quota = int(cfg.get("quota") or 0)
                except (ValueError, TypeError):
                    raise S3Error("InvalidArgument",
                                  "malformed quota configuration") \
                        from None
                if quota < 0:
                    raise S3Error("InvalidArgument",
                                  "quota must be non-negative")
                with server.bucket_meta_lock:
                    meta = server.object_layer.get_bucket_meta(bkt)
                    if quota == 0:
                        meta.pop("config:quota", None)
                    else:
                        meta["config:quota"] = _json.dumps(
                            {"quota": quota,
                             "quotatype": cfg.get("quotatype", "hard")})
                    server.object_layer.set_bucket_meta(bkt, meta)
                return ok()
            if op == "get-bucket-quota" and method == "GET":
                bkt = q1.get("bucket", "")
                server.object_layer.get_bucket_info(bkt)
                raw = server.object_layer.get_bucket_meta(bkt) \
                    .get("config:quota")
                if not raw:
                    raise S3Error("XMinioAdminNoSuchQuotaConfiguration",
                                  bucket=bkt)
                return ok(_json.loads(raw) if isinstance(raw, str)
                          else raw)

            # Replication target management needs no IAM store.
            if op == "set-remote-target" and method == "PUT":
                doc = _json.loads(body)
                for field in ("endpoint", "accessKey", "secretKey"):
                    if not doc.get(field):
                        raise S3Error("InvalidArgument",
                                      f"missing {field}")
                bkt = q1.get("bucket", "")
                server.object_layer.get_bucket_info(bkt)
                with server.bucket_meta_lock:
                    meta = server.object_layer.get_bucket_meta(bkt)
                    meta["config:remote-target"] = _json.dumps(doc)
                    server.object_layer.set_bucket_meta(bkt, meta)
                return ok()
            if op == "get-remote-target" and method == "GET":
                bkt = q1.get("bucket", "")
                doc = server.object_layer.get_bucket_meta(bkt) \
                    .get("config:remote-target")
                rec = _json.loads(doc) if doc else None
                if rec:
                    rec.pop("secretKey", None)   # never echo secrets
                return ok(rec)
            if op == "replication-status" and method == "GET":
                r = server.replicator
                if r is None:
                    return ok(None)
                # Keep the v1 keys at top level; the full stats dict
                # (lanes, WAL, spill, lag) rides alongside them.
                doc = r.stats() if hasattr(r, "stats") else \
                    {"queued": r.queued, "completed": r.completed,
                     "failed": r.failed}
                return ok(doc)
            if op == "replication-resync" and method == "POST":
                r = server.replicator
                if r is None or not hasattr(r, "start_resync"):
                    raise S3Error("NotImplemented")
                bkt = q1.get("bucket", "")
                server.object_layer.get_bucket_info(bkt)
                return ok(r.start_resync(bkt))
            if op == "replication-resync" and method == "GET":
                r = server.replicator
                if r is None or not hasattr(r, "resync_status"):
                    raise S3Error("NotImplemented")
                return ok(r.resync_status(q1.get("bucket") or None))

            iam = server.credentials.iam
            if iam is None:
                raise S3Error("NotImplemented")

            try:
                if op == "add-user" and method == "PUT":
                    doc = _json.loads(body)
                    iam.add_user(q1.get("accessKey", ""),
                                 doc.get("secretKey", ""))
                    return ok()
                if op == "remove-user" and method == "DELETE":
                    iam.remove_user(q1.get("accessKey", ""))
                    return ok()
                if op == "list-users" and method == "GET":
                    return ok(iam.list_users())
                if op == "set-user-status" and method == "PUT":
                    iam.set_user_status(q1.get("accessKey", ""),
                                        q1.get("status", "") == "enabled")
                    return ok()
                if op == "add-canned-policy" and method == "PUT":
                    iam.set_policy(q1.get("name", ""), _json.loads(body))
                    return ok()
                if op == "remove-canned-policy" and method == "DELETE":
                    iam.delete_policy(q1.get("name", ""))
                    return ok()
                if op == "list-canned-policies" and method == "GET":
                    return ok(iam.list_policies())
                if op == "set-user-or-group-policy" and method == "PUT":
                    names = [n for n in
                             q1.get("policyName", "").split(",") if n]
                    iam.attach_policy(q1.get("userOrGroup", ""), names)
                    return ok()
                if op == "add-service-account" and method == "PUT":
                    doc = _json.loads(body)
                    iam.add_service_account(
                        doc.get("parent", server.credentials.access_key),
                        doc.get("accessKey", ""), doc.get("secretKey", ""),
                        doc.get("policy"))
                    return ok()
                if op == "update-group-members" and method == "PUT":
                    doc = _json.loads(body)
                    iam.update_group_members(
                        doc.get("group", ""),
                        list(doc.get("members") or []),
                        remove=bool(doc.get("remove")))
                    return ok()
                if op == "remove-group" and method == "DELETE":
                    iam.remove_group(q1.get("group", ""))
                    return ok()
                if op == "list-groups" and method == "GET":
                    return ok(iam.list_groups())
            except ValueError:
                raise S3Error("MalformedXML") from None
            except Exception as e:
                from minio_tpu.iam import IAMError
                if isinstance(e, IAMError):
                    raise S3Error("InvalidArgument", str(e)) from None
                raise
            raise S3Error("MethodNotAllowed")

        def _delete_object(self, bucket, key, query):
            vid = query.get("versionId", [""])[0]
            h = self._headers_lower()
            self._check_version_deletable(bucket, key, vid, h)
            state = _versioning_state(server.object_layer, bucket)
            # Only versionless deletes (which create markers) replicate;
            # pruning ONE old version must never destroy the replica's
            # live object (DeleteMarkerReplication semantics).  Deletes
            # arriving FROM a peer carry the replica marker header and
            # never re-replicate — an active-active pair would
            # otherwise ping-pong markers forever.
            replicate = (server.replicator is not None and not vid
                         and "x-amz-meta-mtpu-replica" not in h
                         and server.replicator.should_replicate(
                             bucket, key, delete=True))
            opts = DeleteOptions(
                version_id=vid,
                versioned=state == "Enabled",
                null_marker=state == "Suspended" and not vid)
            if not vid and opts.versioned \
                    and "x-amz-meta-mtpu-replica" in h:
                # Replicated delete: mint the marker with the SOURCE
                # marker's version id so active-active peers hold the
                # same marker version (re-delivery replaces in place
                # instead of stacking a second marker).  Only honored
                # on replica traffic, only for uuid-shaped ids — a
                # suspended source sends "null", which the target's own
                # versioning state governs instead.
                import uuid as _uuid
                from minio_tpu.replication.common import H_REPLICA_DM
                dmv = h.get(H_REPLICA_DM, "")
                if dmv and dmv != "null":
                    try:
                        _uuid.UUID(dmv)
                        opts.marker_version_id = dmv
                    except ValueError:
                        pass
            if replicate and (opts.versioned or opts.null_marker):
                # Stamp the marker PENDING at creation: the status
                # commits with the marker's quorum write, so a crash
                # before the enqueue still leaves the scanner a
                # resyncable trail.
                from minio_tpu.replication import REPL_STATUS_KEY
                opts.marker_metadata = {REPL_STATUS_KEY: "PENDING"}
            deleted = server.object_layer.delete_object(bucket, key, opts)
            if replicate:
                server.replicator.enqueue(
                    bucket, key,
                    deleted.delete_marker_version_id
                    if deleted.delete_marker else "",
                    op="delete", mod_time=_time_mod.time_ns())
            if not vid:
                self._site_enqueue("delete", bucket, key)
            self._notify("s3:ObjectRemoved:DeleteMarkerCreated"
                         if deleted.delete_marker
                         else "s3:ObjectRemoved:Delete", bucket, key,
                         version_id=deleted.delete_marker_version_id
                         if deleted.delete_marker else vid)
            headers = {}
            if deleted.delete_marker:
                headers["x-amz-delete-marker"] = "true"
                headers["x-amz-version-id"] = deleted.delete_marker_version_id
            elif vid:
                headers["x-amz-version-id"] = vid
            self._send(204, headers=headers)

    return Handler


def _parse_tagging_xml(body: bytes) -> str:
    """<Tagging><TagSet><Tag><Key>..</Key><Value>..</Value> -> URL-encoded
    tag string; validates count and uniqueness (reference:
    internal/bucket/object/tags)."""
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise S3Error("MalformedXML") from None
    ns = f"{{{XMLNS}}}"
    tags = []
    tagset = root.find(f"{ns}TagSet")
    if tagset is None:
        tagset = root.find("TagSet")
    if tagset is None:
        raise S3Error("MalformedXML")
    for te in list(tagset.findall(f"{ns}Tag")) + list(tagset.findall("Tag")):
        k = te.findtext(f"{ns}Key") or te.findtext("Key") or ""
        v = te.findtext(f"{ns}Value") or te.findtext("Value") or ""
        if not k or len(k) > 128 or len(v) > 256:
            raise S3Error("InvalidTag")
        tags.append((k, v))
    if len(tags) > 10:
        raise S3Error("InvalidTag", "too many tags")
    if len({k for k, _ in tags}) != len(tags):
        raise S3Error("InvalidTag", "duplicate tag key")
    return urllib.parse.urlencode(tags)


def _required_permissions(method: str, bucket: str, key: str, query: dict,
                          h: dict) -> list[tuple[str, str]]:
    """Map one S3 request to the (action, resource) pairs it needs
    (reference: cmd/api-router.go handler -> policy.Action wiring).
    Resources are `bucket` / `bucket/key` (ARN prefix already stripped,
    matching iam.policy's compiled patterns)."""
    if not bucket:
        return [("s3:ListAllMyBuckets", "*")] if method == "GET" else []
    perms: list[tuple[str, str]] = []
    if key and method == "PUT" and "x-amz-copy-source" in h:
        src = urllib.parse.unquote(h["x-amz-copy-source"]).lstrip("/")
        src = src.partition("?versionId=")[0]
        perms.append(("s3:GetObject", src))
    _CONFIG_ACTIONS = {
        "policy": "BucketPolicy", "lifecycle": "LifecycleConfiguration",
        "tagging": "BucketTagging", "cors": "BucketCORS",
        "encryption": "EncryptionConfiguration",
        "notification": "BucketNotification",
        "replication": "ReplicationConfiguration",
    }
    if not key:
        for q, stem in _CONFIG_ACTIONS.items():
            if q in query:
                verb = {"GET": "Get", "HEAD": "Get", "PUT": "Put",
                        "DELETE": "Delete"}.get(method, "Get")
                perms.append((f"s3:{verb}{stem}", bucket))
                return perms
        if "object-lock" in query:
            verb = "Put" if method == "PUT" else "Get"
            return [(f"s3:{verb}BucketObjectLockConfiguration", bucket)]
        if "acl" in query:
            verb = "Put" if method == "PUT" else "Get"
            return [(f"s3:{verb}BucketAcl", bucket)]
        if method == "PUT":
            perms.append(("s3:PutBucketVersioning", bucket)
                         if "versioning" in query
                         else ("s3:CreateBucket", bucket))
        elif method == "DELETE":
            perms.append(("s3:DeleteBucket", bucket))
        elif method == "HEAD":
            perms.append(("s3:ListBucket", bucket))
        elif method == "POST" and "delete" in query:
            perms.append(("s3:DeleteObject", f"{bucket}/*"))
        elif method == "GET":
            if "uploads" in query:
                perms.append(("s3:ListBucketMultipartUploads", bucket))
            elif "versioning" in query:
                perms.append(("s3:GetBucketVersioning", bucket))
            elif "versions" in query:
                perms.append(("s3:ListBucketVersions", bucket))
            elif "location" in query:
                perms.append(("s3:GetBucketLocation", bucket))
            else:
                perms.append(("s3:ListBucket", bucket))
        return perms
    res = f"{bucket}/{key}"
    if method == "POST" and "select" in query:
        return [("s3:GetObjectVersion" if query.get("versionId", [""])[0]
                 else "s3:GetObject", res)]
    if "tagging" in query:
        verb = {"GET": "Get", "PUT": "Put", "DELETE": "Delete"}.get(
            method, "Get")
        perms.append((f"s3:{verb}ObjectTagging", res))
        return perms
    if "acl" in query:
        verb = "Put" if method == "PUT" else "Get"
        return [(f"s3:{verb}ObjectAcl", res)]
    if "attributes" in query and method == "GET":
        # Attribute reads are data-class access; gating on the broad
        # GetObject(Version) keeps canned readonly policies working.
        return [("s3:GetObjectVersion"
                 if query.get("versionId", [""])[0] else "s3:GetObject",
                 res)]
    if "retention" in query:
        verb = "Put" if method == "PUT" else "Get"
        return [(f"s3:{verb}ObjectRetention", res)]
    if "legal-hold" in query:
        verb = "Put" if method == "PUT" else "Get"
        return [(f"s3:{verb}ObjectLegalHold", res)]
    if method in ("GET", "HEAD"):
        if "uploadId" in query:
            perms.append(("s3:ListMultipartUploadParts", res))
        elif query.get("versionId", [""])[0]:
            perms.append(("s3:GetObjectVersion", res))
        else:
            perms.append(("s3:GetObject", res))
    elif method == "PUT":
        perms.append(("s3:PutObject", res))
    elif method == "DELETE":
        perms.append(("s3:AbortMultipartUpload", res)
                     if "uploadId" in query else ("s3:DeleteObject", res))
    elif method == "POST":
        perms.append(("s3:PutObject", res))
    return perms


def _b64e(s: str) -> str:
    import base64
    return base64.urlsafe_b64encode(s.encode()).decode()


def _b64d(s: str) -> str:
    import base64
    try:
        return base64.urlsafe_b64decode(s.encode()).decode()
    except Exception:
        raise S3Error("InvalidArgument", "bad continuation token") from None


def _versioned(ol, bucket: str) -> bool:
    fn = getattr(ol, "bucket_versioning", None)
    return bool(fn(bucket)) if fn else False


def _versioning_state(ol, bucket: str) -> str:
    """"" (never enabled) | "Enabled" | "Suspended" — the reference
    keeps Suspended as a REAL state (internal/bucket/versioning/
    versioning.go:36,76): suspended buckets write null-versionId
    objects replacing the previous null version while Enabled-era
    versions survive."""
    meta = getattr(ol, "get_bucket_meta", lambda b: {})(bucket)
    if meta.get("versioning"):
        return "Enabled"
    if meta.get("versioning-suspended"):
        return "Suspended"
    return ""


def _range_spec(rng: str):
    """Range header -> (start|None, end|None) spec, or None if absent."""
    if not rng:
        return None
    if not rng.startswith("bytes="):
        raise S3Error("InvalidArgument")
    spec = rng[len("bytes="):]
    if "," in spec:
        raise S3Error("NotImplemented", "multiple ranges")
    lo, _, hi = spec.partition("-")
    try:
        if lo == "":
            return (None, int(hi))
        return (int(lo), int(hi) if hi else None)
    except ValueError:
        raise S3Error("InvalidArgument") from None


def _resolve_head_range(spec, size: int):
    from minio_tpu.object.erasure_object import _resolve_range
    return _resolve_range(spec, size, "", "")


def _validate_bucket_name(name: str) -> None:
    import re
    if not (3 <= len(name) <= 63) or \
            not re.fullmatch(r"[a-z0-9][a-z0-9.-]*[a-z0-9]", name):
        raise S3Error("InvalidBucketName", bucket=name)


def _validate_object_name(key: str) -> None:
    if not key or len(key.encode()) > 1024 or "\x00" in key:
        raise S3Error("InvalidObjectName", key=key)
    for seg in key.split("/"):
        # Empty segments ("a//b", trailing "/") would alias to a different
        # key after path normalization on disk — reject them.
        if seg in ("", ".", ".."):
            raise S3Error("InvalidObjectName", key=key)
