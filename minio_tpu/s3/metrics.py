"""Prometheus-style metrics registry for the S3 server.

The observability analogue of the reference's metrics subsystem
(cmd/metrics-v3.go): per-API request counts/latencies/bytes, object and
capacity gauges fed by the scanner, drive online state, heal counters —
rendered in Prometheus text exposition format at
/minio/v2/metrics/cluster (cmd/metrics-router.go).
"""

from __future__ import annotations

import os
import threading
import time

from minio_tpu.utils import tracing as _tracing
from minio_tpu.utils.latency import Histogram, LastMinute, summarize


class Metrics:
    def __init__(self):
        self._mu = threading.Lock()
        self._requests: dict[tuple[str, str], int] = {}
        self._latency_sum: dict[str, float] = {}
        self._latency_count: dict[str, int] = {}
        # Bucketed + rolling latency per API: the sum/count pair above
        # answers "average since boot"; the histogram answers
        # percentiles-over-all-time and the last-minute ring answers
        # "is THIS api slow right now" (reference: metrics-v3
        # histograms + cmd/last-minute.gen.go windows).
        self._latency_hist: dict[str, Histogram] = {}
        self._last_minute: dict[str, LastMinute] = {}
        self._bytes_rx = 0
        self._bytes_tx = 0
        # Connection plane (serve hot loop, s3/hotloop.py): open
        # connections, keep-alive reuse, and native-framer fallbacks to
        # the Python parser.
        self._conn_active = 0
        self._keepalive_reuses = 0
        self._parse_fallbacks = 0
        # Response-path split (event-loop connection plane): sendfile
        # short-circuit / pooled gathered sendmsg / legacy wfile.
        self._response_path = {"sendfile": 0, "pooled": 0, "legacy": 0}
        self._start = time.time()

    def record(self, api: str, status: int, seconds: float,
               rx: int = 0, tx: int = 0) -> None:
        klass = f"{status // 100}xx"
        with self._mu:
            key = (api, klass)
            self._requests[key] = self._requests.get(key, 0) + 1
            self._latency_sum[api] = self._latency_sum.get(api, 0.0) + seconds
            self._latency_count[api] = self._latency_count.get(api, 0) + 1
            self._bytes_rx += rx
            self._bytes_tx += tx
            hist = self._latency_hist.get(api)
            if hist is None:
                hist = self._latency_hist[api] = Histogram()
                self._last_minute[api] = LastMinute()
            minute = self._last_minute[api]
        hist.observe(seconds)
        minute.observe(seconds)

    def conn_open(self) -> None:
        with self._mu:
            self._conn_active += 1

    def conn_close(self) -> None:
        with self._mu:
            self._conn_active -= 1

    def keepalive_reuse(self) -> None:
        with self._mu:
            self._keepalive_reuses += 1

    def parse_fallback(self) -> None:
        with self._mu:
            self._parse_fallbacks += 1

    def response_path(self, kind: str) -> None:
        """One response served via `kind` (sendfile|pooled|legacy) —
        stamped exactly once per response at its final write."""
        with self._mu:
            self._response_path[kind] = \
                self._response_path.get(kind, 0) + 1

    def http_conn_stats(self) -> dict:
        with self._mu:
            return {"connections_active": self._conn_active,
                    "keepalive_reuses": self._keepalive_reuses,
                    "parse_fallbacks": self._parse_fallbacks,
                    "response_path": dict(self._response_path)}

    def last_minute(self) -> dict:
        """Per-API last-minute summaries {api: {count,p50,p99,max}} —
        the admin-info view."""
        with self._mu:
            minutes = dict(self._last_minute)
        return {api: summarize(lm.window()) for api, lm in minutes.items()}

    def state(self) -> dict:
        """JSON-safe counter snapshot for cross-worker aggregation
        (io/workers.py control pipe)."""
        with self._mu:
            hists = dict(self._latency_hist)
            minutes = dict(self._last_minute)
            out = {
                "requests": [[a, s, v]
                             for (a, s), v in self._requests.items()],
                "latency_sum": dict(self._latency_sum),
                "latency_count": dict(self._latency_count),
                "rx": self._bytes_rx,
                "tx": self._bytes_tx,
                "conn_active": self._conn_active,
                "keepalive_reuses": self._keepalive_reuses,
                "parse_fallbacks": self._parse_fallbacks,
                "response_path": dict(self._response_path),
            }
        out["latency_hist"] = {a: h.state() for a, h in hists.items()}
        out["last_minute"] = {a: lm.window() for a, lm in minutes.items()}
        out["slow_ops_total"] = _tracing.slow_total
        return out

    # -- rendering -------------------------------------------------------

    def render(self, object_layer=None, scanner=None, server=None,
               peer_states=None, node_states=None) -> str:
        """Prometheus text. With `peer_states` (every worker's control
        snapshot, this worker included), request counters render as
        the FLEET totals and per-worker gauges are appended — one
        scrape of any worker sees the whole front-end.

        With `node_states` (every cluster node's peer.metrics snapshot,
        the local node flagged "local": True), the merge goes one level
        further the same way: remote workers' states join the fleet
        totals and per-node families (requests, slow ops, last-minute
        latency, replication lag) are appended with `node` identity
        labels — one scrape of ANY node answers for the cluster."""
        lines: list[str] = []

        def metric(name, help_, type_, samples):
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {type_}")
            for labels, value in samples:
                if labels:
                    lab = ",".join(f'{k}="{v}"' for k, v in labels.items())
                    lines.append(f"{name}{{{lab}}} {value}")
                else:
                    lines.append(f"{name} {value}")

        def hist_metric(name, help_, samples):
            """Prometheus histogram family: per-label-set cumulative
            `_bucket{le=}` lines plus `_sum`/`_count`. `samples` is
            [(labels, hist_state)]."""
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} histogram")
            for labels, st in samples:
                base = ",".join(f'{k}="{v}"' for k, v in labels.items())
                for le, cum in Histogram.cumulative(st):
                    lab = f'{base},le="{le}"' if base else f'le="{le}"'
                    lines.append(f"{name}_bucket{{{lab}}} {cum}")
                suffix = f"{{{base}}}" if base else ""
                lines.append(f"{name}_sum{suffix} {st.get('sum', 0.0)}")
                lines.append(f"{name}_count{suffix} {st.get('count', 0)}")

        with self._mu:
            reqs = dict(self._requests)
            lat_sum = dict(self._latency_sum)
            lat_count = dict(self._latency_count)
            rx, tx = self._bytes_rx, self._bytes_tx
            conn_active = self._conn_active
            keepalive_reuses = self._keepalive_reuses
            parse_fallbacks = self._parse_fallbacks
            resp_path = dict(self._response_path)
            hists = {a: h.state() for a, h in self._latency_hist.items()}
            minutes = {a: lm.window()
                       for a, lm in self._last_minute.items()}
        slow_total = _tracing.slow_total
        peer_metrics = [p["metrics"] for p in (peer_states or [])
                        if isinstance(p.get("metrics"), dict)]
        # Cluster federation: remote nodes' worker states join the
        # fleet totals (the local node's own states already sit in
        # peer_metrics — or, single-process, in this instance — so its
        # node_states entry is flagged "local" and skipped here).
        remote_states = []
        for ns in (node_states or []):
            if not isinstance(ns, dict) or ns.get("local"):
                continue
            remote_states.extend(s for s in ns.get("states") or []
                                 if isinstance(s, dict))
        if remote_states:
            if not peer_metrics:
                peer_metrics = [self.state()]
            peer_metrics = peer_metrics + remote_states
        if peer_metrics:
            reqs, lat_sum, lat_count = {}, {}, {}
            rx = tx = 0
            conn_active = keepalive_reuses = parse_fallbacks = 0
            resp_path = {}
            slow_total = 0
            hist_states: dict[str, list] = {}
            minute_states: dict[str, list] = {}
            for st in peer_metrics:
                for a, s, v in st.get("requests", []):
                    reqs[(a, s)] = reqs.get((a, s), 0) + v
                for a, v in st.get("latency_sum", {}).items():
                    lat_sum[a] = lat_sum.get(a, 0.0) + v
                for a, v in st.get("latency_count", {}).items():
                    lat_count[a] = lat_count.get(a, 0) + v
                for a, hs in st.get("latency_hist", {}).items():
                    hist_states.setdefault(a, []).append(hs)
                for a, w in st.get("last_minute", {}).items():
                    minute_states.setdefault(a, []).append(w)
                rx += st.get("rx", 0)
                tx += st.get("tx", 0)
                conn_active += st.get("conn_active", 0)
                keepalive_reuses += st.get("keepalive_reuses", 0)
                parse_fallbacks += st.get("parse_fallbacks", 0)
                for k, v in st.get("response_path", {}).items():
                    resp_path[k] = resp_path.get(k, 0) + v
                slow_total += st.get("slow_ops_total", 0)
            hists = {a: Histogram.merge(sts)
                     for a, sts in hist_states.items()}
            minutes = {a: LastMinute.merge(ws)
                       for a, ws in minute_states.items()}

        metric("minio_tpu_http_requests_total",
               "HTTP requests by API and status class", "counter",
               [({"api": a, "status": s}, v)
                for (a, s), v in sorted(reqs.items())])
        metric("minio_tpu_http_request_seconds_sum",
               "Cumulative request latency per API", "counter",
               [({"api": a}, round(v, 6)) for a, v in sorted(lat_sum.items())])
        metric("minio_tpu_http_request_seconds_count",
               "Request count per API (latency sample count)", "counter",
               [({"api": a}, v) for a, v in sorted(lat_count.items())])
        metric("minio_tpu_http_rx_bytes_total",
               "Bytes received in request bodies", "counter", [({}, rx)])
        metric("minio_tpu_http_tx_bytes_total",
               "Bytes sent in response bodies", "counter", [({}, tx)])
        metric("minio_tpu_http_connections_active",
               "Open front-end HTTP connections", "gauge",
               [({}, conn_active)])
        metric("minio_tpu_http_keepalive_reuses_total",
               "Requests served on an already-open keep-alive connection",
               "counter", [({}, keepalive_reuses)])
        metric("minio_tpu_http_parse_fallbacks_total",
               "Requests the native head framer declined to the Python "
               "parser", "counter", [({}, parse_fallbacks)])
        metric("minio_tpu_http_response_path_total",
               "Responses by final-write mechanism (hotcache RAM hit / "
               "sendfile short-circuit / pooled gathered sendmsg / "
               "legacy buffered writes)", "counter",
               [({"path": k}, v) for k, v in sorted(resp_path.items())])
        # Event-loop connection plane (s3/eventloop.py): parked vs
        # active fds, fresh accepts vs keep-alive re-parks, shed and
        # reaped connections, and the loop-lag histogram. Fleet-merged
        # from every worker's control snapshot when available.
        loop_stats = None
        if peer_states:
            peer_loops = [p.get("connections") for p in peer_states
                          if isinstance(p.get("connections"), dict)]
            if peer_loops:
                loop_stats = merge_loop_stats(peer_loops)
        if loop_stats is None and server is not None:
            es = getattr(server, "eventloop_stats", None)
            loop_stats = es() if es is not None else None
        ls = loop_stats or {}
        metric("minio_tpu_http_eventloop_enabled",
               "1 when the epoll event-loop front end serves this "
               "fleet, 0 under thread-per-connection", "gauge",
               [({}, 1 if ls.get("enabled") else 0)])
        metric("minio_tpu_http_parked_connections",
               "Keep-alive connections parked in the epoll set "
               "(no thread, hibernated recv buffer)", "gauge",
               [({}, ls.get("parked", 0))])
        metric("minio_tpu_http_dispatched_connections",
               "Connections currently owned by an executor thread or "
               "a loop-owned response-tail drain", "gauge",
               [({}, ls.get("active", 0))])
        metric("minio_tpu_http_conns_accepted_total",
               "Fresh connections accepted by the event loop",
               "counter", [({}, ls.get("accepted_total", 0))])
        metric("minio_tpu_http_conns_shed_total",
               "Connections shed at accept (connection-level "
               "backpressure past MTPU_MAX_CONNS)", "counter",
               [({}, ls.get("shed_total", 0))])
        metric("minio_tpu_http_conn_reparks_total",
               "Keep-alive turnarounds re-parked into the epoll set "
               "instead of pinning a thread", "counter",
               [({}, ls.get("reparks_total", 0))])
        metric("minio_tpu_http_idle_reaped_total",
               "Connections reaped by the idle deadline (includes "
               "slowloris partial heads)", "counter",
               [({}, ls.get("reaped_idle_total", 0))])
        if ls.get("loop_lag"):
            hist_metric("minio_tpu_http_loop_lag_seconds",
                        "Event-loop tick service lag (ready events to "
                        "handled)", [({}, ls["loop_lag"])])
        hist_metric("minio_tpu_api_request_duration_seconds",
                    "Bucketed request latency per API",
                    [({"api": a}, st) for a, st in sorted(hists.items())])
        lm_samples, lm_counts = [], []
        for a, w in sorted(minutes.items()):
            s = summarize(w)
            lm_counts.append(({"api": a}, s["count"]))
            for q in ("p50", "p99", "max"):
                lm_samples.append(({"api": a, "q": q}, s[q]))
        metric("minio_tpu_api_last_minute_seconds",
               "Rolling last-minute request latency per API "
               "(p50/p99/max over 60 one-second slots)", "gauge",
               lm_samples)
        metric("minio_tpu_api_last_minute_requests",
               "Requests observed in the trailing minute per API",
               "gauge", lm_counts)
        metric("minio_tpu_slow_ops_total",
               "Spans that crossed the MTPU_SLOW_OP_MS threshold "
               "(slow-op log records emitted)", "counter",
               [({}, slow_total)])
        metric("minio_tpu_process_uptime_seconds",
               "Seconds since server start", "gauge",
               [({}, round(time.time() - self._start, 1))])

        if scanner is not None:
            u = scanner.usage
            metric("minio_tpu_cluster_objects_total",
                   "Objects at last scanner cycle", "gauge",
                   [({}, u.objects)])
            metric("minio_tpu_cluster_versions_total",
                   "Object versions at last scanner cycle", "gauge",
                   [({}, u.versions)])
            metric("minio_tpu_cluster_usage_bytes",
                   "Logical bytes stored at last scanner cycle", "gauge",
                   [({}, u.total_size)])
            metric("minio_tpu_bucket_usage_bytes",
                   "Logical bytes per bucket", "gauge",
                   [({"bucket": b}, bu.size)
                    for b, bu in sorted(u.buckets.items())])
            metric("minio_tpu_heal_objects_healed_total",
                   "Objects healed by the scanner", "counter",
                   [({}, u.healed)])
            metric("minio_tpu_heal_failures_total",
                   "Scanner heal failures", "counter",
                   [({}, u.heal_failures)])
            metric("minio_tpu_scanner_cycles_total",
                   "Completed scanner cycles", "counter", [({}, u.cycles)])

        if object_layer is not None:
            online, offline = 0, 0
            total_cap = free_cap = 0
            for _, _, di in probe_disks(object_layer):
                if di is None:
                    offline += 1
                else:
                    online += 1
                    total_cap += di.total
                    free_cap += di.free
            metric("minio_tpu_drives_online", "Drives responding", "gauge",
                   [({}, online)])
            metric("minio_tpu_drives_offline", "Drives not responding",
                   "gauge", [({}, offline)])
            metric("minio_tpu_capacity_raw_total_bytes",
                   "Raw capacity across online drives", "gauge",
                   [({}, total_cap)])
            metric("minio_tpu_capacity_raw_free_bytes",
                   "Raw free capacity across online drives", "gauge",
                   [({}, free_cap)])
            # Metacache effectiveness across the layer's sets.
            mcs = {"hits": 0, "misses": 0, "walks_active": 0,
                   "walks_started": 0, "persisted_loads": 0,
                   "compactions": 0}
            for s in layer_sets(object_layer):
                mc = getattr(s, "metacache", None)
                if mc is not None:
                    st = mc.stats()
                    for key in mcs:
                        mcs[key] += st[key]
            metric("minio_tpu_metacache_hits_total",
                   "Listing pages served from cache", "counter",
                   [({}, mcs["hits"])])
            metric("minio_tpu_metacache_misses_total",
                   "Listing pages that required a drive walk", "counter",
                   [({}, mcs["misses"])])
            metric("minio_tpu_metacache_walks_active",
                   "Background listing walks currently producing",
                   "gauge", [({}, mcs["walks_active"])])
            metric("minio_tpu_metacache_walks_started_total",
                   "Background listing walks started", "counter",
                   [({}, mcs["walks_started"])])
            metric("minio_tpu_metacache_persisted_loads_total",
                   "Listings warm-started from persisted walk segments",
                   "counter", [({}, mcs["persisted_loads"])])
            metric("minio_tpu_metacache_compactions_total",
                   "Continuation walks compacted onto persisted base "
                   "runs", "counter", [({}, mcs["compactions"])])
            # Native journal-scan split: fallbacks are blobs the native
            # scanner handed back to the Python parser.
            from minio_tpu.storage import meta_scan as _ms
            metric("minio_tpu_meta_scan_blobs_total",
                   "xl.meta journals decoded by the listing walk, by "
                   "path", "counter",
                   [({"path": p}, _ms.counters[p])
                    for p in ("native", "fallback")])
            # MRF queue health: drops must be VISIBLE — a heal that
            # silently vanishes is a future quorum loss (s._mrf, not
            # s.mrf: rendering metrics must not start a worker).
            mrf = {"healed": 0, "spilled": 0, "dropped": 0, "pending": 0}
            for s in layer_sets(object_layer):
                q = getattr(s, "_mrf", None)
                if q is not None:
                    st = q.stats()
                    for key in mrf:
                        mrf[key] += st[key]
            metric("minio_tpu_mrf_healed_total",
                   "Objects healed off the MRF retry queue", "counter",
                   [({}, mrf["healed"])])
            metric("minio_tpu_mrf_spilled_total",
                   "MRF entries that overflowed the bounded queue into "
                   "the persisted pending set (replayed, not lost)",
                   "counter", [({}, mrf["spilled"])])
            metric("minio_tpu_mrf_dropped_total",
                   "MRF heals abandoned after exhausting retries "
                   "(real loss — alert on this)", "counter",
                   [({}, mrf["dropped"])])
            metric("minio_tpu_mrf_pending",
                   "Heal entries awaiting MRF repair", "gauge",
                   [({}, mrf["pending"])])

        if server is not None:
            adm = getattr(server, "admission", None)
            if adm is not None:
                snap = adm.snapshot()
                classes = sorted(k for k, v in snap.items()
                                 if isinstance(v, dict))
                metric("minio_tpu_api_requests_max",
                       "Configured in-flight request limit per class "
                       "(0 = unlimited)", "gauge",
                       [({"class": c}, snap[c]["limit"]) for c in classes])
                metric("minio_tpu_api_requests_in_flight",
                       "Requests currently admitted per class", "gauge",
                       [({"class": c}, snap[c]["in_flight"])
                        for c in classes])
                metric("minio_tpu_api_requests_waiting",
                       "Requests queued for an admission slot", "gauge",
                       [({"class": c}, snap[c]["waiting"])
                        for c in classes])
                metric("minio_tpu_api_requests_admitted_total",
                       "Requests admitted per class", "counter",
                       [({"class": c}, snap[c]["admitted_total"])
                        for c in classes])
                metric("minio_tpu_api_requests_shed_total",
                       "Requests shed with 503 by admission control",
                       "counter",
                       [({"class": c, "reason": r},
                         snap[c][f"shed_{r}_total"])
                        for c in classes
                        for r in ("queue_full", "deadline")])
                metric("minio_tpu_api_request_deadline_exceeded_total",
                       "Requests that exhausted their deadline budget "
                       "mid-flight (408)", "counter",
                       [({}, snap["deadline_exceeded_total"])])
            aud = getattr(server, "audit", None)
            if aud is not None:
                # Audit delivery health: a full retry queue used to
                # evict records with no visible trace — dropped MUST be
                # exported (it is real audit loss, alert on it).
                ast = aud.stats()
                metric("minio_tpu_audit_sent_total",
                       "Audit records delivered to the webhook target",
                       "counter", [({}, ast["sent"])])
                metric("minio_tpu_audit_dropped_total",
                       "Audit records lost to retry-queue overflow or "
                       "exhausted delivery attempts (alert on this)",
                       "counter", [({}, ast["dropped"])])
                metric("minio_tpu_audit_pending",
                       "Audit records waiting in the retry queue",
                       "gauge", [({}, ast["pending"])])
            repl = getattr(server, "replicator", None)
            if repl is not None:
                metric("minio_tpu_replication_queued_total",
                       "Bucket-replication tasks enqueued", "counter",
                       [({}, repl.queued)])
                metric("minio_tpu_replication_completed_total",
                       "Bucket-replication tasks delivered", "counter",
                       [({}, repl.completed)])
                metric("minio_tpu_replication_failed_total",
                       "Bucket-replication tasks failed", "counter",
                       [({}, repl.failed)])
                # The spilled/dropped split mirrors MRF: spilled items
                # persist and replay (lossless), dropped is real intent
                # loss — alert on it staying nonzero.
                metric("minio_tpu_replication_spilled_total",
                       "Bucket-replication intents spilled to the "
                       "persisted pending set on queue overflow "
                       "(replayed, not lost)", "counter",
                       [({}, getattr(repl, "spilled", 0))])
                metric("minio_tpu_replication_dropped_total",
                       "Bucket-replication intents lost outright "
                       "(alert on this)", "counter",
                       [({}, getattr(repl, "dropped", 0))])
                metric("minio_tpu_replication_sse_skipped_total",
                       "Versions not replicated because they are "
                       "SSE-encrypted (keys bind to this cluster)",
                       "counter", [({}, getattr(repl, "sse_skipped", 0))])
                if hasattr(repl, "stats"):
                    rst = repl.stats()
                    metric("minio_tpu_replication_pending",
                           "Replication intents between enqueue and "
                           "terminal outcome (includes spilled backlog)",
                           "gauge", [({}, rst.get("pending", 0))])
                    metric("minio_tpu_replication_wal_live",
                           "Incomplete intents in the replication WAL",
                           "gauge",
                           [({}, (rst.get("wal") or {}).get("live", 0))])
                    lanes = rst.get("lanes") or []
                    if lanes:
                        # Breaker state per remote target: closed=0,
                        # half-open=1, open=2 (same scale as the grid
                        # transport breakers).
                        code = {"closed": 0, "half-open": 1, "open": 2}
                        metric("minio_tpu_replication_breaker_state",
                               "Delivery-lane circuit state per remote "
                               "target (0=closed 1=half-open 2=open)",
                               "gauge",
                               [({"target": ln["target"]},
                                 code.get(ln["state"], 0))
                                for ln in lanes])
                        metric("minio_tpu_replication_lane_pending",
                               "Queued intents per delivery lane",
                               "gauge",
                               [({"target": ln["target"]},
                                 ln["pending"]) for ln in lanes])
                    if rst.get("lag_hist"):
                        hist_metric("minio_tpu_replication_lag_seconds",
                                    "Enqueue-to-delivered replication "
                                    "lag", [({}, rst["lag_hist"])])
            site = getattr(server, "site", None)
            if site is not None:
                metric("minio_tpu_site_replication_queued_total",
                       "Site-replication tasks enqueued", "counter",
                       [({}, site.queued)])
                metric("minio_tpu_site_replication_completed_total",
                       "Site-replication tasks delivered", "counter",
                       [({}, site.completed)])
                metric("minio_tpu_site_replication_failed_total",
                       "Site-replication tasks failed", "counter",
                       [({}, site.failed)])
            batch = getattr(server, "batch", None)
            if batch is not None:
                jobs = batch.list_jobs()
                by_status: dict[str, int] = {}
                for j in jobs:
                    st = j.get("status", "unknown")
                    by_status[st] = by_status.get(st, 0) + 1
                metric("minio_tpu_batch_jobs",
                       "Batch jobs by status", "gauge",
                       [({"status": s2}, v)
                        for s2, v in sorted(by_status.items())])
            dh = getattr(server, "drive_heal", None)
            st = None
            if peer_states:
                # Pre-forked mode: bulk heals run on worker 0 only,
                # but scrapes land on any worker — render the FLEET's
                # drive-heal state so every scrape sees the heal.
                merged = {"formats_restored": 0, "drives": []}
                found = False
                for p in peer_states:
                    pst = p.get("drive_heal")
                    if isinstance(pst, dict):
                        found = True
                        merged["formats_restored"] += \
                            pst.get("formats_restored", 0)
                        merged["drives"].extend(pst.get("drives", []))
                if found:
                    st = merged
            if st is None and dh is not None:
                st = dh.status()
            if st is not None:
                # Drive replacement bulk-heal progress: one sample per
                # healing (or recently finished) drive, so operators
                # can watch a swap converge from any dashboard.
                samples = {"scanned": [], "healed": [], "failed": [],
                           "bytes": [], "eta": []}
                healing_now = 0
                for entry in st.get("drives", []):
                    lab = {"set": entry.get("set", 0),
                           "drive": entry.get("drive", 0)}
                    if entry.get("state") != "done":
                        healing_now += 1
                    samples["scanned"].append(
                        (lab, entry.get("objects_scanned", 0)))
                    samples["healed"].append(
                        (lab, entry.get("objects_healed", 0)))
                    samples["failed"].append(
                        (lab, entry.get("objects_failed", 0)))
                    samples["bytes"].append(
                        (lab, entry.get("bytes_healed", 0)))
                    if "eta_seconds" in entry:
                        samples["eta"].append(
                            (lab, entry["eta_seconds"]))
                metric("minio_tpu_drives_healing",
                       "Drives currently under bulk heal", "gauge",
                       [({}, healing_now)])
                metric("minio_tpu_drive_heal_objects_scanned",
                       "Objects scanned by each drive's bulk heal",
                       "gauge", samples["scanned"])
                metric("minio_tpu_drive_heal_objects_healed",
                       "Objects repaired onto each replaced drive",
                       "gauge", samples["healed"])
                metric("minio_tpu_drive_heal_objects_failed",
                       "Objects the bulk heal failed to repair "
                       "(MRF/scanner retry later)", "gauge",
                       samples["failed"])
                metric("minio_tpu_drive_heal_bytes_healed",
                       "Logical bytes repaired onto each replaced "
                       "drive", "gauge", samples["bytes"])
                metric("minio_tpu_drive_heal_eta_seconds",
                       "Estimated seconds to bulk-heal completion "
                       "(rate-based; needs a scanner object count)",
                       "gauge", samples["eta"])
                metric("minio_tpu_drive_formats_restored_total",
                       "Fresh drives re-formatted into their slot at "
                       "runtime", "counter",
                       [({}, st.get("formats_restored", 0))])
            decom_status = getattr(server.object_layer,
                                   "decommission_status", None) \
                if getattr(server, "object_layer", None) is not None \
                else None
            if decom_status is not None:
                st = decom_status()
                if st:
                    metric("minio_tpu_decommission_migrated_total",
                           "Objects migrated by the active/last drain",
                           "counter", [({}, st.get("migrated", 0))])
                    metric("minio_tpu_decommission_failed_total",
                           "Objects the drain failed to migrate",
                           "counter", [({}, st.get("failed", 0))])
                    metric("minio_tpu_decom_bytes_moved_total",
                           "Data bytes restored into surviving pools "
                           "by the active/last drain", "counter",
                           [({}, st.get("bytes_moved", 0))])
                    metric("minio_tpu_decom_yields_total",
                           "Drain pauses taken to yield to queueing "
                           "foreground requests", "counter",
                           [({}, st.get("yields", 0))])
                    if st.get("checkpoint_ns"):
                        age = max(0.0, time.time() -
                                  st["checkpoint_ns"] / 1e9)
                        metric("minio_tpu_decom_checkpoint_age_seconds",
                               "Seconds since the drain checkpoint "
                               "last persisted (resume staleness "
                               "bound)", "gauge", [({}, age)])
            rb_status = getattr(server.object_layer,
                                "rebalance_status", None) \
                if getattr(server, "object_layer", None) is not None \
                else None
            if rb_status is not None:
                st = rb_status()
                if st:
                    recs = sorted((st.get("pools") or {}).items())
                    metric("minio_tpu_rebalance_active",
                           "1 while a rebalance walk is in progress",
                           "gauge",
                           [({}, 1 if st.get("status") in
                             ("planning", "rebalancing") else 0)])
                    metric("minio_tpu_rebalance_migrated_total",
                           "Objects each participating pool shed in "
                           "the active/last rebalance", "counter",
                           [({"pool": p}, r.get("migrated", 0))
                            for p, r in recs])
                    metric("minio_tpu_rebalance_bytes_moved_total",
                           "Bytes each participating pool shed",
                           "counter",
                           [({"pool": p}, r.get("bytes_moved", 0))
                            for p, r in recs])
                    metric("minio_tpu_rebalance_failed_total",
                           "Objects the rebalance failed to migrate",
                           "counter",
                           [({"pool": p}, r.get("failed", 0))
                            for p, r in recs])
                    metric("minio_tpu_rebalance_pool_fill_fraction",
                           "Used/capacity per pool as of rebalance "
                           "planning", "gauge",
                           [({"pool": p},
                             r.get("used", 0) / (r.get("capacity") or 1))
                            for p, r in recs])
                    metric("minio_tpu_rebalance_yields_total",
                           "Rebalance pauses taken to yield to "
                           "queueing foreground requests", "counter",
                           [({}, st.get("yields", 0))])
                    if st.get("checkpoint_ns"):
                        age = max(0.0, time.time() -
                                  st["checkpoint_ns"] / 1e9)
                        metric(
                            "minio_tpu_rebalance_checkpoint_age_seconds",
                            "Seconds since the rebalance checkpoint "
                            "last persisted", "gauge", [({}, age)])

        # -- I/O engine observability (io/bufpool + io/engine) ----------
        # Saturation diagnosis: pool hit rate says whether hot paths
        # recycle window buffers; outstanding/leaks say whether leases
        # return; per-drive queue depth says which drive is the wall.
        from minio_tpu.io.bufpool import global_pool
        bp = global_pool().stats()
        for name, help_, type_, key in (
                ("minio_tpu_bufpool_hits_total",
                 "Buffer leases served from the pool", "counter", "hits"),
                ("minio_tpu_bufpool_misses_total",
                 "Buffer leases that allocated fresh memory", "counter",
                 "misses"),
                ("minio_tpu_bufpool_oversized_total",
                 "Leases larger than every size class (unpooled)",
                 "counter", "oversized"),
                ("minio_tpu_bufpool_outstanding",
                 "Leases currently held", "gauge", "outstanding"),
                ("minio_tpu_bufpool_leaks_total",
                 "Dropped leases returned by the leak net", "counter",
                 "leaks"),
                ("minio_tpu_bufpool_idle_bytes",
                 "Bytes parked on pool free lists", "gauge",
                 "idle_bytes")):
            metric(name, help_, type_, [({}, bp[key])])

        # -- cross-request stripe batcher (ops/batcher) -----------------
        # Occupancy diagnosis: route counters say whether PUTs actually
        # ride the device; bucket counters + fill ratio say whether
        # coalescing fills the mesh-wide batches it compiles for; the
        # wait histogram bounds the latency the accumulation window
        # adds; deadline failures count members culled before dispatch.
        from minio_tpu.ops import batcher as _batcher_mod
        bst = _batcher_mod.aggregate_stats()
        routes = sorted(bst["routes"].items())
        metric("minio_tpu_batcher_dispatches_total",
               "Coalesced stripe-batch dispatches by route "
               "(put|get|reconstruct) and resolved path", "counter",
               [({"route": r, "path": p}, v) for r, st in routes
                for p, v in sorted(st["dispatches"].items())])
        metric("minio_tpu_batcher_requests_total",
               "Stripe windows routed through the batcher by route "
               "(bypass = calibrated host pass-through)", "counter",
               [({"route": r, "path": p}, v) for r, st in routes
                for p, v in sorted(st["requests"].items())])
        metric("minio_tpu_batcher_bucket_dispatches_total",
               "Device dispatches per batch padding bucket", "counter",
               [({"route": r, "bucket": b}, v) for r, st in routes
                for b, v in sorted(st["buckets"].items())])
        metric("minio_tpu_batcher_batched_blocks_total",
               "Stripe blocks carried by device dispatches", "counter",
               [({"route": r}, st["batched_blocks"])
                for r, st in routes])
        metric("minio_tpu_batcher_capacity_blocks_total",
               "Padded bucket capacity of those dispatches "
               "(batched/capacity = fill ratio)", "counter",
               [({"route": r}, st["capacity_blocks"])
                for r, st in routes])
        metric("minio_tpu_batcher_fill_ratio",
               "Mean batch fill ratio (blocks dispatched / bucket "
               "capacity) since boot", "gauge",
               [({"route": r}, round(st["fill_ratio"], 4))
                for r, st in routes])
        metric("minio_tpu_batcher_deadline_failures_total",
               "Batch members failed for exhausted deadlines before "
               "dispatch (batch-mates unaffected)", "counter",
               [({"route": r}, st["deadline_failures"])
                for r, st in routes])
        metric("minio_tpu_batcher_mesh_devices",
               "Chips the batched dispatch shards over", "gauge",
               [({}, bst["mesh_devices"])])
        hist_metric("minio_tpu_batcher_wait_seconds",
                    "Coalescing wait per batched stripe window "
                    "(enqueue to dispatch start)",
                    [({"route": r}, st["wait_hist"])
                     for r, st in routes])
        hist_metric("minio_tpu_kernel_lane_decode_service_seconds",
                    "Kernel-lane service time of decode-route "
                    "(get/reconstruct) device dispatches",
                    [({}, bst["decode_lane_hist"])])
        # -- fused transform plane (object/transform) -------------------
        # Path split is the conformance signal: with fusion on, the
        # legacy counters must stay ZERO for buffered traffic — any
        # legacy tick means a request silently fell back to the
        # layered per-stage walks the fused pass exists to remove.
        from minio_tpu.object import transform as _tf_mod
        tst = _tf_mod.stats()
        metric("minio_tpu_transform_requests_total",
               "Transform-plane requests by direction and path "
               "(fused = single native pass, legacy = layered "
               "per-stage walks)", "counter",
               [({"dir": "put", "path": p}, v)
                for p, v in sorted(tst["put_requests"].items())] +
               [({"dir": "get", "path": p}, v)
                for p, v in sorted(tst["get_requests"].items())])
        metric("minio_tpu_transform_bytes_total",
               "Logical bytes through the transform plane", "counter",
               [({"dir": d}, v) for d, v in sorted(tst["bytes"].items())])
        metric("minio_tpu_transform_fused_enabled",
               "1 when the fused single-pass plane is active "
               "(native kernel present, MTPU_TRANSFORM_FUSED not off)",
               "gauge", [({}, 1 if tst["fused_enabled"] else 0)])
        hist_metric("minio_tpu_transform_stage_service_seconds",
                    "Per-stage service time inside the fused native "
                    "pass (digest|compress|encrypt|frame)",
                    [({"stage": s}, h)
                     for s, h in sorted(tst["stage_hists"].items())])
        # -- group-commit write plane (storage/group_commit) ------------
        # Occupancy diagnosis for the small-object commit lanes: batch
        # size distribution + mean fill say whether concurrent PUTs
        # actually coalesce; fsyncs_saved is the durability-cost
        # amortization; culls/demotions are the isolation escape
        # hatches firing.
        from minio_tpu.storage import group_commit as _gc_mod
        gst = _gc_mod.aggregate_stats()
        peers_gc = [p.get("group_commit") for p in (peer_states or [])
                    if isinstance(p.get("group_commit"), dict)]
        if peers_gc:
            # Pre-forked mode: each worker runs its own lanes and a
            # scrape lands on an arbitrary one — merge the fleet.
            gst = _gc_mod.merge_stats(peers_gc)
        metric("minio_tpu_group_commit_batches_total",
               "Coalesced per-drive commit batches dispatched",
               "counter", [({}, gst["batches"])])
        metric("minio_tpu_group_commit_members_total",
               "Commit members carried by those batches", "counter",
               [({}, gst["members"])])
        metric("minio_tpu_group_commit_solo_total",
               "Group-eligible commits that took the solo fan-out "
               "(no coalescing company)", "counter",
               [({}, gst["solo_bypass"])])
        metric("minio_tpu_group_commit_batch_size_dispatches_total",
               "Batches per power-of-two member-count bucket",
               "counter",
               [({"size": str(b)}, v) for b, v in
                sorted(gst["size_buckets"].items())])
        metric("minio_tpu_group_commit_fill_mean",
               "Mean members per batch since boot", "gauge",
               [({}, round(gst["fill_mean"], 3))])
        metric("minio_tpu_group_commit_merged_members_total",
               "Same-object members merged into one journal rewrite",
               "counter", [({}, gst["merged_members"])])
        metric("minio_tpu_group_commit_noop_skips_total",
               "Byte-identical version re-adds short-circuited "
               "without a journal rewrite", "counter",
               [({}, gst["noop_skips"])])
        metric("minio_tpu_group_commit_fsyncs_saved_total",
               "Per-journal fdatasyncs replaced by batch WAL syncs",
               "counter", [({}, gst["fsyncs_saved"])])
        metric("minio_tpu_group_commit_deadline_culls_total",
               "Members culled for exhausted deadlines before their "
               "batch dispatched (batch-mates unaffected)", "counter",
               [({}, gst["deadline_culls"])])
        metric("minio_tpu_group_commit_solo_demotions_total",
               "Members demoted to the solo commit path after a batch "
               "fault", "counter", [({}, gst["solo_demotions"])])
        metric("minio_tpu_group_commit_checkpoints_total",
               "Background WAL checkpoints (one os.sync each)",
               "counter", [({}, gst["checkpoints"])])
        metric("minio_tpu_group_commit_wals_retired_total",
               "WAL frames retired by checkpoints", "counter",
               [({}, gst["wals_retired"])])
        hist_metric("minio_tpu_group_commit_wait_seconds",
                    "Coalescing wait per commit member (enqueue to "
                    "batch dispatch)", [({}, gst["wait_hist"])])
        # Report the lane without CREATING it: kernel_lane() lazily
        # spawns a worker thread, and a scrape on a host-codec-only
        # process should not pay a permanent thread to export zeros.
        from minio_tpu.io import engine as _engine
        from minio_tpu.utils.latency import Histogram as _Hist
        if _engine._kernel_lane is not None:
            kst = _engine._kernel_lane.stats()
        else:
            kst = {"queued": 0, "submitted_total": 0,
                   "service_hist": _Hist().state()}
        metric("minio_tpu_kernel_lane_queued",
               "Device dispatches waiting in the shared kernel lane",
               "gauge", [({}, kst["queued"])])
        metric("minio_tpu_kernel_lane_dispatches_total",
               "Device dispatches submitted to the kernel lane",
               "counter", [({}, kst["submitted_total"])])
        hist_metric("minio_tpu_kernel_lane_op_duration_seconds",
                    "Bucketed service time of kernel-lane device "
                    "dispatches", [({}, kst["service_hist"])])
        if object_layer is not None or peer_states:
            # One row per (worker, set, drive). In pre-forked mode each
            # worker runs its OWN queues over the same physical drives
            # and a scrape lands on an arbitrary worker — merge the
            # FLEET's rows (gauges sum, histograms/windows merge) so
            # "which drive is the wall" is answered for the whole
            # front-end, not this worker's 1/N slice.
            rows = []
            for p in (peer_states or []):
                lst = p.get("engine")
                if isinstance(lst, list):
                    rows.extend(st for st in lst
                                if isinstance(st, dict) and "drive" in st)
            if not rows and object_layer is not None:
                for si, s in enumerate(layer_sets(object_layer)):
                    eng = getattr(s, "io", None)
                    if eng is None:
                        continue
                    rows.extend({"set": si, "drive": di, **st}
                                for di, st in enumerate(eng.stats()))
            agg: dict = {}
            for st in rows:
                a = agg.setdefault(
                    (st.get("set", 0), st.get("drive", 0)),
                    {"queued": 0, "in_flight": 0, "rejected_total": 0,
                     "hists": [], "svc": [], "wait": []})
                for k in ("queued", "in_flight", "rejected_total"):
                    a[k] += st.get(k, 0)
                if "service_hist" in st:
                    a["hists"].append(st["service_hist"])
                if "last_minute_window" in st:
                    a["svc"].append(st["last_minute_window"])
                if "last_minute_wait_window" in st:
                    a["wait"].append(st["last_minute_wait_window"])
            samples_q, samples_f, samples_r = [], [], []
            samples_h, samples_lm, samples_lw = [], [], []
            for (si, di), a in sorted(agg.items()):
                lab = {"set": si, "drive": di}
                samples_q.append((lab, a["queued"]))
                samples_f.append((lab, a["in_flight"]))
                samples_r.append((lab, a["rejected_total"]))
                if a["hists"]:
                    samples_h.append((lab, Histogram.merge(a["hists"])))
                for wins, out in ((a["svc"], samples_lm),
                                  (a["wait"], samples_lw)):
                    if wins:
                        s2 = summarize(LastMinute.merge(wins))
                        for q in ("p50", "p99", "max"):
                            out.append(({**lab, "q": q}, s2[q]))
            metric("minio_tpu_drive_queue_depth",
                   "Ops waiting in each drive's submission queue",
                   "gauge", samples_q)
            metric("minio_tpu_drive_queue_in_flight",
                   "Ops executing on each drive's worker crew",
                   "gauge", samples_f)
            metric("minio_tpu_drive_queue_rejected_total",
                   "Submissions shed by bounded drive queues",
                   "counter", samples_r)
            # Per-drive latency attribution: which drive is the wall,
            # now (last-minute ring) and cumulatively (histogram);
            # queue-wait separately from service so a convoyed drive
            # is distinguishable from a slow one.
            hist_metric("minio_tpu_drive_op_duration_seconds",
                        "Bucketed service time of drive-queue ops",
                        samples_h)
            metric("minio_tpu_drive_last_minute_seconds",
                   "Rolling last-minute drive-op service time "
                   "(p50/p99/max)", "gauge", samples_lm)
            metric("minio_tpu_drive_queue_wait_last_minute_seconds",
                   "Rolling last-minute queue wait before each drive op "
                   "(p50/p99/max)", "gauge", samples_lw)

        # -- read path: quorum-fileinfo cache + fused GET kernel --------
        # Hit rate says whether repeat GETs skip the k-drive metadata
        # fan-out; invalidations say writes are being observed; the
        # kernel split says whether reads ride the native fast path.
        if object_layer is not None:
            fic = {"hits": 0, "misses": 0, "evictions": 0,
                   "invalidations": 0, "entries": 0, "bytes": 0,
                   "stat_hits": 0, "stat_misses": 0, "stat_entries": 0,
                   "stat_evictions": 0}
            gk = {"native": 0, "numpy": 0, "demoted": 0, "device": 0}
            for s in layer_sets(object_layer):
                cache = getattr(s, "fi_cache", None)
                if cache is not None:
                    st = cache.stats()
                    for key in fic:
                        fic[key] += st[key]
                for key in gk:
                    gk[key] += getattr(s, "get_kernel", {}).get(key, 0)
            for name, help_, type_, key in (
                    ("minio_tpu_fileinfo_cache_hits_total",
                     "GET/HEAD metadata served from the fileinfo cache",
                     "counter", "hits"),
                    ("minio_tpu_fileinfo_cache_misses_total",
                     "Fileinfo lookups that paid the drive fan-out",
                     "counter", "misses"),
                    ("minio_tpu_fileinfo_cache_evictions_total",
                     "Entries LRU-evicted from the fileinfo cache",
                     "counter", "evictions"),
                    ("minio_tpu_fileinfo_cache_invalidations_total",
                     "Write/heal invalidations of cached fileinfo",
                     "counter", "invalidations"),
                    ("minio_tpu_fileinfo_cache_entries",
                     "Keys currently cached", "gauge", "entries"),
                    ("minio_tpu_fileinfo_cache_bytes",
                     "Resident inline bytes held by cached fileinfo",
                     "gauge", "bytes"),
                    ("minio_tpu_fileinfo_cache_stat_hits_total",
                     "HEADs served from the stat class (or a data "
                     "entry)", "counter", "stat_hits"),
                    ("minio_tpu_fileinfo_cache_stat_misses_total",
                     "HEADs that paid the drive fan-out", "counter",
                     "stat_misses"),
                    ("minio_tpu_fileinfo_cache_stat_entries",
                     "Stat-class keys currently cached", "gauge",
                     "stat_entries"),
                    ("minio_tpu_fileinfo_cache_stat_evictions_total",
                     "Stat-class entries LRU-trimmed (healthy under "
                     "HEAD storms — distinct from data-class thrash)",
                     "counter", "stat_evictions")):
                metric(name, help_, type_, [({}, fic[key])])
            metric("minio_tpu_get_kernel_windows_total",
                   "GET windows decoded, by path",
                   "counter", [({"path": p}, v) for p, v in gk.items()])

        # -- hot-object read tier (object/hotcache.py) ------------------
        # Hits are GETs that never touched the object layer (served
        # from a pinned RAM buffer, most straight off the epoll loop);
        # admits vs rejects say whether tinyLFU is filtering scans;
        # invalidations say mutations are being observed. Per-worker
        # caches merge into the fleet view like the loop stats above.
        hot_states = [p.get("hot_cache") for p in (peer_states or [])
                      if isinstance(p.get("hot_cache"), dict)]
        if not hot_states and server is not None:
            hc = getattr(server, "hot_cache", None)
            if hc is not None:
                hot_states = [hc.stats()]
        if hot_states:
            hot = {"hits": 0, "misses": 0, "admits": 0, "rejects": 0,
                   "evictions": 0, "invalidations": 0, "entries": 0,
                   "bytes": 0}
            hot_enabled = 0
            for st in hot_states:
                if st.get("enabled"):
                    hot_enabled = 1
                for key in hot:
                    hot[key] += st.get(key, 0)
            metric("minio_tpu_hot_cache_enabled",
                   "1 when the hot-object read tier is admitting "
                   "(MTPU_HOT_CACHE kill switch)", "gauge",
                   [({}, hot_enabled)])
            for name, help_, type_, key in (
                    ("minio_tpu_hot_cache_hits_total",
                     "GETs served from the hot-object RAM tier (no "
                     "object-layer work)", "counter", "hits"),
                    ("minio_tpu_hot_cache_misses_total",
                     "Hot-tier lookups that fell through to the "
                     "object layer", "counter", "misses"),
                    ("minio_tpu_hot_cache_admits_total",
                     "Objects admitted into the hot tier", "counter",
                     "admits"),
                    ("minio_tpu_hot_cache_admission_rejects_total",
                     "Candidates the tinyLFU filter kept out (scan "
                     "resistance at work)", "counter", "rejects"),
                    ("minio_tpu_hot_cache_evictions_total",
                     "Entries evicted by the byte/entry caps",
                     "counter", "evictions"),
                    ("minio_tpu_hot_cache_invalidations_total",
                     "Mutation/coherence flushes of hot entries",
                     "counter", "invalidations"),
                    ("minio_tpu_hot_cache_entries",
                     "Objects currently pinned in the hot tier",
                     "gauge", "entries"),
                    ("minio_tpu_hot_cache_bytes",
                     "Resident bytes pinned in the hot tier", "gauge",
                     "bytes")):
                metric(name, help_, type_, [({}, hot[key])])
        # -- distributed plane: grid peer breakers, notify fan-out,
        #    cross-node coherence -----------------------------------------
        from minio_tpu.grid import client as _grid_client
        from minio_tpu.grid import peers as _grid_peers
        gstats = _grid_client.peer_stats()
        _STATE_NUM = {"closed": 0, "half-open": 1, "open": 2}
        metric("minio_tpu_grid_peer_state",
               "Per-peer grid circuit breaker state "
               "(0 closed, 1 half-open, 2 open)", "gauge",
               [({"peer": g["peer"]}, _STATE_NUM.get(g["state"], 2))
                for g in gstats])
        metric("minio_tpu_grid_peer_reconnects_total",
               "Grid connections re-established per peer", "counter",
               [({"peer": g["peer"]}, g["reconnects"]) for g in gstats])
        metric("minio_tpu_grid_peer_rpc_errors_total",
               "Grid transport failures per peer (timeouts, resets, "
               "refused connects; remote handler errors excluded)",
               "counter",
               [({"peer": g["peer"]}, g["rpc_errors"]) for g in gstats])
        # Native data plane (grid/loop.py epoll poller): multiplexed
        # stream frames, raw bulk transfer, zero-copy sendfile.
        from minio_tpu.grid import loop as _grid_loop
        lst_ = _grid_loop.stats()
        metric("minio_tpu_grid_native_enabled",
               "1 when the native grid data plane is active "
               "(MTPU_GRID_NATIVE kill switch + epoll availability)",
               "gauge", [({}, 1 if lst_["native"] else 0)])
        metric("minio_tpu_grid_stream_raw_tx_frames_total",
               "Raw bulk frames sent on the native plane", "counter",
               [({}, lst_["raw_tx_frames"])])
        metric("minio_tpu_grid_stream_raw_tx_bytes_total",
               "Raw bulk payload bytes sent on the native plane",
               "counter", [({}, lst_["raw_tx_bytes"])])
        metric("minio_tpu_grid_stream_raw_rx_frames_total",
               "Raw bulk frames received into pooled leases",
               "counter", [({}, lst_["raw_rx_frames"])])
        metric("minio_tpu_grid_stream_raw_rx_bytes_total",
               "Raw bulk payload bytes received into pooled leases",
               "counter", [({}, lst_["raw_rx_bytes"])])
        metric("minio_tpu_grid_stream_credit_stalls_total",
               "Times a bulk sender parked on an exhausted credit "
               "window (receiver not draining)", "counter",
               [({}, lst_["credit_stalls"])])
        metric("minio_tpu_grid_sendfile_transfers_total",
               "Shard transfers shipped via os.sendfile (zero "
               "Python-level copies send-side)", "counter",
               [({}, lst_["sendfile_transfers"])])
        metric("minio_tpu_grid_sendfile_bytes_total",
               "Bytes shipped via os.sendfile", "counter",
               [({}, lst_["sendfile_bytes"])])
        nst = _grid_peers.notify_stats()
        metric("minio_tpu_peer_notify_sent_total",
               "Peer reload notifications acknowledged", "counter",
               [({}, nst["sent"])])
        metric("minio_tpu_peer_notify_failed_total",
               "Peer reload notifications that failed (best-effort "
               "path; the receiver's TTL/resync is the fallback)",
               "counter", [({}, nst["failed"])])
        coh = getattr(server, "coherence", None) if server is not None \
            else None
        if coh is not None:
            cst = coh.stats()
            metric("minio_tpu_cluster_peers_armed",
                   "Peers whose generation state is synced (caches "
                   "serve hits only with every peer armed)", "gauge",
                   [({}, cst["armed"])])
            metric("minio_tpu_cluster_gen_resyncs_total",
                   "Generation resync rounds completed against peers",
                   "counter", [({}, cst["resyncs"])])
            metric("minio_tpu_cluster_invalidations_applied_total",
                   "Cross-node cache invalidations applied locally "
                   "(pushed + recovered by resync)", "counter",
                   [({}, cst["inv_applied"])])
            metric("minio_tpu_cluster_invalidations_failed_total",
                   "Invalidation pushes a peer failed to ack "
                   "(escalated: logged, connection reset, covered by "
                   "the peer's next resync)", "counter",
                   [({}, cst["inv_failed"])])
        if peer_states:
            metric("minio_tpu_worker_in_flight",
                   "In-flight requests per pre-forked worker", "gauge",
                   [({"worker": p.get("worker", "?")},
                     p.get("in_flight", 0))
                    for p in peer_states if not p.get("unreachable")])
            metric("minio_tpu_worker_up",
                   "Pre-forked worker control-plane reachability",
                   "gauge",
                   [({"worker": p.get("worker", "?")},
                     0 if p.get("unreachable") else 1)
                    for p in peer_states])
            metric("minio_tpu_workers_total",
                   "Configured pre-forked worker count", "gauge",
                   [({}, len(peer_states))])

        # -- SLO engine (utils/slo.py): burn-rate / budget gauges ------
        slo = getattr(server, "slo", None) if server is not None else None
        if slo is not None:
            snap = slo.snapshot(metrics=self)
            objs = snap.get("objectives", [])
            verdict_code = {"pass": 0, "warn": 1, "burn": 2}
            metric("minio_tpu_slo_objectives",
                   "Declared SLO objectives under continuous "
                   "evaluation", "gauge", [({}, len(objs))])
            metric("minio_tpu_slo_burn_rate",
                   "Error-budget burn rate per objective (1.0 = "
                   "burning exactly the declared budget)", "gauge",
                   [({"objective": o["name"]}, o["burn_rate"])
                    for o in objs])
            metric("minio_tpu_slo_error_budget_remaining",
                   "Fraction of the declared error budget left in the "
                   "rolling window", "gauge",
                   [({"objective": o["name"]}, o["budget_remaining"])
                    for o in objs])
            metric("minio_tpu_slo_p99_seconds",
                   "Observed p99 latency of the objective's API class "
                   "over the last minute", "gauge",
                   [({"objective": o["name"]}, o["p99_s"])
                    for o in objs])
            metric("minio_tpu_slo_shed_rate",
                   "Fraction of the objective's requests shed (503) in "
                   "the rolling window", "gauge",
                   [({"objective": o["name"]}, o["shed_rate"])
                    for o in objs])
            metric("minio_tpu_slo_verdict",
                   "Objective verdict: 0 pass, 1 warn, 2 burn",
                   "gauge",
                   [({"objective": o["name"]},
                     verdict_code.get(o["verdict"], 2)) for o in objs])

        # -- cluster federation: per-node identity families ------------
        if node_states:
            node_rows = []
            for ns in node_states:
                if isinstance(ns, dict):
                    node_rows.append((ns.get("node", "?") or "?", ns))
            metric("minio_tpu_cluster_node_up",
                   "Per-node reachability of the cluster telemetry "
                   "verb (peer.metrics)", "gauge",
                   [({"node": n}, 0 if ns.get("unreachable") else 1)
                    for n, ns in node_rows])
            req_rows, slow_rows, lm_rows, lag_rows = [], [], [], []
            for n, ns in node_rows:
                if ns.get("unreachable"):
                    continue
                total = 0
                wins = []
                for st in ns.get("states") or []:
                    if not isinstance(st, dict):
                        continue
                    total += sum(v for _, _, v in
                                 st.get("requests", []))
                    wins.extend(w for w in
                                st.get("last_minute", {}).values())
                req_rows.append(({"node": n}, total))
                slow_rows.append(({"node": n}, ns.get("slow_ops", 0)))
                if wins:
                    summ = summarize(LastMinute.merge(wins))
                    for q in ("p50", "p99"):
                        lm_rows.append(({"node": n, "q": q},
                                        round(summ.get(q, 0.0), 6)))
                lag = (ns.get("replication") or {}).get("lag_ms")
                if isinstance(lag, dict):
                    for q in ("p50", "p99"):
                        lag_rows.append(({"node": n, "q": q},
                                         lag.get(f"{q}_ms", 0.0)))
            metric("minio_tpu_cluster_node_requests_total",
                   "HTTP requests served per cluster node (all APIs)",
                   "counter", req_rows)
            metric("minio_tpu_cluster_node_slow_ops_total",
                   "Slow-op records per cluster node", "counter",
                   slow_rows)
            metric("minio_tpu_cluster_node_last_minute_seconds",
                   "Last-minute request latency quantiles per node "
                   "(all APIs merged)", "gauge", lm_rows)
            metric("minio_tpu_cluster_node_replication_lag_ms",
                   "Enqueue-to-delivered replication lag quantiles "
                   "per node", "gauge", lag_rows)

        return "\n".join(lines) + "\n"


def layer_sets(object_layer) -> list:
    """Erasure sets behind any object-layer shape (set / sets / pools)."""
    pools = getattr(object_layer, "pools", None)
    if pools is not None:
        return [s for p in pools for s in p.sets]
    sets = getattr(object_layer, "sets", None)
    if sets is not None:
        return list(sets)
    return [object_layer] if hasattr(object_layer, "disks") else []


def probe_disks(object_layer) -> list:
    """(set_idx, disk, DiskInfo-or-None) for every drive, probed in
    PARALLEL per set — one hung remote drive must not stack its timeout
    onto every other drive's (health probes have deadlines)."""
    out = []
    for si, s in enumerate(layer_sets(object_layer)):
        fanout = getattr(s, "_fanout", None)
        if fanout is not None:
            results, _ = fanout([lambda d=d: d.disk_info()
                                 for d in s.disks])
        else:  # pragma: no cover - every set has _fanout
            results = []
            for d in s.disks:
                try:
                    results.append(d.disk_info())
                except Exception:  # noqa: BLE001
                    results.append(None)
        for d, di in zip(s.disks, results):
            out.append((si, d, di))
    return out


def _lag_summary(state: dict) -> dict:
    """Approximate p50/p99 in milliseconds from a bucketed histogram
    state (latency.percentile: upper bound of the quantile's bucket)."""
    from minio_tpu.utils.latency import percentile
    counts = state.get("counts", [])
    total = state.get("count", 0)
    return {
        "count": total,
        "mean_ms": round(1000.0 * state.get("sum", 0.0) / total, 3)
        if total else 0.0,
        "p50_ms": round(percentile(counts, total, 0.5) * 1000.0, 3),
        "p99_ms": round(percentile(counts, total, 0.99) * 1000.0, 3),
    }


def merge_loop_stats(stats_list) -> dict:
    """Fleet merge of per-worker EventLoopServer.stats() snapshots:
    counters and gauges sum, max_conns sums (fleet capacity), the
    loop-lag histograms merge."""
    out = {"enabled": False, "parked": 0, "active": 0, "writing": 0,
           "max_conns": 0, "accepted_total": 0, "shed_total": 0,
           "reparks_total": 0, "reaped_idle_total": 0,
           "dispatch_total": 0, "hot_hits_total": 0,
           "executor_threads": 0, "executor_queue": 0}
    lags = []
    for st in stats_list:
        if not isinstance(st, dict):
            continue
        out["enabled"] = out["enabled"] or bool(st.get("enabled"))
        for k in list(out):
            if k != "enabled":
                out[k] += st.get(k, 0)
        if st.get("loop_lag"):
            lags.append(st["loop_lag"])
    if lags:
        out["loop_lag"] = Histogram.merge(lags)
    return out


def peer_metrics_state(server) -> dict:
    """One node's telemetry snapshot for the cluster-federation verb
    (grid `peer.metrics`): every local worker's Metrics.state() —
    fleet-merged through the pre-forked hub exactly the way a local
    scrape merges them, one topology level down — plus the node's
    slow-op total and replication lag summary, all under the node's
    self-declared identity. JSON/msgpack-safe by construction."""
    states = []
    cs = getattr(server, "cluster_stats", None)
    if cs is not None:
        try:
            states = [w["metrics"] for w in cs()
                      if isinstance(w.get("metrics"), dict)]
        except Exception:  # noqa: BLE001 - serve own snapshot
            states = []
    if not states:
        states = [server.metrics.state()]
    out = {"node": getattr(server, "node_id", "") or "",
           "states": states,
           "slow_ops": _tracing.slow_total}
    repl = getattr(server, "replicator", None)
    if repl is not None and hasattr(repl, "stats"):
        try:
            rst = repl.stats()
            lag = rst.pop("lag_hist", None)
            if lag:
                rst["lag_ms"] = _lag_summary(lag)
            out["replication"] = rst
        except Exception:  # noqa: BLE001 - lag is advisory
            pass
    return out


def node_info(server) -> dict:
    """One node's admin-info summary (drives, usage, heal state) —
    served locally by the admin handler and remotely over the grid's
    peer.info endpoint so cluster info covers every node (reference:
    cmd/notification.go ServerInfo fan-out)."""
    scanner = getattr(server.object_layer, "scanner", None)
    sets = layer_sets(server.object_layer)
    drives = []
    for si, d, di in probe_disks(server.object_layer):
        entry = {"set": si,
                 "endpoint": getattr(d, "endpoint", "")
                 or getattr(d, "root", "")}
        if di is not None:
            entry.update(state="ok", total=di.total,
                         used=di.used, free=di.free)
        else:
            entry.update(state="offline")
        drives.append(entry)
    usage = {}
    total_objects = 0
    if scanner is not None:
        u = scanner.usage
        total_objects = u.objects
        usage = {"objects": u.objects, "versions": u.versions,
                 "total_size": u.total_size,
                 "buckets": len(u.buckets),
                 "last_update": u.last_update}
    info = {
        "mode": "online",
        "node": getattr(server, "node_id", "") or "",
        "sets": len(sets),
        "drives": drives,
        "drives_online": sum(1 for d in drives if d["state"] == "ok"),
        "drives_offline": sum(1 for d in drives if d["state"] != "ok"),
        "objects": total_objects,
        "usage": usage,
        "heal": server.heal_status,
    }
    if getattr(server, "drive_heal", None) is not None:
        try:
            info["drive_heal"] = server.drive_heal.status()
        except Exception:  # noqa: BLE001 - status best effort
            pass
    # Elastic-fleet migrations (object/decom.py + object/rebalance.py):
    # the any-node status docs — a live local driver's counters when
    # this node coordinates, else the persisted rev-voted checkpoint.
    for sec, attr in (("decommission", "decommission_status"),
                      ("rebalance", "rebalance_status")):
        fn = getattr(server.object_layer, attr, None)
        if fn is not None:
            try:
                st = fn()
                if st:
                    info[sec] = st
            except Exception:  # noqa: BLE001 - status best effort
                pass
    adm = getattr(server, "admission", None)
    if adm is not None:
        # Shed/queue/deadline counters per request class: the operator-
        # facing view of admission control (reference: madmin info's
        # requests fields).
        info["admission"] = adm.snapshot()
    aud = getattr(server, "audit", None)
    if aud is not None:
        info["audit"] = aud.stats()
    repl = getattr(server, "replicator", None)
    if repl is not None and hasattr(repl, "stats"):
        try:
            rst = repl.stats()
            lag = rst.pop("lag_hist", None)
            if lag:
                rst["lag_ms"] = _lag_summary(lag)
            info["replication"] = rst
        except Exception:  # noqa: BLE001 - status best effort
            pass
    # Rolling last-minute latency per API + the recent slow-op records
    # (deep tracing's operator surface: a slow GET names its slow
    # span ancestry here without any trace subscriber attached).
    m = getattr(server, "metrics", None)
    if m is not None:
        info["last_minute"] = m.last_minute()
        # Connection plane (serve hot loop): open connections,
        # keep-alive reuse, native-parse fallbacks. Fleet-merged below
        # when the pre-forked control plane is up.
        info["http"] = m.http_conn_stats()
    # Event-loop connection plane (s3/eventloop.py): parked/active fd
    # gauges, accept/shed/re-park counters, loop-lag summary. Replaced
    # by the fleet merge below in worker mode.
    es = getattr(server, "eventloop_stats", None)
    loop_st = es() if es is not None else None
    if loop_st is not None:
        lag = loop_st.pop("loop_lag", None)
        if lag:
            loop_st["loop_lag_ms"] = _lag_summary(lag)
        info["connections"] = loop_st
    info["slow_ops"] = {"total": _tracing.slow_total,
                        "threshold_ms": _tracing.slow_ms(),
                        "recent": _tracing.slow_ops()[-20:]}
    # Continuous SLO engine (utils/slo.py): per-objective burn-rate /
    # remaining-budget with pass/warn/burn verdicts.
    slo = getattr(server, "slo", None)
    if slo is not None:
        try:
            info["slo"] = slo.snapshot(metrics=m)
        except Exception:  # noqa: BLE001 - verdicts are advisory
            pass
    # I/O engine: pool + per-drive queue health (and, in worker mode,
    # the whole fleet's per-worker snapshots via the control pipe).
    from minio_tpu.io.bufpool import global_pool
    info["bufpool"] = global_pool().stats()
    engine = []
    fileinfo = []
    metacache = []
    get_kernel = {"native": 0, "numpy": 0, "demoted": 0, "device": 0}
    for si, s in enumerate(sets):
        eng = getattr(s, "io", None)
        if eng is not None:
            engine.append({"set": si, "drives": eng.stats()})
        cache = getattr(s, "fi_cache", None)
        if cache is not None:
            fileinfo.append({"set": si, **cache.stats()})
        mc = getattr(s, "metacache", None)
        if mc is not None:
            metacache.append({"set": si, **mc.stats()})
        for key in get_kernel:
            get_kernel[key] += getattr(s, "get_kernel", {}).get(key, 0)
    # Group-commit write plane: per-set lane occupancy + the process's
    # WAL checkpoint counters (storage/group_commit).
    from minio_tpu.storage import group_commit as _gc_mod
    gst = _gc_mod.aggregate_stats()
    gst.pop("wait_hist", None)
    info["group_commit"] = gst
    # Fused transform plane: path split + bytes (object/transform).
    from minio_tpu.object import transform as _tf_mod
    tst = _tf_mod.stats()
    tst.pop("stage_hists", None)
    info["transform"] = tst
    info["io_engine"] = engine
    info["fileinfo_cache"] = fileinfo
    # Hot-object read tier (object/hotcache.py): this process's cache;
    # replaced by the fleet merge below in worker mode.
    hc = getattr(server, "hot_cache", None)
    if hc is not None:
        info["hot_cache"] = hc.stats()
    from minio_tpu.storage import meta_scan as _ms
    info["metacache"] = {"sets": metacache, "scan": dict(_ms.counters)}
    info["get_kernel"] = get_kernel
    # Distributed plane: per-peer breaker states, notify fan-out
    # outcomes, and the coherence protocol's arm/generation state.
    from minio_tpu.grid import client as _grid_client
    from minio_tpu.grid import peers as _grid_peers
    gstats = _grid_client.peer_stats()
    if gstats:
        info["grid"] = {"peers": gstats,
                        "notify": _grid_peers.notify_stats()}
    coh = getattr(server, "coherence", None)
    if coh is not None:
        info["coherence"] = coh.stats()
    cluster = getattr(server, "cluster_stats", None)
    if cluster is not None:
        try:
            peers = cluster()
            info["workers"] = [
                {k: p.get(k) for k in ("worker", "pid", "in_flight",
                                       "unreachable", "bufpool",
                                       "fileinfo_cache", "hot_cache",
                                       "drive_heal")
                 if k in p}
                for p in peers]
            peer_hot = [p.get("hot_cache") for p in peers
                        if isinstance(p.get("hot_cache"), dict)]
            if peer_hot:
                hot_agg: dict = {}
                for pst in peer_hot:
                    for k, v in pst.items():
                        if isinstance(v, bool):
                            hot_agg[k] = bool(hot_agg.get(k)) or v
                        elif isinstance(v, (int, float)):
                            hot_agg[k] = hot_agg.get(k, 0) + v
                info["hot_cache"] = hot_agg
            http_tot = {"connections_active": 0, "keepalive_reuses": 0,
                        "parse_fallbacks": 0,
                        "response_path": {"sendfile": 0, "pooled": 0,
                                          "legacy": 0}}
            merged = False
            for p in peers:
                st = p.get("metrics")
                if isinstance(st, dict):
                    merged = True
                    http_tot["connections_active"] += \
                        st.get("conn_active", 0)
                    http_tot["keepalive_reuses"] += \
                        st.get("keepalive_reuses", 0)
                    http_tot["parse_fallbacks"] += \
                        st.get("parse_fallbacks", 0)
                    for k, v in st.get("response_path", {}).items():
                        http_tot["response_path"][k] = \
                            http_tot["response_path"].get(k, 0) + v
            if merged:
                info["http"] = http_tot
            peer_loops = [p.get("connections") for p in peers
                          if isinstance(p.get("connections"), dict)]
            if peer_loops:
                fleet = merge_loop_stats(peer_loops)
                lag = fleet.pop("loop_lag", None)
                if lag:
                    fleet["loop_lag_ms"] = _lag_summary(lag)
                info["connections"] = fleet
        except Exception:  # noqa: BLE001 - control plane down; own view
            info["workers"] = [{"worker": getattr(server, "worker_id", 0),
                                "pid": os.getpid(),
                                "in_flight": server._inflight}]
    return info
