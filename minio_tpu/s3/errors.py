"""S3 API error catalogue and exception mapping (reference: cmd/api-errors.go)."""

from __future__ import annotations

from minio_tpu.object import types as ot
from minio_tpu.s3.sigv4 import SigError
from minio_tpu.utils.deadline import DeadlineExceeded

# code -> (http status, default message)
_CATALOG = {
    "AccessDenied": (403, "Access Denied."),
    "InvalidAccessKeyId": (403, "The Access Key Id you provided does not exist in our records."),
    "SignatureDoesNotMatch": (403, "The request signature we calculated does not match the signature you provided."),
    "AuthorizationHeaderMalformed": (400, "The authorization header is malformed."),
    "AuthorizationQueryParametersError": (400, "Query-string authorization parameters are malformed."),
    "XAmzContentSHA256Mismatch": (400, "The provided 'x-amz-content-sha256' header does not match what was computed."),
    "IncompleteBody": (400, "You did not provide the number of bytes specified by the Content-Length HTTP header."),
    "InvalidChunkSizeError": (400, "Invalid chunk size."),
    "NoSuchBucket": (404, "The specified bucket does not exist."),
    "BucketAlreadyOwnedByYou": (409, "Your previous request to create the named bucket succeeded and you already own it."),
    "BucketNotEmpty": (409, "The bucket you tried to delete is not empty."),
    "NoSuchKey": (404, "The specified key does not exist."),
    "NoSuchVersion": (404, "The specified version does not exist."),
    "MethodNotAllowed": (405, "The specified method is not allowed against this resource."),
    "InvalidRange": (416, "The requested range is not satisfiable."),
    "InvalidArgument": (400, "Invalid argument."),
    "InvalidBucketName": (400, "The specified bucket is not valid."),
    "InvalidObjectName": (400, "Object name contains unsupported characters."),
    "EntityTooLarge": (400, "Your proposed upload exceeds the maximum allowed object size."),
    "MissingContentLength": (411, "You must provide the Content-Length HTTP header."),
    "InternalError": (500, "We encountered an internal error, please try again."),
    "SlowDownRead": (503, "Resource requested is unreadable, please reduce your request rate"),
    "SlowDownWrite": (503, "Resource requested is unwritable, please reduce your request rate"),
    "SlowDown": (503, "Please reduce your request rate."),
    "RequestTimeout": (408, "The request did not complete within the allotted time, please reduce your request rate."),
    "MalformedXML": (400, "The XML you provided was not well-formed or did not validate against our published schema."),
    "NoSuchUpload": (404, "The specified multipart upload does not exist."),
    "InvalidPart": (400, "One or more of the specified parts could not be found."),
    "InvalidPartOrder": (400, "The list of parts was not in ascending order."),
    "EntityTooSmall": (400, "Your proposed upload is smaller than the minimum allowed object size."),
    "PreconditionFailed": (412, "At least one of the pre-conditions you specified did not hold."),
    "NotModified": (304, "Not Modified"),
    "NoSuchBucketPolicy": (404, "The bucket policy does not exist."),
    "NoSuchLifecycleConfiguration": (404, "The lifecycle configuration does not exist."),
    "NoSuchTagSet": (404, "The TagSet does not exist."),
    "ReplicationConfigurationNotFoundError": (404, "The replication configuration was not found."),
    "ServerSideEncryptionConfigurationNotFoundError": (404, "The server side encryption configuration was not found."),
    "ObjectLockConfigurationNotFoundError": (404, "Object Lock configuration does not exist for this bucket."),
    "NoSuchCORSConfiguration": (404, "The CORS configuration does not exist."),
    "NotImplemented": (501, "A header you provided implies functionality that is not implemented."),
    "MalformedPolicy": (400, "Policy has invalid resource."),
    "InvalidRequest": (400, "Invalid Request"),
    "InvalidDigest": (400, "The Content-Md5 you specified is not valid."),
    "MalformedPOSTRequest": (400, "The body of your POST request is not well-formed multipart/form-data."),
    "InvalidTag": (400, "The tag provided was not a valid tag."),
    "InvalidBucketState": (409, "The request is not valid with the current state of the bucket."),
    "NoSuchObjectLockConfiguration": (404, "The specified object does not have an ObjectLock configuration."),
    "MalformedACLError": (400, "The ACL that you provided was not well formed or did not validate against our published schema."),
    "XAmzContentChecksumMismatch": (400, "The provided checksum does not match the computed checksum."),
    "InvalidRetentionDate": (400, "Date must be provided in ISO 8601 format."),
    "XMinioAdminBucketQuotaExceeded": (400, "Bucket quota exceeded"),
    "XMinioAdminNoSuchQuotaConfiguration": (404, "The quota configuration does not exist"),
}


class S3Error(Exception):
    def __init__(self, code: str, message: str = "", bucket: str = "",
                 key: str = ""):
        status, default = _CATALOG.get(code, (500, code))
        self.code = code
        self.status = status
        self.message = message or default
        self.bucket = bucket
        self.key = key
        super().__init__(f"{code}: {self.message}")


def from_exception(e: Exception) -> S3Error:
    """Translate object-layer / auth exceptions into S3 errors."""
    if isinstance(e, S3Error):
        return e
    if isinstance(e, SigError):
        return S3Error(e.code if e.code in _CATALOG else "AccessDenied",
                       str(e))
    if isinstance(e, DeadlineExceeded):
        # The request outlived its admission-granted budget: the
        # correct verdict is "you timed out", never a hang and never a
        # misleading quorum error.
        return S3Error("RequestTimeout")
    from minio_tpu.object import multipart as mp
    mp_map = {mp.UploadNotFound: "NoSuchUpload", mp.InvalidPart: "InvalidPart",
              mp.InvalidPartOrder: "InvalidPartOrder",
              mp.EntityTooSmall: "EntityTooSmall"}
    for cls, code in mp_map.items():
        if isinstance(e, cls):
            return S3Error(code, str(e))
    from minio_tpu.object.nslock import LockTimeout
    if isinstance(e, LockTimeout):
        # Lock starvation — including a dsync lock quorum that is
        # unreachable (nodes down/partitioned) — answers an HONEST
        # 503 + Retry-After, not a 500 after the full lock timeout.
        return S3Error("SlowDown", str(e))
    b = getattr(e, "bucket", "")
    k = getattr(e, "object", "")
    mapping = {
        ot.BucketNotFound: "NoSuchBucket",
        ot.BucketExists: "BucketAlreadyOwnedByYou",
        ot.BucketNotEmpty: "BucketNotEmpty",
        ot.ObjectNotFound: "NoSuchKey",
        ot.VersionNotFound: "NoSuchVersion",
        ot.MethodNotAllowed: "MethodNotAllowed",
        ot.InvalidRange: "InvalidRange",
        ot.InvalidArgument: "InvalidArgument",
        ot.PreconditionFailed: "PreconditionFailed",
        ot.ReadQuorumError: "SlowDownRead",
        ot.WriteQuorumError: "SlowDownWrite",
    }
    for cls, code in mapping.items():
        if isinstance(e, cls):
            return S3Error(code, bucket=b, key=k)
    return S3Error("InternalError", str(e), bucket=b, key=k)
