"""Event-loop connection plane: epoll front end for 10k+ connections.

The thread-per-connection front end (ThreadingHTTPServer) spends a
thread stack on every OPEN connection — at SDK connection-pool fan-in
the connection count, not the per-request cost, becomes the wall. This
module replaces the accept path inside each pre-forked worker with one
epoll event loop:

  * idle connections PARK in a single epoll set costing a file
    descriptor and a small Python object — their pooled recv buffer is
    hibernated (returned to io/bufpool) whenever it is empty, so 10k
    idle keep-alive connections hold zero recv buffers;
  * readable sockets drain non-blocking into their per-connection
    ConnReader (s3/hotloop.py) until the native framer
    (`mtpu_http_head`) frames a COMPLETE request head — only then is
    the request dispatched to a bounded executor running the existing
    handler stack (partial heads never occupy a thread: slowloris
    clients are reaped by the idle deadline while parked);
  * keep-alive turnaround RE-PARKS the fd instead of pinning a thread;
    pipelined requests already buffered are served back-to-back on the
    same dispatch;
  * a response's FINAL gathered write is EAGAIN-aware: when the socket
    buffer fills, the remainder is handed to the loop's EPOLLOUT
    machinery and the executor thread returns to the pool
    (`offload_final`), the loop finishing the drain and re-parking;
  * connection-level backpressure runs BEFORE request-level shedding:
    past MTPU_MAX_CONNS the loop answers accepts with an immediate
    503 + Retry-After and closes, so an fd storm can never starve the
    admission gates of descriptors.

With the native framer disabled (MTPU_HTTP_NATIVE=off) the loop still
parks idle connections; a readable socket dispatches the stock
blocking parser (head framing then happens in the executor under the
keep-alive timeout).

Environment:
  MTPU_HTTP_EVENTLOOP  "off"/"0"/"false" reverts wholesale to the
                       thread-per-connection path (kill-switch)
  MTPU_LOOP_WORKERS    executor threads per worker process
                       (default max(8, 4 x cores))
  MTPU_MAX_CONNS       per-worker open-connection cap (default: soft
                       RLIMIT_NOFILE minus 512 headroom, min 64)
  MTPU_HTTP_KEEPALIVE_S  idle deadline for parked connections (shared
                       with the thread path; <= 0 disables reaping)
"""

from __future__ import annotations

import collections
import os
import queue
import select
import socket
import sys
import threading
import time

from minio_tpu.s3 import hotloop
from minio_tpu.utils.env import env_int
from minio_tpu.utils.latency import Histogram

_LISTEN_BACKLOG = 1024
_REAP_INTERVAL = 1.0
# Pipelined requests served per dispatch before the connection yields
# the executor thread back (fairness under a hot pipelining client).
_PIPELINE_BURST = 32

# Connection-level backpressure: the canned response for accepts past
# MTPU_MAX_CONNS — shed BEFORE any byte is read, so request-level
# admission (s3/admission.py) never sees the overflow.
_SHED_RESPONSE = (b"HTTP/1.1 503 Service Unavailable\r\n"
                  b"Retry-After: 1\r\nContent-Length: 0\r\n"
                  b"Connection: close\r\n\r\n")

# _Conn states.
_PARKED = 0        # in the epoll set, waiting for bytes
_DISPATCHED = 1    # an executor thread owns the socket
_WRITING = 2       # loop owns a response tail (EPOLLOUT drain)


def loop_enabled(env=os.environ) -> bool:
    """MTPU_HTTP_EVENTLOOP kill-switch + platform gate (epoll is
    Linux; other platforms keep the thread path)."""
    if env.get("MTPU_HTTP_EVENTLOOP", "").lower() in ("off", "0", "false"):
        return False
    return hasattr(select, "epoll")


def default_max_conns() -> int:
    """Per-worker connection cap: the soft fd limit minus headroom for
    drives, pool internals, and the control plane."""
    try:
        import resource
        soft, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
    except Exception:  # noqa: BLE001 - exotic platform
        soft = 1024
    if soft <= 0 or soft >= (1 << 30):      # RLIM_INFINITY
        soft = 1 << 20
    return max(64, soft - 512)


_EXIT = object()                 # _Executor pool-release sentinel


class _Executor:
    """Bounded lazy pool of DAEMON worker threads (ThreadPoolExecutor
    threads are non-daemon and would block interpreter exit — the
    thread front end uses daemon handler threads, and drain-on-stop is
    owned by S3Server's in-flight counter, not by thread joins)."""

    def __init__(self, max_workers: int):
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._max = max(1, max_workers)
        self._mu = threading.Lock()
        self.threads = 0
        self._idle = 0
        self._pending = 0

    def submit(self, fn) -> None:
        # Spawn whenever queued-but-unclaimed tasks outnumber threads
        # actually blocked in q.get(): a burst of submits from the loop
        # thread must not serialize behind one idle thread that hasn't
        # woken yet (an admin/health dispatch queued behind a slow data
        # request would starve).
        with self._mu:
            self._pending += 1
            spawn = self._pending > self._idle and self.threads < self._max
            if spawn:
                self.threads += 1
        if spawn:
            try:
                threading.Thread(target=self._run, daemon=True,
                                 name="loop-exec").start()
            except Exception:
                # Thread exhaustion: roll the count back so a later
                # submit retries the spawn. With at least one live
                # thread the queued task still drains; with none the
                # dispatch fails loudly (caller closes that conn only).
                with self._mu:
                    self.threads -= 1
                    if self.threads == 0:
                        self._pending -= 1
                        raise
        self._q.put(fn)

    def _run(self) -> None:
        while True:
            with self._mu:
                self._idle += 1
            fn = self._q.get()
            if fn is _EXIT:
                with self._mu:
                    self._idle -= 1
                    self.threads -= 1
                return
            with self._mu:
                self._idle -= 1
                self._pending -= 1
            try:
                fn()
            except Exception:  # noqa: BLE001 - a task must not kill a worker
                pass

    def shutdown(self) -> None:
        """Release the pool: one exit sentinel per live thread, queued
        BEHIND any remaining tasks (SimpleQueue is FIFO, so queued
        dispatches still drain). Must run after the loop thread has
        stopped submitting — threads parked in q.get() forever would
        compound across server lifecycles."""
        with self._mu:
            n = self.threads
        for _ in range(n):
            self._q.put(_EXIT)

    def depth(self) -> int:
        return self._q.qsize()


class _Conn:
    __slots__ = ("sock", "fd", "handler", "reader", "state", "registered",
                 "last_activity", "pending", "close_after_write")

    def __init__(self, sock, fd, handler, reader):
        self.sock = sock
        self.fd = fd
        self.handler = handler
        self.reader = reader               # ConnReader or None (native off)
        self.state = _PARKED
        self.registered = False
        self.last_activity = time.monotonic()
        self.pending = None                # loop-owned response tail
        self.close_after_write = False


class EventLoopServer:
    """epoll accept/dispatch front end, API-compatible with the subset
    of ThreadingHTTPServer that S3Server drives (server_address,
    serve_forever/shutdown/server_close)."""

    daemon_threads = True        # attribute parity with the thread path

    def __init__(self, server_address, HandlerClass, reuse_port: bool = False,
                 keepalive_s: float | None = 75.0,
                 max_conns: int | None = None, workers: int | None = None):
        self.handler_cls = HandlerClass
        self.keepalive_s = keepalive_s
        self.max_conns = max_conns if max_conns is not None else \
            env_int("MTPU_MAX_CONNS", default_max_conns())
        self._native_lib = getattr(HandlerClass, "loop_native_lib", None)
        # Hot-cache short circuit (object/hotcache.py via the handler's
        # loop_hot_probe): answer resident GETs ON the loop thread,
        # before dispatch. None = handler has no hot tier wired.
        self._hot_probe = getattr(HandlerClass, "loop_hot_probe", None)
        self.socket = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuse_port:
            self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self.socket.bind(server_address)
        self.server_address = self.socket.getsockname()
        self.socket.listen(_LISTEN_BACKLOG)
        self.socket.setblocking(False)
        self._epoll = select.epoll()
        self._wr, self._ww = os.pipe()
        os.set_blocking(self._wr, False)
        n_workers = workers if workers is not None else env_int(
            "MTPU_LOOP_WORKERS", max(8, 4 * (os.cpu_count() or 1)))
        self._executor = _Executor(n_workers)
        self._mu = threading.Lock()
        self._conns: dict[int, _Conn] = {}
        self._inbox: collections.deque = collections.deque()
        self._running = False
        self._stopping = False
        self._closed = False
        self._done = threading.Event()
        # Connection-plane counters (loop thread is the only writer for
        # most; reads are snapshots for metrics/admin).
        self.loop_lag = Histogram()
        self.accepted_total = 0
        self.shed_total = 0
        self.reparks_total = 0
        self.reaped_idle_total = 0
        self.dispatch_total = 0
        self.hot_hits_total = 0

    # -- loop ------------------------------------------------------------

    def serve_forever(self, poll_interval: float = 0.5) -> None:
        self._running = True
        ep = self._epoll
        lfd = self.socket.fileno()
        ep.register(lfd, select.EPOLLIN)
        ep.register(self._wr, select.EPOLLIN)
        last_reap = time.monotonic()
        try:
            while not self._stopping:
                try:
                    events = ep.poll(poll_interval)
                except InterruptedError:
                    continue
                if self._stopping:
                    break
                t0 = time.monotonic()
                had_events = bool(events) or bool(self._inbox)
                for fd, ev in events:
                    try:
                        if fd == lfd:
                            self._accept_burst()
                        elif fd == self._wr:
                            self._drain_wakeup()
                        else:
                            self._on_event(fd, ev)
                    except Exception:  # noqa: BLE001 - one conn only
                        self._oops(fd)
                self._process_inbox()
                now = time.monotonic()
                if had_events:
                    # Loop lag: how long this tick's ready events waited
                    # on the loop thread — the dispatch latency the
                    # single-threaded plane adds on top of the kernel.
                    self.loop_lag.observe(now - t0)
                if self.keepalive_s is not None \
                        and now - last_reap >= _REAP_INTERVAL:
                    last_reap = now
                    self._reap_idle(now)
        finally:
            self._running = False
            self._teardown()
            self._done.set()

    def _oops(self, fd: int) -> None:
        """Last-ditch per-connection failure containment: the loop must
        survive any single socket's misbehavior."""
        with self._mu:
            conn = self._conns.get(fd)
        if conn is not None:
            self._destroy(conn)

    def _drain_wakeup(self) -> None:
        try:
            while os.read(self._wr, 4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    def _post(self, item) -> bool:
        """Hand a connection back to the loop thread; False when the
        loop is gone (caller must clean up inline)."""
        with self._mu:
            if self._stopping or not self._running:
                return False
            self._inbox.append(item)
        try:
            os.write(self._ww, b"x")
        except OSError:
            return False
        return True

    def _process_inbox(self) -> None:
        while True:
            try:
                op, conn = self._inbox.popleft()
            except IndexError:
                return
            try:
                if op == "park":
                    self._park(conn)
                elif op == "write":
                    self._begin_write(conn)
                elif op == "close":
                    self._destroy(conn)
            except Exception:  # noqa: BLE001 - one conn only, loop survives
                self._oops(conn.fd)

    # -- accept / backpressure -------------------------------------------

    def _accept_burst(self) -> None:
        # Bounded per tick: an accept storm must not starve parked
        # connections' events (level-triggered epoll re-arms the rest).
        for _ in range(256):
            try:
                s, addr = self.socket.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            with self._mu:
                n_conns = len(self._conns)
            if self._stopping:
                s.close()
                return
            if n_conns >= self.max_conns:
                self._shed(s)
                continue
            self.accepted_total += 1
            s.setblocking(False)
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = self._new_conn(s, addr)
            if conn is None:
                s.close()
                continue
            with self._mu:
                self._conns[conn.fd] = conn
            self._register(conn, select.EPOLLIN)

    def _shed(self, s: socket.socket) -> None:
        """Connection-level backpressure: immediate 503 + close, no
        handler, no buffer, no thread."""
        self.shed_total += 1
        try:
            s.setblocking(False)
            s.send(_SHED_RESPONSE)
        except OSError:
            pass
        finally:
            s.close()

    def _new_conn(self, s, addr):
        h = self.handler_cls.__new__(self.handler_cls)
        h.request = s
        h.client_address = addr
        h.server = self
        h.close_connection = True
        try:
            h.setup()
        except Exception:  # noqa: BLE001 - per-conn alloc failure
            return None
        conn = _Conn(s, s.fileno(), h, getattr(h, "_conn", None))
        h._loop_conn = conn
        return conn

    # -- epoll bookkeeping ----------------------------------------------

    def _register(self, conn: _Conn, mask) -> None:
        if conn.registered:
            self._epoll.modify(conn.fd, mask)
        else:
            self._epoll.register(conn.fd, mask)
            conn.registered = True

    def _unregister(self, conn: _Conn) -> None:
        if conn.registered:
            conn.registered = False
            try:
                self._epoll.unregister(conn.fd)
            except (OSError, ValueError):
                # ValueError: epoll already closed (teardown ordering).
                pass

    # -- read side -------------------------------------------------------

    def _on_event(self, fd: int, ev) -> None:
        with self._mu:
            conn = self._conns.get(fd)
        if conn is None:
            try:
                self._epoll.unregister(fd)
            except OSError:
                pass
            return
        if conn.state == _WRITING:
            if ev & (select.EPOLLHUP | select.EPOLLERR):
                self._destroy(conn)
            else:
                self._drain_pending(conn)
            return
        if conn.state != _PARKED:
            return
        if ev & select.EPOLLERR:
            self._destroy(conn)
            return
        self._read_ready(conn, ev)

    def _read_ready(self, conn: _Conn, ev) -> None:
        reader = conn.reader
        if reader is None:
            # Native framer off: no loop-side buffer exists. EPOLLHUP
            # with no pending bytes is a plain disconnect; otherwise
            # dispatch the stock blocking parser (bytes wait in the
            # kernel buffer until the executor reads them).
            if ev & select.EPOLLHUP and not ev & select.EPOLLIN:
                self._destroy(conn)
                return
            self._dispatch(conn, "stock", None)
            return
        n = reader.fill_nb()
        if n == 0:
            if reader.buffered:
                # EOF mid-head: stock error path decides (thread-path
                # parity with parse_head's _Fallback on EOF-mid-head).
                self._dispatch(conn, "fallback", None)
            else:
                self._destroy(conn)        # clean close between requests
            return
        if n is None:
            if ev & select.EPOLLHUP:
                self._destroy(conn)
            return
        conn.last_activity = time.monotonic()
        self._advance(conn)

    def _advance(self, conn: _Conn) -> None:
        """Frame-or-park: dispatch when a complete head (or a
        fallback-worthy prefix) is buffered; otherwise stay parked —
        a partial head never holds an executor thread.

        Hot-cache short circuit: each framed head is first offered to
        the handler's loop_hot_probe — a resident GET is answered right
        here on the loop thread (no dispatch, no executor round-trip)
        and the next pipelined head is framed immediately, bounded by
        the same burst cap the executor applies."""
        served = 0
        while True:
            status, head = conn.reader.try_parse_head(self._native_lib)
            if status == "head":
                if served < _PIPELINE_BURST:
                    hot = self._try_hot(conn, head)
                    if hot == "served":
                        served += 1
                        continue
                    if hot == "done":
                        return
                self._dispatch(conn, "head", head)
                return
            if status == "fallback":
                self._dispatch(conn, "fallback", None)
                return
            # "more": remain parked; the idle deadline covers slow
            # heads. After hot hits, drop the pooled recv buffer like
            # a re-park does.
            if served and conn.reader is not None \
                    and not conn.reader.buffered:
                conn.reader.hibernate()
            return

    def _try_hot(self, conn: _Conn, head) -> str | None:
        """Answer one framed request from the hot-object tier, on the
        loop thread. Returns None when the probe declines (caller
        dispatches THIS head to the executor), "served" when the
        response went out fully and the connection stays parked, or
        "done" when the connection was destroyed or handed to the
        EPOLLOUT tail drain."""
        probe = self._hot_probe
        if probe is None:
            return None
        try:
            res = probe(conn.handler, head)
        except Exception:  # noqa: BLE001 - probe failure: full handler
            return None
        if res is None:
            return None
        bufs, close = res
        self.hot_hits_total += 1
        conn.last_activity = time.monotonic()
        try:
            _, rest = hotloop.send_nb(conn.sock, bufs)
        except OSError:
            self._destroy(conn)
            return "done"
        if rest:
            # Slow reader: the remainder becomes a loop-owned response
            # tail. No copy needed — hot-entry buffers are immutable
            # bytes pinned by the cache, unlike pooled windows.
            conn.pending = rest
            conn.close_after_write = close
            conn.state = _WRITING
            self._register(conn, select.EPOLLOUT)
            return "done"
        if close:
            self._destroy(conn)
            return "done"
        return "served"

    def _dispatch(self, conn: _Conn, mode: str, head) -> None:
        conn.state = _DISPATCHED
        self._unregister(conn)
        self.dispatch_total += 1
        self._executor.submit(lambda: self._serve(conn, mode, head))

    # -- executor side ---------------------------------------------------

    def _serve(self, conn: _Conn, mode: str, head) -> None:
        """One dispatch: serve the framed request (and any pipelined
        successors already buffered), then hand the connection back to
        the loop — re-park, tail-write, or close."""
        h = conn.handler
        sock = conn.sock
        broken = False
        try:
            sock.setblocking(True)
            for _ in range(_PIPELINE_BURST):
                if mode == "head":
                    sock.settimeout(None)        # thread-path parity:
                    h._dispatch_head(head)       # body reads block
                else:
                    # "fallback" (native framer declined the buffered
                    # bytes) and "stock" (native off): the handler's own
                    # thread-path entry point — it re-runs the framing
                    # decision on the SAME bytes, counts the fallback,
                    # and applies the stock keep-alive timeout shape.
                    sock.settimeout(self.keepalive_s)
                    h.handle_one_request()
                    sock.settimeout(None)
                if h.close_connection or conn.pending is not None:
                    break
                mode, head = self._next_buffered(conn, h)
                if mode is None:
                    break
        except Exception:  # noqa: BLE001 - dead client / handler failure
            broken = True
        # Hand back to the loop thread.
        if conn.pending is not None and not broken:
            conn.close_after_write = h.close_connection
            if not self._post(("write", conn)):
                self._destroy(conn)
            return
        if broken or h.close_connection:
            if not self._post(("close", conn)):
                self._destroy(conn)
            return
        conn.last_activity = time.monotonic()
        if conn.reader is not None and not conn.reader.buffered:
            # Idle keep-alive: park with ZERO pooled bytes held.
            conn.reader.hibernate()
        try:
            sock.setblocking(False)
        except OSError:
            self._destroy(conn)
            return
        if not self._post(("park", conn)):
            self._destroy(conn)

    def _next_buffered(self, conn: _Conn, h):
        """Pipelining probe after a served request: another complete
        head already buffered? ("head"/"fallback"/"stock", head) to
        keep serving on this thread, (None, None) to re-park."""
        reader = conn.reader
        if reader is not None:
            if not reader.buffered:
                return None, None
            if self._native_lib is None:
                return "fallback", None
            status, head = reader.try_parse_head(self._native_lib)
            if status == "head":
                return "head", head
            if status == "fallback":
                return "fallback", None
            return None, None              # partial next head: park
        # Stock rfile: peek without blocking (non-blocking raw read
        # returns None into the BufferedReader, which then reports
        # only what it already buffered).
        try:
            conn.sock.setblocking(False)
            try:
                buffered = h.rfile.peek(1) if hasattr(h.rfile, "peek") \
                    else b""
            finally:
                conn.sock.setblocking(True)
        except (OSError, ValueError):
            return None, None
        return ("stock", None) if buffered else (None, None)

    # -- loop-owned response tails --------------------------------------

    def offload_final(self, conn: _Conn, bufs) -> bool:
        """A response's FINAL gathered write, EAGAIN-aware (executor
        context): send what the socket takes now; COPY the remainder
        (pooled views die when their generator closes) and leave it on
        the connection for the loop's EPOLLOUT drain. Always handles
        the buffers; raises like send_gathered on a dead peer."""
        sock = conn.sock
        sock.setblocking(False)
        try:
            _, rest = hotloop.send_nb(sock, bufs)
        finally:
            try:
                sock.setblocking(True)
            except OSError:
                pass
        if rest:
            conn.pending = [memoryview(bytes(b)) for b in rest]
        return True

    def _begin_write(self, conn: _Conn) -> None:
        conn.state = _WRITING
        # The executor restored blocking mode for the handler; from
        # here the LOOP owns the socket and every send must EAGAIN,
        # not block the loop thread.
        try:
            conn.sock.setblocking(False)
        except OSError:
            self._destroy(conn)
            return
        self._drain_pending(conn)

    def _drain_pending(self, conn: _Conn) -> None:
        try:
            _, rest = hotloop.send_nb(conn.sock, conn.pending or [])
        except OSError:
            self._destroy(conn)
            return
        if rest:
            conn.pending = rest
            conn.last_activity = time.monotonic()
            self._register(conn, select.EPOLLOUT)
            return
        conn.pending = None
        if conn.close_after_write:
            self._destroy(conn)
            return
        # Tail drained: back to a parked keep-alive connection.
        conn.state = _PARKED
        conn.last_activity = time.monotonic()
        self.reparks_total += 1
        if conn.reader is not None and not conn.reader.buffered:
            conn.reader.hibernate()
        self._register(conn, select.EPOLLIN)
        if conn.reader is not None and conn.reader.buffered:
            self._advance(conn)

    def _park(self, conn: _Conn) -> None:
        if self._stopping:
            self._destroy(conn)
            return
        conn.state = _PARKED
        self.reparks_total += 1
        self._register(conn, select.EPOLLIN)

    # -- reaping / teardown ----------------------------------------------

    def _reap_idle(self, now: float) -> None:
        ks = self.keepalive_s
        with self._mu:
            conns = list(self._conns.values())
        for conn in conns:
            if conn.state != _DISPATCHED \
                    and now - conn.last_activity > ks:
                # Parked idle keep-alive AND parked-with-partial-head
                # (slowloris) AND stalled tail writes all age out on
                # the same deadline.
                self.reaped_idle_total += 1
                self._destroy(conn)

    def _destroy(self, conn: _Conn) -> None:
        """Close one connection: epoll, handler teardown (recv-buffer
        lease, conn gauge), socket. Loop thread or — after the loop has
        stopped — the owning executor thread."""
        with self._mu:
            live = self._conns.pop(conn.fd, None) is not None
        if not live:
            return
        # Only the loop thread ever registers, so a conn reaching here
        # from an executor (post-stop cleanup) is never registered.
        self._unregister(conn)
        try:
            conn.handler.finish()
        except Exception:  # noqa: BLE001 - dead socket teardown
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _teardown(self) -> None:
        self._process_inbox()
        with self._mu:
            conns = list(self._conns.values())
        for conn in conns:
            if conn.state != _DISPATCHED:
                # In-flight requests keep their sockets; their executor
                # threads clean up on completion (_post sees stopping).
                self._destroy(conn)
        self.server_close()
        try:
            self._epoll.close()
        except OSError:
            pass
        for fd in (self._wr, self._ww):
            try:
                os.close(fd)
            except OSError:
                pass

    def shutdown(self) -> None:
        with self._mu:
            self._stopping = True
        if not self._running:
            return
        try:
            os.write(self._ww, b"x")
        except OSError:
            pass
        if not self._done.wait(timeout=10):
            print("eventloop: loop thread failed to stop in 10s",
                  file=sys.stderr)
        self._executor.shutdown()

    def server_close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.socket.close()
        except OSError:
            pass

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            conns = list(self._conns.values())
        parked = sum(1 for c in conns if c.state == _PARKED)
        writing = sum(1 for c in conns if c.state == _WRITING)
        return {
            "enabled": True,
            "parked": parked,
            "active": len(conns) - parked,
            "writing": writing,
            "max_conns": self.max_conns,
            "accepted_total": self.accepted_total,
            "shed_total": self.shed_total,
            "reparks_total": self.reparks_total,
            "reaped_idle_total": self.reaped_idle_total,
            "dispatch_total": self.dispatch_total,
            "hot_hits_total": self.hot_hits_total,
            "executor_threads": self._executor.threads,
            "executor_queue": self._executor.depth(),
            "loop_lag": self.loop_lag.state(),
        }
