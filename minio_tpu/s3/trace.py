"""Request tracing and audit logging.

The observability pair from the reference (§5 aux subsystems):
  * trace — a live pub/sub of per-request records, streamed to admin
    clients over HTTP (reference: cmd/admin-handlers.go TraceHandler +
    pubsub, `mc admin trace` counterpart);
  * audit — one structured record per completed request, delivered to a
    webhook target best-effort with a bounded retry queue (reference:
    internal/logger audit targets).
"""

from __future__ import annotations

import collections
import json
import queue
import threading
import time
import urllib.request
from typing import Optional


def make_entry(api: str, method: str, path: str, bucket: str, key: str,
               status: int, duration_s: float, remote: str,
               access_key: str, rx: int = 0, tx: int = 0) -> dict:
    """One trace/audit record (the reference's madmin.TraceInfo /
    audit.Entry shape, trimmed). Timestamps carry millisecond
    precision — whole-second stamps made entries from one burst
    unsortable — in the same format span entries use."""
    from minio_tpu.utils.tracing import _iso_ms
    return {
        "version": "1",
        "time": _iso_ms(time.time()),
        "api": api,
        "method": method,
        "path": path,
        "bucket": bucket,
        "object": key,
        "statusCode": status,
        "durationMs": round(duration_s * 1000, 3),
        "remoteHost": remote,
        "accessKey": access_key,
        "rx": rx,
        "tx": tx,
    }


class TraceBroadcaster:
    """Bounded pub/sub with per-subscriber TYPE filters: subscribers
    receive every published entry of the types they asked for
    (`s3|storage|grid|kernel|scanner|heal`; default just the top-level
    s3 records) while subscribed; slow subscribers drop oldest entries
    rather than backpressuring the request path.

    Deep (non-s3) span collection is armed only while somebody watches:
    any subscription or remote relay wanting internal types holds an
    utils/tracing arm() token, so the request path's span machinery is
    a single attribute check when nobody does. The remote relay is the
    pre-forked worker side of cross-process streaming (io/workers.py):
    armed workers buffer matching entries in a bounded ring the parent
    drains over the control pipe."""

    _DEPTH = 1000
    _RELAY_DEPTH = 2000
    # The remote relay self-disarms when no drain has refreshed it for
    # this long (drains normally arrive every ~0.2 s): a parent whose
    # trace_stop never reached this worker (timeout, respawn, parent
    # death) must not leave span collection armed forever.
    _REMOTE_TTL = 10.0

    def __init__(self):
        self._mu = threading.Lock()
        self._subs: list[tuple[queue.Queue, frozenset]] = []
        self._remote_types: frozenset = frozenset()
        self._remote_deadline = 0.0
        self._relay: collections.deque = \
            collections.deque(maxlen=self._RELAY_DEPTH)
        # Plain bool refreshed under _mu, read WITHOUT it: every
        # request completion checks `active` — a mutex there would tax
        # the disarmed fast path the whole design protects.
        self._active = False

    @property
    def active(self) -> bool:
        return self._active

    def _rearm_locked(self) -> None:
        from minio_tpu.utils import tracing
        self._active = bool(self._subs) or bool(self._remote_types)
        wanted = set(self._remote_types)
        for _, types in self._subs:
            wanted |= types
        if wanted - {"s3"}:
            tracing.arm(self)
        else:
            tracing.disarm(self)

    def wants_internal(self) -> bool:
        """True when any subscriber (local or remote relay) asked for
        non-s3 span types — the server only renders span entries then."""
        with self._mu:
            if self._remote_types - {"s3"}:
                return True
            return any(types - {"s3"} for _, types in self._subs)

    def subscribe(self, types=None) -> queue.Queue:
        q: queue.Queue = queue.Queue(maxsize=self._DEPTH)
        with self._mu:
            self._subs.append((q, frozenset(types or ("s3",))))
            self._rearm_locked()
        return q

    def unsubscribe(self, q: queue.Queue) -> None:
        with self._mu:
            self._subs = [(sq, t) for sq, t in self._subs if sq is not q]
            self._rearm_locked()

    # -- cross-worker relay (io/workers.py control pipes) ---------------

    def arm_remote(self, types) -> None:
        """Buffer matching entries for the parent's drain poll
        (idempotent; each drain re-arms and refreshes the TTL, so
        respawned workers heal and missed trace_stops age out)."""
        with self._mu:
            self._remote_types = frozenset(types or ("s3",))
            self._remote_deadline = time.monotonic() + self._REMOTE_TTL
            self._rearm_locked()

    def disarm_remote(self) -> None:
        with self._mu:
            self._remote_types = frozenset()
            self._relay.clear()
            self._rearm_locked()

    def drain_remote(self) -> list[dict]:
        with self._mu:
            out = list(self._relay)
            self._relay.clear()
        return out

    def _remote_expired_locked(self) -> bool:
        """Lazy TTL: a relay nobody drains (missed trace_stop, dead
        parent) self-disarms rather than taxing every request forever."""
        if self._remote_types \
                and time.monotonic() > self._remote_deadline:
            self._remote_types = frozenset()
            self._relay.clear()
            self._rearm_locked()
            return True
        return False

    def publish(self, entry: dict) -> None:
        etype = entry.get("trace_type", "s3")
        wild = entry.get("broadcast", False)
        with self._mu:
            if self._remote_types:
                self._remote_expired_locked()
            subs = [q for q, types in self._subs
                    if wild or etype in types]
            if self._remote_types and (wild or etype in self._remote_types):
                self._relay.append(entry)
        for q in subs:
            try:
                q.put_nowait(entry)
            except queue.Full:
                try:
                    q.get_nowait()      # drop oldest
                    q.put_nowait(entry)
                except (queue.Empty, queue.Full):
                    pass


def make_trace_stream(server):
    """Grid stream verb (`trace.stream`) backing ?cluster=true admin
    trace: a peer node pulls THIS node's live trace entries as a
    stream of batches (lists of entry dicts). Subscribes exactly like
    the local admin handler — fleet-wide through the worker control
    pipe when the hub is up, else the local broadcaster — and yields
    an empty batch at least once per second so the grid client's
    per-frame liveness window never lapses on an idle node. Ends when
    the consumer stops draining (credit stall unwinds the generator)
    or the connection drops."""

    def _stream(payload):
        spec = payload if isinstance(payload, dict) else {}
        types = sorted({str(t) for t in spec.get("types") or ["s3"]})
        hub = getattr(server, "cluster_trace", None)
        sub = sub_id = None
        if hub is not None:
            try:
                sub_id = hub.trace_sub(types)
            except Exception:  # noqa: BLE001 - control plane down
                hub = None
        if hub is None:
            sub = server.tracer.subscribe(set(types))
        try:
            last_yield = time.monotonic()
            while True:
                if hub is not None:
                    entries = hub.trace_poll(sub_id)
                    if not entries:
                        if time.monotonic() - last_yield < 1.0:
                            time.sleep(0.2)
                            continue
                else:
                    try:
                        entries = [sub.get(timeout=1.0)]
                    except queue.Empty:
                        entries = []
                yield entries       # empty batch = heartbeat
                last_yield = time.monotonic()
        finally:
            if hub is not None:
                try:
                    hub.trace_unsub(sub_id)
                except Exception:  # noqa: BLE001 - best effort
                    pass
            else:
                server.tracer.unsubscribe(sub)

    return _stream


class AuditLogger:
    """Webhook audit target with a bounded in-memory retry deque.

    Audit is best-effort telemetry: a down target never blocks requests;
    entries beyond the buffer (or failing more than _MAX_ATTEMPTS
    deliveries — one poison entry must not dam the whole stream) count
    as dropped. Delivery reuses the shared events WebhookTarget."""

    _BUFFER = 10_000
    _MAX_ATTEMPTS = 5

    def __init__(self, endpoint: str, timeout: float = 3.0):
        from minio_tpu.events.notify import WebhookTarget
        self._target = WebhookTarget("audit", endpoint, timeout=timeout)
        self.endpoint = endpoint
        self.sent = 0
        self.dropped = 0
        self._q: collections.deque = collections.deque(maxlen=self._BUFFER)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def submit(self, entry: dict) -> None:
        if len(self._q) == self._q.maxlen:
            # Overflow evicts the OLDEST queued record; it must be
            # counted (and is exported — minio_tpu_audit_dropped_total),
            # never silently vanish.
            self.dropped += 1
        self._q.append((entry, 0))
        self._wake.set()

    def stats(self) -> dict:
        """Delivery counters for metrics/admin info: drops are real
        audit loss and must be VISIBLE (alertable), not silent."""
        return {"endpoint": self.endpoint, "sent": self.sent,
                "dropped": self.dropped, "pending": len(self._q)}

    def _run(self) -> None:
        backoff = 0.5
        while not self._stop.is_set():
            if not self._q:
                self._wake.wait(timeout=1.0)
                self._wake.clear()
                continue
            entry, attempts = self._q[0]
            try:
                self._target.send(entry, wrap=False)
            except Exception:  # noqa: BLE001 - retry with backoff
                try:
                    self._q.popleft()
                except IndexError:
                    continue
                if attempts + 1 >= self._MAX_ATTEMPTS:
                    self.dropped += 1
                else:
                    self._q.appendleft((entry, attempts + 1))
                self._stop.wait(timeout=backoff)
                backoff = min(backoff * 2, 30.0)
                continue
            try:
                self._q.popleft()
            except IndexError:
                pass
            self.sent += 1
            backoff = 0.5

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._worker.join(timeout=2)
