"""Request tracing and audit logging.

The observability pair from the reference (§5 aux subsystems):
  * trace — a live pub/sub of per-request records, streamed to admin
    clients over HTTP (reference: cmd/admin-handlers.go TraceHandler +
    pubsub, `mc admin trace` counterpart);
  * audit — one structured record per completed request, delivered to a
    webhook target best-effort with a bounded retry queue (reference:
    internal/logger audit targets).
"""

from __future__ import annotations

import collections
import json
import queue
import threading
import time
import urllib.request
from typing import Optional


def make_entry(api: str, method: str, path: str, bucket: str, key: str,
               status: int, duration_s: float, remote: str,
               access_key: str, rx: int = 0, tx: int = 0) -> dict:
    """One trace/audit record (the reference's madmin.TraceInfo /
    audit.Entry shape, trimmed)."""
    return {
        "version": "1",
        "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "api": api,
        "method": method,
        "path": path,
        "bucket": bucket,
        "object": key,
        "statusCode": status,
        "durationMs": round(duration_s * 1000, 3),
        "remoteHost": remote,
        "accessKey": access_key,
        "rx": rx,
        "tx": tx,
    }


class TraceBroadcaster:
    """Bounded pub/sub: subscribers receive every published entry while
    subscribed; slow subscribers drop oldest entries rather than
    backpressuring the request path."""

    _DEPTH = 1000

    def __init__(self):
        self._mu = threading.Lock()
        self._subs: list[queue.Queue] = []

    @property
    def active(self) -> bool:
        return bool(self._subs)

    def subscribe(self) -> queue.Queue:
        q: queue.Queue = queue.Queue(maxsize=self._DEPTH)
        with self._mu:
            self._subs.append(q)
        return q

    def unsubscribe(self, q: queue.Queue) -> None:
        with self._mu:
            try:
                self._subs.remove(q)
            except ValueError:
                pass

    def publish(self, entry: dict) -> None:
        with self._mu:
            subs = list(self._subs)
        for q in subs:
            try:
                q.put_nowait(entry)
            except queue.Full:
                try:
                    q.get_nowait()      # drop oldest
                    q.put_nowait(entry)
                except (queue.Empty, queue.Full):
                    pass


class AuditLogger:
    """Webhook audit target with a bounded in-memory retry deque.

    Audit is best-effort telemetry: a down target never blocks requests;
    entries beyond the buffer (or failing more than _MAX_ATTEMPTS
    deliveries — one poison entry must not dam the whole stream) count
    as dropped. Delivery reuses the shared events WebhookTarget."""

    _BUFFER = 10_000
    _MAX_ATTEMPTS = 5

    def __init__(self, endpoint: str, timeout: float = 3.0):
        from minio_tpu.events.notify import WebhookTarget
        self._target = WebhookTarget("audit", endpoint, timeout=timeout)
        self.endpoint = endpoint
        self.sent = 0
        self.dropped = 0
        self._q: collections.deque = collections.deque(maxlen=self._BUFFER)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def submit(self, entry: dict) -> None:
        if len(self._q) == self._q.maxlen:
            self.dropped += 1
        self._q.append((entry, 0))
        self._wake.set()

    def _run(self) -> None:
        backoff = 0.5
        while not self._stop.is_set():
            if not self._q:
                self._wake.wait(timeout=1.0)
                self._wake.clear()
                continue
            entry, attempts = self._q[0]
            try:
                self._target.send(entry, wrap=False)
            except Exception:  # noqa: BLE001 - retry with backoff
                try:
                    self._q.popleft()
                except IndexError:
                    continue
                if attempts + 1 >= self._MAX_ATTEMPTS:
                    self.dropped += 1
                else:
                    self._q.appendleft((entry, attempts + 1))
                self._stop.wait(timeout=backoff)
                backoff = min(backoff * 2, 30.0)
                continue
            try:
                self._q.popleft()
            except IndexError:
                pass
            self.sent += 1
            backoff = 0.5

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._worker.join(timeout=2)
