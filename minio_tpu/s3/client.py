"""Minimal SigV4 S3 client for server-to-server traffic.

Replication (and future tiering) needs to speak S3 to a remote
cluster; this is the in-tree client for that — header-signed SigV4
requests over plain HTTP, sharing the signing helpers with the server
side (reference: the madmin/minio-go clients embedded in cmd/)."""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import urllib.parse
from typing import Optional

from minio_tpu.s3 import sigv4


class S3ClientError(Exception):
    def __init__(self, status: int, body: bytes = b""):
        super().__init__(f"HTTP {status}: {body[:200]!r}")
        self.status = status
        self.body = body


class RemoteS3:
    def __init__(self, address: str, access_key: str, secret_key: str,
                 region: str = "us-east-1", timeout: float = 30.0):
        self.address = address
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout = timeout

    def request(self, method: str, path: str,
                query: Optional[dict] = None, body: bytes = b"",
                headers: Optional[dict] = None):
        query = {k: [v] if isinstance(v, str) else v
                 for k, v in (query or {}).items()}
        headers = dict(headers or {})
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date = amz_date[:8]
        scope = f"{date}/{self.region}/s3/aws4_request"
        payload_hash = hashlib.sha256(body).hexdigest()
        send = {"host": self.address, "x-amz-date": amz_date,
                "x-amz-content-sha256": payload_hash}
        send.update({k.lower(): v for k, v in headers.items()})
        signed = sorted(send)
        canon = sigv4.canonical_request(method, path, query, send,
                                        signed, payload_hash)
        sts = sigv4.string_to_sign(amz_date, scope, canon)
        skey = sigv4.signing_key(self.secret_key, date, self.region)
        sig = hmac.new(skey, sts.encode(), hashlib.sha256).hexdigest()
        send["Authorization"] = (
            f"{sigv4.ALGORITHM} Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")
        qs = urllib.parse.urlencode(
            [(k, v) for k, vs in query.items() for v in vs])
        url = sigv4.uri_encode(path, encode_slash=False) + \
            ("?" + qs if qs else "")
        conn = http.client.HTTPConnection(self.address,
                                          timeout=self.timeout)
        try:
            conn.request(method, url, body=body, headers=send)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

    # -- convenience wrappers -------------------------------------------

    def put_object(self, bucket: str, key: str, body: bytes,
                   headers: Optional[dict] = None) -> None:
        st, _, data = self.request("PUT", f"/{bucket}/{key}", body=body,
                                   headers=headers)
        if st != 200:
            raise S3ClientError(st, data)

    def delete_object(self, bucket: str, key: str,
                      headers: Optional[dict] = None) -> None:
        st, _, data = self.request("DELETE", f"/{bucket}/{key}",
                                   headers=headers)
        if st not in (200, 204):
            raise S3ClientError(st, data)

    def get_object(self, bucket: str, key: str) -> bytes:
        st, _, data = self.request("GET", f"/{bucket}/{key}")
        if st != 200:
            raise S3ClientError(st, data)
        return data

    def head_bucket(self, bucket: str) -> bool:
        st, _, _ = self.request("HEAD", f"/{bucket}")
        return st == 200
