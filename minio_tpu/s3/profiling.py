"""Admin profiling: start/stop CPU profiles, bundle results.

The analogue of the reference's profiling handlers
(cmd/admin-handlers.go:1021 StartProfilingHandler /
DownloadProfilingDataHandler): an admin starts a profile, load runs,
and the download returns a zip bundle of per-node profile data. The
reference captures Go pprof profiles; the runtime here is Python, so
the capture is cProfile — the zip carries both the raw marshaled stats
(loadable with pstats.Stats) and a rendered text summary per node.

In distributed mode the start/stop fan out over the grid
(PROFILE_HANDLER) so the bundle covers every peer, the way the
reference's NotificationSys collects remote profiles.
"""

from __future__ import annotations

import contextlib
import cProfile
import io
import marshal
import pstats
import threading
import time
import zipfile

PROFILE_HANDLER = "peer.profile"


class ProfileError(Exception):
    pass


class Profiler:
    """One node's profile capture (CPU via cProfile)."""

    # Per-request capture cap: an admin who forgets to stop a profile
    # on a busy server must not accumulate profiles without bound.
    _MAX_REQUEST_PROFILES = 4096

    def __init__(self):
        self._mu = threading.Lock()
        self._prof: cProfile.Profile | None = None
        self._request_profs: list[cProfile.Profile] = []
        self._started_ns = 0

    def start(self) -> None:
        with self._mu:
            if self._prof is not None:
                raise ProfileError("a profile is already running")
            self._prof = cProfile.Profile()
            self._request_profs = []
            self._started_ns = time.time_ns()
            self._prof.enable()

    @contextlib.contextmanager
    def request_profile(self):
        """Per-request capture on the HANDLER thread. cProfile hooks
        are per-thread, so the start() enable() only ever sees the
        admin thread; each request records its own profile here and
        the bundle merges them at stop — without this the downloaded
        profile is empty of the very load it was meant to explain."""
        # Lock-free fast path: this wraps EVERY request's dispatch,
        # and profiling is almost always off — a single attribute read
        # (atomic in CPython) must not become a shared-lock point.
        if self._prof is None:
            yield
            return
        with self._mu:
            active = self._prof is not None and \
                len(self._request_profs) < self._MAX_REQUEST_PROFILES
        if not active:
            yield
            return
        p = cProfile.Profile()
        p.enable()
        try:
            yield
        finally:
            p.disable()
            with self._mu:
                if self._prof is not None and \
                        len(self._request_profs) < \
                        self._MAX_REQUEST_PROFILES:
                    self._request_profs.append(p)

    def stop(self) -> dict:
        """Stop and return {"stats": marshaled pstats bytes,
        "text": rendered summary, "duration_s": float}."""
        with self._mu:
            if self._prof is None:
                raise ProfileError("no profile is running")
            prof, self._prof = self._prof, None
            request_profs, self._request_profs = self._request_profs, []
        prof.disable()
        stats = pstats.Stats(prof)
        for p in request_profs:
            try:
                stats.add(p)
            except Exception:  # noqa: BLE001 - one bad capture != no bundle
                continue
        out = io.StringIO()
        stats.stream = out
        stats.sort_stats("cumulative").print_stats(60)
        return {
            "stats": marshal.dumps(stats.stats),
            "text": out.getvalue(),
            "duration_s": (time.time_ns() - self._started_ns) / 1e9,
        }

    @property
    def running(self) -> bool:
        with self._mu:
            return self._prof is not None


def bundle(per_node: dict[str, dict]) -> bytes:
    """zip bytes: <node>/profile.pstats + <node>/profile.txt per node
    (the shape of the reference's profiling zip download)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for node, rec in per_node.items():
            z.writestr(f"{node}/profile.pstats", rec.get("stats", b""))
            z.writestr(f"{node}/profile.txt", rec.get("text", ""))
    return buf.getvalue()


def make_profile_handler(profiler: Profiler):
    """Grid handler: peers start/stop their local profiler on request
    (the receiving half of the cluster-wide fan-out)."""

    def handler(payload):
        action = (payload or {}).get("action", "")
        if action == "start":
            try:
                profiler.start()
            except ProfileError:
                pass                      # already running: converged
            return {"ok": True}
        if action == "stop":
            try:
                rec = profiler.stop()
            except ProfileError:
                return {"ok": False}
            import base64
            return {"ok": True, "text": rec["text"],
                    "duration_s": rec["duration_s"],
                    "stats_b64": base64.b64encode(rec["stats"]).decode()}
        return {"ok": False}

    return handler
