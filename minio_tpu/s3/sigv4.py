"""AWS Signature Version 4 verification (header + presigned + chunked).

Re-implements the S3 SigV4 scheme from the public AWS specification, as
the reference does (cmd/signature-v4.go, cmd/streaming-signature-v4.go):
canonical request -> string-to-sign -> HMAC chain, plus presigned query
auth and the aws-chunked streaming payload decoder with per-chunk
signatures.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import time
import urllib.parse
from dataclasses import dataclass
from typing import Optional

ALGORITHM = "AWS4-HMAC-SHA256"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
STREAMING_PAYLOAD_TRAILER = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD-TRAILER"
STREAMING_UNSIGNED_TRAILER = "STREAMING-UNSIGNED-PAYLOAD-TRAILER"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


class SigError(Exception):
    """Maps to S3 SignatureDoesNotMatch / AccessDenied family errors."""

    def __init__(self, code: str, msg: str = ""):
        self.code = code
        super().__init__(msg or code)


@dataclass
class Credential:
    access_key: str
    date: str        # YYYYMMDD
    region: str
    service: str

    @classmethod
    def parse(cls, scope: str) -> "Credential":
        parts = scope.split("/")
        if len(parts) != 5 or parts[4] != "aws4_request" \
                or parts[3] not in ("s3", "sts"):
            raise SigError("AuthorizationHeaderMalformed",
                           f"bad credential scope {scope!r}")
        return cls(access_key=parts[0], date=parts[1], region=parts[2],
                   service=parts[3])

    def scope(self) -> str:
        return f"{self.date}/{self.region}/{self.service}/aws4_request"


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str = "s3") -> bytes:
    k = _hmac(b"AWS4" + secret.encode(), date.encode())
    k = _hmac(k, region.encode())
    k = _hmac(k, service.encode())
    return _hmac(k, b"aws4_request")


def uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-_.~" if encode_slash else "-_.~/"
    return urllib.parse.quote(s, safe=safe)


def canonical_query(query: dict[str, list[str]],
                    drop: tuple[str, ...] = ()) -> str:
    pairs = []
    for key in sorted(query):
        if key in drop:
            continue
        for v in sorted(query[key]):
            pairs.append(f"{uri_encode(key)}={uri_encode(v)}")
    return "&".join(pairs)


def canonical_request(method: str, path: str, query: dict[str, list[str]],
                      headers: dict[str, str], signed_headers: list[str],
                      payload_hash: str,
                      drop_query: tuple[str, ...] = (),
                      raw_path: Optional[str] = None) -> str:
    """`path` is percent-encoded by this function (signing-side use);
    verifiers pass `raw_path` — the exact still-encoded URI from the wire
    — because S3 signs the raw request path without re-encoding (clients
    whose percent-encoding differs from urllib's safe set, or keys with
    non-UTF-8 bytes, would otherwise mismatch)."""
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n" for h in signed_headers)
    uri = raw_path if raw_path is not None \
        else uri_encode(path, encode_slash=False)
    return "\n".join([
        method.upper(),
        uri or "/",
        canonical_query(query, drop=drop_query),
        canon_headers,
        ";".join(signed_headers),
        payload_hash,
    ])


def string_to_sign(amz_date: str, scope: str, canon_req: str) -> str:
    return "\n".join([ALGORITHM, amz_date, scope,
                      hashlib.sha256(canon_req.encode()).hexdigest()])


@dataclass
class ParsedAuth:
    credential: Credential
    signed_headers: list[str]
    signature: str
    amz_date: str
    payload_hash: str
    presigned: bool = False
    anonymous: bool = False


def anonymous_auth() -> ParsedAuth:
    """Pseudo-auth for requests carrying no credentials at all; the
    caller authorizes them against bucket policy (reference:
    cmd/auth-handler.go authTypeAnonymous). Body is by definition
    unsigned."""
    return ParsedAuth(
        credential=Credential(access_key="", date="", region="", service="s3"),
        signed_headers=[], signature="", amz_date="",
        payload_hash=UNSIGNED_PAYLOAD, anonymous=True)


def parse_auth_header(headers: dict[str, str]) -> ParsedAuth:
    """Parse `Authorization: AWS4-HMAC-SHA256 Credential=..., ...`."""
    auth = headers.get("authorization", "")
    if not auth.startswith(ALGORITHM):
        raise SigError("AccessDenied", "unsupported authorization scheme")
    fields: dict[str, str] = {}
    for part in auth[len(ALGORITHM):].split(","):
        part = part.strip()
        if "=" in part:
            k, v = part.split("=", 1)
            fields[k.strip()] = v.strip()
    try:
        cred = Credential.parse(fields["Credential"])
        signed = fields["SignedHeaders"].lower().split(";")
        sig = fields["Signature"]
    except KeyError as e:
        raise SigError("AuthorizationHeaderMalformed", str(e)) from None
    amz_date = headers.get("x-amz-date") or headers.get("date", "")
    payload_hash = headers.get("x-amz-content-sha256", UNSIGNED_PAYLOAD)
    if "host" not in signed:
        raise SigError("SignatureDoesNotMatch", "host header not signed")
    return ParsedAuth(credential=cred, signed_headers=signed, signature=sig,
                      amz_date=amz_date, payload_hash=payload_hash)


def parse_presigned(query: dict[str, list[str]]) -> ParsedAuth:
    def one(k: str) -> str:
        v = query.get(k, [""])
        return v[0] if v else ""
    if one("X-Amz-Algorithm") != ALGORITHM:
        raise SigError("AccessDenied", "unsupported algorithm")
    cred = Credential.parse(one("X-Amz-Credential"))
    amz_date = one("X-Amz-Date")
    expires = one("X-Amz-Expires")
    try:
        exp_s = int(expires)
    except ValueError:
        raise SigError("AuthorizationQueryParametersError",
                       "bad X-Amz-Expires") from None
    if not (0 < exp_s <= 7 * 24 * 3600):
        raise SigError("AuthorizationQueryParametersError",
                       "X-Amz-Expires out of range")
    try:
        t0 = datetime.datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ") \
            .replace(tzinfo=datetime.timezone.utc)
    except ValueError:
        raise SigError("AccessDenied", "bad X-Amz-Date") from None
    now = datetime.datetime.now(datetime.timezone.utc)
    if now < t0 - datetime.timedelta(minutes=15):
        raise SigError("AccessDenied", "request not yet valid")
    if now > t0 + datetime.timedelta(seconds=exp_s):
        raise SigError("AccessDenied", "Request has expired")
    return ParsedAuth(
        credential=cred,
        signed_headers=one("X-Amz-SignedHeaders").lower().split(";"),
        signature=one("X-Amz-Signature"), amz_date=amz_date,
        payload_hash=UNSIGNED_PAYLOAD, presigned=True)


def verify_request(method: str, path: str, query: dict[str, list[str]],
                   headers: dict[str, str], secret_for, body_hash: Optional[str] = None
                   ) -> ParsedAuth:
    """Verify a header-signed or presigned request.

    `path` must be the RAW (still percent-encoded) request path from the
    wire — it is signed verbatim, never re-encoded.
    `secret_for(access_key) -> secret | None`. Raises SigError on any
    mismatch; returns the parsed auth (callers use the access key for
    policy checks and the payload-hash mode for body handling).
    """
    # Legacy SigV2 (header "AWS AKID:sig" or presigned ?Signature=):
    # verified by its own HMAC-SHA1 scheme, mapped into a ParsedAuth.
    if headers.get("authorization", "").startswith("AWS ") or \
            ("Signature" in query and "AWSAccessKeyId" in query):
        return _verify_v2(method, path, query, headers, secret_for)

    presigned = "X-Amz-Signature" in query
    auth = parse_presigned(query) if presigned else parse_auth_header(headers)
    secret = secret_for(auth.credential.access_key)
    if secret is None:
        raise SigError("InvalidAccessKeyId", auth.credential.access_key)

    sts_date = auth.amz_date
    if not presigned:
        # Replay window: signed requests are valid for +/-15 minutes
        # (the reference enforces the same max skew on header auth).
        # Clients may sign with only a Date header (RFC1123 format); the
        # SigV4 spec then puts the ISO8601 rendering of that instant in
        # the string-to-sign, so normalize for verification too.
        try:
            t0 = datetime.datetime.strptime(
                auth.amz_date, "%Y%m%dT%H%M%SZ").replace(
                    tzinfo=datetime.timezone.utc)
        except ValueError:
            import email.utils
            try:
                t0 = email.utils.parsedate_to_datetime(auth.amz_date)
            except (TypeError, ValueError):
                raise SigError("AccessDenied", "bad x-amz-date") from None
            if t0.tzinfo is None:
                t0 = t0.replace(tzinfo=datetime.timezone.utc)
            sts_date = t0.astimezone(datetime.timezone.utc) \
                .strftime("%Y%m%dT%H%M%SZ")
        now = datetime.datetime.now(datetime.timezone.utc)
        if abs((now - t0).total_seconds()) > 15 * 60:
            raise SigError("AccessDenied",
                           "request time too skewed from server time")

    if presigned:
        payload_hash = UNSIGNED_PAYLOAD
        drop = ("X-Amz-Signature",)
    else:
        payload_hash = auth.payload_hash
        if body_hash is not None and payload_hash not in (
                UNSIGNED_PAYLOAD, STREAMING_PAYLOAD,
                STREAMING_PAYLOAD_TRAILER, STREAMING_UNSIGNED_TRAILER):
            if body_hash != payload_hash:
                raise SigError("XAmzContentSHA256Mismatch", "payload mismatch")
        drop = ()

    canon = canonical_request(method, "", query, headers,
                              auth.signed_headers, payload_hash,
                              drop_query=drop, raw_path=path)
    sts = string_to_sign(sts_date, auth.credential.scope(), canon)
    key = signing_key(secret, auth.credential.date, auth.credential.region,
                      auth.credential.service)
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, auth.signature):
        raise SigError("SignatureDoesNotMatch")
    return auth


# ---------------------------------------------------------------------------
# Legacy SigV2 (reference: cmd/signature-v4.go's v2 sibling,
# cmd/auth-handler.go routing)
# ---------------------------------------------------------------------------

# Subresources included in the V2 canonicalized resource, per the spec.
_V2_SUBRESOURCES = {
    "acl", "delete", "lifecycle", "location", "logging", "notification",
    "partNumber", "policy", "requestPayment", "replication", "response-content-type",
    "response-content-language", "response-expires", "response-cache-control",
    "response-content-disposition", "response-content-encoding", "select",
    "select-type", "tagging", "torrent", "uploadId", "uploads", "versionId",
    "versioning", "versions", "website", "encryption", "cors",
}


def _v2_string_to_sign(method: str, path: str, query: dict,
                       headers: dict, expires: str = "") -> str:
    md5 = headers.get("content-md5", "")
    ctype = headers.get("content-type", "")
    # Per the V2 spec: when x-amz-date is present it rides in the
    # CanonicalizedAmzHeaders section and the Date slot is EMPTY;
    # presigned requests put Expires in the Date slot.
    if expires:
        date = expires
    elif "x-amz-date" in headers:
        date = ""
    else:
        date = headers.get("date", "")
    amz = []
    for k in sorted(headers):
        if k.startswith("x-amz-"):
            amz.append(f"{k}:{headers[k].strip()}")
    sub = []
    for k in sorted(query):
        if k in _V2_SUBRESOURCES:
            v = query[k][0]
            sub.append(f"{k}={v}" if v else k)
    resource = path + ("?" + "&".join(sub) if sub else "")
    return "\n".join([method, md5, ctype, date] + amz + [resource])


def _verify_v2(method: str, path: str, query: dict, headers: dict,
               secret_for) -> ParsedAuth:
    import base64
    presigned = "Signature" in query
    if presigned:
        access = query.get("AWSAccessKeyId", [""])[0]
        signature = query.get("Signature", [""])[0]
        expires = query.get("Expires", [""])[0]
        try:
            if time.time() > int(expires):
                raise SigError("AccessDenied", "Request has expired")
        except ValueError:
            raise SigError("AccessDenied", "bad Expires") from None
    else:
        hdr = headers.get("authorization", "")
        rest = hdr[len("AWS "):]
        access, _, signature = rest.partition(":")
        expires = ""
        if not access or not signature:
            raise SigError("AuthorizationHeaderMalformed", hdr)
        # Same +/-15 min replay window the V4 path enforces.
        import email.utils as _eu
        date_hdr = headers.get("x-amz-date") or headers.get("date", "")
        try:
            when = _eu.parsedate_to_datetime(date_hdr)
            if when.tzinfo is None:
                when = when.replace(tzinfo=datetime.timezone.utc)
        except (TypeError, ValueError):
            raise SigError("AccessDenied",
                           "missing or malformed Date header") from None
        skew = abs((datetime.datetime.now(datetime.timezone.utc)
                    - when).total_seconds())
        if skew > 15 * 60:
            raise SigError("AccessDenied",
                           "request time too skewed from server time")
    secret = secret_for(access)
    if secret is None:
        raise SigError("InvalidAccessKeyId", access)
    # The RAW (still percent-encoded) request path is what V2 clients
    # sign — never a decoded re-rendering of it.
    sts = _v2_string_to_sign(method, path, query, headers, expires)
    want = base64.b64encode(hmac.new(secret.encode(), sts.encode("utf-8"),
                                     hashlib.sha1).digest()).decode()
    if not hmac.compare_digest(want, signature):
        raise SigError("SignatureDoesNotMatch")
    # Map into the V4 auth shape: full body already read & unverified
    # (V2 has no payload hash), so treat as UNSIGNED-PAYLOAD.
    cred = Credential(access_key=access, date=time.strftime("%Y%m%d"),
                      region="us-east-1", service="s3")
    return ParsedAuth(credential=cred, signed_headers=[],
                      signature=signature, amz_date="",
                      payload_hash=UNSIGNED_PAYLOAD)


# ---------------------------------------------------------------------------
# aws-chunked streaming payload (per-chunk signatures)
# ---------------------------------------------------------------------------

class ChunkedPayloadReader:
    """Incremental STREAMING-AWS4-HMAC-SHA256-PAYLOAD decoder.

    Same framing and chunk-signature chain as decode_chunked_payload,
    but pull-based: `.read(n)` parses frames as bytes arrive from the
    socket, so multi-GiB streamed PUTs never materialize the encoded
    body (reference: cmd/streaming-signature-v4.go's s3ChunkedReader).
    `finalize()` consumes the terminal 0-chunk (verifying its signature
    in signed mode) and drains any trailers; the put path runs it via
    the Payload finish hook BEFORE committing the object.
    """

    _FILL = 64 * 1024
    # Bounds (the reference's maxLineLength / chunk-size discipline,
    # cmd/streaming-signature-v4.go): without them one malicious giant
    # chunk or a header with no CRLF would buffer the whole body.
    _MAX_HEADER = 4 * 1024
    _MAX_CHUNK = 16 << 20

    def __init__(self, raw, auth: ParsedAuth, secret: str,
                 verify_signatures: bool = True):
        self._raw = raw
        self._auth = auth
        self._verify = verify_signatures
        self._seed_key = signing_key(secret, auth.credential.date,
                                     auth.credential.region,
                                     auth.credential.service)
        self._prev_sig = auth.signature
        self._scope = auth.credential.scope()
        self._buf = bytearray()
        self._chunk = memoryview(b"")
        self._done = False
        self.trailers: dict[str, str] = {}

    # -- buffered raw access -------------------------------------------

    def _fill(self) -> bool:
        data = self._raw.read(self._FILL)
        if not data:
            return False
        self._buf += data
        return True

    def _readline(self) -> bytes:
        while True:
            nl = self._buf.find(b"\r\n")
            if nl >= 0:
                line = bytes(self._buf[:nl])
                del self._buf[:nl + 2]
                return line
            if len(self._buf) > self._MAX_HEADER:
                raise SigError("InvalidChunkSizeError",
                               "chunk header too long")
            if not self._fill():
                raise SigError("IncompleteBody", "truncated chunk header")

    def _read_raw(self, n: int) -> bytes:
        while len(self._buf) < n:
            if not self._fill():
                raise SigError("IncompleteBody", "short chunk")
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    # -- frame parsing --------------------------------------------------

    def _next_frame(self) -> None:
        header = self._readline().decode("latin-1")
        size_hex, _, ext = header.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise SigError("InvalidChunkSizeError", size_hex) from None
        if size < 0 or size > self._MAX_CHUNK:
            raise SigError("InvalidChunkSizeError", size_hex)
        data = self._read_raw(size)
        if size > 0:
            if self._read_raw(2) != b"\r\n":
                raise SigError("IncompleteBody", "bad chunk delimiter")
        if self._verify and (
                self._auth.payload_hash == STREAMING_PAYLOAD
                or (self._auth.payload_hash == STREAMING_PAYLOAD_TRAILER
                    and (size > 0 or "chunk-signature=" in ext))):
            # Signed-trailer mode: AWS signs the terminal 0-chunk too
            # and the trailer signature chains off it (reference:
            # cmd/streaming-signature-v4.go seedSignature update); a
            # bare `0` final frame is tolerated — the chain then ends
            # at the last data chunk.
            chunk_sig = ""
            for kv in ext.split(";"):
                if kv.startswith("chunk-signature="):
                    chunk_sig = kv[len("chunk-signature="):]
            sts = "\n".join([
                "AWS4-HMAC-SHA256-PAYLOAD", self._auth.amz_date,
                self._scope, self._prev_sig, EMPTY_SHA256,
                hashlib.sha256(data).hexdigest()])
            want = hmac.new(self._seed_key, sts.encode(),
                            hashlib.sha256).hexdigest()
            if not hmac.compare_digest(want, chunk_sig):
                raise SigError("SignatureDoesNotMatch", "chunk signature")
            self._prev_sig = want
        if size == 0:
            self._done = True
        else:
            self._chunk = memoryview(data)

    def read(self, n: int) -> bytes:
        while not self._chunk and not self._done:
            self._next_frame()
        if not self._chunk:
            return b""
        out = self._chunk[:n]
        self._chunk = self._chunk[len(out):]
        return bytes(out)

    def finalize(self) -> None:
        """Consume the 0-chunk + trailers; any further data chunk means
        the body was longer than the declared decoded length. Trailer
        lines PARSE into self.trailers (modern SDKs ship their default
        upload checksums here, x-amz-checksum-crc32 et al.) instead of
        being drained blind."""
        while not self._done:
            self._next_frame()
            if self._chunk:
                raise SigError("IncompleteBody",
                               "body exceeds decoded content length")
        self.trailers: dict[str, str] = {}
        # Trailer section: `name:value\r\n` lines, then the
        # x-amz-trailer-signature line (signed mode), then the final
        # blank. Buffered remains first, then the raw tail.
        trailer_raw = bytearray()       # lines as sent, '\n'-terminated
        trailer_sig = ""
        while True:
            nl = self._buf.find(b"\r\n")
            if nl < 0:
                data = self._raw.read(self._FILL)
                if not data:
                    break
                self._buf += data
                continue
            line = bytes(self._buf[:nl])
            del self._buf[:nl + 2]
            if not line:
                continue
            name, sep, value = line.partition(b":")
            if not sep:
                continue
            lname = name.decode("latin-1").strip().lower()
            if lname == "x-amz-trailer-signature":
                trailer_sig = value.decode("latin-1").strip()
                continue
            trailer_raw += line + b"\n"
            self.trailers[lname] = value.decode("latin-1").strip()
        # Anything after a blank line was drained by the loop above.
        # Signed-trailer mode authenticates the trailer section too
        # (reference: cmd/streaming-signature-v4.go readTrailers):
        # string-to-sign is AWS4-HMAC-SHA256-TRAILER over the hash of
        # the '\n'-terminated trailer lines, chained off the last data
        # chunk's signature. Without this check the declared trailing
        # checksums would be attacker-tamperable.
        if self._verify \
                and self._auth.payload_hash == STREAMING_PAYLOAD_TRAILER \
                and (self.trailers or trailer_sig):
            sts = "\n".join([
                "AWS4-HMAC-SHA256-TRAILER", self._auth.amz_date,
                self._scope, self._prev_sig,
                hashlib.sha256(bytes(trailer_raw)).hexdigest()])
            want = hmac.new(self._seed_key, sts.encode(),
                            hashlib.sha256).hexdigest()
            if not hmac.compare_digest(want, trailer_sig):
                raise SigError("SignatureDoesNotMatch",
                               "trailer signature")


class PooledChunkedReader:
    """Native-scan aws-chunked decoder over ONE pooled recv buffer.

    Byte-identical in output, trailers and rejection behavior to
    ChunkedPayloadReader (golden-tested in tests/test_native_http.py),
    but the hot loop is different: frame headers and chunk-signature
    extensions are located by a GIL-free native scan
    (native/native.cc mtpu_chunk_head) straight out of a pooled
    io/bufpool lease the socket bytes land in ONCE — no bytearray
    append/delete churn per frame, chunk sha256 runs over a memoryview
    of the same buffer, and the decoded bytes are sliced out exactly
    once on their way to the frame kernel's staging window.

    `close()` returns the buffer lease; the serve path calls it from
    the request's finally (the reader may be dropped mid-body on error
    paths, and the pool's leak net must stay at zero).
    """

    _FILL = 64 * 1024
    _MAX_CHUNK = 16 << 20

    def __init__(self, raw, auth: ParsedAuth, secret: str,
                 verify_signatures: bool = True, lib=None):
        import ctypes

        from minio_tpu.io.bufpool import global_pool
        if lib is None:
            raise ValueError("native library required")
        self._raw = raw
        self._auth = auth
        self._verify = verify_signatures
        self._seed_key = signing_key(secret, auth.credential.date,
                                     auth.credential.region,
                                     auth.credential.service)
        self._prev_sig = auth.signature
        self._scope = auth.credential.scope()
        self._lib = lib
        self._ctypes = ctypes
        self._pool = global_pool()
        self._lease = self._pool.lease(256 << 10)
        self._attach(self._lease)
        self._pos = 0              # parse cursor
        self._end = 0              # valid bytes
        self._data_lo = 0          # current chunk's unread data span
        self._data_hi = 0
        self._done = False
        self._closed = False
        self.trailers: dict[str, str] = {}

    # -- buffer plumbing -------------------------------------------------

    def _attach(self, lease) -> None:
        self._buf = lease.raw
        self._cap = len(self._buf)
        self._mv = memoryview(self._buf)
        self._arr = (self._ctypes.c_uint8 * self._cap) \
            .from_buffer(self._buf)
        self._out = (self._ctypes.c_int64 * 4)()

    def _detach(self) -> None:
        # Exported views released BEFORE the lease returns: a live
        # ctypes array over a free-listed buffer would alias the next
        # lease.
        self._arr = None
        self._out = None
        self._mv.release()

    def _compact(self) -> None:
        if self._pos:
            n = self._end - self._pos
            self._mv[:n] = self._mv[self._pos:self._end]
            self._pos, self._end = 0, n

    def _grow(self, need: int) -> None:
        """Swap to a larger lease holding [pos, end) (a chunk bigger
        than the buffer; bounded by the 16 MiB chunk cap)."""
        old_lease, old_mv = self._lease, self._mv
        data = bytes(old_mv[self._pos:self._end])
        lease = self._pool.lease(need + self._FILL)
        self._detach()
        old_lease.release()
        self._lease = lease
        self._attach(lease)
        self._mv[:len(data)] = data
        self._pos, self._end = 0, len(data)

    def _fill(self) -> int:
        """Pull more raw bytes into the buffer tail (readinto straight
        into the pooled buffer when the source supports it)."""
        if self._end == self._cap:
            self._compact()
            if self._end == self._cap:
                return 0
        want = min(self._FILL, self._cap - self._end)
        ri = getattr(self._raw, "readinto", None)
        if ri is not None:
            n = ri(self._mv[self._end:self._end + want])
            n = n or 0
        else:
            data = self._raw.read(want)
            n = len(data)
            if n:
                self._mv[self._end:self._end + n] = data
        self._end += n
        return n

    def _ensure(self, need: int) -> None:
        """Make buf[pos:pos+need) valid (fill/compact/grow)."""
        if need > self._cap:
            self._grow(need)
        while self._end - self._pos < need:
            if self._cap - self._pos < need:
                self._compact()
            if not self._fill():
                raise SigError("IncompleteBody", "short chunk")

    # -- frame parsing ---------------------------------------------------

    def _next_frame(self) -> None:
        while True:
            r = self._lib.mtpu_chunk_head(self._arr, self._end, self._pos,
                                          self._out)
            if r == 1:
                break
            if r != 0:
                raise SigError("InvalidChunkSizeError", "bad chunk header")
            if self._end - self._pos > self._cap - 8:
                self._compact()
            if not self._fill():
                raise SigError("IncompleteBody", "truncated chunk header")
        hlen, size, sig_off, sig_len = (int(v) for v in self._out)
        base = self._pos
        self._ensure(hlen + size + (2 if size else 0))
        if self._pos != base:
            # _ensure compacted/regrew: the frame moved to offset 0 and
            # the native offsets shifted with it.
            shift = base - self._pos
            if sig_off:
                sig_off -= shift
        doff = self._pos + hlen
        if size and bytes(self._mv[doff + size:doff + size + 2]) != b"\r\n":
            raise SigError("IncompleteBody", "bad chunk delimiter")
        if self._verify and (
                self._auth.payload_hash == STREAMING_PAYLOAD
                or (self._auth.payload_hash == STREAMING_PAYLOAD_TRAILER
                    and (size > 0 or sig_off > 0))):
            chunk_sig = bytes(self._mv[sig_off:sig_off + sig_len]) \
                .decode("latin-1") if sig_off else ""
            sts = "\n".join([
                "AWS4-HMAC-SHA256-PAYLOAD", self._auth.amz_date,
                self._scope, self._prev_sig, EMPTY_SHA256,
                hashlib.sha256(self._mv[doff:doff + size]).hexdigest()])
            want = hmac.new(self._seed_key, sts.encode(),
                            hashlib.sha256).hexdigest()
            if not hmac.compare_digest(want, chunk_sig):
                raise SigError("SignatureDoesNotMatch", "chunk signature")
            self._prev_sig = want
        self._pos = doff + size + (2 if size else 0)
        if size == 0:
            self._done = True
        else:
            self._data_lo, self._data_hi = doff, doff + size

    def read(self, n: int) -> bytes:
        while self._data_lo >= self._data_hi and not self._done:
            self._next_frame()
        if self._data_lo >= self._data_hi:
            return b""
        take = min(n, self._data_hi - self._data_lo) if n >= 0 else 0
        out = bytes(self._mv[self._data_lo:self._data_lo + take])
        self._data_lo += take
        return out

    def finalize(self) -> None:
        """Consume the 0-chunk + trailer section (same semantics as
        ChunkedPayloadReader.finalize: trailers parsed, signed-trailer
        mode authenticated)."""
        while not self._done:
            self._next_frame()
            if self._data_hi > self._data_lo:
                raise SigError("IncompleteBody",
                               "body exceeds decoded content length")
        self.trailers = {}
        trailer_raw = bytearray()
        trailer_sig = ""
        while True:
            nl = self._buf.find(b"\r\n", self._pos, self._end)
            if nl < 0:
                if not self._fill():
                    break
                continue
            line = bytes(self._mv[self._pos:nl])
            self._pos = nl + 2
            if not line:
                continue
            name, sep, value = line.partition(b":")
            if not sep:
                continue
            lname = name.decode("latin-1").strip().lower()
            if lname == "x-amz-trailer-signature":
                trailer_sig = value.decode("latin-1").strip()
                continue
            trailer_raw += line + b"\n"
            self.trailers[lname] = value.decode("latin-1").strip()
        if self._verify \
                and self._auth.payload_hash == STREAMING_PAYLOAD_TRAILER \
                and (self.trailers or trailer_sig):
            sts = "\n".join([
                "AWS4-HMAC-SHA256-TRAILER", self._auth.amz_date,
                self._scope, self._prev_sig,
                hashlib.sha256(bytes(trailer_raw)).hexdigest()])
            want = hmac.new(self._seed_key, sts.encode(),
                            hashlib.sha256).hexdigest()
            if not hmac.compare_digest(want, trailer_sig):
                raise SigError("SignatureDoesNotMatch",
                               "trailer signature")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._detach()
        self._lease.release()


def chunked_reader(raw, auth: ParsedAuth, secret: str,
                   verify_signatures: bool = True):
    """The aws-chunked streaming decoder for the serve path: the
    native-scan pooled reader when the native lib is loaded and
    MTPU_HTTP_NATIVE is not off, else the pure-Python reader —
    byte-identical either way."""
    from minio_tpu.s3 import hotloop
    lib = hotloop.lib() if hotloop.native_enabled() else None
    if lib is not None:
        try:
            return PooledChunkedReader(raw, auth, secret,
                                       verify_signatures, lib=lib)
        except (ValueError, OSError):
            pass
    return ChunkedPayloadReader(raw, auth, secret, verify_signatures)


def decode_chunked_payload(body: bytes, auth: ParsedAuth, secret: str,
                           verify_signatures: bool = True) -> bytes:
    """Decode STREAMING-AWS4-HMAC-SHA256-PAYLOAD framing.

    Frame: `hex-size;chunk-signature=<sig>\r\n<data>\r\n` ... terminated
    by a zero-size chunk. Each chunk signature chains off the previous
    (reference: cmd/streaming-signature-v4.go). Unsigned-trailer variants
    skip signature checks.
    """
    out = bytearray()
    pos = 0
    seed_key = signing_key(secret, auth.credential.date,
                           auth.credential.region, auth.credential.service)
    prev_sig = auth.signature
    scope = auth.credential.scope()
    while True:
        nl = body.find(b"\r\n", pos)
        if nl < 0:
            raise SigError("IncompleteBody", "bad chunk header")
        header = body[pos:nl].decode("latin-1")
        pos = nl + 2
        size_hex, _, ext = header.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise SigError("InvalidChunkSizeError", size_hex) from None
        data = body[pos:pos + size]
        if len(data) != size:
            raise SigError("IncompleteBody", "short chunk")
        pos += size
        if body[pos:pos + 2] == b"\r\n":
            pos += 2
        # Trailer mode signs every data chunk but its final 0-chunk has no
        # chunk-signature (the x-amz-trailer-signature covers the tail).
        if verify_signatures and (
                auth.payload_hash == STREAMING_PAYLOAD
                or (auth.payload_hash == STREAMING_PAYLOAD_TRAILER
                    and size > 0)):
            chunk_sig = ""
            for kv in ext.split(";"):
                if kv.startswith("chunk-signature="):
                    chunk_sig = kv[len("chunk-signature="):]
            sts = "\n".join([
                "AWS4-HMAC-SHA256-PAYLOAD", auth.amz_date, scope, prev_sig,
                EMPTY_SHA256, hashlib.sha256(data).hexdigest()])
            want = hmac.new(seed_key, sts.encode(), hashlib.sha256).hexdigest()
            if not hmac.compare_digest(want, chunk_sig):
                raise SigError("SignatureDoesNotMatch", "chunk signature")
            prev_sig = want
        if size == 0:
            break
        out += data
    return bytes(out)
