"""S3 additional checksums (x-amz-checksum-*).

The analogue of the reference's hash/checksum support
(internal/hash/checksum.go): clients may declare a CRC32, SHA1, or
SHA256 checksum of the payload — as a header, or as an aws-chunked
TRAILER (what modern SDKs send by default: boto3 >= 1.36 adds a CRC32
trailer to every upload) — and the server verifies it before commit,
stores it with the version, and returns it on requests that ask
(x-amz-checksum-mode: ENABLED) and in GetObjectAttributes.

CRC32C and CRC64NVME need tables the stdlib doesn't carry; declaring
them is answered with NotImplemented rather than silently skipping
verification.
"""

from __future__ import annotations

import base64
import hashlib
import struct
import zlib

# algo name (lowercase, as in the header suffix) -> internal meta key.
ALGOS = ("crc32", "sha1", "sha256")
UNSUPPORTED = ("crc32c", "crc64nvme")

META_PREFIX = "x-internal-checksum-"
H_PREFIX = "x-amz-checksum-"


class ChecksumError(Exception):
    def __init__(self, code: str, msg: str = ""):
        self.code = code
        super().__init__(msg or code)


class _CRC32:
    def __init__(self):
        self._v = 0

    def update(self, b: bytes) -> None:
        self._v = zlib.crc32(b, self._v)

    def digest(self) -> bytes:
        return struct.pack(">I", self._v & 0xFFFFFFFF)


def new_hasher(algo: str):
    if algo == "crc32":
        return _CRC32()
    if algo == "sha1":
        return hashlib.sha1()
    if algo == "sha256":
        return hashlib.sha256()
    raise ChecksumError("NotImplemented", f"checksum {algo!r}")


def declared_algos(h: dict) -> list[tuple[str, str]]:
    """(algo, expected-b64) pairs declared as request HEADERS; raises
    for algorithms we cannot verify (silently storing unverified
    checksums would be worse than refusing)."""
    out = []
    for algo in ALGOS:
        v = h.get(H_PREFIX + algo)
        if v:
            out.append((algo, v))
    for algo in UNSUPPORTED:
        if h.get(H_PREFIX + algo):
            raise ChecksumError("NotImplemented",
                                f"checksum algorithm {algo} is not "
                                "supported; use crc32, sha1 or sha256")
    return out


def single_algo(declared: dict, t_algos: list) -> list:
    """The single algorithm a request may declare, combining header and
    trailer declarations; S3 answers InvalidRequest when a request
    declares more than one (rather than verifying them all)."""
    algos = set(declared) | set(t_algos)
    if len(algos) > 1:
        raise ChecksumError("InvalidRequest",
                            "only one checksum algorithm may be "
                            "declared per request")
    return sorted(algos)


def trailer_algos(h: dict) -> list[str]:
    """Checksum algorithms announced in x-amz-trailer."""
    out = []
    for name in (h.get("x-amz-trailer") or "").split(","):
        name = name.strip().lower()
        if not name.startswith(H_PREFIX):
            continue
        algo = name[len(H_PREFIX):]
        if algo in ALGOS:
            out.append(algo)
        elif algo in UNSUPPORTED:
            raise ChecksumError("NotImplemented",
                                f"checksum algorithm {algo} is not "
                                "supported; use crc32, sha1 or sha256")
    return out


class ChecksumingReader:
    """Reader wrapper computing checksums over the LOGICAL payload
    bytes as they stream through (before SSE/compression transforms)."""

    def __init__(self, inner, algos):
        self._inner = inner
        self._hashers = {a: new_hasher(a) for a in algos}

    def read(self, n: int) -> bytes:
        b = self._inner.read(n)
        if b:
            for hsh in self._hashers.values():
                hsh.update(b)
        return b

    def b64(self, algo: str) -> str:
        return base64.b64encode(self._hashers[algo].digest()).decode()


class DigestValues:
    """ChecksumingReader-compatible digest source for values the fused
    native transform pass already computed (object/transform.py): the
    declared/trailer verification then costs ZERO extra walks of the
    body — the single fused pass produced every digest."""

    def __init__(self, raw_by_algo: dict):
        self._raw = dict(raw_by_algo)

    def b64(self, algo: str) -> str:
        return base64.b64encode(self._raw[algo]).decode()


def verify_and_meta(reader: ChecksumingReader, expected: dict) -> dict:
    """Compare computed digests with the declared ones; returns the
    internal-metadata entries to store. `expected[algo]` may be None
    for trailer algorithms whose value never arrived."""
    meta = {}
    for algo, want in expected.items():
        got = reader.b64(algo)
        if want is None:
            raise ChecksumError("InvalidRequest",
                                f"declared trailer checksum "
                                f"{H_PREFIX}{algo} never arrived")
        if got != want:
            raise ChecksumError(
                "XAmzContentChecksumMismatch",
                f"{algo} checksum mismatch: computed {got}, "
                f"declared {want}")
        meta[META_PREFIX + algo] = got
    return meta


def response_headers(internal_meta: dict) -> dict:
    """Stored checksums -> x-amz-checksum-* response headers."""
    out = {}
    for algo in ALGOS:
        v = internal_meta.get(META_PREFIX + algo)
        if v:
            out[H_PREFIX + algo] = v
    return out
