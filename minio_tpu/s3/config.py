"""Server configuration subsystem: persisted KV settings + hot apply.

The lightweight analogue of the reference's config system
(internal/config/config.go KV store with hot reload via admin API):
settings live in one JSON document quorum-replicated across the first
pool's drives, are loaded at boot, and apply live ON THE NODE that
serves the set-config request (other nodes of a distributed deployment
pick the persisted document up at their next boot — cross-node hot
reload would ride the peer control plane).

Supported keys (unknown keys persist but are inert):
  compression        "on" | "off"   — transparent object compression
  scanner_interval   seconds (float) — background scanner cadence
  scanner_deep_every N               — deep-heal sampling rate
  scanner_throttle   seconds (float) — per-object scanner sleep
  identity_openid_*  OIDC provider for AssumeRoleWithWebIdentity
                     (jwks_url | jwks inline, client_id, claim_name,
                     issuer — see iam/oidc.py)
"""

from __future__ import annotations

import json

SYS_VOL = ".mtpu.sys"
CONFIG_PATH = "config/server.json"


class ConfigError(Exception):
    pass


def _disks(object_layer):
    from minio_tpu.s3.metrics import layer_sets
    return [d for s in layer_sets(object_layer) for d in s.disks]


def load_config(object_layer) -> dict:
    votes: dict[bytes, int] = {}
    for d in _disks(object_layer):
        try:
            blob = d.read_all(SYS_VOL, CONFIG_PATH)
            votes[blob] = votes.get(blob, 0) + 1
        except Exception:  # noqa: BLE001 - absent / offline
            continue
    if not votes:
        return {}
    blob = max(votes.items(), key=lambda kv: kv[1])[0]
    try:
        cfg = json.loads(blob)
        return cfg if isinstance(cfg, dict) else {}
    except ValueError:
        return {}


def save_config(object_layer, cfg: dict,
                prev: dict | None = None) -> None:
    """Quorum-write the config; on quorum failure, best-effort restore
    `prev` to any drives that took the new blob, so a REJECTED update
    cannot win the plurality vote at the next load."""
    blob = json.dumps(cfg, sort_keys=True).encode()
    disks = _disks(object_layer)
    wrote = []
    for d in disks:
        try:
            d.write_all(SYS_VOL, CONFIG_PATH, blob)
            wrote.append(d)
        except Exception:  # noqa: BLE001 - offline drive
            continue
    if len(wrote) < len(disks) // 2 + 1:
        if prev is not None:
            old = json.dumps(prev, sort_keys=True).encode()
            for d in wrote:
                try:
                    d.write_all(SYS_VOL, CONFIG_PATH, old)
                except Exception:  # noqa: BLE001 - best effort
                    pass
        raise ConfigError("could not persist config to a drive quorum")


def validate(updates: dict) -> None:
    for k, v in updates.items():
        if k == "compression" and v not in ("on", "off"):
            raise ConfigError("compression must be 'on' or 'off'")
        if k in ("scanner_interval", "scanner_throttle"):
            try:
                if float(v) < 0:
                    raise ValueError
            except (TypeError, ValueError):
                raise ConfigError(
                    f"{k} must be a non-negative number") from None
        if k == "scanner_deep_every":
            try:
                if int(v) < 1:
                    raise ValueError
            except (TypeError, ValueError):
                raise ConfigError(
                    f"{k} must be a positive integer") from None


def apply_config(server, cfg: dict) -> list[str]:
    """Apply live-reloadable settings; returns the keys that changed
    behavior."""
    applied = []
    if "compression" in cfg:
        server.compression = cfg["compression"] == "on"
        applied.append("compression")
    scanner = getattr(server.object_layer, "scanner", None)
    if scanner is not None:
        if "scanner_interval" in cfg:
            scanner.interval = float(cfg["scanner_interval"])
            applied.append("scanner_interval")
        if "scanner_deep_every" in cfg:
            scanner.deep_every = int(cfg["scanner_deep_every"])
            applied.append("scanner_deep_every")
        if "scanner_throttle" in cfg:
            scanner.throttle = float(cfg["scanner_throttle"])
            applied.append("scanner_throttle")
    if any(k.startswith("identity_openid") for k in cfg):
        # Drop the cached validator; the next STS web-identity call
        # rebuilds it from the new provider settings.
        server.oidc = None
        applied.append("identity_openid")
    return applied
