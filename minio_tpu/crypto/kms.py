"""Local KMS: data-key generation and sealing under a master key.

The shape of the reference's built-in KMS (internal/kms/ with
MINIO_KMS_SECRET_KEY): one named 256-bit master key; GenerateKey
returns a fresh random data key plus that key sealed (AES-256-GCM)
under the master key with the usage context bound as associated data.
Unsealing with a different context or master key fails loudly.
"""

from __future__ import annotations

import base64
import json
import os

from cryptography.hazmat.primitives.ciphers.aead import AESGCM


class KMSError(Exception):
    pass


class KMS:
    """Single-master-key KMS (key id -> 32-byte secret)."""

    def __init__(self, keys: dict[str, bytes], default_key: str):
        if default_key not in keys:
            raise KMSError(f"default key {default_key!r} not configured")
        for kid, secret in keys.items():
            if len(secret) != 32:
                raise KMSError(f"key {kid!r} must be 32 bytes")
        self._keys = dict(keys)
        self.default_key = default_key

    @classmethod
    def from_env(cls, env: str = "MTPU_KMS_SECRET_KEY"):
        """`name:base64key` (the reference's MINIO_KMS_SECRET_KEY
        format). Returns None when unset — SSE then reports an error."""
        raw = os.environ.get(env, "")
        if not raw:
            return None
        name, _, b64 = raw.partition(":")
        if not name or not b64:
            raise KMSError(f"{env} must be name:base64(32 bytes)")
        try:
            secret = base64.b64decode(b64)
        except ValueError:
            raise KMSError(f"{env}: bad base64") from None
        return cls({name: secret}, name)

    def generate_key(self, context: dict) -> tuple[bytes, str]:
        """(plaintext 32-byte data key, sealed blob string)."""
        key = os.urandom(32)
        return key, self.seal(key, context)

    def seal(self, key: bytes, context: dict) -> str:
        master = self._keys[self.default_key]
        nonce = os.urandom(12)
        aad = json.dumps(context, sort_keys=True).encode()
        ct = AESGCM(master).encrypt(nonce, key, aad)
        blob = {"v": 1, "kid": self.default_key,
                "n": base64.b64encode(nonce).decode(),
                "c": base64.b64encode(ct).decode()}
        return json.dumps(blob, sort_keys=True)

    def unseal(self, sealed: str, context: dict) -> bytes:
        try:
            blob = json.loads(sealed)
            master = self._keys[blob["kid"]]
            nonce = base64.b64decode(blob["n"])
            ct = base64.b64decode(blob["c"])
        except (ValueError, KeyError, TypeError):
            raise KMSError("malformed sealed key") from None
        aad = json.dumps(context, sort_keys=True).encode()
        try:
            return AESGCM(master).decrypt(nonce, ct, aad)
        except Exception:
            raise KMSError("sealed key does not unseal "
                           "(wrong master key or context)") from None
