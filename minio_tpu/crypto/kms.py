"""Local KMS: data-key generation and sealing under a master key.

The shape of the reference's built-in KMS (internal/kms/ with
MINIO_KMS_SECRET_KEY): one named 256-bit master key; GenerateKey
returns a fresh random data key plus that key sealed (AES-256-GCM)
under the master key with the usage context bound as associated data.
Unsealing with a different context or master key fails loudly.
"""

from __future__ import annotations

import base64
import json
import os

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # gated optional dep: KMS/SSE need the wheel,
    AESGCM = None    # the rest of the server must boot without it


class KMSError(Exception):
    pass


class _NativeAESGCM:
    """AESGCM-compatible AES-256-GCM over the native kernel
    (native/native.cc mtpu_gcm_seal/mtpu_gcm_open): same deterministic
    output as the `cryptography` wheel — GCM has exactly one valid
    ciphertext per (key, nonce, aad, plaintext) — validated against the
    NIST SP 800-38D vectors in tests/test_transform_fused.py. Restores
    the whole SSE/KMS surface in containers without the wheel, and the
    bulk DARE paths ride the same kernels GIL-free."""

    __slots__ = ("_key",)

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise KMSError("native AES-GCM supports 256-bit keys only")
        self._key = bytes(key)

    def encrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        import ctypes

        from minio_tpu import native
        lib = native.load()
        if len(nonce) != 12:
            raise KMSError("native AES-GCM requires a 96-bit nonce")
        aad = aad or b""
        out = (ctypes.c_uint8 * (len(data) + 16))()
        lib.mtpu_gcm_seal(native._u8(self._key), native._u8(nonce),
                          native._u8(aad), len(aad), native._u8(data),
                          len(data), out)
        return bytes(out)

    def decrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        import ctypes

        from minio_tpu import native
        lib = native.load()
        if len(nonce) != 12:
            raise KMSError("native AES-GCM requires a 96-bit nonce")
        aad = aad or b""
        if len(data) < 16:
            raise ValueError("ciphertext shorter than the GCM tag")
        out = (ctypes.c_uint8 * (len(data) - 16))()
        got = lib.mtpu_gcm_open(native._u8(self._key), native._u8(nonce),
                                native._u8(aad), len(aad),
                                native._u8(data), len(data), out)
        if got < 0:
            raise ValueError("GCM tag verification failed")
        return bytes(out)


def _native_gcm_available() -> bool:
    try:
        from minio_tpu import native
        lib = native.load()
        return lib is not None and hasattr(lib, "mtpu_gcm_seal")
    except Exception:  # noqa: BLE001 - loader failure = unavailable
        return False


def aesgcm_impl():
    """The AEAD class backing KMS/SSE/DARE: the `cryptography` wheel
    when installed, else the native kernel, else None (SSE features
    report unavailable at use)."""
    if AESGCM is not None:
        return AESGCM
    if _native_gcm_available():
        return _NativeAESGCM
    return None


def aesgcm(key: bytes):
    """An AEAD instance for `key` (raises KMSError when no backend)."""
    require_aesgcm()
    return aesgcm_impl()(key)


def require_aesgcm() -> None:
    """Fail loudly AT USE TIME when no AES-GCM backend exists (neither
    the optional `cryptography` wheel nor the native kernel library): a
    deployment that never touches KMS/SSE must not pay an import-time
    crash for a feature it does not use."""
    if aesgcm_impl() is None:
        raise KMSError(
            "no AES-GCM backend (the 'cryptography' package is not "
            "installed and the native kernel library is unavailable); "
            "KMS/SSE features are unavailable")


class KMS:
    """Single-master-key KMS (key id -> 32-byte secret)."""

    def __init__(self, keys: dict[str, bytes], default_key: str):
        if default_key not in keys:
            raise KMSError(f"default key {default_key!r} not configured")
        for kid, secret in keys.items():
            if len(secret) != 32:
                raise KMSError(f"key {kid!r} must be 32 bytes")
        self._keys = dict(keys)
        self.default_key = default_key

    @classmethod
    def from_env(cls, env: str = "MTPU_KMS_SECRET_KEY"):
        """`name:base64key` (the reference's MINIO_KMS_SECRET_KEY
        format). Returns None when unset — SSE then reports an error."""
        raw = os.environ.get(env, "")
        if not raw:
            return None
        name, _, b64 = raw.partition(":")
        if not name or not b64:
            raise KMSError(f"{env} must be name:base64(32 bytes)")
        try:
            secret = base64.b64decode(b64)
        except ValueError:
            raise KMSError(f"{env}: bad base64") from None
        return cls({name: secret}, name)

    def generate_key(self, context: dict) -> tuple[bytes, str]:
        """(plaintext 32-byte data key, sealed blob string)."""
        key = os.urandom(32)
        return key, self.seal(key, context)

    def seal(self, key: bytes, context: dict, kid: str = "") -> str:
        """Seal under the default master key, or a NAMED key (batch
        key rotation reseals existing objects under a new key)."""
        require_aesgcm()
        kid = kid or self.default_key
        if kid not in self._keys:
            # Mirror unseal(): the key may have been created on another
            # node since this process loaded — refresh once.
            ks = getattr(self, "_keystore", None)
            if ks is not None:
                ks.reload()
        if kid not in self._keys:
            raise KMSError(f"no such key {kid!r}")
        master = self._keys[kid]
        nonce = os.urandom(12)
        aad = json.dumps(context, sort_keys=True).encode()
        ct = aesgcm(master).encrypt(nonce, key, aad)
        blob = {"v": 1, "kid": kid,
                "n": base64.b64encode(nonce).decode(),
                "c": base64.b64encode(ct).decode()}
        return json.dumps(blob, sort_keys=True)

    def unseal(self, sealed: str, context: dict) -> bytes:
        require_aesgcm()
        try:
            blob = json.loads(sealed)
            kid = blob["kid"]
            if kid not in self._keys:
                # A key created on ANOTHER node since this process
                # loaded: refresh the attached store once before
                # failing (the cross-node analogue of the IAM TTL).
                ks = getattr(self, "_keystore", None)
                if ks is not None:
                    ks.reload()
            master = self._keys[kid]
            nonce = base64.b64decode(blob["n"])
            ct = base64.b64decode(blob["c"])
        except (ValueError, KeyError, TypeError):
            raise KMSError("malformed sealed key") from None
        aad = json.dumps(context, sort_keys=True).encode()
        try:
            return aesgcm(master).decrypt(nonce, ct, aad)
        except Exception:
            raise KMSError("sealed key does not unseal "
                           "(wrong master key or context)") from None


class KeyStore:
    """Drive-persisted named-key registry behind the KMS admin API.

    The analogue of the reference's KMS key management surface
    (cmd/kms-handlers.go KMSCreateKey/KMSListKeys/KMSKeyStatus,
    internal/kms/): named 256-bit keys, each stored SEALED under the
    env master key (MTPU_KMS_SECRET_KEY) on a quorum of the given
    drives, loaded into the live KMS so SSE can seal/unseal under any
    of them. Without an env master key the store refuses to operate —
    persisting key material unwrapped is not an option.
    """

    PATH = "config/kms/keys.json"
    _SYS = ".mtpu.sys"

    # Named keys created on other nodes become visible within this
    # window (plus immediately on an unknown-kid unseal).
    _TTL = 2.0

    def __init__(self, kms: "KMS", disks):
        if kms is None:
            raise KMSError("KMS key store requires MTPU_KMS_SECRET_KEY")
        self.kms = kms
        self._disks = list(disks)
        self._load()
        import time as _time
        self._loaded_at = _time.monotonic()
        kms._keystore = self

    def reload(self) -> None:
        import time as _time
        if _time.monotonic() - self._loaded_at < self._TTL:
            return
        self._load()
        self._loaded_at = _time.monotonic()

    def _ctx(self, name: str) -> dict:
        return {"kms-key": name}

    def _load(self) -> None:
        votes: dict[bytes, int] = {}
        for d in self._disks:
            try:
                blob = d.read_all(self._SYS, self.PATH)
                votes[blob] = votes.get(blob, 0) + 1
            except Exception:  # noqa: BLE001 - absent / offline
                continue
        self._sealed: dict[str, str] = {}
        if votes:
            try:
                doc = json.loads(max(votes.items(),
                                     key=lambda kv: kv[1])[0])
                if isinstance(doc, dict):
                    self._sealed = doc
            except ValueError:
                pass
        for name, sealed in self._sealed.items():
            try:
                self.kms._keys[name] = self.kms.unseal(sealed,
                                                       self._ctx(name))
            except KMSError:
                continue            # sealed under a different master

    def _save(self) -> None:
        blob = json.dumps(self._sealed, sort_keys=True).encode()
        ok = 0
        for d in self._disks:
            try:
                d.write_all(self._SYS, self.PATH, blob)
                ok += 1
            except Exception:  # noqa: BLE001 - offline drive
                continue
        if ok < len(self._disks) // 2 + 1:
            raise KMSError("could not persist KMS keys to a quorum")

    def create(self, name: str) -> None:
        if not name or "/" in name:
            raise KMSError("invalid key name")
        if name in self.kms._keys:
            raise KMSError(f"key {name!r} already exists")
        secret = os.urandom(32)
        self._sealed[name] = self.kms.seal(secret, self._ctx(name))
        self._save()
        self.kms._keys[name] = secret

    def list(self) -> list[dict]:
        return [{"name": n, "default": n == self.kms.default_key}
                for n in sorted(self.kms._keys)]

    def status(self, name: str) -> dict:
        """Liveness probe: encrypt/decrypt a canary under the key (the
        reference's KMSKeyStatus does the same round trip)."""
        if name not in self.kms._keys:
            raise KMSError(f"no such key {name!r}")
        canary = os.urandom(16)
        require_aesgcm()
        nonce = os.urandom(12)
        ct = aesgcm(self.kms._keys[name]).encrypt(nonce, canary, b"")
        ok = aesgcm(self.kms._keys[name]).decrypt(nonce, ct, b"") == canary
        return {"name": name, "encrypt_ok": ok, "decrypt_ok": ok}
