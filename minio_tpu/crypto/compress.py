"""Transparent object compression (the reference's compression layer,
cmd/object-api-utils.go S2/seekable: internal metadata records the
scheme and a per-block index so ranged reads decompress only the blocks
they touch).

Scheme: the plaintext splits into fixed 1 MiB blocks, each deflated
independently (zlib — the in-tree codec; the reference uses S2). The
stored stream is the concatenation of compressed blocks; the block
index (cumulative compressed offsets) lives in internal metadata, so
plaintext offset -> block -> stored byte range is one lookup.

v1 scope: objects up to the streaming threshold (32 MiB) — exactly the
buffered-put path — and never combined with SSE (the reference also
disables compression for encrypted objects by default). Incompressible
payloads (compressed >= original) store uncompressed automatically.
"""

from __future__ import annotations

import base64
import struct
import zlib

BLOCK = 1 << 20

META_SCHEME = "x-internal-comp"          # "zlib-blocks"
META_SIZE = "x-internal-comp-size"       # plaintext size
META_INDEX = "x-internal-comp-index"     # base64 packed u32 cumulative ends

SCHEME = "zlib-blocks"

# Extensions/content-types that compress well (reference default
# allowlist shape, internal/config/compress).
DEFAULT_EXTENSIONS = (".txt", ".log", ".csv", ".json", ".tar", ".xml",
                      ".bin", ".ndjson", ".tsv", ".yaml", ".yml", ".md")
DEFAULT_MIME_PREFIXES = ("text/", "application/json", "application/xml",
                         "application/csv")


class CompressionError(Exception):
    pass


def eligible(key: str, content_type: str) -> bool:
    k = key.lower()
    if any(k.endswith(ext) for ext in DEFAULT_EXTENSIONS):
        return True
    ct = (content_type or "").lower()
    return any(ct.startswith(p) for p in DEFAULT_MIME_PREFIXES)


def compress(data: bytes) -> tuple[bytes, dict] | None:
    """Compress into the block scheme; None when not worth storing
    (incompressible)."""
    blocks = []
    ends = []
    total = 0
    for off in range(0, len(data), BLOCK):
        blob = zlib.compress(data[off:off + BLOCK], 6)
        blocks.append(blob)
        total += len(blob)
        ends.append(total)
    if total >= len(data):
        return None
    index = base64.b64encode(
        struct.pack(f">{len(ends)}I", *ends)).decode()
    meta = {META_SCHEME: SCHEME, META_SIZE: str(len(data)),
            META_INDEX: index}
    return b"".join(blocks), meta


def _index(meta: dict) -> list[int]:
    try:
        raw = base64.b64decode(meta[META_INDEX])
        if not raw or len(raw) % 4:
            raise ValueError("bad index length")
        return list(struct.unpack(f">{len(raw) // 4}I", raw))
    except (KeyError, ValueError, struct.error):
        raise CompressionError("corrupt compression index") from None


def decompress_range(stored: bytes, meta: dict, offset: int,
                     length: int, stored_base: int = 0) -> bytes:
    """Plaintext [offset, offset+length) from stored bytes.

    stored_base: the absolute offset `stored[0]` corresponds to in the
    full stored stream (ranged readers fetch only the covering blocks).
    """
    if meta.get(META_SCHEME) != SCHEME:
        raise CompressionError(f"unknown scheme {meta.get(META_SCHEME)!r}")
    plain_size = int(meta.get(META_SIZE, "0"))
    if offset < 0 or length < 0 or offset + length > plain_size:
        raise CompressionError("range out of bounds")
    if length == 0:
        return b""
    ends = _index(meta)
    first = offset // BLOCK
    last = (offset + length - 1) // BLOCK
    out = bytearray()
    for b in range(first, last + 1):
        lo = (ends[b - 1] if b else 0) - stored_base
        hi = ends[b] - stored_base
        if lo < 0 or hi > len(stored):
            raise CompressionError("stored window does not cover range")
        try:
            out += zlib.decompress(stored[lo:hi])
        except zlib.error:
            raise CompressionError(
                f"block {b} fails decompression") from None
    skip = offset - first * BLOCK
    return bytes(out[skip:skip + length])


def stored_range(meta: dict, offset: int, length: int) -> tuple[int, int]:
    """Stored byte range covering plaintext [offset, offset+length)."""
    ends = _index(meta)
    first = offset // BLOCK
    last = (offset + length - 1) // BLOCK if length else first
    lo = ends[first - 1] if first else 0
    return lo, ends[min(last, len(ends) - 1)] - lo
