"""Transparent object compression (the reference's compression layer,
cmd/object-api-utils.go S2/seekable: internal metadata records the
scheme and a per-block index so ranged reads decompress only the blocks
they touch).

Scheme: the plaintext splits into fixed 1 MiB blocks, each deflated
independently (zlib — the in-tree codec; the reference uses S2). The
stored stream is the concatenation of compressed blocks; the block
index (cumulative compressed offsets) lives in internal metadata, so
plaintext offset -> block -> stored byte range is one lookup.

v1 scope: objects up to the streaming threshold (32 MiB) — exactly the
buffered-put path — and never combined with SSE (the reference also
disables compression for encrypted objects by default). Incompressible
payloads (compressed >= original) store uncompressed automatically.
"""

from __future__ import annotations

import base64
import struct
import zlib

BLOCK = 1 << 20

META_SCHEME = "x-internal-comp"          # "zlib-blocks"
META_SIZE = "x-internal-comp-size"       # plaintext size
META_INDEX = "x-internal-comp-index"     # base64 packed u32 cumulative ends

SCHEME = "zlib-blocks"

# Extensions/content-types that compress well (reference default
# allowlist shape, internal/config/compress).
DEFAULT_EXTENSIONS = (".txt", ".log", ".csv", ".json", ".tar", ".xml",
                      ".bin", ".ndjson", ".tsv", ".yaml", ".yml", ".md")
DEFAULT_MIME_PREFIXES = ("text/", "application/json", "application/xml",
                         "application/csv")


class CompressionError(Exception):
    pass


def _native_lib():
    """Native kernel library when it carries the zlib block entry
    points (built against the same zlib the Python module wraps, so
    output is byte-identical; None -> Python fallback). The fused-plane
    kill-switch (MTPU_TRANSFORM_FUSED=off) disables this too, so "off"
    exercises the layered pipeline end to end."""
    from minio_tpu import native
    return native.feature("mtpu_deflate_blocks")


def deflate_blocks(data) -> "tuple[bytes, list[int]] | None":
    """All blocks deflated in ONE GIL-free native call: (stored bytes,
    cumulative ends), or None when the native path is unavailable or
    errored (caller falls back to the per-block Python loop)."""
    lib = _native_lib()
    if lib is None or not len(data):
        return None
    import ctypes
    nblocks = (len(data) + BLOCK - 1) // BLOCK
    # compressBound-style headroom per block so an incompressible body
    # still deflates (the caller compares totals and stores raw).
    cap = len(data) + nblocks * 1104 + 64
    out = (ctypes.c_uint8 * cap)()
    ends = (ctypes.c_int64 * nblocks)()
    from minio_tpu import native
    got = lib.mtpu_deflate_blocks(native._u8(data), len(data), BLOCK, 6,
                                  out, cap, ends)
    if got < 0:
        return None
    return bytes(memoryview(out)[:got]), list(ends)


def inflate_blocks(stored, ends: list[int], first_block: int,
                   nblocks: int, stored_base: int) -> "bytes | None":
    """Inflate stored blocks [first_block, first_block+nblocks) out of
    a stored window in ONE native call; None -> Python fallback,
    CompressionError on corrupt blocks/windows."""
    lib = _native_lib()
    if lib is None or nblocks <= 0:
        return None if nblocks > 0 else b""
    import ctypes

    import numpy as _np
    cap = nblocks * BLOCK
    out = (ctypes.c_uint8 * cap)()
    ends_arr = (ctypes.c_int64 * len(ends))(*ends)
    src = _np.frombuffer(stored, dtype=_np.uint8)
    got = lib.mtpu_inflate_blocks(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(src),
        ends_arr, first_block, nblocks, stored_base, out, cap)
    if got == -2:
        return None
    if got < 0:
        raise CompressionError(
            f"block range {first_block}+{nblocks} fails decompression")
    return bytes(memoryview(out)[:got])


def eligible(key: str, content_type: str) -> bool:
    k = key.lower()
    if any(k.endswith(ext) for ext in DEFAULT_EXTENSIONS):
        return True
    ct = (content_type or "").lower()
    return any(ct.startswith(p) for p in DEFAULT_MIME_PREFIXES)


def compress(data: bytes) -> tuple[bytes, dict] | None:
    """Compress into the block scheme; None when not worth storing
    (incompressible)."""
    native_out = deflate_blocks(data)
    if native_out is not None:
        stored, ends = native_out
        total = len(stored)
    else:
        blocks = []
        ends = []
        total = 0
        for off in range(0, len(data), BLOCK):
            blob = zlib.compress(data[off:off + BLOCK], 6)
            blocks.append(blob)
            total += len(blob)
            ends.append(total)
        stored = b"".join(blocks)
    if total >= len(data):
        return None
    return stored, index_meta(len(data), ends)


def index_meta(plain_size: int, ends: list[int]) -> dict:
    """The internal-metadata entries recording the block scheme (shared
    by the layered compressor above and the fused native transform)."""
    index = base64.b64encode(
        struct.pack(f">{len(ends)}I", *ends)).decode()
    return {META_SCHEME: SCHEME, META_SIZE: str(plain_size),
            META_INDEX: index}


def _index(meta: dict) -> list[int]:
    try:
        raw = base64.b64decode(meta[META_INDEX])
        if not raw or len(raw) % 4:
            raise ValueError("bad index length")
        return list(struct.unpack(f">{len(raw) // 4}I", raw))
    except (KeyError, ValueError, struct.error):
        raise CompressionError("corrupt compression index") from None


def decompress_range(stored: bytes, meta: dict, offset: int,
                     length: int, stored_base: int = 0) -> bytes:
    """Plaintext [offset, offset+length) from stored bytes.

    stored_base: the absolute offset `stored[0]` corresponds to in the
    full stored stream (ranged readers fetch only the covering blocks).
    """
    if meta.get(META_SCHEME) != SCHEME:
        raise CompressionError(f"unknown scheme {meta.get(META_SCHEME)!r}")
    plain_size = int(meta.get(META_SIZE, "0"))
    if offset < 0 or length < 0 or offset + length > plain_size:
        raise CompressionError("range out of bounds")
    if length == 0:
        return b""
    ends = _index(meta)
    first = offset // BLOCK
    last = (offset + length - 1) // BLOCK
    native_out = inflate_blocks(stored, ends, first, last - first + 1,
                                stored_base)
    if native_out is not None:
        out = native_out
    else:
        acc = bytearray()
        for b in range(first, last + 1):
            lo = (ends[b - 1] if b else 0) - stored_base
            hi = ends[b] - stored_base
            if lo < 0 or hi > len(stored):
                raise CompressionError(
                    "stored window does not cover range")
            try:
                acc += zlib.decompress(stored[lo:hi])
            except zlib.error:
                raise CompressionError(
                    f"block {b} fails decompression") from None
        out = bytes(acc)
    skip = offset - first * BLOCK
    return bytes(out[skip:skip + length])


def stored_range(meta: dict, offset: int, length: int) -> tuple[int, int]:
    """Stored byte range covering plaintext [offset, offset+length)."""
    ends = _index(meta)
    first = offset // BLOCK
    last = (offset + length - 1) // BLOCK if length else first
    lo = ends[first - 1] if first else 0
    return lo, ends[min(last, len(ends) - 1)] - lo
