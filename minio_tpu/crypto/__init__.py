"""Server-side encryption: KMS, DARE-style streaming AEAD, SSE plumbing.

The content-transform column of the reference (cmd/encryption-v1.go,
internal/crypto/, internal/kms/): objects encrypt before they reach the
erasure layer, per-object data keys seal under a KMS master key (SSE-S3)
or a client-supplied key (SSE-C), and ciphertext is framed in
fixed-size AES-256-GCM packages so ranged reads decrypt only the
packages they touch.
"""

from minio_tpu.crypto.kms import KMS, KMSError
from minio_tpu.crypto.dare import (PACKAGE_SIZE, DareError,
                                   decrypt_packages, encrypt_stream_size,
                                   EncryptingPayload, package_range,
                                   plaintext_size)

__all__ = ["KMS", "KMSError", "PACKAGE_SIZE", "DareError",
           "decrypt_packages", "encrypt_stream_size", "EncryptingPayload",
           "package_range", "plaintext_size"]
