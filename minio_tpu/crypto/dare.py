"""DARE-style streaming AEAD: fixed-size AES-256-GCM packages.

The reference encrypts object streams with DARE (Data At Rest
Encryption, github.com/minio/sio): the plaintext splits into fixed
64 KiB packages, each sealed independently with a nonce derived from a
random base nonce and the package sequence number. Random access
follows: byte x of plaintext lives in package x // PACKAGE_SIZE, so a
ranged GET decrypts only the packages covering the range. Reordering or
truncating packages breaks their sequence-bound nonces/tags.

Layout per package: AESGCM(key, nonce=base_nonce XOR seq) over the
plaintext chunk with the sequence number as associated data; ciphertext
is chunk + 16-byte tag. No header — the base nonce and sealed key live
in object metadata, not the data stream.
"""

from __future__ import annotations

import struct
from typing import Iterator

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # optional dep, gated at use (crypto/kms.py)
    AESGCM = None

from minio_tpu.crypto.kms import require_aesgcm

PACKAGE_SIZE = 64 * 1024
TAG_SIZE = 16


class DareError(Exception):
    pass


def _nonce(base: bytes, seq: int) -> bytes:
    tail = int.from_bytes(base[4:], "big") ^ seq
    return base[:4] + tail.to_bytes(8, "big")


def encrypt_stream_size(plain_size: int) -> int:
    """Ciphertext size for a plaintext of plain_size bytes."""
    if plain_size == 0:
        return 0
    packages = (plain_size + PACKAGE_SIZE - 1) // PACKAGE_SIZE
    return plain_size + packages * TAG_SIZE


def plaintext_size(cipher_size: int) -> int:
    """Inverse of encrypt_stream_size."""
    if cipher_size == 0:
        return 0
    full_pkg = PACKAGE_SIZE + TAG_SIZE
    packages = (cipher_size + full_pkg - 1) // full_pkg
    return cipher_size - packages * TAG_SIZE


def package_range(offset: int, length: int) -> tuple[int, int, int]:
    """Plaintext range -> (first package seq, ciphertext offset,
    ciphertext length) covering it."""
    first = offset // PACKAGE_SIZE
    last = (offset + length - 1) // PACKAGE_SIZE
    c_off = first * (PACKAGE_SIZE + TAG_SIZE)
    c_len = (last - first + 1) * (PACKAGE_SIZE + TAG_SIZE)
    return first, c_off, c_len


class EncryptingPayload:
    """Payload-shaped reader producing the DARE ciphertext of an inner
    Payload: .read(n), .size (ciphertext size). Packages seal as the
    plaintext streams through — O(package) memory."""

    def __init__(self, inner, key: bytes, base_nonce: bytes):
        require_aesgcm()
        self._inner = inner
        self._aead = AESGCM(key)
        self._base = base_nonce
        self.size = encrypt_stream_size(inner.size)
        self._seq = 0
        self._buf = memoryview(b"")
        self._plain_left = inner.size

    def read(self, n: int) -> bytes:
        while not self._buf and self._plain_left > 0:
            chunk = _read_exact(self._inner, min(PACKAGE_SIZE,
                                                 self._plain_left))
            self._plain_left -= len(chunk)
            sealed = self._aead.encrypt(_nonce(self._base, self._seq),
                                        chunk, _aad(self._seq))
            self._seq += 1
            self._buf = memoryview(sealed)
        out = self._buf[:n]
        self._buf = self._buf[len(out):]
        return bytes(out)


def _aad(seq: int) -> bytes:
    return struct.pack(">Q", seq)


def _read_exact(reader, n: int) -> bytes:
    parts = []
    while n > 0:
        c = reader.read(n)
        if not c:
            raise DareError("plaintext stream ended early")
        parts.append(c)
        n -= len(c)
    return parts[0] if len(parts) == 1 else b"".join(parts)


def decrypt_packages(chunks: Iterator, key: bytes, base_nonce: bytes,
                     first_seq: int, skip: int, length: int):
    """Decrypt a ciphertext byte stream of whole packages starting at
    package `first_seq`; yield plaintext, dropping `skip` leading bytes
    and stopping after `length` bytes (range-GET trimming)."""
    require_aesgcm()
    aead = AESGCM(key)
    try:
        yield from _decrypt_inner(chunks, aead, base_nonce, first_seq,
                                  skip, length)
    finally:
        close = getattr(chunks, "close", None)
        if close is not None:
            close()


def _decrypt_inner(chunks, aead, base_nonce, first_seq, skip, length):
    seq = first_seq
    buf = bytearray()
    produced = 0

    def packages():
        nonlocal buf
        for chunk in chunks:
            buf += chunk
            while len(buf) >= PACKAGE_SIZE + TAG_SIZE:
                yield bytes(buf[:PACKAGE_SIZE + TAG_SIZE])
                del buf[:PACKAGE_SIZE + TAG_SIZE]
        if buf:
            yield bytes(buf)

    for pkg in packages():
        if produced >= length:
            break
        try:
            plain = aead.decrypt(_nonce(base_nonce, seq), pkg, _aad(seq))
        except Exception:
            raise DareError(
                f"package {seq} fails authentication") from None
        seq += 1
        if skip:
            drop = min(skip, len(plain))
            plain = plain[drop:]
            skip -= drop
        if not plain:
            continue
        take = min(len(plain), length - produced)
        produced += take
        yield plain[:take]
    if produced < length:
        raise DareError("ciphertext stream ended early")
