"""DARE-style streaming AEAD: fixed-size AES-256-GCM packages.

The reference encrypts object streams with DARE (Data At Rest
Encryption, github.com/minio/sio): the plaintext splits into fixed
64 KiB packages, each sealed independently with a nonce derived from a
random base nonce and the package sequence number. Random access
follows: byte x of plaintext lives in package x // PACKAGE_SIZE, so a
ranged GET decrypts only the packages covering the range. Reordering or
truncating packages breaks their sequence-bound nonces/tags.

Layout per package: AESGCM(key, nonce=base_nonce XOR seq) over the
plaintext chunk with the sequence number as associated data; ciphertext
is chunk + 16-byte tag. No header — the base nonce and sealed key live
in object metadata, not the data stream.
"""

from __future__ import annotations

import struct
from typing import Iterator

from minio_tpu.crypto.kms import aesgcm, require_aesgcm

PACKAGE_SIZE = 64 * 1024
TAG_SIZE = 16

# Native bulk window: how many plaintext bytes one GIL-free
# mtpu_dare_seal/open call covers (16 packages = 1 MiB).
_BULK_PACKAGES = 16


class DareError(Exception):
    pass


def _native_lib():
    """The native kernel library when it carries the DARE entry points
    (None -> per-package Python AEAD fallback, byte-identical). The
    fused-plane kill-switch (MTPU_TRANSFORM_FUSED=off) disables the
    bulk path too, so "off" exercises the layered pipeline end to
    end."""
    from minio_tpu import native
    return native.feature("mtpu_dare_seal")


def seal_bulk(key: bytes, base_nonce: bytes, first_seq: int,
              plain: bytes):
    """Seal whole packages of `plain` in ONE native call; None when the
    native library is unavailable (caller falls back per package)."""
    lib = _native_lib()
    if lib is None:
        return None
    import ctypes

    from minio_tpu import native
    pkgs = (len(plain) + PACKAGE_SIZE - 1) // PACKAGE_SIZE
    out = (ctypes.c_uint8 * (len(plain) + pkgs * TAG_SIZE))()
    n = lib.mtpu_dare_seal(native._u8(key), native._u8(base_nonce),
                           first_seq, native._u8(plain), len(plain), out)
    return bytes(out)[:n]


def open_bulk(key: bytes, base_nonce: bytes, first_seq: int,
              cipher):
    """Open whole sealed packages in ONE native call: plaintext bytes,
    DareError on authentication failure, None when the native library
    is unavailable. `cipher` may be any contiguous buffer (pooled GET
    windows pass memoryviews; the native call reads them in place —
    no staging copy)."""
    lib = _native_lib()
    if lib is None:
        return None
    import ctypes

    import numpy as _np

    from minio_tpu import native
    src = _np.frombuffer(cipher, dtype=_np.uint8)
    out = (ctypes.c_uint8 * max(1, len(src)))()
    n = lib.mtpu_dare_open(
        native._u8(key), native._u8(base_nonce), first_seq,
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(src),
        out)
    if n < 0:
        raise DareError(
            f"package {first_seq + (-n - 1)} fails authentication")
    return bytes(memoryview(out)[:n])


def _nonce(base: bytes, seq: int) -> bytes:
    tail = int.from_bytes(base[4:], "big") ^ seq
    return base[:4] + tail.to_bytes(8, "big")


def encrypt_stream_size(plain_size: int) -> int:
    """Ciphertext size for a plaintext of plain_size bytes."""
    if plain_size == 0:
        return 0
    packages = (plain_size + PACKAGE_SIZE - 1) // PACKAGE_SIZE
    return plain_size + packages * TAG_SIZE


def plaintext_size(cipher_size: int) -> int:
    """Inverse of encrypt_stream_size."""
    if cipher_size == 0:
        return 0
    full_pkg = PACKAGE_SIZE + TAG_SIZE
    packages = (cipher_size + full_pkg - 1) // full_pkg
    return cipher_size - packages * TAG_SIZE


def package_range(offset: int, length: int) -> tuple[int, int, int]:
    """Plaintext range -> (first package seq, ciphertext offset,
    ciphertext length) covering it."""
    first = offset // PACKAGE_SIZE
    last = (offset + length - 1) // PACKAGE_SIZE
    c_off = first * (PACKAGE_SIZE + TAG_SIZE)
    c_len = (last - first + 1) * (PACKAGE_SIZE + TAG_SIZE)
    return first, c_off, c_len


class EncryptingPayload:
    """Payload-shaped reader producing the DARE ciphertext of an inner
    Payload: .read(n), .size (ciphertext size). Packages seal as the
    plaintext streams through — O(package) memory."""

    def __init__(self, inner, key: bytes, base_nonce: bytes):
        require_aesgcm()
        self._inner = inner
        self._key = bytes(key)
        self._aead = None if _native_lib() is not None else aesgcm(key)
        self._base = base_nonce
        self.size = encrypt_stream_size(inner.size)
        self._seq = 0
        self._buf = memoryview(b"")
        self._plain_left = inner.size

    def read(self, n: int) -> bytes:
        while not self._buf and self._plain_left > 0:
            if self._aead is None:
                # Native bulk: up to _BULK_PACKAGES packages sealed in
                # one GIL-free call instead of one AEAD hop per 64 KiB.
                want = min(_BULK_PACKAGES * PACKAGE_SIZE, self._plain_left)
                chunk = _read_exact(self._inner, want)
                self._plain_left -= len(chunk)
                sealed = seal_bulk(self._key, self._base, self._seq, chunk)
                if sealed is None:       # library vanished mid-stream
                    self._aead = aesgcm(self._key)
                    sealed = b"".join(
                        self._aead.encrypt(
                            _nonce(self._base, self._seq + i),
                            bytes(memoryview(chunk)[o:o + PACKAGE_SIZE]),
                            _aad(self._seq + i))
                        for i, o in enumerate(
                            range(0, len(chunk), PACKAGE_SIZE)))
                self._seq += (len(chunk) + PACKAGE_SIZE - 1) // PACKAGE_SIZE
                self._buf = memoryview(sealed)
                continue
            chunk = _read_exact(self._inner, min(PACKAGE_SIZE,
                                                 self._plain_left))
            self._plain_left -= len(chunk)
            sealed = self._aead.encrypt(_nonce(self._base, self._seq),
                                        chunk, _aad(self._seq))
            self._seq += 1
            self._buf = memoryview(sealed)
        out = self._buf[:n]
        self._buf = self._buf[len(out):]
        return bytes(out)


def _aad(seq: int) -> bytes:
    return struct.pack(">Q", seq)


def _read_exact(reader, n: int) -> bytes:
    parts = []
    while n > 0:
        c = reader.read(n)
        if not c:
            raise DareError("plaintext stream ended early")
        parts.append(c)
        n -= len(c)
    return parts[0] if len(parts) == 1 else b"".join(parts)


def decrypt_packages(chunks: Iterator, key: bytes, base_nonce: bytes,
                     first_seq: int, skip: int, length: int):
    """Decrypt a ciphertext byte stream of whole packages starting at
    package `first_seq`; yield plaintext, dropping `skip` leading bytes
    and stopping after `length` bytes (range-GET trimming). Whole
    pooled windows open through ONE native call when the kernel
    library is present (byte-identical to the per-package AEAD loop)."""
    require_aesgcm()
    try:
        if _native_lib() is not None:
            yield from _decrypt_inner_native(chunks, bytes(key),
                                             base_nonce, first_seq, skip,
                                             length)
        else:
            yield from _decrypt_inner(chunks, aesgcm(key), base_nonce,
                                      first_seq, skip, length)
    finally:
        close = getattr(chunks, "close", None)
        if close is not None:
            close()


def _trim(plain, skip, produced, length):
    """(emit, skip', produced') applying the range head-drop and tail
    cap shared by both decryptors."""
    if skip:
        drop = min(skip, len(plain))
        plain = plain[drop:]
        skip -= drop
    take = min(len(plain), length - produced)
    return plain[:take], skip, produced + take


def _decrypt_inner_native(chunks, key, base_nonce, first_seq, skip,
                          length):
    seq = first_seq
    carry = b""
    produced = 0
    full_pkg = PACKAGE_SIZE + TAG_SIZE
    for chunk in chunks:
        if produced >= length:
            break
        # Open every whole package the current window carries straight
        # out of the (possibly pooled) chunk. The sub-package carry
        # from the previous window completes into its own small open —
        # never by copying the whole new chunk onto it — so a 32 MiB
        # GET readahead window decrypts with zero staging memcpy.
        view = memoryview(chunk)
        if carry:
            head_take = min(full_pkg - len(carry), len(view))
            carry = carry + bytes(view[:head_take])
            view = view[head_take:]
            if len(carry) < full_pkg:
                continue
            plain = open_bulk(key, base_nonce, seq, carry)
            carry = b""
            seq += 1
            out, skip, produced = _trim(plain, skip, produced, length)
            if out:
                yield out
            if produced >= length:
                break
        usable = len(view) - (len(view) % full_pkg)
        if usable:
            plain = open_bulk(key, base_nonce, seq, view[:usable])
            seq += usable // full_pkg
            out, skip, produced = _trim(plain, skip, produced, length)
            if out:
                yield out
        carry = bytes(view[usable:])
    if carry and produced < length:
        # Tail: one final short sealed package.
        plain = open_bulk(key, base_nonce, seq, carry)
        out, skip, produced = _trim(plain, skip, produced, length)
        if out:
            yield out
    if produced < length:
        raise DareError("ciphertext stream ended early")


def _decrypt_inner(chunks, aead, base_nonce, first_seq, skip, length):
    seq = first_seq
    buf = bytearray()
    produced = 0

    def packages():
        nonlocal buf
        for chunk in chunks:
            buf += chunk
            while len(buf) >= PACKAGE_SIZE + TAG_SIZE:
                yield bytes(buf[:PACKAGE_SIZE + TAG_SIZE])
                del buf[:PACKAGE_SIZE + TAG_SIZE]
        if buf:
            yield bytes(buf)

    for pkg in packages():
        if produced >= length:
            break
        try:
            plain = aead.decrypt(_nonce(base_nonce, seq), pkg, _aad(seq))
        except Exception:
            raise DareError(
                f"package {seq} fails authentication") from None
        seq += 1
        plain, skip, produced = _trim(plain, skip, produced, length)
        if plain:
            yield plain
    if produced < length:
        raise DareError("ciphertext stream ended early")
