"""SSE request plumbing: header parsing, key sealing, metadata schema.

Maps the S3 SSE surface onto the DARE/KMS core (reference:
cmd/encryption-v1.go, internal/crypto/): SSE-S3 seals the per-object
data key under the KMS master key; SSE-C seals it under the
client-supplied 256-bit key (which is never stored — only its MD5, to
validate later requests).
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
from typing import Optional

from minio_tpu.crypto.kms import KMS, KMSError, aesgcm, require_aesgcm

ALG_SSE_S3 = "SSE-S3"
ALG_SSE_C = "SSE-C"

META_ALG = "x-internal-sse-alg"
META_KEY = "x-internal-sse-key"          # sealed data key (json)
META_NONCE = "x-internal-sse-nonce"      # base64 12-byte base nonce
META_SIZE = "x-internal-sse-size"        # plaintext size (decimal str)
META_KEY_MD5 = "x-internal-sse-c-md5"    # SSE-C customer key MD5 (b64)
META_MULTIPART = "x-internal-sse-mp"     # "1": per-part DARE streams

H_SSE = "x-amz-server-side-encryption"
H_C_ALG = "x-amz-server-side-encryption-customer-algorithm"
H_C_KEY = "x-amz-server-side-encryption-customer-key"
H_C_MD5 = "x-amz-server-side-encryption-customer-key-md5"


class SSEError(Exception):
    def __init__(self, code: str, message: str = ""):
        super().__init__(message or code)
        self.code = code


def parse_sse_c(h: dict) -> Optional[tuple[bytes, str]]:
    """(customer key, key md5 b64) from SSE-C headers, or None."""
    alg = h.get(H_C_ALG)
    if alg is None:
        return None
    if alg != "AES256":
        raise SSEError("InvalidArgument", "SSE-C algorithm must be AES256")
    try:
        key = base64.b64decode(h.get(H_C_KEY, ""))
    except ValueError:
        raise SSEError("InvalidArgument", "bad SSE-C key") from None
    if len(key) != 32:
        raise SSEError("InvalidArgument", "SSE-C key must be 256 bits")
    md5 = base64.b64encode(hashlib.md5(key).digest()).decode()
    declared = h.get(H_C_MD5, "")
    if declared and declared != md5:
        raise SSEError("InvalidDigest", "SSE-C key MD5 mismatch")
    return key, md5


def wants_sse_s3(h: dict, bucket_encryption_cfg: Optional[str]) -> bool:
    """Request header or bucket default encryption selects SSE-S3."""
    val = h.get(H_SSE, "")
    if val in ("AES256", "aws:kms"):
        return True
    if val:
        raise SSEError("InvalidArgument",
                       f"unsupported SSE algorithm {val!r}")
    return bool(bucket_encryption_cfg and
                "AES256" in bucket_encryption_cfg)


def _context(bucket: str, key: str) -> dict:
    return {"bucket": bucket, "object": key}


def part_key(data_key: bytes, part_number: int) -> bytes:
    """Per-part encryption key for multipart DARE streams.

    Each part is an independent DARE stream; deriving a distinct key
    per part (HMAC over the object data key, like the reference's
    DerivePartKey in cmd/encryption-v1.go:643 territory) makes the
    shared base nonce safe — (key, nonce, seq) never repeats across
    parts — and binds each part's ciphertext to its part number, so
    parts cannot be reordered on disk undetected."""
    import hmac as _hmac
    return _hmac.new(data_key, b"dare-part-%d" % part_number,
                     hashlib.sha256).digest()


def seal_with_customer_key(data_key: bytes, customer_key: bytes,
                           context: dict) -> str:
    require_aesgcm()
    nonce = os.urandom(12)
    aad = json.dumps(context, sort_keys=True).encode()
    ct = aesgcm(customer_key).encrypt(nonce, data_key, aad)
    return json.dumps({"v": 1, "n": base64.b64encode(nonce).decode(),
                       "c": base64.b64encode(ct).decode()},
                      sort_keys=True)


def unseal_with_customer_key(sealed: str, customer_key: bytes,
                             context: dict) -> bytes:
    require_aesgcm()
    try:
        blob = json.loads(sealed)
        nonce = base64.b64decode(blob["n"])
        ct = base64.b64decode(blob["c"])
    except (ValueError, KeyError, TypeError):
        raise SSEError("InvalidArgument", "malformed sealed key") from None
    aad = json.dumps(context, sort_keys=True).encode()
    try:
        return aesgcm(customer_key).decrypt(nonce, ct, aad)
    except Exception:
        raise SSEError("AccessDenied",
                       "SSE-C key does not decrypt this object") from None


def encrypt_metadata(bucket: str, key: str, plain_size: int,
                     kms: Optional[KMS],
                     customer: Optional[tuple[bytes, str]]
                     ) -> tuple[bytes, bytes, dict]:
    """Choose/seal the data key: returns (data_key, base_nonce,
    internal_metadata)."""
    base_nonce = os.urandom(12)
    ctx = _context(bucket, key)
    if customer is not None:
        data_key = os.urandom(32)
        sealed = seal_with_customer_key(data_key, customer[0], ctx)
        meta = {META_ALG: ALG_SSE_C, META_KEY: sealed,
                META_KEY_MD5: customer[1]}
    else:
        if kms is None:
            raise SSEError("InvalidRequest",
                           "SSE-S3 requested but no KMS is configured "
                           "(set MTPU_KMS_SECRET_KEY)")
        data_key, sealed = kms.generate_key(ctx)
        meta = {META_ALG: ALG_SSE_S3, META_KEY: sealed}
    meta[META_NONCE] = base64.b64encode(base_nonce).decode()
    meta[META_SIZE] = str(plain_size)
    return data_key, base_nonce, meta


def decrypt_params(bucket: str, key: str, internal: dict,
                   kms: Optional[KMS],
                   customer: Optional[tuple[bytes, str]]
                   ) -> tuple[bytes, bytes]:
    """(data_key, base_nonce) for an encrypted object's GET path."""
    alg = internal.get(META_ALG, "")
    ctx = _context(bucket, key)
    try:
        base_nonce = base64.b64decode(internal.get(META_NONCE, ""))
    except ValueError:
        raise SSEError("InternalError", "corrupt SSE nonce") from None
    if alg == ALG_SSE_C:
        if customer is None:
            raise SSEError("InvalidRequest",
                           "object is SSE-C encrypted; key headers "
                           "required")
        if internal.get(META_KEY_MD5) != customer[1]:
            raise SSEError("AccessDenied", "wrong SSE-C key")
        data_key = unseal_with_customer_key(internal.get(META_KEY, ""),
                                            customer[0], ctx)
    elif alg == ALG_SSE_S3:
        if kms is None:
            raise SSEError("InvalidRequest", "KMS not configured")
        try:
            data_key = kms.unseal(internal.get(META_KEY, ""), ctx)
        except KMSError as e:
            raise SSEError("InternalError", str(e)) from None
    else:
        raise SSEError("InternalError", f"unknown SSE algorithm {alg!r}")
    return data_key, base_nonce
