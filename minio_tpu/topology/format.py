"""format.json: drive identity and set layout, quorum-verified at boot.

The analogue of the reference's formatErasureV3
(cmd/format-erasure.go:112-126, cmd/prepare-storage.go): every drive
stores the deployment id, the full sets layout (a matrix of drive
UUIDs), and its own UUID ("this"). At boot the layouts are
quorum-compared, drives are re-ordered into their format positions (so
shuffled CLI arguments or fstab reordering cannot scramble shard
placement), fresh drives are initialized in place of their missing
UUIDs, and a drive carrying a foreign identity is refused.
"""

from __future__ import annotations

import uuid as uuid_mod
from dataclasses import dataclass
from typing import Optional, Sequence

FORMAT_VERSION = "1"
FORMAT_BACKEND = "xl"
XL_VERSION = "3"
DIST_ALGO = "SIPMOD+PARITY"


class FormatError(Exception):
    pass


@dataclass
class FormatInfo:
    deployment_id: str
    sets: list[list[str]]      # sets x drives-per-set of drive UUIDs
    this: str                  # this drive's UUID

    def to_json(self) -> dict:
        return {
            "version": FORMAT_VERSION,
            "format": FORMAT_BACKEND,
            "id": self.deployment_id,
            "xl": {
                "version": XL_VERSION,
                "this": self.this,
                "sets": self.sets,
                "distributionAlgo": DIST_ALGO,
            },
        }

    @classmethod
    def from_json(cls, m: dict) -> "FormatInfo":
        try:
            if m["format"] != FORMAT_BACKEND or m["version"] != FORMAT_VERSION:
                raise FormatError(f"unsupported format {m.get('format')!r}")
            xl = m["xl"]
            return cls(deployment_id=m["id"], sets=[list(s) for s in xl["sets"]],
                       this=xl["this"])
        except (KeyError, TypeError) as e:
            raise FormatError(f"malformed format.json: {e}") from None


def init_formats(disks: Sequence, set_size: int,
                 deployment_id: Optional[str] = None) -> list[FormatInfo]:
    """First boot: assign fresh UUIDs and write format.json everywhere."""
    n = len(disks)
    if n % set_size:
        raise FormatError(f"{n} drives not divisible into sets of {set_size}")
    deployment_id = deployment_id or str(uuid_mod.uuid4())
    uuids = [str(uuid_mod.uuid4()) for _ in range(n)]
    sets = [uuids[i:i + set_size] for i in range(0, n, set_size)]
    fmts = []
    for i, d in enumerate(disks):
        fmt = FormatInfo(deployment_id=deployment_id, sets=sets, this=uuids[i])
        d.write_format(fmt.to_json())
        fmts.append(fmt)
    return fmts


def load_and_order(disks: Sequence, set_size: int) -> tuple[list, FormatInfo]:
    """Boot an existing/partial layout: quorum-verify and order drives.

    Returns (ordered_disks, reference_format) where ordered_disks[i] is
    the drive whose UUID occupies position i of the flattened sets
    layout (None for positions whose drive is missing/offline). Fresh
    (formatless) drives are healed into missing positions with a new
    format.json carrying the expected UUID (reference: formatErasureFixV3
    / initFormatErasure healing). Drives whose format disagrees with the
    quorum layout are refused (left out as None).

    Raises FormatError when no quorum layout exists AND some drive has a
    format (a half-wiped cluster must not be silently re-initialized) —
    callers fall back to init_formats only when every drive is fresh.
    """
    read: list[Optional[FormatInfo]] = []
    for d in disks:
        try:
            raw = d.read_format()
            read.append(FormatInfo.from_json(raw) if raw else None)
        except Exception:  # noqa: BLE001 - corrupt/unreachable drive
            # (incl. remote StorageError): treated as absent for quorum
            # purposes, never crashes the whole boot.
            read.append(None)

    if all(f is None for f in read):
        raise FormatError("all drives are fresh (no format.json)")

    # Quorum on (deployment id, layout).
    votes: dict[tuple, int] = {}
    for f in read:
        if f is not None:
            key = (f.deployment_id, tuple(tuple(s) for s in f.sets))
            votes[key] = votes.get(key, 0) + 1
    (dep_id, layout), count = max(votes.items(), key=lambda kv: kv[1])
    if count < len(disks) // 2 + 1:
        raise FormatError(
            f"no format quorum: best layout has {count}/{len(disks)} votes")
    flat = [u for s in layout for u in s]
    if len(flat) != len(disks):
        raise FormatError(
            f"layout describes {len(flat)} drives, {len(disks)} given")
    if any(len(s) != set_size for s in layout):
        raise FormatError("layout set size disagrees with requested topology")

    ref = FormatInfo(deployment_id=dep_id,
                     sets=[list(s) for s in layout], this="")
    by_uuid = {}
    fresh = []
    for d, f in zip(disks, read):
        if f is None:
            fresh.append(d)
        elif (f.deployment_id, tuple(tuple(s) for s in f.sets)) == (dep_id, layout):
            by_uuid[f.this] = d
        # else: foreign/odd-format drive — refused, never written to.

    ordered: list = []
    for pos, u in enumerate(flat):
        d = by_uuid.get(u)
        if d is None and fresh:
            # Heal a fresh drive into this missing position.
            d = fresh.pop(0)
            fmt = FormatInfo(deployment_id=dep_id,
                             sets=[list(s) for s in layout], this=u)
            try:
                d.write_format(fmt.to_json())
            except Exception:  # noqa: BLE001 - unreachable/readonly drive
                d = None
            if d is not None:
                # A fresh drive adopting a previously-formatted slot is
                # a REPLACED drive: every object committed before the
                # swap is missing from it. Mark it healing so the drive
                # lifecycle manager (object/drive_heal) runs — and, via
                # the persisted tracker, RESUMES — a bulk heal; reads
                # meanwhile reconstruct around the hole and writes land
                # on it immediately.
                _mark_fresh_healing(d, pos, set_size)
        ordered.append(d)
    return ordered, ref


def _mark_fresh_healing(d, pos: int, set_size: int) -> None:
    """Write the healing marker for a freshly-adopted drive (boot-time
    analogue of the reference's initHealingTracker on a fresh disk,
    cmd/background-newdisks-heal-ops.go). Indices are the pool-local
    (row, column); the lifecycle manager re-stamps them when it adopts
    the tracker. Best effort: a marker that cannot be written only
    costs the bulk heal its restart resume."""
    try:
        from minio_tpu.object.drive_heal import mark_healing
        mark_healing(d, pos // set_size, pos % set_size,
                     getattr(d, "endpoint", ""))
    except Exception:  # noqa: BLE001 - marker is an optimization
        pass


def _safe_read(d) -> Optional[dict]:
    try:
        return d.read_format()
    except Exception:  # noqa: BLE001 - corrupt/unreachable == absent
        return None


def boot(disks: Sequence, set_size: int,
         deployment_id: Optional[str] = None) -> tuple[list, FormatInfo]:
    """init_formats on a fully-fresh layout, load_and_order otherwise."""
    if all(_safe_read(d) is None for d in disks):
        fmts = init_formats(disks, set_size, deployment_id)
        return list(disks), FormatInfo(
            deployment_id=fmts[0].deployment_id, sets=fmts[0].sets, this="")
    return load_and_order(disks, set_size)
