"""Cluster topology: ellipses expansion, set sizing, format.json identity."""
