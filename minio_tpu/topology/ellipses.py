"""Ellipses drive-spec expansion and erasure-set sizing.

The analogue of the reference's endpoint ellipses parsing
(cmd/endpoint-ellipses.go:48 and internal/config/... `{1...64}` syntax):
`/data/d{1...16}` expands to 16 drive paths, and the total drive count
is split into equal erasure sets of 2-16 drives (GCD-style sizing,
reference setSizes). Each CLI argument group forms one server pool.
"""

from __future__ import annotations

import re
import urllib.parse
from dataclasses import dataclass
from typing import Optional

_ELLIPSES = re.compile(r"\{(\d+)\.\.\.(\d+)\}")

SET_SIZES = tuple(range(2, 17))   # valid erasure set sizes (reference)


@dataclass(frozen=True)
class Endpoint:
    """One drive endpoint: a plain local path, or http://host:port/path
    naming the node that owns the drive (reference: cmd/endpoint.go)."""
    host: Optional[str]       # None == plain local path
    port: int
    path: str

    @property
    def is_url(self) -> bool:
        return self.host is not None

    def __str__(self) -> str:
        if self.host is None:
            return self.path
        return f"http://{self.host}:{self.port}{self.path}"


def parse_endpoint(spec: str) -> Endpoint:
    if spec.startswith("http://") or spec.startswith("https://"):
        u = urllib.parse.urlsplit(spec)
        if not u.hostname or not u.port or not u.path:
            raise ValueError(f"endpoint {spec!r} needs host, port and path")
        return Endpoint(host=u.hostname, port=u.port, path=u.path)
    return Endpoint(host=None, port=0, path=spec)


def has_ellipses(spec: str) -> bool:
    return bool(_ELLIPSES.search(spec))


def expand(spec: str) -> list[str]:
    """Expand every `{a...b}` range in the spec (cartesian, left-first).

    Numbers keep their zero-padding: `d{01...04}` -> d01..d04.
    """
    m = _ELLIPSES.search(spec)
    if not m:
        return [spec]
    lo_s, hi_s = m.group(1), m.group(2)
    lo, hi = int(lo_s), int(hi_s)
    if hi < lo:
        raise ValueError(f"bad ellipses range {m.group(0)} in {spec!r}")
    width = len(lo_s) if lo_s.startswith("0") else 0
    out = []
    for i in range(lo, hi + 1):
        num = str(i).zfill(width) if width else str(i)
        out.extend(expand(spec[:m.start()] + num + spec[m.end():]))
    return out


def choose_set_size(count: int) -> int:
    """Largest valid set size (2-16) that divides the drive count
    (reference possibleSetCounts/commonSetDriveCount shape). A single
    drive is the degenerate 1-drive single set."""
    if count == 1:
        return 1
    for size in sorted(SET_SIZES, reverse=True):
        if count % size == 0:
            return size
    raise ValueError(
        f"cannot split {count} drives into sets of 2-16; "
        f"use a drive count divisible by a number in 2..16")


def split_sets(drives: list[str], set_size: int | None = None) -> list[list[str]]:
    size = set_size or choose_set_size(len(drives))
    return [drives[i:i + size] for i in range(0, len(drives), size)]


def parse_pools(args: list[str]) -> list[list[str]]:
    """CLI drive args -> pools of drive paths.

    Mirrors the reference server CLI: every ellipses argument is its own
    pool; all plain (non-ellipses) arguments together form one pool.

    Extension over the reference: a COMMA-SEPARATED argument forms its
    own pool of exactly those endpoints. Ellipses expansion is cartesian
    and left-first, so a multi-node pool whose port and drive number
    must advance together (`http://h:{9000...9003}/d{0...1}`) cannot be
    written as one ellipses pattern — the comma form spells such pools
    out explicitly: `http://h:9000/d0,http://h:9001/d0`.
    """
    pools: list[list[str]] = []
    plain: list[str] = []
    for a in args:
        if "," in a:
            eps = [e for e in (s.strip() for s in a.split(",")) if e]
            if not eps:
                raise ValueError(f"empty pool spec {a!r}")
            pool: list[str] = []
            for e in eps:
                pool.extend(expand(e) if has_ellipses(e) else [e])
            pools.append(pool)
        elif has_ellipses(a):
            pools.append(expand(a))
        else:
            plain.append(a)
    if plain:
        pools.append(plain)
    return pools
