"""OpenID Connect web-identity validation for federated STS.

The analogue of the reference's identity_openid provider
(cmd/sts-handlers.go AssumeRoleWithWebIdentity +
internal/config/identity/openid): an external IdP issues a signed JWT;
the STS endpoint validates it against the provider's JWKS and maps a
configured claim to IAM policy names, minting temporary credentials
with no pre-existing user record.

Configured through the persisted config subsystem (s3/config.py keys):
  identity_openid_jwks_url    URL serving a JWKS document
  identity_openid_jwks        inline JWKS JSON (alternative to the URL)
  identity_openid_client_id   required `aud` value ("" = not checked)
  identity_openid_claim_name  claim carrying policy name(s); default
                              "policy" (the reference's default)
  identity_openid_issuer      required `iss` value ("" = not checked)

Only RS256 is implemented (the overwhelmingly common IdP default; the
reference's JWKS path centers on RSA too). Verification uses the
`cryptography` primitives already shipped for SSE — no JWT dependency.
"""

from __future__ import annotations

import base64
import json
import time
import urllib.request
from typing import Optional

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding, rsa
except ImportError:  # optional dep: OIDC validation needs the wheel;
    InvalidSignature = hashes = padding = rsa = None  # gated at use

DEFAULT_CLAIM = "policy"
# JWKS responses are cached briefly: one fetch per token would hammer
# the IdP, but key rotation must still take effect promptly.
_JWKS_TTL_S = 300.0


class OIDCError(Exception):
    pass


def _b64url(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    try:
        return base64.urlsafe_b64decode(data + pad)
    except (ValueError, TypeError):
        raise OIDCError("malformed base64url segment") from None


def _uint(b: bytes) -> int:
    return int.from_bytes(b, "big")


class OpenIDValidator:
    """Validates RS256 JWTs against a JWKS and extracts the policy
    claim."""

    def __init__(self, jwks_url: str = "", jwks_inline: str = "",
                 client_id: str = "", claim_name: str = DEFAULT_CLAIM,
                 issuer: str = ""):
        if rsa is None:
            raise OIDCError(
                "the 'cryptography' package is not installed; "
                "OIDC token validation is unavailable")
        if not jwks_url and not jwks_inline:
            raise OIDCError("no JWKS source configured")
        self.jwks_url = jwks_url
        self.jwks_inline = jwks_inline
        self.client_id = client_id
        self.claim_name = claim_name or DEFAULT_CLAIM
        self.issuer = issuer
        self._keys: dict[str, rsa.RSAPublicKey] = {}
        self._fetched = 0.0

    @classmethod
    def from_config(cls, cfg: dict) -> Optional["OpenIDValidator"]:
        """None when the config carries no OIDC provider."""
        url = cfg.get("identity_openid_jwks_url", "")
        inline = cfg.get("identity_openid_jwks", "")
        if not url and not inline:
            return None
        return cls(jwks_url=url, jwks_inline=inline,
                   client_id=cfg.get("identity_openid_client_id", ""),
                   claim_name=cfg.get("identity_openid_claim_name",
                                      DEFAULT_CLAIM),
                   issuer=cfg.get("identity_openid_issuer", ""))

    # -- JWKS -----------------------------------------------------------

    # Floor between FORCED refetches (unknown-kid path): without it an
    # anonymous attacker spraying random kids turns every STS request
    # into an outbound JWKS fetch.
    _FORCE_MIN_S = 60.0

    def _load_keys(self, force: bool = False) -> None:
        now = time.monotonic()
        if self._keys and not force and now - self._fetched < _JWKS_TTL_S:
            return
        if force and self._keys and \
                now - self._fetched < self._FORCE_MIN_S:
            return
        if self.jwks_inline:
            try:
                doc = json.loads(self.jwks_inline)
            except ValueError:
                raise OIDCError("inline JWKS is not valid JSON") from None
        else:
            try:
                with urllib.request.urlopen(self.jwks_url,
                                            timeout=10) as r:
                    doc = json.loads(r.read())
            except Exception as e:  # noqa: BLE001 - network/parse
                if self._keys:
                    return            # keep serving the cached set
                raise OIDCError(f"JWKS fetch failed: {e}") from None
        keys = {}
        for jwk in doc.get("keys", []):
            if jwk.get("kty") != "RSA" or \
                    jwk.get("alg", "RS256") != "RS256":
                continue
            try:
                pub = rsa.RSAPublicNumbers(
                    _uint(_b64url(jwk["e"])),
                    _uint(_b64url(jwk["n"]))).public_key()
            except (KeyError, ValueError):
                continue
            keys[jwk.get("kid", "")] = pub
        if not keys:
            raise OIDCError("JWKS carries no usable RS256 keys")
        self._keys = keys
        self._fetched = now

    # -- validation -----------------------------------------------------

    def validate(self, token: str) -> dict:
        """Verify signature + standard claims; returns the payload."""
        parts = token.split(".")
        if len(parts) != 3:
            raise OIDCError("not a JWS compact token")
        try:
            header = json.loads(_b64url(parts[0]))
            payload = json.loads(_b64url(parts[1]))
        except ValueError:
            raise OIDCError("malformed token JSON") from None
        if header.get("alg") != "RS256":
            raise OIDCError(f"unsupported alg {header.get('alg')!r}")
        self._load_keys()
        kid = header.get("kid", "")
        key = self._keys.get(kid)
        if key is None:
            # Unknown kid: the IdP may have rotated; refetch once.
            self._load_keys(force=True)
            key = self._keys.get(kid)
            if key is None and len(self._keys) == 1 and not kid:
                key = next(iter(self._keys.values()))
            if key is None:
                raise OIDCError(f"no JWKS key for kid {kid!r}")
        signed = f"{parts[0]}.{parts[1]}".encode()
        try:
            key.verify(_b64url(parts[2]), signed, padding.PKCS1v15(),
                       hashes.SHA256())
        except InvalidSignature:
            raise OIDCError("token signature invalid") from None
        now = time.time()
        if "exp" not in payload or now >= float(payload["exp"]):
            raise OIDCError("token expired")
        if "nbf" in payload and now < float(payload["nbf"]):
            raise OIDCError("token not yet valid")
        if self.issuer and payload.get("iss") != self.issuer:
            raise OIDCError("issuer mismatch")
        if self.client_id:
            aud = payload.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self.client_id not in auds:
                raise OIDCError("audience mismatch")
        return payload

    def policies_from(self, payload: dict) -> list[str]:
        """Policy names the configured claim maps this identity to
        (reference: claim_name -> policy mapping, empty = rejected so
        an unmapped identity gets NOTHING)."""
        raw = payload.get(self.claim_name)
        if raw is None:
            raise OIDCError(f"token carries no {self.claim_name!r} claim")
        if isinstance(raw, str):
            names = [n.strip() for n in raw.split(",") if n.strip()]
        elif isinstance(raw, list):
            names = [str(n) for n in raw if str(n)]
        else:
            raise OIDCError("policy claim must be a string or list")
        if not names:
            raise OIDCError("policy claim is empty")
        return names
