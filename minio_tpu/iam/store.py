"""IAM store: users, service accounts, named policies, persistence.

The runtime registry behind credential resolution and per-request
authorization (reference: cmd/iam-store.go). State is one JSON document
quorum-replicated across every drive of the first pool under the system
volume (`config/iam/iam.json`), mirroring how the reference keeps IAM
objects under .minio.sys/config/iam/ with quorum writes; a short TTL
cache keeps request-path lookups off the drives.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from minio_tpu.iam.policy import (Policy, PolicyError, canned_policies,
                                  compile_policy)

IAM_PATH = "config/iam/iam.json"
SYS_VOL = ".mtpu.sys"


class IAMError(Exception):
    pass


class IAMSys:
    """Users + service accounts + policies with quorum persistence.

    `sets`: the erasure sets whose drives replicate the IAM document
    (the first pool's sets, like bucket metadata). root credentials are
    implicit and NOT stored — root always passes authorization
    (reference: cmd/iam.go's owner short-circuit)."""

    _TTL = 2.0

    def __init__(self, sets, root_access: str, root_secret: str):
        self._sets = list(sets)
        self.root_access = root_access
        self.root_secret = root_secret
        self._mu = threading.RLock()
        # groups: name -> [member access keys]; group policy attachments
        # share the user_policies map (the reference keeps one mapped-
        # policy space for users and groups too, cmd/iam-store.go).
        # sts: temporary credentials from AssumeRole — expiring keys
        # whose permissions are the parent's, intersected with an
        # optional session policy (cmd/sts-handlers.go:61).
        self._state = {"users": {}, "service_accounts": {},
                       "policies": {}, "user_policies": {},
                       "groups": {}, "sts": {}}
        self._loaded_at = 0.0
        # Peer fan-out hook: called after every successful _save so the
        # other nodes drop their IAM caches immediately (reference:
        # cmd/iam.go notifies peers on every IAM object write).
        self.on_change = None
        # Fired only when MIRRORED durable state (users/policies/...)
        # changes — site replication hangs here so STS mints don't
        # push the whole document to every peer site.
        self.on_mirror_change = None
        self._load()

    # -- persistence ----------------------------------------------------

    def _disks(self):
        return [d for es in self._sets for d in es.disks]

    def _load(self) -> None:
        votes: dict[bytes, int] = {}
        for d in self._disks():
            try:
                blob = d.read_all(SYS_VOL, IAM_PATH)
                votes[blob] = votes.get(blob, 0) + 1
            except Exception:  # noqa: BLE001 - absent / offline
                continue
        if votes:
            blob = max(votes.items(), key=lambda kv: kv[1])[0]
            try:
                loaded = json.loads(blob)
            except ValueError:
                loaded = None
            if isinstance(loaded, dict):
                # Older persisted documents predate groups/sts/rev.
                loaded.setdefault("groups", {})
                loaded.setdefault("sts", {})
                loaded.setdefault("rev", 0)
                self._state = loaded
        self._loaded_at = time.monotonic()

    def _save(self, bump: bool = True) -> None:
        if bump:
            # Monotonic document revision: site replication's IAM
            # mirror gates on it so a stale (e.g. bootstrap-empty) peer
            # push can never clobber newer local state.
            self._state["rev"] = self._state.get("rev", 0) + 1
        blob = json.dumps(self._state, sort_keys=True).encode()
        ok = 0
        for d in self._disks():
            try:
                d.write_all(SYS_VOL, IAM_PATH, blob)
                ok += 1
            except Exception:  # noqa: BLE001 - offline drive
                continue
        if ok < len(self._disks()) // 2 + 1:
            raise IAMError("could not persist IAM state to a drive quorum")

    def _fire_change(self, mirrored: bool = True) -> None:
        """Run the peer fan-out AFTER the mutator released _mu: the
        broadcast can block up to its timeout on a partitioned peer,
        and holding the lock through it would stall every credential
        lookup on this node (and deadlock-by-timeout against a peer
        mutating concurrently). `mirrored` additionally fires the site
        replication hook — STS-only writes pass False so temp-credential
        mints don't push the IAM document across sites."""
        for cb in ((self.on_change,)
                   + ((self.on_mirror_change,) if mirrored else ())):
            if cb is not None:
                try:
                    cb()
                except Exception:  # noqa: BLE001 - must not fail writes
                    pass

    def _refresh(self) -> None:
        if time.monotonic() - self._loaded_at > self._TTL:
            self._load()

    def invalidate(self) -> None:
        """Force the next lookup to re-read from the drives (called by
        the peer control plane when another node changed IAM state —
        a revoked credential must stop working NOW, not after the TTL)."""
        with self._mu:
            self._loaded_at = 0.0

    # -- credential resolution ------------------------------------------

    def secret_for(self, access_key: str) -> Optional[str]:
        """Secret key for signature verification; None = unknown key.
        Expired STS credentials resolve to None — an expired temporary
        key fails auth exactly like an unknown one."""
        if access_key == self.root_access:
            return self.root_secret
        with self._mu:
            self._refresh()
            u = self._state["users"].get(access_key)
            if u is not None and u.get("status", "enabled") == "enabled":
                return u["secret"]
            sa = self._state["service_accounts"].get(access_key)
            if sa is not None and sa.get("status", "enabled") == "enabled":
                return sa["secret"]
            st = self._state["sts"].get(access_key)
            if st is not None and time.time_ns() < st.get("expiry_ns", 0) \
                    and self._sts_live(st):
                return st["secret"]
        return None

    def _parent_live(self, parent: str) -> bool:
        """Disabling or deleting a user must revoke its outstanding
        STS credentials immediately, not at their expiry (call under
        _mu)."""
        if parent == self.root_access:
            return True
        u = self._state["users"].get(parent)
        return u is not None and u.get("status", "enabled") == "enabled"

    def _sts_live(self, st: dict) -> bool:
        """Liveness beyond expiry: parented STS keys die with their
        parent; web-identity keys (no local parent — the IdP was the
        identity) live by expiry alone."""
        if st.get("web_identity"):
            return True
        return self._parent_live(st.get("parent", ""))

    def session_token_for(self, access_key: str) -> Optional[str]:
        """The session token an STS credential must present on every
        request (None for permanent credentials)."""
        with self._mu:
            self._refresh()
            st = self._state["sts"].get(access_key)
            if st is not None and time.time_ns() < st.get("expiry_ns", 0) \
                    and self._sts_live(st):
                return st.get("token", "")
        return None

    def is_root(self, access_key: str) -> bool:
        return access_key == self.root_access

    # -- authorization ---------------------------------------------------

    def _compile_names(self, names) -> list[Policy]:
        docs = []
        canned = canned_policies()
        for name in names:
            stored = self._state["policies"].get(name)
            if stored is not None:
                try:
                    docs.append(compile_policy(stored))
                    continue
                except (PolicyError, TypeError):
                    continue
            if name in canned:
                docs.append(canned[name])
        return docs

    def policies_for(self, access_key: str) -> list[Policy]:
        """The identity's own policies: directly attached ones plus
        those of every group it belongs to (reference: PolicyDBGet
        merges user and group mappings, cmd/iam-store.go). STS keys
        resolve to their parent's policies; the session policy is
        intersected separately in decide()."""
        with self._mu:
            self._refresh()
            sa = self._state["service_accounts"].get(access_key)
            if sa is not None:
                embedded = sa.get("policy")
                if embedded:
                    try:
                        return [compile_policy(embedded)]
                    except (PolicyError, TypeError):
                        return []
                # No embedded policy: inherit the parent user's.
                access_key = sa.get("parent", access_key)
            st = self._state["sts"].get(access_key)
            if st is not None:
                if time.time_ns() >= st.get("expiry_ns", 0) or \
                        not self._sts_live(st):
                    return []
                if st.get("web_identity"):
                    # Web-identity keys carry their own policy-name
                    # mapping (the OIDC claim), no local parent.
                    return self._compile_names(
                        list(st.get("policies") or []))
                access_key = st.get("parent", access_key)
                if access_key == self.root_access:
                    # Root-parented STS keys inherit everything; the
                    # session policy (if any) still bounds them.
                    return [canned_policies()["consoleAdmin"]]
            names = list(self._state["user_policies"].get(access_key, []))
            for gname, members in self._state["groups"].items():
                if access_key in (members or []):
                    names.extend(self._state["user_policies"].get(gname, []))
            return self._compile_names(names)

    def _session_policy(self, access_key: str) -> Optional[Policy]:
        with self._mu:
            self._refresh()
            st = self._state["sts"].get(access_key)
            if st is None or not st.get("policy"):
                return None
            try:
                return compile_policy(st["policy"])
            except (PolicyError, TypeError):
                # An unevaluable session policy grants NOTHING (the
                # intersection direction must fail closed).
                from minio_tpu.iam.policy import Policy as _P
                return _P(statements=[])

    def is_allowed(self, access_key: str, action: str, resource: str,
                   context: Optional[dict] = None) -> bool:
        return self.decide(access_key, action, resource,
                           context) == "Allow"

    def decide(self, access_key: str, action: str, resource: str,
               context: Optional[dict] = None) -> Optional[str]:
        """Tri-state identity decision ("Allow"/"Deny"/None) so callers
        can merge with bucket policy (root short-circuits to Allow).
        STS session policies INTERSECT: the request must be allowed by
        both the parent's policies and the session policy (reference:
        cmd/iam.go IsAllowedSTS)."""
        if self.is_root(access_key):
            return "Allow"
        from minio_tpu.iam.policy import decide
        base = decide(self.policies_for(access_key), action, resource,
                      context)
        sess = self._session_policy(access_key)
        if sess is not None:
            sp = decide([sess], action, resource, context)
            if sp == "Deny" or base == "Deny":
                return "Deny"
            if base == "Allow" and sp == "Allow":
                return "Allow"
            return None
        return base

    # -- management (root-only; enforcement is the admin handler's job) --

    def add_user(self, access_key: str, secret_key: str) -> None:
        if not access_key or access_key == self.root_access:
            raise IAMError("invalid access key")
        if len(secret_key) < 8:
            raise IAMError("secret key too short")
        with self._mu:
            if access_key in self._state["groups"]:
                # users and groups share the policy-attachment
                # namespace; a collision would make attach/remove
                # ambiguous.
                raise IAMError("a group with that name exists")
            self._state["users"][access_key] = {
                "secret": secret_key, "status": "enabled"}
            self._save()
        self._fire_change()

    def remove_user(self, access_key: str) -> None:
        with self._mu:
            if self._state["users"].pop(access_key, None) is None:
                raise IAMError("no such user")
            self._state["user_policies"].pop(access_key, None)
            # Orphan its service accounts too.
            for k in [k for k, sa in self._state["service_accounts"].items()
                      if sa.get("parent") == access_key]:
                self._state["service_accounts"].pop(k, None)
            # Its STS keys die with it, and its group memberships go —
            # a future user recreated under the same name must not
            # inherit this one's group grants.
            for k in [k for k, st in self._state["sts"].items()
                      if st.get("parent") == access_key]:
                self._state["sts"].pop(k, None)
            for g, members in self._state["groups"].items():
                if access_key in (members or []):
                    self._state["groups"][g] = \
                        [m for m in members if m != access_key]
            self._save()
        self._fire_change()

    def set_user_status(self, access_key: str, enabled: bool) -> None:
        with self._mu:
            u = self._state["users"].get(access_key)
            if u is None:
                raise IAMError("no such user")
            u["status"] = "enabled" if enabled else "disabled"
            self._save()
        self._fire_change()

    def list_users(self) -> dict:
        with self._mu:
            self._refresh()
            return {k: {"status": u.get("status", "enabled"),
                        "policies": self._state["user_policies"].get(k, [])}
                    for k, u in self._state["users"].items()}

    def add_service_account(self, parent: str, access_key: str,
                            secret_key: str,
                            policy: Optional[dict] = None) -> None:
        if parent != self.root_access and \
                parent not in self._state["users"]:
            raise IAMError("no such parent user")
        if policy is not None:
            Policy.from_json(policy)   # validate
        with self._mu:
            self._state["service_accounts"][access_key] = {
                "secret": secret_key, "parent": parent,
                "policy": policy, "status": "enabled"}
            self._save()
        self._fire_change()

    def set_policy(self, name: str, doc: dict) -> None:
        Policy.from_json(doc)   # validate before storing
        with self._mu:
            self._state["policies"][name] = doc
            self._save()
        self._fire_change()

    def delete_policy(self, name: str) -> None:
        with self._mu:
            if self._state["policies"].pop(name, None) is None:
                raise IAMError("no such policy")
            self._save()
        self._fire_change()

    def list_policies(self) -> dict:
        with self._mu:
            self._refresh()
            out = {name: doc for name, doc in self._state["policies"].items()}
            for name, p in canned_policies().items():
                out.setdefault(name, p.to_json())
            return out

    def attach_policy(self, access_key: str, names: list[str]) -> None:
        """Attach named policies to a user OR a group."""
        with self._mu:
            if access_key not in self._state["users"] and \
                    access_key not in self._state["groups"]:
                raise IAMError("no such user or group")
            known = set(self._state["policies"]) | set(canned_policies())
            for n in names:
                if n not in known:
                    raise IAMError(f"no such policy {n!r}")
            self._state["user_policies"][access_key] = list(names)
            self._save()
        self._fire_change()

    # -- groups ----------------------------------------------------------

    def update_group_members(self, group: str, members: list[str],
                             remove: bool = False) -> None:
        """Add (or remove) members; an unknown group is created on add
        (reference: cmd/iam.go AddUsersToGroup semantics). Members must
        be existing users."""
        if not group:
            raise IAMError("invalid group name")
        with self._mu:
            if group in self._state["users"]:
                raise IAMError("a user with that name exists")
            if remove and group not in self._state["groups"]:
                raise IAMError("no such group")
            for m in members:
                if not remove and m not in self._state["users"]:
                    raise IAMError(f"no such user {m!r}")
            cur = list(self._state["groups"].get(group, []))
            if remove:
                cur = [m for m in cur if m not in members]
            else:
                cur.extend(m for m in members if m not in cur)
            self._state["groups"][group] = cur
            self._save()
        self._fire_change()

    def remove_group(self, group: str) -> None:
        with self._mu:
            if self._state["groups"].pop(group, None) is None:
                raise IAMError("no such group")
            self._state["user_policies"].pop(group, None)
            self._save()
        self._fire_change()

    def list_groups(self) -> dict:
        with self._mu:
            self._refresh()
            return {g: {"members": list(ms or []),
                        "policies": self._state["user_policies"].get(g, [])}
                    for g, ms in self._state["groups"].items()}

    # -- STS --------------------------------------------------------------

    # AWS bounds: 15 minutes to 12 hours (cmd/sts-handlers.go).
    STS_MIN_S, STS_MAX_S, STS_DEFAULT_S = 900, 12 * 3600, 3600

    def assume_role(self, parent: str, duration_s: Optional[int] = None,
                    session_policy: Optional[dict] = None) -> dict:
        """Mint temporary credentials for an authenticated identity
        (reference: cmd/sts-handlers.go:61 AssumeRole). The temp key's
        permissions are the parent's, intersected with the optional
        session policy; it expires hard at `duration_s`."""
        import base64
        import os as _os
        if duration_s is None:
            duration_s = self.STS_DEFAULT_S
        if not self.STS_MIN_S <= duration_s <= self.STS_MAX_S:
            raise IAMError(f"DurationSeconds must be in "
                           f"[{self.STS_MIN_S}, {self.STS_MAX_S}]")
        if session_policy is not None:
            Policy.from_json(session_policy)   # validate before storing
        ak = "STS" + base64.b32encode(_os.urandom(10)).decode().rstrip("=")
        sk = base64.b64encode(_os.urandom(30)).decode()
        token = base64.b64encode(_os.urandom(48)).decode()
        expiry_ns = time.time_ns() + duration_s * 10**9
        with self._mu:
            # Parent check under the lock on FRESH state: a user revoked
            # on a peer moments ago must not mint 12-hour credentials
            # from this node's stale cache.
            self._refresh()
            if parent != self.root_access and \
                    not self._parent_live(parent):
                # Service accounts and STS keys cannot chain AssumeRole
                # (the reference rejects non-user parents too).
                raise IAMError("AssumeRole requires an active user "
                               "identity")
            self._prune_expired_sts()
            self._state["sts"][ak] = {
                "secret": sk, "parent": parent, "token": token,
                "expiry_ns": expiry_ns, "policy": session_policy}
            # STS records are NOT mirrored: no rev bump (a burst of
            # mints must not outrank a peer's real identity edits in
            # the import gate) and no site push.
            self._save(bump=False)
        self._fire_change(mirrored=False)
        return {"access_key": ak, "secret_key": sk, "session_token": token,
                "expiry_ns": expiry_ns}

    # -- site replication mirror ------------------------------------------

    _MIRROR_KEYS = ("users", "service_accounts", "policies",
                    "user_policies", "groups")

    def export_doc(self) -> dict:
        """The durable identity state site replication mirrors to peer
        clusters (reference: cmd/site-replication.go replicates IAM
        users/policies/service accounts). STS temp credentials stay
        local — they expire and their tokens bind to this cluster."""
        with self._mu:
            self._refresh()
            out = json.loads(json.dumps(
                {k: self._state.get(k, {}) for k in self._MIRROR_KEYS}))
            out["rev"] = self._state.get("rev", 0)
            return out

    def import_doc(self, doc: dict) -> None:
        """Receiving side of the IAM mirror: replace the durable
        sections wholesale, gated on the document REVISION — a stale
        push (a just-registered peer's near-empty bootstrap racing this
        site's fresh writes) must never clobber newer state; only a
        strictly newer document applies. Deliberately does NOT fire
        on_change — an applied mirror must never re-broadcast (site
        ping-pong); intra-cluster nodes pick the document up within
        the TTL."""
        incoming = int(doc.get("rev", 0))
        with self._mu:
            self._refresh()
            if incoming <= self._state.get("rev", 0):
                return
            for k in self._MIRROR_KEYS:
                v = doc.get(k)
                if isinstance(v, dict):
                    self._state[k] = v
            self._state["rev"] = incoming
            self._save(bump=False)

    def assume_role_web_identity(self, subject: str, policy_names: list,
                                 duration_s: Optional[int] = None,
                                 session_policy: Optional[dict] = None
                                 ) -> dict:
        """Mint temporary credentials for an OIDC-validated external
        identity (reference: cmd/sts-handlers.go:61-65
        AssumeRoleWithWebIdentity): no local user exists — the record
        carries the claim-mapped policy names directly, intersected
        with the optional session policy like AssumeRole."""
        import base64
        import os as _os
        if duration_s is None:
            duration_s = self.STS_DEFAULT_S
        if not self.STS_MIN_S <= duration_s <= self.STS_MAX_S:
            raise IAMError(f"DurationSeconds must be in "
                           f"[{self.STS_MIN_S}, {self.STS_MAX_S}]")
        if not policy_names:
            raise IAMError("web identity maps to no policies")
        if session_policy is not None:
            Policy.from_json(session_policy)   # validate before storing
        ak = "STS" + base64.b32encode(_os.urandom(10)).decode().rstrip("=")
        sk = base64.b64encode(_os.urandom(30)).decode()
        token = base64.b64encode(_os.urandom(48)).decode()
        expiry_ns = time.time_ns() + duration_s * 10**9
        with self._mu:
            self._refresh()
            self._prune_expired_sts()
            self._state["sts"][ak] = {
                "secret": sk, "parent": "", "token": token,
                "expiry_ns": expiry_ns, "policy": session_policy,
                "web_identity": True, "subject": subject,
                "policies": list(policy_names)}
            self._save(bump=False)
        self._fire_change(mirrored=False)
        return {"access_key": ak, "secret_key": sk, "session_token": token,
                "expiry_ns": expiry_ns}

    def _prune_expired_sts(self) -> None:
        """Drop long-expired temp credentials so the document cannot
        grow without bound (called under _mu before STS writes)."""
        now = time.time_ns()
        dead = [k for k, st in self._state["sts"].items()
                if now >= st.get("expiry_ns", 0)]
        for k in dead:
            self._state["sts"].pop(k, None)
