"""IAM store: users, service accounts, named policies, persistence.

The runtime registry behind credential resolution and per-request
authorization (reference: cmd/iam-store.go). State is one JSON document
quorum-replicated across every drive of the first pool under the system
volume (`config/iam/iam.json`), mirroring how the reference keeps IAM
objects under .minio.sys/config/iam/ with quorum writes; a short TTL
cache keeps request-path lookups off the drives.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from minio_tpu.iam.policy import (Policy, PolicyError, canned_policies,
                                  compile_policy)

IAM_PATH = "config/iam/iam.json"
SYS_VOL = ".mtpu.sys"


class IAMError(Exception):
    pass


class IAMSys:
    """Users + service accounts + policies with quorum persistence.

    `sets`: the erasure sets whose drives replicate the IAM document
    (the first pool's sets, like bucket metadata). root credentials are
    implicit and NOT stored — root always passes authorization
    (reference: cmd/iam.go's owner short-circuit)."""

    _TTL = 2.0

    def __init__(self, sets, root_access: str, root_secret: str):
        self._sets = list(sets)
        self.root_access = root_access
        self.root_secret = root_secret
        self._mu = threading.RLock()
        self._state = {"users": {}, "service_accounts": {},
                       "policies": {}, "user_policies": {}}
        self._loaded_at = 0.0
        # Peer fan-out hook: called after every successful _save so the
        # other nodes drop their IAM caches immediately (reference:
        # cmd/iam.go notifies peers on every IAM object write).
        self.on_change = None
        self._load()

    # -- persistence ----------------------------------------------------

    def _disks(self):
        return [d for es in self._sets for d in es.disks]

    def _load(self) -> None:
        votes: dict[bytes, int] = {}
        for d in self._disks():
            try:
                blob = d.read_all(SYS_VOL, IAM_PATH)
                votes[blob] = votes.get(blob, 0) + 1
            except Exception:  # noqa: BLE001 - absent / offline
                continue
        if votes:
            blob = max(votes.items(), key=lambda kv: kv[1])[0]
            try:
                self._state = json.loads(blob)
            except ValueError:
                pass
        self._loaded_at = time.monotonic()

    def _save(self) -> None:
        blob = json.dumps(self._state, sort_keys=True).encode()
        ok = 0
        for d in self._disks():
            try:
                d.write_all(SYS_VOL, IAM_PATH, blob)
                ok += 1
            except Exception:  # noqa: BLE001 - offline drive
                continue
        if ok < len(self._disks()) // 2 + 1:
            raise IAMError("could not persist IAM state to a drive quorum")

    def _fire_change(self) -> None:
        """Run the peer fan-out AFTER the mutator released _mu: the
        broadcast can block up to its timeout on a partitioned peer,
        and holding the lock through it would stall every credential
        lookup on this node (and deadlock-by-timeout against a peer
        mutating concurrently)."""
        cb = self.on_change
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 - fan-out must not fail writes
                pass

    def _refresh(self) -> None:
        if time.monotonic() - self._loaded_at > self._TTL:
            self._load()

    def invalidate(self) -> None:
        """Force the next lookup to re-read from the drives (called by
        the peer control plane when another node changed IAM state —
        a revoked credential must stop working NOW, not after the TTL)."""
        with self._mu:
            self._loaded_at = 0.0

    # -- credential resolution ------------------------------------------

    def secret_for(self, access_key: str) -> Optional[str]:
        """Secret key for signature verification; None = unknown key."""
        if access_key == self.root_access:
            return self.root_secret
        with self._mu:
            self._refresh()
            u = self._state["users"].get(access_key)
            if u is not None and u.get("status", "enabled") == "enabled":
                return u["secret"]
            sa = self._state["service_accounts"].get(access_key)
            if sa is not None and sa.get("status", "enabled") == "enabled":
                return sa["secret"]
        return None

    def is_root(self, access_key: str) -> bool:
        return access_key == self.root_access

    # -- authorization ---------------------------------------------------

    def policies_for(self, access_key: str) -> list[Policy]:
        with self._mu:
            self._refresh()
            names: list[str] = []
            sa = self._state["service_accounts"].get(access_key)
            if sa is not None:
                embedded = sa.get("policy")
                if embedded:
                    try:
                        return [compile_policy(embedded)]
                    except (PolicyError, TypeError):
                        return []
                # No embedded policy: inherit the parent user's.
                access_key = sa.get("parent", access_key)
            names = list(self._state["user_policies"].get(access_key, []))
            docs = []
            canned = canned_policies()
            for name in names:
                stored = self._state["policies"].get(name)
                if stored is not None:
                    try:
                        docs.append(compile_policy(stored))
                        continue
                    except (PolicyError, TypeError):
                        continue
                if name in canned:
                    docs.append(canned[name])
            return docs

    def is_allowed(self, access_key: str, action: str, resource: str,
                   context: Optional[dict] = None) -> bool:
        if self.is_root(access_key):
            return True
        from minio_tpu.iam.policy import evaluate
        return evaluate(self.policies_for(access_key), action, resource,
                        context)

    def decide(self, access_key: str, action: str, resource: str,
               context: Optional[dict] = None) -> Optional[str]:
        """Tri-state identity decision ("Allow"/"Deny"/None) so callers
        can merge with bucket policy (root short-circuits to Allow)."""
        if self.is_root(access_key):
            return "Allow"
        from minio_tpu.iam.policy import decide
        return decide(self.policies_for(access_key), action, resource,
                      context)

    # -- management (root-only; enforcement is the admin handler's job) --

    def add_user(self, access_key: str, secret_key: str) -> None:
        if not access_key or access_key == self.root_access:
            raise IAMError("invalid access key")
        if len(secret_key) < 8:
            raise IAMError("secret key too short")
        with self._mu:
            self._state["users"][access_key] = {
                "secret": secret_key, "status": "enabled"}
            self._save()
        self._fire_change()

    def remove_user(self, access_key: str) -> None:
        with self._mu:
            if self._state["users"].pop(access_key, None) is None:
                raise IAMError("no such user")
            self._state["user_policies"].pop(access_key, None)
            # Orphan its service accounts too.
            for k in [k for k, sa in self._state["service_accounts"].items()
                      if sa.get("parent") == access_key]:
                self._state["service_accounts"].pop(k, None)
            self._save()
        self._fire_change()

    def set_user_status(self, access_key: str, enabled: bool) -> None:
        with self._mu:
            u = self._state["users"].get(access_key)
            if u is None:
                raise IAMError("no such user")
            u["status"] = "enabled" if enabled else "disabled"
            self._save()
        self._fire_change()

    def list_users(self) -> dict:
        with self._mu:
            self._refresh()
            return {k: {"status": u.get("status", "enabled"),
                        "policies": self._state["user_policies"].get(k, [])}
                    for k, u in self._state["users"].items()}

    def add_service_account(self, parent: str, access_key: str,
                            secret_key: str,
                            policy: Optional[dict] = None) -> None:
        if parent != self.root_access and \
                parent not in self._state["users"]:
            raise IAMError("no such parent user")
        if policy is not None:
            Policy.from_json(policy)   # validate
        with self._mu:
            self._state["service_accounts"][access_key] = {
                "secret": secret_key, "parent": parent,
                "policy": policy, "status": "enabled"}
            self._save()
        self._fire_change()

    def set_policy(self, name: str, doc: dict) -> None:
        Policy.from_json(doc)   # validate before storing
        with self._mu:
            self._state["policies"][name] = doc
            self._save()
        self._fire_change()

    def delete_policy(self, name: str) -> None:
        with self._mu:
            if self._state["policies"].pop(name, None) is None:
                raise IAMError("no such policy")
            self._save()
        self._fire_change()

    def list_policies(self) -> dict:
        with self._mu:
            self._refresh()
            out = {name: doc for name, doc in self._state["policies"].items()}
            for name, p in canned_policies().items():
                out.setdefault(name, p.to_json())
            return out

    def attach_policy(self, access_key: str, names: list[str]) -> None:
        with self._mu:
            if access_key not in self._state["users"]:
                raise IAMError("no such user")
            known = set(self._state["policies"]) | set(canned_policies())
            for n in names:
                if n not in known:
                    raise IAMError(f"no such policy {n!r}")
            self._state["user_policies"][access_key] = list(names)
            self._save()
        self._fire_change()
