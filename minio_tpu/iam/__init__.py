"""Identity and access management: users, service accounts, policies.

The framework's analogue of the reference's IAM subsystem (cmd/iam.go,
cmd/iam-store.go, internal/policy): credentials resolve to policy
documents, every S3 request maps to an (action, resource) pair, and the
policy engine decides allow/deny with explicit-deny-wins semantics.
"""

from minio_tpu.iam.policy import (Policy, PolicyError, Statement,
                                  canned_policies, evaluate)
from minio_tpu.iam.store import IAMSys, IAMError

__all__ = ["Policy", "PolicyError", "Statement", "canned_policies",
           "evaluate", "IAMSys", "IAMError"]
