"""Policy documents and their evaluation.

Mirrors the reference's policy semantics (internal/policy/policy.go):
a document is a list of statements, each Allow or Deny over wildcarded
Actions and Resources; an explicit Deny always wins, absence of an
Allow denies. Wildcards are AWS-style (`*` any run, `?` one char).

Statements may carry Condition blocks (internal/policy/condition/) —
operator -> {key -> values} — evaluated against a per-request context
(aws:SourceIp, s3:prefix, ...), and, for bucket policies, a Principal
(internal/policy/statement.go) matched against the requesting access
key, with "*" covering anonymous requests.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import functools
import ipaddress
import json
import re
from typing import Optional, Sequence

ARN_PREFIX = "arn:aws:s3:::"

# Principal value meaning "everyone, including anonymous".
ANY_PRINCIPAL = "*"


class PolicyError(Exception):
    pass


def _compile(pattern: str) -> re.Pattern:
    return re.compile(fnmatch.translate(pattern))


def _str_values(v) -> list[str]:
    """Condition values normalized to strings (JSON allows bool/number)."""
    vals = v if isinstance(v, list) else [v]
    out = []
    for x in vals:
        if isinstance(x, bool):
            out.append("true" if x else "false")
        else:
            out.append(str(x))
    return out


def _cond_op(op: str, ctx_vals: list[str], want: list[str]) -> bool:
    """One condition operator over the request's values for a key.

    `ctx_vals` empty means the key is absent from the request: positive
    operators fail, negated ones pass (AWS "if the key is not present,
    the condition is not met / is met" semantics; the reference encodes
    the same in each condition function's evaluate())."""
    negated = op.startswith("StringNot") or op == "NotIpAddress" or \
        op.startswith("NumericNot")
    if not ctx_vals:
        return negated
    if op in ("StringEquals", "StringNotEquals"):
        hit = any(c in want for c in ctx_vals)
    elif op in ("StringEqualsIgnoreCase", "StringNotEqualsIgnoreCase"):
        wl = [w.lower() for w in want]
        hit = any(c.lower() in wl for c in ctx_vals)
    elif op in ("StringLike", "StringNotLike"):
        pats = [_compile(w) for w in want]
        hit = any(p.match(c) for p in pats for c in ctx_vals)
    elif op in ("IpAddress", "NotIpAddress"):
        nets = []
        for w in want:
            try:
                nets.append(ipaddress.ip_network(w, strict=False))
            except ValueError:
                continue
        def _in(c):
            try:
                a = ipaddress.ip_address(c)
            except ValueError:
                return False
            return any(a in n for n in nets)
        hit = any(_in(c) for c in ctx_vals)
    elif op == "Bool":
        hit = any(c.lower() == w.lower() for w in want for c in ctx_vals)
    elif op.startswith("Numeric"):
        try:
            cv = [float(c) for c in ctx_vals]
            wv = [float(w) for w in want]
        except ValueError:
            return False
        cmps = {"NumericEquals": lambda a, b: a == b,
                "NumericNotEquals": lambda a, b: a == b,  # negated below
                "NumericLessThan": lambda a, b: a < b,
                "NumericLessThanEquals": lambda a, b: a <= b,
                "NumericGreaterThan": lambda a, b: a > b,
                "NumericGreaterThanEquals": lambda a, b: a >= b}
        f = cmps.get(op)
        if f is None:
            return False
        hit = any(f(a, b) for a in cv for b in wv)
    else:
        # Unknown operator: validated away at parse time; reaching here
        # means an old stored doc — fail closed (see from_json).
        return False
    return not hit if negated else hit


_KNOWN_OPS = {"StringEquals", "StringNotEquals", "StringEqualsIgnoreCase",
              "StringNotEqualsIgnoreCase", "StringLike", "StringNotLike",
              "IpAddress", "NotIpAddress", "Bool", "NumericEquals",
              "NumericNotEquals", "NumericLessThan", "NumericLessThanEquals",
              "NumericGreaterThan", "NumericGreaterThanEquals"}


@dataclasses.dataclass
class Statement:
    effect: str                 # "Allow" | "Deny"
    actions: list
    resources: list
    # Condition: {operator: {key: [values]}}; empty = unconditional.
    conditions: dict = dataclasses.field(default_factory=dict)
    # Principal patterns (bucket policies); None = identity policy,
    # applies to whomever it is attached to.
    principals: Optional[list] = None
    _action_res: list = dataclasses.field(default_factory=list, repr=False)
    _resource_res: list = dataclasses.field(default_factory=list, repr=False)

    def __post_init__(self):
        if self.effect not in ("Allow", "Deny"):
            raise PolicyError(f"bad Effect {self.effect!r}")
        if not self.actions or not self.resources:
            raise PolicyError("statement needs Action and Resource")
        if not isinstance(self.conditions, dict):
            raise PolicyError("Condition must be an object")
        for op, kv in self.conditions.items():
            # ForAllValues:/ForAnyValue: qualifiers are accepted and
            # treated as their base operator (our context keys are
            # single-valued, where the two coincide).
            base = op.split(":", 1)[-1]
            if base not in _KNOWN_OPS:
                raise PolicyError(f"unsupported condition operator {op!r}")
            if not isinstance(kv, dict):
                raise PolicyError("condition operator needs {key: values}")
            # Values must be evaluable NOW: a CIDR or number that fails
            # to parse at request time would make the condition never
            # match, silently disarming any Deny it guards.
            for vals in kv.values():
                for v in _str_values(vals):
                    if base in ("IpAddress", "NotIpAddress"):
                        try:
                            ipaddress.ip_network(v, strict=False)
                        except ValueError:
                            raise PolicyError(
                                f"bad CIDR {v!r} in {op}") from None
                    elif base.startswith("Numeric"):
                        try:
                            float(v)
                        except ValueError:
                            raise PolicyError(
                                f"bad number {v!r} in {op}") from None
        self._action_res = [_compile(a) for a in self.actions]
        self._resource_res = [_compile(r[len(ARN_PREFIX):]
                                       if r.startswith(ARN_PREFIX) else r)
                              for r in self.resources]

    def conditions_met(self, context: Optional[dict]) -> bool:
        if not self.conditions:
            return True
        ctx = {k.lower(): v for k, v in (context or {}).items()}
        for op, kv in self.conditions.items():
            base = op.split(":", 1)[-1]
            for ckey, want in kv.items():
                got = ctx.get(ckey.lower())
                ctx_vals = [] if got is None else _str_values(got)
                if not _cond_op(base, ctx_vals, _str_values(want)):
                    return False
        return True

    def principal_matches(self, access_key: Optional[str]) -> bool:
        """`access_key` None/"" = anonymous. Identity policies (no
        Principal) match whoever they are attached to; bucket-policy
        principals match "*" (everyone) or the key itself, accepting
        both bare access keys and user-ARN forms the reference stores
        (arn:aws:iam::...:user/<name>)."""
        if self.principals is None:
            return True
        ak = access_key or ""
        for p in self.principals:
            if p == ANY_PRINCIPAL:
                return True
            if not ak:
                continue
            if p == ak or p.rpartition("/")[2] == ak:
                return True
        return False

    def matches(self, action: str, resource: str,
                context: Optional[dict] = None,
                access_key: Optional[str] = None,
                require_principal: bool = False) -> bool:
        if require_principal and self.principals is None:
            # Bucket-policy evaluation: a statement without a Principal
            # is an identity-policy shape and must grant nobody there —
            # matching everyone would silently make the bucket public.
            return False
        return any(p.match(action) for p in self._action_res) and \
            any(p.match(resource) for p in self._resource_res) and \
            self.principal_matches(access_key) and \
            self.conditions_met(context)


@dataclasses.dataclass
class Policy:
    statements: list

    @classmethod
    def from_json(cls, doc: dict) -> "Policy":
        stmts = doc.get("Statement")
        if stmts is None:
            raise PolicyError("missing Statement")
        if isinstance(stmts, dict):
            stmts = [stmts]
        out = []
        for s in stmts:
            # Negated selectors are NOT supported: silently ignoring
            # NotPrincipal would turn "everyone except X" into
            # "everyone including X" — reject the document instead.
            for neg in ("NotPrincipal", "NotAction", "NotResource"):
                if neg in s:
                    raise PolicyError(f"{neg} is not supported")
            actions = s.get("Action", [])
            resources = s.get("Resource", [])
            if isinstance(actions, str):
                actions = [actions]
            if isinstance(resources, str):
                resources = [resources]
            out.append(Statement(effect=s.get("Effect", ""),
                                 actions=list(actions),
                                 resources=list(resources),
                                 conditions=s.get("Condition") or {},
                                 principals=_parse_principal(
                                     s.get("Principal"))))
        return cls(statements=out)

    def to_json(self) -> dict:
        out = []
        for s in self.statements:
            d = {"Effect": s.effect, "Action": s.actions,
                 "Resource": s.resources}
            if s.conditions:
                d["Condition"] = s.conditions
            if s.principals is not None:
                d["Principal"] = {"AWS": s.principals}
            out.append(d)
        return {"Version": "2012-10-17", "Statement": out}


def _parse_principal(p) -> Optional[list]:
    """S3 Principal forms -> list of principal patterns, None if absent.
    Accepts "*", {"AWS": "*"}, {"AWS": [...]}, {"CanonicalUser": ...}."""
    if p is None:
        return None
    if isinstance(p, str):
        return [p]
    if isinstance(p, dict):
        vals: list[str] = []
        for v in p.values():
            vals.extend(v if isinstance(v, list) else [v])
        return vals
    raise PolicyError("bad Principal")


def evaluate(policies: Sequence[Policy], action: str, resource: str,
             context: Optional[dict] = None,
             access_key: Optional[str] = None) -> bool:
    """Explicit Deny wins; otherwise any Allow permits; default deny
    (reference: policy.Policy.IsAllowed)."""
    return decide(policies, action, resource, context, access_key) == "Allow"


def decide(policies: Sequence[Policy], action: str, resource: str,
           context: Optional[dict] = None,
           access_key: Optional[str] = None,
           require_principal: bool = False) -> Optional[str]:
    """Tri-state evaluation: "Deny" on an explicit matching Deny,
    "Allow" on a matching Allow with no Deny, None when nothing
    matches — so identity and bucket policies can be merged deny-wins
    with 'neither said anything' distinguishable from 'allowed'
    (reference: cmd/auth-handler.go isPutActionAllowed merging IAM and
    policy decisions). `require_principal=True` is the bucket-policy
    mode: statements without a Principal match nobody."""
    allowed = False
    for p in policies:
        for s in p.statements:
            if s.matches(action, resource, context, access_key,
                         require_principal):
                if s.effect == "Deny":
                    return "Deny"
                allowed = True
    return "Allow" if allowed else None


@functools.lru_cache(maxsize=4096)
def _policy_from_canonical(doc_json: str) -> Policy:
    return Policy.from_json(json.loads(doc_json))


def compile_policy(doc: dict) -> Policy:
    """Cached document -> compiled Policy (the per-request hot path:
    regex compilation happens once per distinct document)."""
    return _policy_from_canonical(json.dumps(doc, sort_keys=True))


@functools.lru_cache(maxsize=1)
def canned_policies() -> dict[str, Policy]:
    """The reference's built-in policies (cmd/iam.go embedded policies)."""
    def mk(effect, actions, resources):
        return Statement(effect=effect, actions=actions, resources=resources)

    return {
        "readonly": Policy([mk("Allow",
                               ["s3:GetBucketLocation", "s3:GetObject",
                                "s3:GetObjectVersion", "s3:ListBucket",
                                "s3:ListAllMyBuckets",
                                "s3:GetBucketVersioning"],
                               ["*"])]),
        "writeonly": Policy([mk("Allow",
                                ["s3:PutObject", "s3:AbortMultipartUpload",
                                 "s3:ListMultipartUploadParts",
                                 "s3:ListBucketMultipartUploads"],
                                ["*"])]),
        "readwrite": Policy([mk("Allow", ["s3:*"], ["*"])]),
        "diagnostics": Policy([mk("Allow", ["admin:ServerInfo",
                                            "admin:Prometheus"], ["*"])]),
        "consoleAdmin": Policy([mk("Allow", ["s3:*", "admin:*"], ["*"])]),
    }
