"""Policy documents and their evaluation.

Mirrors the reference's policy semantics (internal/policy/policy.go):
a document is a list of statements, each Allow or Deny over wildcarded
Actions and Resources; an explicit Deny always wins, absence of an
Allow denies. Wildcards are AWS-style (`*` any run, `?` one char).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import functools
import json
import re
from typing import Sequence

ARN_PREFIX = "arn:aws:s3:::"


class PolicyError(Exception):
    pass


def _compile(pattern: str) -> re.Pattern:
    return re.compile(fnmatch.translate(pattern))


@dataclasses.dataclass
class Statement:
    effect: str                 # "Allow" | "Deny"
    actions: list
    resources: list
    _action_res: list = dataclasses.field(default_factory=list, repr=False)
    _resource_res: list = dataclasses.field(default_factory=list, repr=False)

    def __post_init__(self):
        if self.effect not in ("Allow", "Deny"):
            raise PolicyError(f"bad Effect {self.effect!r}")
        if not self.actions or not self.resources:
            raise PolicyError("statement needs Action and Resource")
        self._action_res = [_compile(a) for a in self.actions]
        self._resource_res = [_compile(r[len(ARN_PREFIX):]
                                       if r.startswith(ARN_PREFIX) else r)
                              for r in self.resources]

    def matches(self, action: str, resource: str) -> bool:
        return any(p.match(action) for p in self._action_res) and \
            any(p.match(resource) for p in self._resource_res)


@dataclasses.dataclass
class Policy:
    statements: list

    @classmethod
    def from_json(cls, doc: dict) -> "Policy":
        stmts = doc.get("Statement")
        if stmts is None:
            raise PolicyError("missing Statement")
        if isinstance(stmts, dict):
            stmts = [stmts]
        out = []
        for s in stmts:
            actions = s.get("Action", [])
            resources = s.get("Resource", [])
            if isinstance(actions, str):
                actions = [actions]
            if isinstance(resources, str):
                resources = [resources]
            out.append(Statement(effect=s.get("Effect", ""),
                                 actions=list(actions),
                                 resources=list(resources)))
        return cls(statements=out)

    def to_json(self) -> dict:
        return {"Version": "2012-10-17",
                "Statement": [{"Effect": s.effect, "Action": s.actions,
                               "Resource": s.resources}
                              for s in self.statements]}


def evaluate(policies: Sequence[Policy], action: str, resource: str) -> bool:
    """Explicit Deny wins; otherwise any Allow permits; default deny
    (reference: policy.Policy.IsAllowed)."""
    allowed = False
    for p in policies:
        for s in p.statements:
            if s.matches(action, resource):
                if s.effect == "Deny":
                    return False
                allowed = True
    return allowed


@functools.lru_cache(maxsize=4096)
def _policy_from_canonical(doc_json: str) -> Policy:
    return Policy.from_json(json.loads(doc_json))


def compile_policy(doc: dict) -> Policy:
    """Cached document -> compiled Policy (the per-request hot path:
    regex compilation happens once per distinct document)."""
    return _policy_from_canonical(json.dumps(doc, sort_keys=True))


@functools.lru_cache(maxsize=1)
def canned_policies() -> dict[str, Policy]:
    """The reference's built-in policies (cmd/iam.go embedded policies)."""
    def mk(effect, actions, resources):
        return Statement(effect=effect, actions=actions, resources=resources)

    return {
        "readonly": Policy([mk("Allow",
                               ["s3:GetBucketLocation", "s3:GetObject",
                                "s3:GetObjectVersion", "s3:ListBucket",
                                "s3:ListAllMyBuckets",
                                "s3:GetBucketVersioning"],
                               ["*"])]),
        "writeonly": Policy([mk("Allow",
                                ["s3:PutObject", "s3:AbortMultipartUpload",
                                 "s3:ListMultipartUploadParts",
                                 "s3:ListBucketMultipartUploads"],
                                ["*"])]),
        "readwrite": Policy([mk("Allow", ["s3:*"], ["*"])]),
        "diagnostics": Policy([mk("Allow", ["admin:ServerInfo",
                                            "admin:Prometheus"], ["*"])]),
        "consoleAdmin": Policy([mk("Allow", ["s3:*", "admin:*"], ["*"])]),
    }
