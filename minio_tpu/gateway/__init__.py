"""Protocol gateways re-exposing the object layer (FTP; the reference
also ships SFTP, which needs an SSH stack this image doesn't carry)."""

from minio_tpu.gateway.ftp import FTPGateway  # noqa: F401
