"""FTP gateway: the object namespace over RFC 959.

The analogue of the reference's FTP server (cmd/ftp-server.go, which
wraps an FTP library around the object layer): implemented from the
socket up — control-connection command loop, passive-mode data
connections, and the object-layer bridge. The namespace maps the S3
world the way the reference does: the root directory lists buckets,
`/bucket/key...` paths are objects.

Supported: USER/PASS (verified against the same credential resolver
the S3 API uses, with per-command IAM authorization), SYST, FEAT,
TYPE, PWD, CWD/CDUP, PASV/EPSV, LIST/NLST, RETR, STOR, DELE, SIZE,
MKD, RMD, NOOP, QUIT. Transfers are binary; active mode (PORT) is not
offered (NATs broke it decades ago; the reference's library also
prefers passive).
"""

from __future__ import annotations

import posixpath
import socket
import socketserver
import threading
from typing import Optional


# STOR buffers in memory (FTP sends no size upfront, and the object
# layer's streaming path needs one); cap it hard. Large uploads belong
# on the S3 API, which streams in O(window).
STOR_MAX_BYTES = 512 * 1024 * 1024


class FTPGateway:
    """FTP server bridging to an object layer + credential resolver."""

    def __init__(self, object_layer, credentials,
                 address: str = "127.0.0.1:0",
                 passive_host: Optional[str] = None, kms=None):
        from minio_tpu.crypto.kms import KMS
        self.object_layer = object_layer
        self.credentials = credentials
        # Same sealing key as the S3 front end: RETR must decrypt what
        # the S3 API encrypted, STOR must honor bucket default SSE.
        self.kms = kms if kms is not None else KMS.from_env()
        host, _, port = address.rpartition(":")
        gateway = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    _Session(gateway, self).run()
                finally:
                    with gateway._sessions_mu:
                        gateway._sessions -= 1

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

            def process_request(self, request, client_address):
                # Count in the ACCEPT path, not the handler thread:
                # stop()'s drain must never observe zero while an
                # accepted connection's handler is still unscheduled.
                with gateway._sessions_mu:
                    gateway._sessions += 1
                try:
                    super().process_request(request, client_address)
                except Exception:
                    with gateway._sessions_mu:
                        gateway._sessions -= 1
                    raise

        self.server = Server((host or "127.0.0.1", int(port)), Handler)
        self.passive_host = passive_host or self.server.server_address[0]
        self._thread: Optional[threading.Thread] = None
        self._sessions = 0
        self._sessions_mu = threading.Lock()

    @property
    def address(self) -> str:
        h, p = self.server.server_address[:2]
        return f"{h}:{p}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="ftp-gateway")
        self._thread.start()

    def stop(self) -> None:
        """Stop accepting, then drain in-flight sessions briefly — the
        caller closes the object layer next, and an active transfer
        must not hit a shut-down executor."""
        import time as _t
        self.server.shutdown()
        self.server.server_close()
        deadline = _t.monotonic() + 10
        while self._sessions > 0 and _t.monotonic() < deadline:
            _t.sleep(0.05)


class _Session:
    """One control connection."""

    def __init__(self, gw: FTPGateway, rh):
        self.gw = gw
        self.rh = rh
        self.user = ""
        self.authed = False
        self.cwd = "/"
        self.type = "I"
        self._pasv: Optional[socket.socket] = None

    # -- plumbing --------------------------------------------------------

    def send(self, line: str) -> None:
        self.rh.wfile.write((line + "\r\n").encode())

    def run(self) -> None:
        self.send("220 minio-tpu FTP gateway ready")
        try:
            while True:
                raw = self.rh.rfile.readline()
                if not raw:
                    return
                line = raw.decode("utf-8", "replace").rstrip("\r\n")
                if not line:
                    continue
                cmd, _, arg = line.partition(" ")
                cmd = cmd.upper()
                handler = getattr(self, f"cmd_{cmd.lower()}", None)
                try:
                    if handler is None:
                        self.send("502 command not implemented")
                    elif cmd in ("USER", "PASS", "QUIT", "SYST", "FEAT",
                                 "NOOP") or self.authed:
                        if handler(arg) is False:
                            return
                    else:
                        self.send("530 please login with USER and PASS")
                except _FTPError as e:
                    self.send(str(e))
                except Exception as e:  # noqa: BLE001 - session survives
                    self.send(f"451 local error: {e}")
        finally:
            self._close_pasv()

    def _close_pasv(self) -> None:
        if self._pasv is not None:
            try:
                self._pasv.close()
            except OSError:
                pass
            self._pasv = None

    def _data_conn(self) -> socket.socket:
        if self._pasv is None:
            raise _FTPError("425 use PASV first")
        listener, self._pasv = self._pasv, None
        listener.settimeout(30)
        try:
            conn, _ = listener.accept()
            # Accepted sockets do NOT inherit the listener's timeout:
            # without one, a silent client pins this session thread
            # (and any buffered upload bytes) forever.
            conn.settimeout(120)
            return conn
        finally:
            listener.close()

    # -- namespace helpers ----------------------------------------------

    def _resolve(self, arg: str) -> str:
        path = arg if arg.startswith("/") else \
            posixpath.join(self.cwd, arg)
        # normpath on an ABSOLUTE path resolves every ".." within the
        # virtual root — "/../etc" becomes "/etc", i.e. bucket "etc".
        # Nothing here ever touches the host filesystem; paths only
        # ever name buckets and keys.
        path = posixpath.normpath(path)
        if path in (".", "/"):
            return "/"
        return path

    def _split(self, path: str) -> tuple[str, str]:
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        return bucket, key

    def _allowed(self, action: str, resource: str) -> None:
        if not self.gw.credentials.is_allowed(self.user, action, resource):
            raise _FTPError("550 permission denied")

    # -- auth ------------------------------------------------------------

    def cmd_user(self, arg):
        # Switching users DE-authenticates: keeping authed=True here
        # would let any logged-in session assume root by sending
        # "USER minioadmin" with no password.
        self.user = arg.strip()
        self.authed = False
        self.send("331 password required")

    def cmd_pass(self, arg):
        import hmac as _hmac
        secret = self.gw.credentials.secret_for(self.user)
        if secret is None or not _hmac.compare_digest(secret.encode(),
                                                      arg.encode()):
            self.authed = False
            self.send("530 login incorrect")
            return
        self.authed = True
        self.send("230 login successful")

    def cmd_quit(self, arg):
        self.send("221 goodbye")
        return False

    # -- session state ---------------------------------------------------

    def cmd_syst(self, arg):
        self.send("215 UNIX Type: L8")

    def cmd_feat(self, arg):
        self.rh.wfile.write(b"211-features\r\n SIZE\r\n EPSV\r\n"
                            b" UTF8\r\n211 end\r\n")

    def cmd_noop(self, arg):
        self.send("200 ok")

    def cmd_type(self, arg):
        self.type = (arg or "I").upper()
        self.send("200 type set")

    def cmd_pwd(self, arg):
        self.send(f'257 "{self.cwd}"')

    def cmd_cwd(self, arg):
        path = self._resolve(arg)
        if path != "/":
            bucket, key = self._split(path)
            try:
                self.gw.object_layer.get_bucket_info(bucket)
            except Exception:  # noqa: BLE001 - absent bucket
                raise _FTPError("550 no such directory") from None
        self.cwd = path
        self.send("250 directory changed")

    def cmd_cdup(self, arg):
        self.cwd = posixpath.dirname(self.cwd) or "/"
        self.send("250 directory changed")

    # -- passive data ----------------------------------------------------

    def _open_pasv(self) -> tuple[str, int]:
        self._close_pasv()
        # Advertise the address the CLIENT reached us on (the control
        # connection's local interface): a 0.0.0.0 bind must never be
        # advertised — it is unconnectable. An explicit passive_host
        # override (NAT) wins.
        ctl_host = self.rh.connection.getsockname()[0]
        bind_host = self.gw.server.server_address[0]
        s = socket.socket()
        s.bind((bind_host, 0))
        s.listen(1)
        self._pasv = s
        host = self.gw.passive_host
        if host in ("0.0.0.0", "", "::"):
            host = ctl_host
        return host, s.getsockname()[1]

    def cmd_pasv(self, arg):
        host, port = self._open_pasv()
        h = host.replace(".", ",")
        self.send(f"227 entering passive mode "
                  f"({h},{port >> 8},{port & 0xFF})")

    def cmd_epsv(self, arg):
        _, port = self._open_pasv()
        self.send(f"229 entering extended passive mode (|||{port}|)")

    # -- listings --------------------------------------------------------

    def _entries(self, path: str):
        """(name, is_dir, size) entries for `path`."""
        ol = self.gw.object_layer
        if path == "/":
            self._allowed("s3:ListAllMyBuckets", "*")
            return [(b.name, True, 0) for b in ol.list_buckets()]
        bucket, key = self._split(path)
        self._allowed("s3:ListBucket", bucket)
        prefix = key + "/" if key else ""
        out = []
        marker = ""
        # Follow pagination: a truncation-blind listing would make FTP
        # sync tools conclude objects past entry 1000 don't exist.
        # Bounded at 100k entries per listing as an abuse stop.
        while len(out) < 100_000:
            page = ol.list_objects(bucket, prefix=prefix, delimiter="/",
                                   marker=marker, max_keys=1000)
            for p in page.prefixes:
                out.append((p[len(prefix):].rstrip("/"), True, 0))
            for o in page.objects:
                out.append((o.name[len(prefix):], False, o.size))
            if not page.is_truncated:
                break
            marker = page.next_marker
        return out

    @staticmethod
    def _strip_flags(arg: str) -> str:
        """Drop leading `-x` option words ('LIST -al path'); a plain
        lstrip over a character set would eat path letters."""
        words = arg.split()
        while words and words[0].startswith("-"):
            words.pop(0)
        return " ".join(words)

    def cmd_list(self, arg):
        path = self._resolve(self._strip_flags(arg))
        entries = self._entries(path)
        conn = self._data_conn()
        self.send("150 listing")
        try:
            for name, is_dir, size in entries:
                kind = "d" if is_dir else "-"
                conn.sendall(
                    f"{kind}rw-r--r-- 1 s3 s3 {size:>12} Jan  1 00:00 "
                    f"{name}\r\n".encode())
        finally:
            conn.close()
        self.send("226 done")

    def cmd_nlst(self, arg):
        path = self._resolve(arg)
        entries = self._entries(path)
        conn = self._data_conn()
        self.send("150 listing")
        try:
            for name, _, _ in entries:
                conn.sendall((name + "\r\n").encode())
        finally:
            conn.close()
        self.send("226 done")

    # -- transfers -------------------------------------------------------

    def cmd_retr(self, arg):
        from minio_tpu.crypto.sse import SSEError
        from minio_tpu.object import transform
        bucket, key = self._split(self._resolve(arg))
        if not key:
            raise _FTPError("550 not a file")
        self._allowed("s3:GetObject", f"{bucket}/{key}")
        try:
            # The shared transform seam: SSE-S3 decrypts, compressed
            # objects decompress — RETR always sends LOGICAL bytes
            # (matching what SIZE/LIST report). SSE-C objects need a
            # client-held key FTP cannot carry: refuse, don't leak
            # ciphertext.
            _, chunks = transform.plaintext_stream(
                self.gw.object_layer, self.gw.kms, bucket, key)
        except SSEError:
            raise _FTPError("550 object requires SSE-C key headers; "
                            "use the S3 API") from None
        except Exception:  # noqa: BLE001 - absent object
            raise _FTPError("550 no such file") from None
        conn = self._data_conn()
        self.send("150 opening data connection")
        try:
            for chunk in chunks:
                conn.sendall(chunk)
        finally:
            conn.close()
        self.send("226 transfer complete")

    def cmd_stor(self, arg):
        from minio_tpu.object.types import PutOptions
        bucket, key = self._split(self._resolve(arg))
        if not key:
            raise _FTPError("550 not a file")
        self._allowed("s3:PutObject", f"{bucket}/{key}")
        conn = self._data_conn()
        self.send("150 ready for data")
        chunks = []
        total = 0
        try:
            while True:
                b = conn.recv(1 << 16)
                if not b:
                    break
                total += len(b)
                if total > STOR_MAX_BYTES:
                    raise _FTPError("552 upload exceeds the FTP "
                                    "gateway's size limit (use the S3 "
                                    "API for large objects)")
                chunks.append(b)
        finally:
            conn.close()
        from minio_tpu.crypto.sse import SSEError
        from minio_tpu.object import transform
        from minio_tpu.utils.streams import Payload
        versioned = bool(self.gw.object_layer.get_bucket_meta(bucket)
                         .get("versioning"))
        opts = PutOptions(versioned=versioned)
        # Bucket default encryption applies to every writer, FTP
        # included — storing plaintext in a bucket whose config demands
        # SSE would silently break its compliance posture.
        try:
            payload, _ = transform.sse_payload(
                self.gw.object_layer, self.gw.kms, bucket, key,
                Payload.wrap(b"".join(chunks)), opts)
        except SSEError as e:
            raise _FTPError(f"550 {e}") from None
        self.gw.object_layer.put_object(bucket, key, payload, opts)
        self.send("226 transfer complete")

    def cmd_dele(self, arg):
        from minio_tpu.object.types import DeleteOptions
        bucket, key = self._split(self._resolve(arg))
        if not key:
            raise _FTPError("550 not a file")
        self._allowed("s3:DeleteObject", f"{bucket}/{key}")
        versioned = bool(self.gw.object_layer.get_bucket_meta(bucket)
                         .get("versioning"))
        self.gw.object_layer.delete_object(
            bucket, key, DeleteOptions(versioned=versioned))
        self.send("250 deleted")

    def cmd_size(self, arg):
        from minio_tpu.object.types import GetOptions
        bucket, key = self._split(self._resolve(arg))
        if not key:
            raise _FTPError("550 not a file")
        self._allowed("s3:GetObject", f"{bucket}/{key}")
        try:
            info = self.gw.object_layer.get_object_info(bucket, key,
                                                        GetOptions())
        except Exception:  # noqa: BLE001 - absent object
            raise _FTPError("550 no such file") from None
        self.send(f"213 {info.size}")

    def cmd_mkd(self, arg):
        path = self._resolve(arg)
        bucket, key = self._split(path)
        if key:
            # Keys are created implicitly by STOR; directories within a
            # bucket need no materialization in an object namespace.
            self.send(f'257 "{path}"')
            return
        self._allowed("s3:CreateBucket", bucket)
        self.gw.object_layer.make_bucket(bucket)
        self.send(f'257 "{path}"')

    def cmd_rmd(self, arg):
        bucket, key = self._split(self._resolve(arg))
        if key:
            raise _FTPError("550 only buckets can be removed")
        self._allowed("s3:DeleteBucket", bucket)
        try:
            self.gw.object_layer.delete_bucket(bucket)
        except Exception as e:  # noqa: BLE001 - not empty / absent
            raise _FTPError(f"550 {e}") from None
        self.send("250 removed")


class _FTPError(Exception):
    """str(self) is the full FTP response line."""
