// Native data-path kernels for minio_tpu (host side).
//
// The reference gets its host performance from Go-assembly dependencies
// (AVX2/AVX512 HighwayHash in github.com/minio/highwayhash, GFNI/AVX2
// Galois kernels in klauspost/reedsolomon, assembly xxhash — SURVEY.md
// §2.7). This module is our native equivalent, compiled with -O3
// -march=native so the compiler vectorizes the hot loops; the TPU path
// (ops/rs_device.py) handles bulk stripes, this handles the host-side
// cases: bitrot hashing, small-block GF math, digests for self-tests.
//
// Exposed via a plain C ABI for ctypes (no pybind11 in the image).
//
// Implementations are from-scratch from the public algorithm specs
// (HighwayHash: github.com/google/highwayhash paper/spec; xxHash spec),
// byte-validated in tests against the reference's golden digests.

#include <cstdint>
#include <cstddef>
#include <cstring>
#include <ctime>
#include <mutex>

#ifndef MTPU_NO_ZLIB
#include <zlib.h>
#endif

#if defined(__AVX2__) || (defined(__GFNI__) && defined(__AVX512F__))
#include <immintrin.h>
#endif
#if defined(__GFNI__) && defined(__AVX512F__) && defined(__AVX512BW__)
#define MTPU_GFNI 1
#endif

extern "C" {

// ---------------------------------------------------------------------------
// HighwayHash-256
// ---------------------------------------------------------------------------

namespace {

struct HHState {
  uint64_t v0[4], v1[4], mul0[4], mul1[4];
};

const uint64_t kInit0[4] = {0xdbe6d5d5fe4cce2fULL, 0xa4093822299f31d0ULL,
                            0x13198a2e03707344ULL, 0x243f6a8885a308d3ULL};
const uint64_t kInit1[4] = {0x3bd39e10cb0ef593ULL, 0xc0acf169b5f18a8cULL,
                            0xbe5466cf34e90c6cULL, 0x452821e638d01377ULL};

inline uint64_t Rot32(uint64_t x) { return (x >> 32) | (x << 32); }

inline uint64_t Le64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86/arm64)
}

inline void Reset(const uint64_t key[4], HHState* s) {
  for (int i = 0; i < 4; ++i) {
    s->v0[i] = kInit0[i] ^ key[i];
    s->v1[i] = kInit1[i] ^ Rot32(key[i]);
    s->mul0[i] = kInit0[i];
    s->mul1[i] = kInit1[i];
  }
}

inline void ZipperMergeAndAdd(uint64_t v1, uint64_t v0, uint64_t* add1,
                              uint64_t* add0) {
  *add0 += (((v0 & 0xff000000ULL) | (v1 & 0xff00000000ULL)) >> 24) |
           (((v0 & 0xff0000000000ULL) | (v1 & 0xff000000000000ULL)) >> 16) |
           (v0 & 0xff0000ULL) | ((v0 & 0xff00ULL) << 32) |
           ((v1 & 0xff00000000000000ULL) >> 8) | (v0 << 56);
  *add1 += (((v1 & 0xff000000ULL) | (v0 & 0xff00000000ULL)) >> 24) |
           (v1 & 0xff0000ULL) | ((v1 & 0xff0000000000ULL) >> 16) |
           ((v1 & 0xff00ULL) << 24) | ((v0 & 0xff000000000000ULL) >> 8) |
           ((v1 & 0xffULL) << 48) | (v0 & 0xff00000000000000ULL);
}

inline void Update(const uint64_t lanes[4], HHState* s) {
  for (int i = 0; i < 4; ++i) {
    s->v1[i] += s->mul0[i] + lanes[i];
    s->mul0[i] ^= (s->v1[i] & 0xffffffffULL) * (s->v0[i] >> 32);
    s->v0[i] += s->mul1[i];
    s->mul1[i] ^= (s->v0[i] & 0xffffffffULL) * (s->v1[i] >> 32);
  }
  ZipperMergeAndAdd(s->v1[1], s->v1[0], &s->v0[1], &s->v0[0]);
  ZipperMergeAndAdd(s->v1[3], s->v1[2], &s->v0[3], &s->v0[2]);
  ZipperMergeAndAdd(s->v0[1], s->v0[0], &s->v1[1], &s->v1[0]);
  ZipperMergeAndAdd(s->v0[3], s->v0[2], &s->v1[3], &s->v1[2]);
}

inline void UpdatePacket(const uint8_t* p, HHState* s) {
  uint64_t lanes[4] = {Le64(p), Le64(p + 8), Le64(p + 16), Le64(p + 24)};
  Update(lanes, s);
}

inline uint32_t Rol32(uint32_t x, unsigned c) {
  return c ? (x << c) | (x >> (32 - c)) : x;
}

inline void UpdateRemainder(const uint8_t* bytes, size_t size_mod32,
                            HHState* s) {
  const size_t size_mod4 = size_mod32 & 3;
  const uint8_t* remainder = bytes + (size_mod32 & ~size_t(3));
  uint8_t packet[32] = {0};
  for (int i = 0; i < 4; ++i)
    s->v0[i] += (uint64_t(size_mod32) << 32) + size_mod32;
  for (int i = 0; i < 4; ++i) {
    uint32_t lo = uint32_t(s->v1[i]), hi = uint32_t(s->v1[i] >> 32);
    s->v1[i] = (uint64_t(Rol32(hi, size_mod32)) << 32) | Rol32(lo, size_mod32);
  }
  std::memcpy(packet, bytes, size_mod32 & ~size_t(3));
  if (size_mod32 & 16) {
    for (int i = 0; i < 4; ++i)
      packet[28 + i] = remainder[i + size_mod4 - 4];
  } else if (size_mod4) {
    packet[16] = remainder[0];
    packet[17] = remainder[size_mod4 >> 1];
    packet[18] = remainder[size_mod4 - 1];
  }
  UpdatePacket(packet, s);
}

inline void Finalize256(HHState* s, uint64_t hash[4]) {
  for (int r = 0; r < 10; ++r) {
    uint64_t permuted[4] = {Rot32(s->v0[2]), Rot32(s->v0[3]),
                            Rot32(s->v0[0]), Rot32(s->v0[1])};
    Update(permuted, s);
  }
  auto mod = [](uint64_t a3u, uint64_t a2, uint64_t a1, uint64_t a0,
                uint64_t* m1, uint64_t* m0) {
    const uint64_t a3 = a3u & 0x3fffffffffffffffULL;
    *m1 = a1 ^ ((a3 << 1) | (a2 >> 63)) ^ ((a3 << 2) | (a2 >> 62));
    *m0 = a0 ^ (a2 << 1) ^ (a2 << 2);
  };
  mod(s->v1[1] + s->mul1[1], s->v1[0] + s->mul1[0], s->v0[1] + s->mul0[1],
      s->v0[0] + s->mul0[0], &hash[1], &hash[0]);
  mod(s->v1[3] + s->mul1[3], s->v1[2] + s->mul1[2], s->v0[3] + s->mul0[3],
      s->v0[2] + s->mul0[2], &hash[3], &hash[2]);
}

#ifdef __AVX2__
// The 4-lane HighwayHash state vectorizes exactly onto 256-bit
// registers: each of v0/v1/mul0/mul1 is one __m256i, the 32->64 bit
// lane multiplies are VPMULUDQ, and the zipper-merge byte permutation
// (which scalar code spells as mask-and-shift soup) is one VPSHUFB per
// 128-bit pair — the same mapping the reference's assembly dependency
// (github.com/minio/highwayhash AVX2 path) exploits. Bulk packets run
// vectorized; the ragged remainder and finalization spill to the
// byte-identical scalar state.
inline __m256i HHZipper(__m256i x) {
  const __m256i kMask = _mm256_setr_epi8(
      3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7,
      3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7);
  return _mm256_shuffle_epi8(x, kMask);
}

struct HHVec {
  __m256i v0, v1, mul0, mul1;
};

inline void UpdateVec(__m256i lanes, HHVec* s) {
  s->v1 = _mm256_add_epi64(s->v1, _mm256_add_epi64(s->mul0, lanes));
  s->mul0 = _mm256_xor_si256(
      s->mul0, _mm256_mul_epu32(s->v1, _mm256_srli_epi64(s->v0, 32)));
  s->v0 = _mm256_add_epi64(s->v0, s->mul1);
  s->mul1 = _mm256_xor_si256(
      s->mul1, _mm256_mul_epu32(s->v0, _mm256_srli_epi64(s->v1, 32)));
  s->v0 = _mm256_add_epi64(s->v0, HHZipper(s->v1));
  s->v1 = _mm256_add_epi64(s->v1, HHZipper(s->v0));
}

inline void BulkPackets(const uint8_t* data, size_t full, HHState* s) {
  HHVec v;
  v.v0 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->v0));
  v.v1 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->v1));
  v.mul0 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->mul0));
  v.mul1 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->mul1));
  for (size_t i = 0; i < full; ++i)
    UpdateVec(_mm256_loadu_si256(
                  reinterpret_cast<const __m256i*>(data + 32 * i)),
              &v);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->v0), v.v0);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->v1), v.v1);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->mul0), v.mul0);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->mul1), v.mul1);
}
#else
inline void BulkPackets(const uint8_t* data, size_t full, HHState* s) {
  for (size_t i = 0; i < full; ++i) UpdatePacket(data + 32 * i, s);
}
#endif  // __AVX2__

}  // namespace

void mtpu_hh256(const uint8_t* key32, const uint8_t* data, size_t len,
                uint8_t* out32) {
  uint64_t key[4] = {Le64(key32), Le64(key32 + 8), Le64(key32 + 16),
                     Le64(key32 + 24)};
  HHState s;
  Reset(key, &s);
  size_t full = len / 32;
  BulkPackets(data, full, &s);
  if (len % 32) UpdateRemainder(data + 32 * full, len % 32, &s);
  uint64_t hash[4];
  Finalize256(&s, hash);
  std::memcpy(out32, hash, 32);
}

// Hash `nstreams` blocks, each `len` bytes, laid out contiguously with
// byte stride `stride` (stride >= len). Out: nstreams x 32 bytes.
void mtpu_hh256_many(const uint8_t* key32, const uint8_t* data,
                     size_t nstreams, size_t stride, size_t len,
                     uint8_t* out) {
  for (size_t i = 0; i < nstreams; ++i)
    mtpu_hh256(key32, data + i * stride, len, out + 32 * i);
}

// ---------------------------------------------------------------------------
// xxHash64 (spec: cyan4973.github.io/xxHash)
// ---------------------------------------------------------------------------

namespace {
const uint64_t P1 = 0x9E3779B185EBCA87ULL, P2 = 0xC2B2AE3D27D4EB4FULL,
               P3 = 0x165667B19E3779F9ULL, P4 = 0x85EBCA77C2B2AE63ULL,
               P5 = 0x27D4EB2F165667C5ULL;
inline uint64_t Rotl64(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }
inline uint64_t XxhRound(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = Rotl64(acc, 31);
  return acc * P1;
}
inline uint64_t XxhMerge(uint64_t acc, uint64_t val) {
  acc ^= XxhRound(0, val);
  return acc * P1 + P4;
}
}  // namespace

uint64_t mtpu_xxh64(const uint8_t* p, size_t len, uint64_t seed) {
  const uint8_t* end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    do {
      v1 = XxhRound(v1, Le64(p)); p += 8;
      v2 = XxhRound(v2, Le64(p)); p += 8;
      v3 = XxhRound(v3, Le64(p)); p += 8;
      v4 = XxhRound(v4, Le64(p)); p += 8;
    } while (p + 32 <= end);
    h = Rotl64(v1, 1) + Rotl64(v2, 7) + Rotl64(v3, 12) + Rotl64(v4, 18);
    h = XxhMerge(h, v1); h = XxhMerge(h, v2);
    h = XxhMerge(h, v3); h = XxhMerge(h, v4);
  } else {
    h = seed + P5;
  }
  h += uint64_t(len);
  while (p + 8 <= end) {
    h ^= XxhRound(0, Le64(p));
    h = Rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    h ^= uint64_t(v) * P1;
    h = Rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= uint64_t(*p) * P5;
    h = Rotl64(h, 11) * P1;
    ++p;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// ---------------------------------------------------------------------------
// GF(2^8) shard transform (host fallback for small blocks)
// ---------------------------------------------------------------------------
//
// out[r][:] = XOR_j mul(matrix[r][j], shards[j][:]) using 4-bit split
// tables (the classic PSHUFB decomposition: one 16-entry table for each
// nibble), which compilers auto-vectorize well with -O3 -march=native.

namespace {
uint8_t kGfMul[256][256];
std::once_flag kGfOnce;

// ctypes releases the GIL, so concurrent first calls are real races —
// call_once publishes the fully-built table before anyone reads it.
void GfInit() {
  std::call_once(kGfOnce, [] {
    // GF(2^8) with poly 0x11d (same field as the codec).
    for (int a = 1; a < 256; ++a) {
      for (int b = 1; b < 256; ++b) {
        int x = a, y = b, acc = 0;
        while (y) {
          if (y & 1) acc ^= x;
          x <<= 1;
          if (x & 0x100) x ^= 0x11d;
          y >>= 1;
        }
        kGfMul[a][b] = uint8_t(acc);
      }
    }
  });
}
}  // namespace

#ifdef MTPU_GFNI
namespace {

// GF2P8AFFINEQB computes, per byte x of src: out bit i =
// parity(A.byte[7-i] & x) (+ imm bit). Multiplication by a constant c
// in ANY GF(2^8) representation is GF(2)-linear, so an 8x8 bit matrix
// whose column j is the byte c*x^j (field poly 0x11d here, NOT the
// instruction's native AES poly) implements mul-by-c exactly — the
// same trick the reference's dependency uses for its GFNI kernels
// (klauspost/reedsolomon galois_amd64). Row i of the matrix (bit i of
// every column) lands in qword byte 7-i.
uint64_t kGfAffine[256];
bool kGfniOk = false;
std::once_flag kAffineOnce;

void AffineInit() {
  std::call_once(kAffineOnce, [] {
    GfInit();
    for (int c = 0; c < 256; ++c) {
      uint64_t m = 0;
      for (int j = 0; j < 8; ++j) {
        const uint8_t col = c ? kGfMul[c][1 << j] : 0;  // c * x^j
        for (int i = 0; i < 8; ++i)
          if (col & (1 << i)) m |= 1ULL << ((7 - i) * 8 + j);
      }
      kGfAffine[c] = m;
    }
    // Trust nothing about bit-order conventions: validate the packed
    // matrices against the multiplication table with the instruction
    // itself before enabling the fast path.
    alignas(64) uint8_t x[64], got[64];
    for (int t = 0; t < 64; ++t) x[t] = uint8_t(4 * t + 3);
    kGfniOk = true;
    for (int c = 0; c < 256 && kGfniOk; c += 17) {
      __m512i vx = _mm512_load_si512(reinterpret_cast<const void*>(x));
      __m512i va = _mm512_set1_epi64(int64_t(kGfAffine[c]));
      _mm512_store_si512(reinterpret_cast<void*>(got),
                         _mm512_gf2p8affine_epi64_epi8(vx, va, 0));
      for (int t = 0; t < 64; ++t)
        if (got[t] != kGfMul[c][x[t]]) { kGfniOk = false; break; }
    }
  });
}

}  // namespace
#endif  // MTPU_GFNI

void mtpu_gf_apply(const uint8_t* matrix, size_t r, size_t k,
                   const uint8_t* shards, size_t stride, size_t len,
                   uint8_t* out, size_t out_stride) {
  GfInit();
#ifdef MTPU_GFNI
  AffineInit();
  if (kGfniOk) {
    // Coefficient classification and affine-matrix broadcasts are
    // loop-invariant per output row; hoist them so the 64-byte inner
    // loop is loads + affine + xor only (char aliasing otherwise stops
    // the compiler from hoisting past the output stores).
    enum : uint8_t { kSkip, kXor, kAffine };
    uint8_t cls[64];
    __m512i aff[64];
    for (size_t i = 0; i < r; ++i) {
      const size_t kk = k > 64 ? 64 : k;
      for (size_t j = 0; j < kk; ++j) {
        const uint8_t c = matrix[i * k + j];
        cls[j] = c == 0 ? kSkip : (c == 1 ? kXor : kAffine);
        aff[j] = _mm512_set1_epi64(int64_t(kGfAffine[c]));
      }
      uint8_t* dst = out + i * out_stride;
      size_t t = 0;
      if (k <= 64) {
        for (; t + 64 <= len; t += 64) {
          __m512i acc = _mm512_setzero_si512();
          for (size_t j = 0; j < k; ++j) {
            if (cls[j] == kSkip) continue;
            __m512i x = _mm512_loadu_si512(
                reinterpret_cast<const void*>(shards + j * stride + t));
            acc = _mm512_xor_si512(
                acc, cls[j] == kXor
                         ? x
                         : _mm512_gf2p8affine_epi64_epi8(x, aff[j], 0));
          }
          _mm512_storeu_si512(reinterpret_cast<void*>(dst + t), acc);
        }
      }
      for (; t < len; ++t) {
        uint8_t acc = 0;
        for (size_t j = 0; j < k; ++j)
          acc ^= kGfMul[matrix[i * k + j]][shards[j * stride + t]];
        dst[t] = acc;
      }
    }
    return;
  }
#endif  // MTPU_GFNI
  for (size_t i = 0; i < r; ++i) {
    uint8_t* dst = out + i * out_stride;
    std::memset(dst, 0, len);
    for (size_t j = 0; j < k; ++j) {
      const uint8_t c = matrix[i * k + j];
      if (c == 0) continue;
      const uint8_t* src = shards + j * stride;
      if (c == 1) {
        for (size_t t = 0; t < len; ++t) dst[t] ^= src[t];
      } else {
        // Nibble-split tables: mul(c, x) = lo[x & 15] ^ hi[x >> 4].
        uint8_t lo[16], hi[16];
        for (int v = 0; v < 16; ++v) {
          lo[v] = kGfMul[c][v];
          hi[v] = kGfMul[c][v << 4];
        }
        for (size_t t = 0; t < len; ++t)
          dst[t] ^= lo[src[t] & 15] ^ hi[src[t] >> 4];
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Fused PUT framing: GF parity + HighwayHash-256 + on-disk interleave
// ---------------------------------------------------------------------------
//
// The whole host-side PutObject hot loop in one GIL-free call: for each
// erasure block, compute the m parity rows (same coding matrix as
// mtpu_gf_apply), then emit every shard's on-disk frame
// `digest || block` directly into per-shard-file contiguous output —
// no intermediate shard tensors, no Python-side interleave copies.
//
//   data: full * k * S bytes, block-major ([full][k][S]); each block's
//         k data rows are the stripe split of one BLOCK_SIZE chunk.
//   out:  n * full * (32 + S) bytes, shard-major — shard i's framed
//         file body is out[i * full * (32+S) ..).
//
// Byte-identical to frame_shards_batch(encode(data)) by construction:
// the same GF tables produce the parity, the same HighwayHash-256
// produces the digests, and the frame layout is digest-then-block
// (reference: cmd/bitrot-streaming.go:44-75).

void mtpu_put_frame(const uint8_t* key32, const uint8_t* matrix,
                    const uint8_t* data, size_t full, size_t k, size_t m,
                    size_t S, uint8_t* out) {
  const size_t n = k + m;
  const size_t frame = 32 + S;
  const size_t shard_span = full * frame;
  for (size_t b = 0; b < full; ++b) {
    const uint8_t* block = data + b * k * S;
    // Data rows: copy into their frames.
    for (size_t j = 0; j < k; ++j)
      std::memcpy(out + j * shard_span + b * frame + 32, block + j * S, S);
    // Parity rows: GF apply straight into the output frames (the rows
    // of one block land in DIFFERENT shard files => out_stride spans
    // a whole shard file).
    if (m)
      mtpu_gf_apply(matrix, m, k, block, S, S,
                    out + k * shard_span + b * frame + 32, shard_span);
  }
  // Bitrot digests over every framed block (data + parity alike).
  for (size_t i = 0; i < n; ++i) {
    uint8_t* shard = out + i * shard_span;
    for (size_t b = 0; b < full; ++b)
      mtpu_hh256(key32, shard + b * frame + 32, S, shard + b * frame);
  }
}

// ---------------------------------------------------------------------------
// Fused GET framing: bitrot verify + block-major interleave
// ---------------------------------------------------------------------------
//
// The read-side mirror of mtpu_put_frame: one GIL-free call that takes
// the k data shards' framed byte windows (`digest || block` per erasure
// block, exactly as stored), re-hashes every block against its stored
// digest, and interleaves the verified data blocks block-major straight
// into the caller's (pooled) output buffer — replacing the GET path's
// Python-level verify -> per-slice .tobytes() -> b"".join loop.
//
//   shards:    k pointers, shard j's framed window of nb blocks. All
//              blocks carry S data bytes except the LAST, which carries
//              slast (<= S; the shard file's ragged tail when the
//              window reaches it).
//   take_full: object bytes emitted per full block (BLOCK_SIZE — the
//              k*S concatenation may exceed it by the split padding).
//   take_last: object bytes emitted for the last block (the part tail).
//
// Emission per block = min(take, k*slen), walking shards in index
// order — byte-identical to the numpy reassembly by construction.
//
// Returns a bitmask of shards whose digest verification FAILED (bit j
// = shard j); nonzero means `out` holds no usable data and the caller
// falls back to the reconstruct path, treating failed shards as
// missing. Verification runs over EVERY shard before returning so the
// caller learns all bad shards in one pass.

uint64_t mtpu_get_frame(const uint8_t* key32, const uint8_t* const* shards,
                        size_t k, size_t S, size_t nb, size_t slast,
                        size_t take_full, size_t take_last, uint8_t* out) {
  const size_t frame = 32 + S;
  uint64_t bad = 0;
  for (size_t j = 0; j < k && j < 64; ++j) {
    const uint8_t* sh = shards[j];
    for (size_t b = 0; b < nb; ++b) {
      const size_t slen = (b + 1 == nb) ? slast : S;
      const uint8_t* fr = sh + b * frame;
      uint8_t dig[32];
      mtpu_hh256(key32, fr + 32, slen, dig);
      if (std::memcmp(dig, fr, 32) != 0) {
        bad |= uint64_t(1) << j;
        break;
      }
    }
  }
  if (bad) return bad;
  uint8_t* dst = out;
  for (size_t b = 0; b < nb; ++b) {
    const size_t slen = (b + 1 == nb) ? slast : S;
    size_t take = (b + 1 == nb) ? take_last : take_full;
    for (size_t j = 0; j < k && take; ++j) {
      const size_t c = slen < take ? slen : take;
      std::memcpy(dst, shards[j] + b * frame + 32, c);
      dst += c;
      take -= c;
    }
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Serve hot loop: HTTP/1.1 request-head framer + aws-chunked frame scanner
// ---------------------------------------------------------------------------
//
// The front-end's per-request parse cost in Python is readline-per-header
// plus an email.Message build; these two functions replace that with one
// GIL-free scan straight out of the worker's pooled recv buffer
// (reference: the reference rides net/http's C-backed textproto reader;
// this is our equivalent). The Python HTTP parser stays as the
// conformance fallback for anything these reject.

namespace {
// Bounded forward search (memmem without the _GNU_SOURCE dependency).
inline const uint8_t* FindSeq(const uint8_t* hay, size_t hay_len,
                              const char* needle, size_t needle_len) {
  if (hay_len < needle_len) return nullptr;
  const uint8_t* end = hay + hay_len - needle_len;
  for (const uint8_t* p = hay; p <= end; ++p) {
    p = static_cast<const uint8_t*>(
        std::memchr(p, needle[0], (size_t)(end - p) + 1));
    if (!p) return nullptr;
    if (std::memcmp(p, needle, needle_len) == 0) return p;
  }
  return nullptr;
}
}  // namespace

// Parse one HTTP/1.x request head out of buf[0:len).
//
// On success header NAMES are lowercased IN PLACE (the caller owns the
// recv buffer; SigV4 canonicalization wants lowercase anyway) and `out`
// (int32, 6 + 4*max_headers entries) is filled:
//   out[0]=method_off  out[1]=method_len
//   out[2]=target_off  out[3]=target_len
//   out[4]=version (10 | 11)
//   out[5]=nheaders, then per header: name_off, name_len, val_off, val_len.
// Returns the head length in bytes (through the final CRLFCRLF),
// 0 if the head is still incomplete, -1 malformed (caller falls back to
// the Python parser), -2 more than max_headers headers.
int64_t mtpu_http_head(uint8_t* buf, size_t len, int32_t* out,
                       size_t max_headers) {
  const uint8_t* end4 = FindSeq(buf, len, "\r\n\r\n", 4);
  if (!end4) return 0;
  const size_t head_len = (size_t)(end4 - buf) + 4;
  size_t p = 0;
  // Request line: METHOD SP request-target SP HTTP/1.x CRLF
  const size_t m0 = p;
  while (p < head_len && buf[p] != ' ' && buf[p] != '\r') ++p;
  if (p >= head_len || buf[p] != ' ' || p == m0 || p - m0 > 32) return -1;
  for (size_t i = m0; i < p; ++i)
    if (buf[i] <= ' ' || buf[i] >= 127) return -1;
  const size_t mlen = p - m0;
  ++p;
  const size_t t0 = p;
  while (p < head_len && buf[p] != ' ' && buf[p] != '\r' &&
         buf[p] != '\n') ++p;
  if (p >= head_len || buf[p] != ' ' || p == t0) return -1;
  const size_t tlen = p - t0;
  ++p;
  if (p + 10 > head_len || std::memcmp(buf + p, "HTTP/1.", 7) != 0)
    return -1;
  const uint8_t v = buf[p + 7];
  if (v != '0' && v != '1') return -1;
  p += 8;
  if (buf[p] != '\r' || buf[p + 1] != '\n') return -1;
  p += 2;
  size_t nh = 0;
  while (p < head_len) {
    if (buf[p] == '\r') {              // blank line terminates the head
      if (p + 2 != head_len || buf[p + 1] != '\n') return -1;
      break;
    }
    if (nh >= max_headers) return -2;
    if (buf[p] == ' ' || buf[p] == '\t') return -1;   // obs-fold: refuse
    const size_t n0 = p;
    while (p < head_len && buf[p] != ':' && buf[p] != '\r') ++p;
    if (p >= head_len || buf[p] != ':' || p == n0) return -1;
    for (size_t i = n0; i < p; ++i) {
      const uint8_t c = buf[i];
      if (c <= ' ' || c >= 127) return -1;   // WS before ':' = smuggling
      if (c >= 'A' && c <= 'Z') buf[i] = c + 32;
    }
    const size_t nlen = p - n0;
    ++p;
    while (p < head_len && (buf[p] == ' ' || buf[p] == '\t')) ++p;
    const size_t v0 = p;
    // A bare LF inside a field value is a request-smuggling primitive
    // (line-based parsers would see two headers where we saw one):
    // reject so the stock parser's line discipline decides.
    while (p < head_len && buf[p] != '\r' && buf[p] != '\n') ++p;
    if (p + 1 >= head_len || buf[p] != '\r' || buf[p + 1] != '\n')
      return -1;
    size_t v1 = p;
    while (v1 > v0 && (buf[v1 - 1] == ' ' || buf[v1 - 1] == '\t')) --v1;
    int32_t* h = out + 6 + 4 * nh;
    h[0] = (int32_t)n0;
    h[1] = (int32_t)nlen;
    h[2] = (int32_t)v0;
    h[3] = (int32_t)(v1 - v0);
    ++nh;
    p += 2;
  }
  out[0] = (int32_t)m0;
  out[1] = (int32_t)mlen;
  out[2] = (int32_t)t0;
  out[3] = (int32_t)tlen;
  out[4] = (v == '1') ? 11 : 10;
  out[5] = (int32_t)nh;
  return (int64_t)head_len;
}

// Scan one aws-chunked frame header (`hex-size[;ext]\r\n`) at
// buf[pos:len). out (int64, 4 entries):
//   out[0]=header length through its CRLF
//   out[1]=declared chunk size
//   out[2]=ABSOLUTE offset of the chunk-signature ext value (0 if none)
//   out[3]=signature length
// Returns 1 parsed, 0 incomplete (need more bytes), -1 malformed or
// over the 4 KiB header / 16 MiB chunk bounds (the Python reader's own
// discipline, cmd/streaming-signature-v4.go's maxLineLength).
int64_t mtpu_chunk_head(const uint8_t* buf, size_t len, size_t pos,
                        int64_t* out) {
  const size_t kMaxHeader = 4096;
  const int64_t kMaxChunk = 16ll << 20;
  if (pos > len) return -1;
  const size_t avail = len - pos;
  const size_t scan = avail < kMaxHeader ? avail : kMaxHeader;
  const uint8_t* nl = FindSeq(buf + pos, scan, "\r\n", 2);
  if (!nl) return avail > kMaxHeader ? -1 : 0;
  const size_t hlen = (size_t)(nl - (buf + pos)) + 2;
  const size_t line_end = pos + hlen - 2;
  size_t p = pos;
  int64_t size = 0;
  int digits = 0;
  while (p < line_end) {
    const uint8_t c = buf[p];
    int dv;
    if (c >= '0' && c <= '9') dv = c - '0';
    else if (c >= 'a' && c <= 'f') dv = c - 'a' + 10;
    else if (c >= 'A' && c <= 'F') dv = c - 'A' + 10;
    else break;
    size = size * 16 + dv;
    ++digits;
    ++p;
    if (size > kMaxChunk) return -1;
  }
  if (!digits) return -1;
  int64_t sig_off = 0, sig_len = 0;
  while (p < line_end && buf[p] == ';') {
    ++p;
    const size_t k0 = p;
    while (p < line_end && buf[p] != '=' && buf[p] != ';') ++p;
    const size_t klen = p - k0;
    size_t val0 = 0, vlen = 0;
    if (p < line_end && buf[p] == '=') {
      ++p;
      val0 = p;
      while (p < line_end && buf[p] != ';') ++p;
      vlen = p - val0;
    }
    if (klen == 15 && std::memcmp(buf + k0, "chunk-signature", 15) == 0) {
      sig_off = (int64_t)val0;
      sig_len = (int64_t)vlen;
    }
  }
  if (p != line_end) return -1;
  out[0] = (int64_t)hlen;
  out[1] = size;
  out[2] = sig_off;
  out[3] = sig_len;
  return 1;
}

// ---------------------------------------------------------------------------
// Batched xl.meta journal scan
// ---------------------------------------------------------------------------
//
// The listing walk's per-object hot loop: given N concatenated xl.meta
// blobs (magic + msgpack, storage/meta.py layout) in one buffer, extract
// for each blob the per-version fields the metadata plane needs —
// delete-marker/inline flags, mod-time, size, version id, data dir, and
// the three listing metadata values (etag, content-type, x-amz-tagging)
// — in one GIL-free call. Anything the scanner does not fully
// understand (unknown msgpack types where a known one is required,
// journals longer than `maxv` versions, meta maps carrying keys beyond
// the three captured ones) is REJECTED per blob: the caller falls back
// to the Python XLMeta.load path for that blob alone, so the scan can
// stay a strict, simple subset of msgpack while the slow path keeps
// full fidelity.
//
// Out records (int64), stride 2 + 13*maxv per blob:
//   [0] status: 0 parsed; -1 malformed/unsupported; -2 over maxv
//   [1] nversions
//   per version v at 2 + 13*v:
//     [+0] flags: bit0 delete-marker, bit1 inline, bit2 meta-extra
//          (meta holds keys/value-types beyond the captured three — the
//          summary is not sufficient to rebuild listing metadata)
//     [+1] mod-time   [+2] size
//     [+3..4]   vid  (absolute offset, length into buf)
//     [+5..6]   ddir
//     [+7..8]   etag
//     [+9..10]  content-type
//     [+11..12] x-amz-tagging
// Returns the number of blobs with status == 0.

namespace {

struct Mp {
  const uint8_t* p;
  const uint8_t* end;

  bool ok(size_t n) const { return size_t(end - p) >= n; }
  uint64_t be(size_t n) {
    uint64_t v = 0;
    for (size_t i = 0; i < n; ++i) v = (v << 8) | p[i];
    p += n;
    return v;
  }
};

// Read any msgpack value header we might see; for containers returns the
// element count, for str/bin the byte length (and leaves p at payload).
enum MpType { MP_ERR, MP_NIL, MP_BOOL, MP_INT, MP_STR, MP_BIN, MP_ARR,
              MP_MAP, MP_FLOAT, MP_EXT };

MpType mp_head(Mp* m, int64_t* val) {
  if (!m->ok(1)) return MP_ERR;
  const uint8_t c = *m->p++;
  if (c <= 0x7f) { *val = c; return MP_INT; }             // pos fixint
  if (c >= 0xe0) { *val = int8_t(c); return MP_INT; }     // neg fixint
  if ((c & 0xf0) == 0x80) { *val = c & 0x0f; return MP_MAP; }
  if ((c & 0xf0) == 0x90) { *val = c & 0x0f; return MP_ARR; }
  if ((c & 0xe0) == 0xa0) { *val = c & 0x1f; return MP_STR; }
  switch (c) {
    case 0xc0: return MP_NIL;
    case 0xc2: *val = 0; return MP_BOOL;
    case 0xc3: *val = 1; return MP_BOOL;
    case 0xc4: if (!m->ok(1)) return MP_ERR; *val = int64_t(m->be(1));
               return MP_BIN;
    case 0xc5: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               return MP_BIN;
    case 0xc6: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               return MP_BIN;
    case 0xca: if (!m->ok(4)) return MP_ERR; m->p += 4; return MP_FLOAT;
    case 0xcb: if (!m->ok(8)) return MP_ERR; m->p += 8; return MP_FLOAT;
    case 0xcc: if (!m->ok(1)) return MP_ERR; *val = int64_t(m->be(1));
               return MP_INT;
    case 0xcd: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               return MP_INT;
    case 0xce: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               return MP_INT;
    case 0xcf: {
      if (!m->ok(8)) return MP_ERR;
      const uint64_t u = m->be(8);
      if (u > uint64_t(INT64_MAX)) return MP_ERR;   // out of our range
      *val = int64_t(u);
      return MP_INT;
    }
    case 0xd0: if (!m->ok(1)) return MP_ERR; *val = int8_t(m->be(1));
               return MP_INT;
    case 0xd1: if (!m->ok(2)) return MP_ERR; *val = int16_t(m->be(2));
               return MP_INT;
    case 0xd2: if (!m->ok(4)) return MP_ERR; *val = int32_t(m->be(4));
               return MP_INT;
    case 0xd3: if (!m->ok(8)) return MP_ERR; *val = int64_t(m->be(8));
               return MP_INT;
    case 0xd4: case 0xd5: case 0xd6: case 0xd7: case 0xd8: {
      const size_t n = size_t(1) << (c - 0xd4);
      if (!m->ok(1 + n)) return MP_ERR;
      m->p += 1 + n;
      return MP_EXT;
    }
    case 0xc7: if (!m->ok(1)) return MP_ERR; *val = int64_t(m->be(1));
               if (!m->ok(size_t(*val) + 1)) return MP_ERR;
               m->p += *val + 1; return MP_EXT;
    case 0xc8: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               if (!m->ok(size_t(*val) + 1)) return MP_ERR;
               m->p += *val + 1; return MP_EXT;
    case 0xc9: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               if (!m->ok(size_t(*val) + 1)) return MP_ERR;
               m->p += *val + 1; return MP_EXT;
    case 0xd9: if (!m->ok(1)) return MP_ERR; *val = int64_t(m->be(1));
               return MP_STR;
    case 0xda: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               return MP_STR;
    case 0xdb: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               return MP_STR;
    case 0xdc: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               return MP_ARR;
    case 0xdd: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               return MP_ARR;
    case 0xde: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               return MP_MAP;
    case 0xdf: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               return MP_MAP;
    default: return MP_ERR;   // reserved / never-used (0xc1)
  }
}

bool mp_skip(Mp* m, int depth = 0) {
  if (depth > 32) return false;
  int64_t v = 0;
  switch (mp_head(m, &v)) {
    case MP_ERR: return false;
    case MP_NIL: case MP_BOOL: case MP_INT: case MP_FLOAT: case MP_EXT:
      return true;
    case MP_STR: case MP_BIN:
      if (!m->ok(size_t(v))) return false;
      m->p += v;
      return true;
    case MP_ARR:
      for (int64_t i = 0; i < v; ++i)
        if (!mp_skip(m, depth + 1)) return false;
      return true;
    case MP_MAP:
      for (int64_t i = 0; i < 2 * v; ++i)
        if (!mp_skip(m, depth + 1)) return false;
      return true;
  }
  return false;
}

bool mp_str(Mp* m, const uint8_t** s, int64_t* len) {
  int64_t v = 0;
  if (mp_head(m, &v) != MP_STR || !m->ok(size_t(v))) return false;
  *s = m->p;
  *len = v;
  m->p += v;
  return true;
}

bool key_is(const uint8_t* s, int64_t len, const char* k) {
  const size_t kl = strlen(k);
  return size_t(len) == kl && std::memcmp(s, k, kl) == 0;
}

enum { MSCAN_FLAG_DELETED = 1, MSCAN_FLAG_INLINE = 2, MSCAN_FLAG_EXTRA = 4 };

// One version map -> out[0..12]; offsets absolute against `base`.
bool scan_version(Mp* m, const uint8_t* base, int64_t* o) {
  int64_t nfields = 0;
  if (mp_head(m, &nfields) != MP_MAP) return false;
  int64_t flags = 0, mt = 0, size = 0, kind = 0;
  bool saw_kind = false, saw_vid = false, saw_mt = false;
  for (int i = 0; i < 13; ++i) o[i] = 0;
  for (int64_t f = 0; f < nfields; ++f) {
    const uint8_t* ks;
    int64_t klen = 0, v = 0;
    if (!mp_str(m, &ks, &klen)) return false;
    if (key_is(ks, klen, "kind")) {
      if (mp_head(m, &v) != MP_INT) return false;
      kind = v;
      saw_kind = true;
    } else if (key_is(ks, klen, "vid")) {
      const uint8_t* s;
      int64_t len;
      if (!mp_str(m, &s, &len)) return false;
      o[3] = s - base;
      o[4] = len;
      saw_vid = true;
    } else if (key_is(ks, klen, "mt")) {
      if (mp_head(m, &v) != MP_INT) return false;
      mt = v;
      saw_mt = true;
    } else if (key_is(ks, klen, "ddir")) {
      const uint8_t* s;
      int64_t len;
      if (!mp_str(m, &s, &len)) return false;
      o[5] = s - base;
      o[6] = len;
    } else if (key_is(ks, klen, "size")) {
      if (mp_head(m, &v) != MP_INT) return false;
      size = v;
    } else if (key_is(ks, klen, "inline")) {
      MpType t = mp_head(m, &v);
      if (t != MP_BOOL && t != MP_NIL) return false;
      if (t == MP_BOOL && v) flags |= MSCAN_FLAG_INLINE;
    } else if (key_is(ks, klen, "meta")) {
      int64_t nm = 0;
      if (mp_head(m, &nm) != MP_MAP) return false;
      for (int64_t j = 0; j < nm; ++j) {
        const uint8_t* ms;
        int64_t mlen = 0;
        if (!mp_str(m, &ms, &mlen)) return false;
        int slot = -1;
        if (key_is(ms, mlen, "etag")) slot = 7;
        else if (key_is(ms, mlen, "content-type")) slot = 9;
        else if (key_is(ms, mlen, "x-amz-tagging")) slot = 11;
        if (slot < 0) {
          flags |= MSCAN_FLAG_EXTRA;       // key beyond the captured set
          if (!mp_skip(m)) return false;
          continue;
        }
        const uint8_t* vs;
        int64_t vlen = 0;
        Mp save = *m;
        if (!mp_str(m, &vs, &vlen)) {
          // Captured key with a non-string value: keep parsing (the
          // Python path will rebuild it), but flag the summary as
          // insufficient.
          *m = save;
          if (!mp_skip(m)) return false;
          flags |= MSCAN_FLAG_EXTRA;
          continue;
        }
        o[slot] = vs - base;
        o[slot + 1] = vlen;
      }
    } else {
      // parts / ec / future keys: skipped, same as the Python reader.
      if (!mp_skip(m)) return false;
    }
  }
  if (!saw_kind || !saw_vid || !saw_mt) return false;
  if (kind == 2) flags |= MSCAN_FLAG_DELETED;
  else if (kind != 1) return false;
  o[0] = flags;
  o[1] = mt;
  o[2] = size;
  return true;
}

int64_t scan_one(const uint8_t* blob, size_t len, const uint8_t* base,
                 int64_t maxv, int64_t* out) {
  const int64_t stride_v = 13;
  out[0] = -1;
  out[1] = 0;
  if (len < 4 || std::memcmp(blob, "XTP1", 4) != 0) return -1;
  Mp m{blob + 4, blob + len};
  int64_t ntop = 0;
  if (mp_head(&m, &ntop) != MP_MAP) return -1;
  int64_t nver = -1;
  for (int64_t t = 0; t < ntop; ++t) {
    const uint8_t* ks;
    int64_t klen = 0;
    if (!mp_str(&m, &ks, &klen)) return -1;
    if (key_is(ks, klen, "versions")) {
      if (mp_head(&m, &nver) != MP_ARR) return -1;
      out[1] = nver;
      if (nver > maxv) { out[0] = -2; return -2; }
      for (int64_t v = 0; v < nver; ++v)
        if (!scan_version(&m, base, out + 2 + stride_v * v)) return -1;
    } else {
      if (!mp_skip(&m)) return -1;
    }
  }
  if (nver < 0) return -1;
  out[0] = 0;
  return 0;
}

}  // namespace

int64_t mtpu_meta_scan(const uint8_t* buf, const int64_t* offs,
                       int64_t nblobs, int64_t maxv, int64_t* out) {
  const int64_t stride = 2 + 13 * maxv;
  int64_t okcnt = 0;
  for (int64_t i = 0; i < nblobs; ++i) {
    const int64_t lo = offs[i], hi = offs[i + 1];
    int64_t* rec = out + i * stride;
    if (lo < 0 || hi < lo) {
      rec[0] = -1;
      rec[1] = 0;
      continue;
    }
    if (scan_one(buf + lo, size_t(hi - lo), buf, maxv, rec) == 0) ++okcnt;
  }
  return okcnt;
}

// ---------------------------------------------------------------------------
// Content digests: MD5 / SHA-1 / SHA-256 / CRC32 streaming contexts
// ---------------------------------------------------------------------------
//
// The per-request etag (md5), declared x-amz-checksum-* values, and the
// SigV4 content sha all walk the full body in Python today — each walk
// a GIL-held pass over bytes the staged codec pipeline already owns.
// These contexts are the digest stage of the fused transform call
// (mtpu_transform_frame below) and are also exposed directly so
// streaming paths (windowed PUT md5, SigV4 payload sha) can update
// GIL-free per window. Context layout is opaque to Python: a fixed
// 128-byte buffer per stream (state + bit count + block remainder).
//
// Implementations are from the public specs (RFC 1321, RFC 3174,
// FIPS 180-4, IEEE CRC-32); byte-validated against hashlib/zlib in
// tests/test_transform_fused.py.

namespace {

inline uint32_t Rotl32d(uint32_t x, int c) {
  return (x << c) | (x >> (32 - c));
}

// -- MD5 --------------------------------------------------------------------

struct Md5Ctx {
  uint32_t h[4];
  uint64_t n;          // total bytes fed
  uint8_t buf[64];     // carry block (n % 64 valid bytes)
};

const uint32_t kMd5K[64] = {
    0xd76aa478, 0xe8c7b756, 0x242070db, 0xc1bdceee, 0xf57c0faf, 0x4787c62a,
    0xa8304613, 0xfd469501, 0x698098d8, 0x8b44f7af, 0xffff5bb1, 0x895cd7be,
    0x6b901122, 0xfd987193, 0xa679438e, 0x49b40821, 0xf61e2562, 0xc040b340,
    0x265e5a51, 0xe9b6c7aa, 0xd62f105d, 0x02441453, 0xd8a1e681, 0xe7d3fbc8,
    0x21e1cde6, 0xc33707d6, 0xf4d50d87, 0x455a14ed, 0xa9e3e905, 0xfcefa3f8,
    0x676f02d9, 0x8d2a4c8a, 0xfffa3942, 0x8771f681, 0x6d9d6122, 0xfde5380c,
    0xa4beea44, 0x4bdecfa9, 0xf6bb4b60, 0xbebfbc70, 0x289b7ec6, 0xeaa127fa,
    0xd4ef3085, 0x04881d05, 0xd9d4d039, 0xe6db99e5, 0x1fa27cf8, 0xc4ac5665,
    0xf4292244, 0x432aff97, 0xab9423a7, 0xfc93a039, 0x655b59c3, 0x8f0ccc92,
    0xffeff47d, 0x85845dd1, 0x6fa87e4f, 0xfe2ce6e0, 0xa3014314, 0x4e0811a1,
    0xf7537e82, 0xbd3af235, 0x2ad7d2bb, 0xeb86d391};

const int kMd5S[64] = {7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
                       7, 12, 17, 22, 5, 9, 14, 20, 5, 9, 14, 20,
                       5, 9, 14, 20, 5, 9, 14, 20, 4, 11, 16, 23,
                       4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
                       6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21,
                       6, 10, 15, 21};

void Md5Block(Md5Ctx* c, const uint8_t* p) {
  uint32_t M[16];
  for (int i = 0; i < 16; ++i) std::memcpy(&M[i], p + 4 * i, 4);  // LE host
  uint32_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3];
  for (int i = 0; i < 64; ++i) {
    uint32_t f;
    int g;
    if (i < 16) {
      f = (b & cc) | (~b & d);
      g = i;
    } else if (i < 32) {
      f = (d & b) | (~d & cc);
      g = (5 * i + 1) & 15;
    } else if (i < 48) {
      f = b ^ cc ^ d;
      g = (3 * i + 5) & 15;
    } else {
      f = cc ^ (b | ~d);
      g = (7 * i) & 15;
    }
    const uint32_t tmp = d;
    d = cc;
    cc = b;
    b = b + Rotl32d(a + f + kMd5K[i] + M[g], kMd5S[i]);
    a = tmp;
  }
  c->h[0] += a;
  c->h[1] += b;
  c->h[2] += cc;
  c->h[3] += d;
}

void Md5Init(Md5Ctx* c) {
  c->h[0] = 0x67452301;
  c->h[1] = 0xefcdab89;
  c->h[2] = 0x98badcfe;
  c->h[3] = 0x10325476;
  c->n = 0;
}

void Md5Update(Md5Ctx* c, const uint8_t* p, size_t len) {
  size_t fill = size_t(c->n % 64);
  c->n += len;
  if (fill) {
    const size_t take = 64 - fill < len ? 64 - fill : len;
    std::memcpy(c->buf + fill, p, take);
    p += take;
    len -= take;
    fill += take;
    if (fill < 64) return;
    Md5Block(c, c->buf);
  }
  for (; len >= 64; p += 64, len -= 64) Md5Block(c, p);
  if (len) std::memcpy(c->buf, p, len);
}

void Md5Final(Md5Ctx* c, uint8_t* out16) {
  const uint64_t bits = c->n * 8;
  uint8_t pad[72] = {0x80};
  const size_t fill = size_t(c->n % 64);
  const size_t padlen = (fill < 56 ? 56 : 120) - fill;
  Md5Update(c, pad, padlen);
  uint8_t lenb[8];
  std::memcpy(lenb, &bits, 8);  // little-endian length
  Md5Update(c, lenb, 8);
  std::memcpy(out16, c->h, 16);  // little-endian words
}

// -- SHA-256 ----------------------------------------------------------------

struct Sha256Ctx {
  uint32_t h[8];
  uint64_t n;
  uint8_t buf[64];
};

const uint32_t kSha256K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t Be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

inline void PutBe32(uint8_t* p, uint32_t v) {
  p[0] = uint8_t(v >> 24);
  p[1] = uint8_t(v >> 16);
  p[2] = uint8_t(v >> 8);
  p[3] = uint8_t(v);
}

inline uint32_t Rotr32(uint32_t x, int c) {
  return (x >> c) | (x << (32 - c));
}

void Sha256Block(Sha256Ctx* c, const uint8_t* p) {
  uint32_t w[64];
  for (int i = 0; i < 16; ++i) w[i] = Be32(p + 4 * i);
  for (int i = 16; i < 64; ++i) {
    const uint32_t s0 = Rotr32(w[i - 15], 7) ^ Rotr32(w[i - 15], 18) ^
                        (w[i - 15] >> 3);
    const uint32_t s1 = Rotr32(w[i - 2], 17) ^ Rotr32(w[i - 2], 19) ^
                        (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3], e = c->h[4],
           f = c->h[5], g = c->h[6], h = c->h[7];
  for (int i = 0; i < 64; ++i) {
    const uint32_t S1 = Rotr32(e, 6) ^ Rotr32(e, 11) ^ Rotr32(e, 25);
    const uint32_t ch = (e & f) ^ (~e & g);
    const uint32_t t1 = h + S1 + ch + kSha256K[i] + w[i];
    const uint32_t S0 = Rotr32(a, 2) ^ Rotr32(a, 13) ^ Rotr32(a, 22);
    const uint32_t maj = (a & b) ^ (a & cc) ^ (b & cc);
    const uint32_t t2 = S0 + maj;
    h = g;
    g = f;
    f = e;
    e = d + t1;
    d = cc;
    cc = b;
    b = a;
    a = t1 + t2;
  }
  c->h[0] += a;
  c->h[1] += b;
  c->h[2] += cc;
  c->h[3] += d;
  c->h[4] += e;
  c->h[5] += f;
  c->h[6] += g;
  c->h[7] += h;
}

void Sha256Init(Sha256Ctx* c) {
  const uint32_t iv[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                          0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  std::memcpy(c->h, iv, sizeof(iv));
  c->n = 0;
}

void Sha256Update(Sha256Ctx* c, const uint8_t* p, size_t len) {
  size_t fill = size_t(c->n % 64);
  c->n += len;
  if (fill) {
    const size_t take = 64 - fill < len ? 64 - fill : len;
    std::memcpy(c->buf + fill, p, take);
    p += take;
    len -= take;
    fill += take;
    if (fill < 64) return;
    Sha256Block(c, c->buf);
  }
  for (; len >= 64; p += 64, len -= 64) Sha256Block(c, p);
  if (len) std::memcpy(c->buf, p, len);
}

void Sha256Final(Sha256Ctx* c, uint8_t* out32) {
  const uint64_t bits = c->n * 8;
  uint8_t pad[72] = {0x80};
  const size_t fill = size_t(c->n % 64);
  const size_t padlen = (fill < 56 ? 56 : 120) - fill;
  Sha256Update(c, pad, padlen);
  uint8_t lenb[8];
  for (int i = 0; i < 8; ++i) lenb[i] = uint8_t(bits >> (56 - 8 * i));
  Sha256Update(c, lenb, 8);
  for (int i = 0; i < 8; ++i) PutBe32(out32 + 4 * i, c->h[i]);
}

// -- SHA-1 ------------------------------------------------------------------

struct Sha1Ctx {
  uint32_t h[5];
  uint64_t n;
  uint8_t buf[64];
};

void Sha1Block(Sha1Ctx* c, const uint8_t* p) {
  uint32_t w[80];
  for (int i = 0; i < 16; ++i) w[i] = Be32(p + 4 * i);
  for (int i = 16; i < 80; ++i)
    w[i] = Rotl32d(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1);
  uint32_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3], e = c->h[4];
  for (int i = 0; i < 80; ++i) {
    uint32_t f, k;
    if (i < 20) {
      f = (b & cc) | (~b & d);
      k = 0x5a827999;
    } else if (i < 40) {
      f = b ^ cc ^ d;
      k = 0x6ed9eba1;
    } else if (i < 60) {
      f = (b & cc) | (b & d) | (cc & d);
      k = 0x8f1bbcdc;
    } else {
      f = b ^ cc ^ d;
      k = 0xca62c1d6;
    }
    const uint32_t tmp = Rotl32d(a, 5) + f + e + k + w[i];
    e = d;
    d = cc;
    cc = Rotl32d(b, 30);
    b = a;
    a = tmp;
  }
  c->h[0] += a;
  c->h[1] += b;
  c->h[2] += cc;
  c->h[3] += d;
  c->h[4] += e;
}

void Sha1Init(Sha1Ctx* c) {
  c->h[0] = 0x67452301;
  c->h[1] = 0xefcdab89;
  c->h[2] = 0x98badcfe;
  c->h[3] = 0x10325476;
  c->h[4] = 0xc3d2e1f0;
  c->n = 0;
}

void Sha1Update(Sha1Ctx* c, const uint8_t* p, size_t len) {
  size_t fill = size_t(c->n % 64);
  c->n += len;
  if (fill) {
    const size_t take = 64 - fill < len ? 64 - fill : len;
    std::memcpy(c->buf + fill, p, take);
    p += take;
    len -= take;
    fill += take;
    if (fill < 64) return;
    Sha1Block(c, c->buf);
  }
  for (; len >= 64; p += 64, len -= 64) Sha1Block(c, p);
  if (len) std::memcpy(c->buf, p, len);
}

void Sha1Final(Sha1Ctx* c, uint8_t* out20) {
  const uint64_t bits = c->n * 8;
  uint8_t pad[72] = {0x80};
  const size_t fill = size_t(c->n % 64);
  const size_t padlen = (fill < 56 ? 56 : 120) - fill;
  Sha1Update(c, pad, padlen);
  uint8_t lenb[8];
  for (int i = 0; i < 8; ++i) lenb[i] = uint8_t(bits >> (56 - 8 * i));
  Sha1Update(c, lenb, 8);
  for (int i = 0; i < 5; ++i) PutBe32(out20 + 4 * i, c->h[i]);
}

// -- CRC32 (IEEE 802.3, reflected, poly 0xEDB88320) -------------------------

uint32_t kCrcTab[256];
std::once_flag kCrcOnce;

void CrcInit() {
  std::call_once(kCrcOnce, [] {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int b = 0; b < 8; ++b)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      kCrcTab[i] = c;
    }
  });
}

uint32_t Crc32Run(uint32_t crc, const uint8_t* p, size_t len) {
  CrcInit();
  crc = ~crc;
  for (size_t i = 0; i < len; ++i)
    crc = kCrcTab[(crc ^ p[i]) & 0xff] ^ (crc >> 8);
  return ~crc;
}

}  // namespace

// Opaque streaming contexts (ctx = caller-owned 128-byte buffer).
// algo: 0 md5, 1 sha256, 2 sha1. Final writes the digest (16/32/20
// bytes) and leaves the context reusable only after a fresh init.

void mtpu_digest_init(int64_t algo, uint8_t* ctx) {
  if (algo == 0) Md5Init(reinterpret_cast<Md5Ctx*>(ctx));
  else if (algo == 1) Sha256Init(reinterpret_cast<Sha256Ctx*>(ctx));
  else if (algo == 2) Sha1Init(reinterpret_cast<Sha1Ctx*>(ctx));
}

void mtpu_digest_update(int64_t algo, uint8_t* ctx, const uint8_t* p,
                        size_t len) {
  if (algo == 0) Md5Update(reinterpret_cast<Md5Ctx*>(ctx), p, len);
  else if (algo == 1) Sha256Update(reinterpret_cast<Sha256Ctx*>(ctx), p, len);
  else if (algo == 2) Sha1Update(reinterpret_cast<Sha1Ctx*>(ctx), p, len);
}

void mtpu_digest_final(int64_t algo, uint8_t* ctx, uint8_t* out) {
  if (algo == 0) Md5Final(reinterpret_cast<Md5Ctx*>(ctx), out);
  else if (algo == 1) Sha256Final(reinterpret_cast<Sha256Ctx*>(ctx), out);
  else if (algo == 2) Sha1Final(reinterpret_cast<Sha1Ctx*>(ctx), out);
}

uint32_t mtpu_crc32(uint32_t crc, const uint8_t* p, size_t len) {
  return Crc32Run(crc, p, len);
}

// ---------------------------------------------------------------------------
// AES-256-GCM (FIPS 197 + NIST SP 800-38D)
// ---------------------------------------------------------------------------
//
// The DARE data-at-rest packages (crypto/dare.py) and the KMS key
// sealing are AES-256-GCM; without this the whole SSE surface needed
// the optional `cryptography` wheel AND paid a Python call per 64 KiB
// package. Portable scalar implementation is the source of truth;
// AES-NI (4-wide CTR) and PCLMUL (GHASH) fast paths are VALIDATED
// against the scalar code at init (same pattern as the GFNI affine
// check above) and disabled on any mismatch, so correctness never
// depends on hand-written intrinsics. GCM is deterministic, so a
// correct implementation is byte-identical to `cryptography`'s.

namespace {

uint8_t kAesSbox[256];
std::once_flag kAesOnce;

inline uint8_t Rotl8(uint8_t x, int c) {
  return uint8_t((x << c) | (x >> (8 - c)));
}

void AesSboxInit() {
  // Canonical Rijndael S-box generation (multiplicative inverse in
  // GF(2^8)/0x11b followed by the affine transform), using 3 as the
  // field generator so p runs the whole group while q tracks 1/p.
  uint8_t p = 1, q = 1;
  do {
    p = uint8_t(p ^ (p << 1) ^ ((p & 0x80) ? 0x1B : 0));
    q ^= uint8_t(q << 1);
    q ^= uint8_t(q << 2);
    q ^= uint8_t(q << 4);
    if (q & 0x80) q ^= 0x09;
    kAesSbox[p] = uint8_t(q ^ Rotl8(q, 1) ^ Rotl8(q, 2) ^ Rotl8(q, 3) ^
                          Rotl8(q, 4) ^ 0x63);
  } while (p != 1);
  kAesSbox[0] = 0x63;
}

struct AesKey {
  uint8_t rk[15][16];  // AES-256: 14 rounds + initial
};

inline uint8_t Xtime(uint8_t x) {
  return uint8_t((x << 1) ^ ((x & 0x80) ? 0x1b : 0));
}

void AesExpand256(const uint8_t key[32], AesKey* ak) {
  uint8_t w[60][4];
  std::memcpy(w, key, 32);
  uint8_t rcon = 1;
  for (int i = 8; i < 60; ++i) {
    uint8_t t[4] = {w[i - 1][0], w[i - 1][1], w[i - 1][2], w[i - 1][3]};
    if (i % 8 == 0) {
      const uint8_t tmp = t[0];
      t[0] = uint8_t(kAesSbox[t[1]] ^ rcon);
      t[1] = kAesSbox[t[2]];
      t[2] = kAesSbox[t[3]];
      t[3] = kAesSbox[tmp];
      rcon = Xtime(rcon);
    } else if (i % 8 == 4) {
      for (int j = 0; j < 4; ++j) t[j] = kAesSbox[t[j]];
    }
    for (int j = 0; j < 4; ++j) w[i][j] = uint8_t(w[i - 8][j] ^ t[j]);
  }
  std::memcpy(ak->rk, w, 240);
}

// Portable block encrypt; state in standard byte order (state[4c + r]
// is row r col c in FIPS 197 terms == plain byte order).
void AesEncryptPortable(const AesKey& ak, const uint8_t in[16],
                        uint8_t out[16]) {
  uint8_t s[16];
  for (int i = 0; i < 16; ++i) s[i] = uint8_t(in[i] ^ ak.rk[0][i]);
  for (int round = 1; round <= 14; ++round) {
    uint8_t t[16];
    // SubBytes + ShiftRows: byte at column c row r comes from column
    // (c + r) % 4 row r.
    for (int c = 0; c < 4; ++c)
      for (int r = 0; r < 4; ++r)
        t[4 * c + r] = kAesSbox[s[4 * ((c + r) & 3) + r]];
    if (round < 14) {
      for (int c = 0; c < 4; ++c) {
        const uint8_t a0 = t[4 * c], a1 = t[4 * c + 1], a2 = t[4 * c + 2],
                      a3 = t[4 * c + 3];
        s[4 * c] = uint8_t(Xtime(a0) ^ (Xtime(a1) ^ a1) ^ a2 ^ a3);
        s[4 * c + 1] = uint8_t(a0 ^ Xtime(a1) ^ (Xtime(a2) ^ a2) ^ a3);
        s[4 * c + 2] = uint8_t(a0 ^ a1 ^ Xtime(a2) ^ (Xtime(a3) ^ a3));
        s[4 * c + 3] = uint8_t((Xtime(a0) ^ a0) ^ a1 ^ a2 ^ Xtime(a3));
      }
    } else {
      std::memcpy(s, t, 16);
    }
    for (int i = 0; i < 16; ++i) s[i] ^= ak.rk[round][i];
  }
  std::memcpy(out, s, 16);
}

// GF(2^128) multiply per NIST SP 800-38D (bit 0 = MSB of byte 0).
struct U128 {
  uint64_t hi, lo;  // hi = bytes 0..7 big-endian, lo = bytes 8..15
};

inline U128 LoadBe128(const uint8_t* p) {
  U128 v{0, 0};
  for (int i = 0; i < 8; ++i) v.hi = (v.hi << 8) | p[i];
  for (int i = 8; i < 16; ++i) v.lo = (v.lo << 8) | p[i];
  return v;
}

inline void StoreBe128(U128 v, uint8_t* p) {
  for (int i = 0; i < 8; ++i) p[i] = uint8_t(v.hi >> (56 - 8 * i));
  for (int i = 0; i < 8; ++i) p[8 + i] = uint8_t(v.lo >> (56 - 8 * i));
}

U128 GfMul128(U128 X, U128 H) {
  U128 Z{0, 0}, V = H;
  for (int half = 0; half < 2; ++half) {
    const uint64_t bits = half ? X.lo : X.hi;
    for (int i = 0; i < 64; ++i) {
      if (bits & (1ULL << (63 - i))) {
        Z.hi ^= V.hi;
        Z.lo ^= V.lo;
      }
      const bool lsb = V.lo & 1;
      V.lo = (V.lo >> 1) | (V.hi << 63);
      V.hi >>= 1;
      if (lsb) V.hi ^= 0xe100000000000000ULL;
    }
  }
  return Z;
}

// Shoup 8-bit table: M[b] = (b in the top byte position) * H. Built
// once per GCM call (4 KiB, ~256 shifts) and amortized over the whole
// window — the scalar GHASH then costs 16 lookups per block instead of
// 128 shift-and-conditional-xor rounds.
struct GhashTab {
  U128 M[256];
  U128 R[256];  // reduction of the byte shifted out low
};

void BuildGhashTab(U128 H, GhashTab* t) {
  t->M[0] = U128{0, 0};
  t->M[0x80] = H;
  // M[i>>1] = M[i] * x (right shift in this bit order).
  for (int i = 0x80; i > 1; i >>= 1) {
    U128 v = t->M[i];
    const bool lsb = v.lo & 1;
    v.lo = (v.lo >> 1) | (v.hi << 63);
    v.hi >>= 1;
    if (lsb) v.hi ^= 0xe100000000000000ULL;
    t->M[i >> 1] = v;
  }
  for (int i = 2; i < 256; i <<= 1)
    for (int j = 1; j < i; ++j) {
      t->M[i + j].hi = t->M[i].hi ^ t->M[j].hi;
      t->M[i + j].lo = t->M[i].lo ^ t->M[j].lo;
    }
  // R[b]: contribution of byte b shifted out past x^127 during the
  // byte-wise walk. Bit (1 << i) of the last byte is the coefficient
  // of x^(127-i); after the *x^8 step it is x^(135-i) =
  // x^(7-i) * (x^128 mod p) — x^128 mod p is the element 0xe1 at byte
  // 0, and multiplying by x^(7-i) is (7-i) right shifts (which can
  // never re-reduce at shift <= 7).
  for (int b = 0; b < 256; ++b) {
    U128 acc{0, 0};
    for (int i = 0; i < 8; ++i) {
      if (b & (1 << i)) {
        U128 v{0xe100000000000000ULL, 0};
        for (int s = 0; s < 7 - i; ++s) {
          const bool lsb = v.lo & 1;
          v.lo = (v.lo >> 1) | (v.hi << 63);
          v.hi >>= 1;
          if (lsb) v.hi ^= 0xe100000000000000ULL;
        }
        acc.hi ^= v.hi;
        acc.lo ^= v.lo;
      }
    }
    t->R[b] = acc;
  }
}

// Z = Z * H using the byte table: walk bytes low to high, shifting Z
// right by 8 each step and folding the shifted-out byte back via R.
U128 GfMulTab(U128 Z, const GhashTab& t) {
  U128 acc{0, 0};
  for (int i = 15; i >= 0; --i) {
    const uint8_t b =
        i < 8 ? uint8_t(Z.hi >> (56 - 8 * i)) : uint8_t(Z.lo >> (120 - 8 * i));
    // acc = acc * x^8 + M[b] ... walking from the LAST byte: first
    // shift acc right by 8 (multiply by x^8) with reduction, then add
    // byte b's row.
    if (i != 15) {
      const uint8_t out = uint8_t(acc.lo & 0xff);
      acc.lo = (acc.lo >> 8) | (acc.hi << 56);
      acc.hi >>= 8;
      acc.hi ^= t.R[out].hi;
      acc.lo ^= t.R[out].lo;
    }
    acc.hi ^= t.M[b].hi;
    acc.lo ^= t.M[b].lo;
  }
  return acc;
}

#if defined(__AES__) && defined(__SSSE3__)
#define MTPU_AESNI 1
bool kAesniOk = false;

inline __m128i AesniEncrypt(const AesKey& ak, __m128i block) {
  block = _mm_xor_si128(
      block, _mm_loadu_si128(reinterpret_cast<const __m128i*>(ak.rk[0])));
  for (int r = 1; r < 14; ++r)
    block = _mm_aesenc_si128(
        block, _mm_loadu_si128(reinterpret_cast<const __m128i*>(ak.rk[r])));
  return _mm_aesenclast_si128(
      block, _mm_loadu_si128(reinterpret_cast<const __m128i*>(ak.rk[14])));
}
#endif

#if defined(__PCLMUL__) && defined(__SSE2__)
#define MTPU_PCLMUL 1
bool kClmulOk = false;

// Carry-less GF(2^128) multiply with GCM's reflected bit order (Intel
// CLMUL white-paper shift+reduce formulation). Operands/results are
// U128 (big-endian halves) to share the scalar interface.
inline __m128i U128ToVec(U128 v) {
  // Reverse to little-endian byte order for the vector math.
  uint8_t b[16];
  StoreBe128(v, b);
  const __m128i raw = _mm_loadu_si128(reinterpret_cast<const __m128i*>(b));
  const __m128i rev = _mm_setr_epi8(15, 14, 13, 12, 11, 10, 9, 8, 7, 6, 5,
                                    4, 3, 2, 1, 0);
  return _mm_shuffle_epi8(raw, rev);
}

inline U128 VecToU128(__m128i v) {
  const __m128i rev = _mm_setr_epi8(15, 14, 13, 12, 11, 10, 9, 8, 7, 6, 5,
                                    4, 3, 2, 1, 0);
  uint8_t b[16];
  _mm_storeu_si128(reinterpret_cast<__m128i*>(b), _mm_shuffle_epi8(v, rev));
  return LoadBe128(b);
}

// Core multiply on already-reversed (little-endian bit-reflected)
// operands; kept free of scalar conversions so the GHASH inner loop
// stays entirely in registers.
inline __m128i GfMulVec(__m128i a, __m128i b) {
  __m128i tmp3 = _mm_clmulepi64_si128(a, b, 0x00);
  __m128i tmp4 = _mm_clmulepi64_si128(a, b, 0x10);
  __m128i tmp5 = _mm_clmulepi64_si128(a, b, 0x01);
  __m128i tmp6 = _mm_clmulepi64_si128(a, b, 0x11);
  tmp4 = _mm_xor_si128(tmp4, tmp5);
  tmp5 = _mm_slli_si128(tmp4, 8);
  tmp4 = _mm_srli_si128(tmp4, 8);
  tmp3 = _mm_xor_si128(tmp3, tmp5);
  tmp6 = _mm_xor_si128(tmp6, tmp4);
  __m128i tmp7 = _mm_srli_epi32(tmp3, 31);
  __m128i tmp8 = _mm_srli_epi32(tmp6, 31);
  tmp3 = _mm_slli_epi32(tmp3, 1);
  tmp6 = _mm_slli_epi32(tmp6, 1);
  __m128i tmp9 = _mm_srli_si128(tmp7, 12);
  tmp8 = _mm_slli_si128(tmp8, 4);
  tmp7 = _mm_slli_si128(tmp7, 4);
  tmp3 = _mm_or_si128(tmp3, tmp7);
  tmp6 = _mm_or_si128(tmp6, tmp8);
  tmp6 = _mm_or_si128(tmp6, tmp9);
  tmp7 = _mm_slli_epi32(tmp3, 31);
  tmp8 = _mm_slli_epi32(tmp3, 30);
  tmp9 = _mm_slli_epi32(tmp3, 25);
  tmp7 = _mm_xor_si128(tmp7, tmp8);
  tmp7 = _mm_xor_si128(tmp7, tmp9);
  tmp8 = _mm_srli_si128(tmp7, 4);
  tmp7 = _mm_slli_si128(tmp7, 12);
  tmp3 = _mm_xor_si128(tmp3, tmp7);
  __m128i tmp2 = _mm_srli_epi32(tmp3, 1);
  tmp4 = _mm_srli_epi32(tmp3, 2);
  tmp5 = _mm_srli_epi32(tmp3, 7);
  tmp2 = _mm_xor_si128(tmp2, tmp4);
  tmp2 = _mm_xor_si128(tmp2, tmp5);
  tmp2 = _mm_xor_si128(tmp2, tmp8);
  tmp3 = _mm_xor_si128(tmp3, tmp2);
  tmp6 = _mm_xor_si128(tmp6, tmp3);
  return tmp6;
}

U128 GfMulClmul(U128 Xs, U128 Hs) {
  return VecToU128(GfMulVec(U128ToVec(Xs), U128ToVec(Hs)));
}

inline __m128i RevMask() {
  return _mm_setr_epi8(15, 14, 13, 12, 11, 10, 9, 8, 7, 6, 5, 4, 3, 2, 1,
                       0);
}
#endif

std::once_flag kGcmOnce;

void GcmInit() {
  std::call_once(kGcmOnce, [] {
    AesSboxInit();
    CrcInit();
    // Validate the intrinsic fast paths against the scalar truth with
    // arbitrary operands; any mismatch disables that path for the
    // process lifetime.
    AesKey ak;
    uint8_t key[32], blk[16], want[16];
    for (int i = 0; i < 32; ++i) key[i] = uint8_t(7 * i + 3);
    for (int i = 0; i < 16; ++i) blk[i] = uint8_t(31 * i + 11);
    AesExpand256(key, &ak);
    AesEncryptPortable(ak, blk, want);
#ifdef MTPU_AESNI
    {
      uint8_t got[16];
      const __m128i v = AesniEncrypt(
          ak, _mm_loadu_si128(reinterpret_cast<const __m128i*>(blk)));
      _mm_storeu_si128(reinterpret_cast<__m128i*>(got), v);
      kAesniOk = std::memcmp(got, want, 16) == 0;
    }
#endif
    U128 x{0x0123456789abcdefULL, 0xfedcba9876543210ULL};
    U128 h{0xdeadbeefcafef00dULL, 0x0badc0ffee15deadULL};
    const U128 ref = GfMul128(x, h);
    GhashTab tab;
    BuildGhashTab(h, &tab);
    const U128 tv = GfMulTab(x, tab);
    if (tv.hi != ref.hi || tv.lo != ref.lo) {
      // Table path broken (should never happen): poison it so GHASH
      // falls back to the bitwise loop via the identity below.
    }
#ifdef MTPU_PCLMUL
    {
      const U128 cv = GfMulClmul(x, h);
      kClmulOk = cv.hi == ref.hi && cv.lo == ref.lo;
    }
#endif
  });
}

struct Ghash {
  U128 y{0, 0};
  U128 h;
  GhashTab tab;
  bool tab_ok = false;
#ifdef MTPU_PCLMUL
  __m128i yv, hv, hv2, hv3, hv4;
  bool vec;
#endif

  explicit Ghash(U128 hh) : h(hh) {
#ifdef MTPU_PCLMUL
    vec = kClmulOk;
    if (vec) {
      yv = _mm_setzero_si128();
      hv = U128ToVec(hh);
      // Powers of H for 4-block aggregation: the y-dependency chain
      // then runs one multiply per FOUR blocks, the other three
      // multiplies are independent and pipeline.
      hv2 = GfMulVec(hv, hv);
      hv3 = GfMulVec(hv2, hv);
      hv4 = GfMulVec(hv2, hv2);
      return;
    }
#endif
    BuildGhashTab(hh, &tab);
    // Verify the table on this key against one bitwise multiply; a
    // mismatch (never expected) demotes to the bitwise loop.
    U128 probe{0x8000000000000000ULL, 1};
    const U128 want = GfMul128(probe, hh);
    const U128 got = GfMulTab(probe, tab);
    tab_ok = want.hi == got.hi && want.lo == got.lo;
  }

  void Block(const uint8_t* p) {
#ifdef MTPU_PCLMUL
    if (vec) {
      const __m128i x = _mm_shuffle_epi8(
          _mm_loadu_si128(reinterpret_cast<const __m128i*>(p)), RevMask());
      yv = GfMulVec(_mm_xor_si128(yv, x), hv);
      return;
    }
#endif
    const U128 x = LoadBe128(p);
    y.hi ^= x.hi;
    y.lo ^= x.lo;
    y = tab_ok ? GfMulTab(y, tab) : GfMul128(y, h);
  }

  void Update(const uint8_t* p, size_t len) {
#ifdef MTPU_PCLMUL
    if (vec) {
      const __m128i rev = RevMask();
      while (len >= 64) {
        const __m128i* ip = reinterpret_cast<const __m128i*>(p);
        const __m128i x0 = _mm_shuffle_epi8(_mm_loadu_si128(ip), rev);
        const __m128i x1 = _mm_shuffle_epi8(_mm_loadu_si128(ip + 1), rev);
        const __m128i x2 = _mm_shuffle_epi8(_mm_loadu_si128(ip + 2), rev);
        const __m128i x3 = _mm_shuffle_epi8(_mm_loadu_si128(ip + 3), rev);
        // y' = (y^x0)H^4 ^ x1 H^3 ^ x2 H^2 ^ x3 H — identical to four
        // sequential Block() steps, with three of the multiplies
        // independent of the y chain.
        yv = _mm_xor_si128(
            _mm_xor_si128(GfMulVec(_mm_xor_si128(yv, x0), hv4),
                          GfMulVec(x1, hv3)),
            _mm_xor_si128(GfMulVec(x2, hv2), GfMulVec(x3, hv)));
        p += 64;
        len -= 64;
      }
    }
#endif
    for (; len >= 16; p += 16, len -= 16) Block(p);
    if (len) {
      uint8_t pad[16] = {0};
      std::memcpy(pad, p, len);
      Block(pad);
    }
  }

  void Final(uint8_t out[16]) {
#ifdef MTPU_PCLMUL
    if (vec) {
      _mm_storeu_si128(reinterpret_cast<__m128i*>(out),
                       _mm_shuffle_epi8(yv, RevMask()));
      return;
    }
#endif
    StoreBe128(y, out);
  }
};

// CTR keystream application: out = in XOR E(ctr++), ctr = 32-bit BE
// counter in bytes 12..15 of j.
void GcmCtr(const AesKey& ak, uint8_t j[16], const uint8_t* in, size_t len,
            uint8_t* out) {
  uint32_t ctr = Be32(j + 12);
#ifdef MTPU_AESNI
  if (kAesniOk) {
    // 4 independent AES chains interleaved per iteration: aesenc has
    // multi-cycle latency but single-cycle throughput, so four streams
    // keep the unit busy instead of serializing on one chain.
    while (len >= 64) {
      uint8_t cb[16];
      std::memcpy(cb, j, 12);
      __m128i b0, b1, b2, b3;
      PutBe32(cb + 12, ++ctr);
      b0 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(cb));
      PutBe32(cb + 12, ++ctr);
      b1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(cb));
      PutBe32(cb + 12, ++ctr);
      b2 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(cb));
      PutBe32(cb + 12, ++ctr);
      b3 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(cb));
      const __m128i* rk = reinterpret_cast<const __m128i*>(ak.rk);
      __m128i r0 = _mm_loadu_si128(rk);
      b0 = _mm_xor_si128(b0, r0);
      b1 = _mm_xor_si128(b1, r0);
      b2 = _mm_xor_si128(b2, r0);
      b3 = _mm_xor_si128(b3, r0);
      for (int r = 1; r < 14; ++r) {
        const __m128i rr = _mm_loadu_si128(rk + r);
        b0 = _mm_aesenc_si128(b0, rr);
        b1 = _mm_aesenc_si128(b1, rr);
        b2 = _mm_aesenc_si128(b2, rr);
        b3 = _mm_aesenc_si128(b3, rr);
      }
      const __m128i rl = _mm_loadu_si128(rk + 14);
      b0 = _mm_aesenclast_si128(b0, rl);
      b1 = _mm_aesenclast_si128(b1, rl);
      b2 = _mm_aesenclast_si128(b2, rl);
      b3 = _mm_aesenclast_si128(b3, rl);
      const __m128i* ip = reinterpret_cast<const __m128i*>(in);
      __m128i* op = reinterpret_cast<__m128i*>(out);
      _mm_storeu_si128(op, _mm_xor_si128(_mm_loadu_si128(ip), b0));
      _mm_storeu_si128(op + 1,
                       _mm_xor_si128(_mm_loadu_si128(ip + 1), b1));
      _mm_storeu_si128(op + 2,
                       _mm_xor_si128(_mm_loadu_si128(ip + 2), b2));
      _mm_storeu_si128(op + 3,
                       _mm_xor_si128(_mm_loadu_si128(ip + 3), b3));
      in += 64;
      out += 64;
      len -= 64;
    }
  }
#endif
  uint8_t cb[16], ks[16];
  std::memcpy(cb, j, 12);
  while (len) {
    ctr++;
    PutBe32(cb + 12, ctr);
#ifdef MTPU_AESNI
    if (kAesniOk) {
      const __m128i v = AesniEncrypt(
          ak, _mm_loadu_si128(reinterpret_cast<const __m128i*>(cb)));
      _mm_storeu_si128(reinterpret_cast<__m128i*>(ks), v);
    } else {
      AesEncryptPortable(ak, cb, ks);
    }
#else
    AesEncryptPortable(ak, cb, ks);
#endif
    const size_t take = len < 16 ? len : 16;
    for (size_t i = 0; i < take; ++i) out[i] = uint8_t(in[i] ^ ks[i]);
    in += take;
    out += take;
    len -= take;
  }
  PutBe32(j + 12, ctr);
}

void GcmTag(const AesKey& ak, const uint8_t iv12[12], const uint8_t* aad,
            size_t aad_len, const uint8_t* cipher, size_t clen,
            uint8_t tag[16]) {
  uint8_t zero[16] = {0}, hbytes[16];
#ifdef MTPU_AESNI
  if (kAesniOk) {
    const __m128i v = AesniEncrypt(ak, _mm_setzero_si128());
    _mm_storeu_si128(reinterpret_cast<__m128i*>(hbytes), v);
  } else {
    AesEncryptPortable(ak, zero, hbytes);
  }
#else
  AesEncryptPortable(ak, zero, hbytes);
#endif
  Ghash gh(LoadBe128(hbytes));
  gh.Update(aad, aad_len);
  gh.Update(cipher, clen);
  uint8_t lens[16];
  const uint64_t abits = uint64_t(aad_len) * 8, cbits = uint64_t(clen) * 8;
  for (int i = 0; i < 8; ++i) lens[i] = uint8_t(abits >> (56 - 8 * i));
  for (int i = 0; i < 8; ++i) lens[8 + i] = uint8_t(cbits >> (56 - 8 * i));
  gh.Block(lens);
  uint8_t s[16], j0[16];
  gh.Final(s);
  std::memcpy(j0, iv12, 12);
  j0[12] = j0[13] = j0[14] = 0;
  j0[15] = 1;
  uint8_t ek[16];
#ifdef MTPU_AESNI
  if (kAesniOk) {
    const __m128i v = AesniEncrypt(
        ak, _mm_loadu_si128(reinterpret_cast<const __m128i*>(j0)));
    _mm_storeu_si128(reinterpret_cast<__m128i*>(ek), v);
  } else {
    AesEncryptPortable(ak, j0, ek);
  }
#else
  AesEncryptPortable(ak, j0, ek);
#endif
  for (int i = 0; i < 16; ++i) tag[i] = uint8_t(s[i] ^ ek[i]);
}

void GcmSealK(const AesKey& ak, const uint8_t iv12[12], const uint8_t* aad,
              size_t aad_len, const uint8_t* plain, size_t plen,
              uint8_t* out) {
  uint8_t j0[16];
  std::memcpy(j0, iv12, 12);
  j0[12] = j0[13] = j0[14] = 0;
  j0[15] = 1;
  GcmCtr(ak, j0, plain, plen, out);
  GcmTag(ak, iv12, aad, aad_len, out, plen, out + plen);
}

int64_t GcmOpenK(const AesKey& ak, const uint8_t iv12[12], const uint8_t* aad,
                 size_t aad_len, const uint8_t* cipher, size_t clen,
                 uint8_t* out) {
  if (clen < 16) return -1;
  const size_t plen = clen - 16;
  uint8_t want[16];
  GcmTag(ak, iv12, aad, aad_len, cipher, plen, want);
  uint8_t diff = 0;
  for (int i = 0; i < 16; ++i) diff |= uint8_t(want[i] ^ cipher[plen + i]);
  if (diff) return -1;
  uint8_t j0[16];
  std::memcpy(j0, iv12, 12);
  j0[12] = j0[13] = j0[14] = 0;
  j0[15] = 1;
  GcmCtr(ak, j0, cipher, plen, out);
  return int64_t(plen);
}

}  // namespace

void mtpu_gcm_seal(const uint8_t* key32, const uint8_t* iv12,
                   const uint8_t* aad, size_t aad_len, const uint8_t* plain,
                   size_t plen, uint8_t* out) {
  GcmInit();
  AesKey ak;
  AesExpand256(key32, &ak);
  GcmSealK(ak, iv12, aad, aad_len, plain, plen, out);
}

int64_t mtpu_gcm_open(const uint8_t* key32, const uint8_t* iv12,
                      const uint8_t* aad, size_t aad_len,
                      const uint8_t* cipher, size_t clen, uint8_t* out) {
  GcmInit();
  AesKey ak;
  AesExpand256(key32, &ak);
  return GcmOpenK(ak, iv12, aad, aad_len, cipher, clen, out);
}

// ---------------------------------------------------------------------------
// DARE streams: seal/open whole windows of 64 KiB packages in one call
// ---------------------------------------------------------------------------
//
// crypto/dare.py's layout: package i (sequence first_seq + i) is
// AES-256-GCM over up to 64 KiB of plaintext, nonce = base[0:4] ||
// (be64(base[4:12]) XOR seq), AAD = be64(seq), ciphertext = chunk +
// 16-byte tag, packages concatenated with no framing. One native call
// per pooled window replaces the per-package Python loop.

namespace {

const size_t kDarePkg = 64 * 1024;
const size_t kDareTag = 16;

void DareNonce(const uint8_t base[12], uint64_t seq, uint8_t out[12]) {
  std::memcpy(out, base, 12);
  uint64_t tail = 0;
  for (int i = 0; i < 8; ++i) tail = (tail << 8) | base[4 + i];
  tail ^= seq;
  for (int i = 0; i < 8; ++i) out[4 + i] = uint8_t(tail >> (56 - 8 * i));
}

}  // namespace

// plain[0:plen] -> out[0:plen + ceil(plen/64Ki)*16]; returns bytes written.
int64_t mtpu_dare_seal(const uint8_t* key32, const uint8_t* base12,
                       uint64_t first_seq, const uint8_t* plain, size_t plen,
                       uint8_t* out) {
  GcmInit();
  AesKey ak;
  AesExpand256(key32, &ak);
  uint64_t seq = first_seq;
  uint8_t* o = out;
  size_t off = 0;
  while (off < plen) {
    const size_t chunk = plen - off < kDarePkg ? plen - off : kDarePkg;
    uint8_t nonce[12], aad[8];
    DareNonce(base12, seq, nonce);
    for (int i = 0; i < 8; ++i) aad[i] = uint8_t(seq >> (56 - 8 * i));
    GcmSealK(ak, nonce, aad, 8, plain + off, chunk, o);
    o += chunk + kDareTag;
    off += chunk;
    ++seq;
  }
  return int64_t(o - out);
}

// cipher[0:clen] = whole packages (the LAST may be short but must be a
// complete sealed package). Returns plaintext bytes written to out, or
// -(1 + bad_seq_index) when package (first_seq + index) fails
// authentication.
int64_t mtpu_dare_open(const uint8_t* key32, const uint8_t* base12,
                       uint64_t first_seq, const uint8_t* cipher, size_t clen,
                       uint8_t* out) {
  GcmInit();
  AesKey ak;
  AesExpand256(key32, &ak);
  uint64_t seq = first_seq;
  uint8_t* o = out;
  size_t off = 0;
  int64_t idx = 0;
  while (off < clen) {
    const size_t chunk =
        clen - off < kDarePkg + kDareTag ? clen - off : kDarePkg + kDareTag;
    uint8_t nonce[12], aad[8];
    DareNonce(base12, seq, nonce);
    for (int i = 0; i < 8; ++i) aad[i] = uint8_t(seq >> (56 - 8 * i));
    const int64_t got = GcmOpenK(ak, nonce, aad, 8, cipher + off, chunk, o);
    if (got < 0) return -(1 + idx);
    o += got;
    off += chunk;
    ++seq;
    ++idx;
  }
  return int64_t(o - out);
}

// ---------------------------------------------------------------------------
// Block compression (zlib deflate, crypto/compress.py's scheme)
// ---------------------------------------------------------------------------

// Deflate `data` in independent `block`-sized blocks at `level` —
// byte-identical to Python's zlib.compress(block, level) (same zlib,
// same parameters). `ends[i]` receives the cumulative compressed end
// of block i. Returns total compressed bytes, or -1 on error/overflow
// of out_cap, or -2 when built without zlib.
int64_t mtpu_deflate_blocks(const uint8_t* data, size_t len, size_t block,
                            int64_t level, uint8_t* out, size_t out_cap,
                            int64_t* ends) {
#ifdef MTPU_NO_ZLIB
  (void)data; (void)len; (void)block; (void)level; (void)out;
  (void)out_cap; (void)ends;
  return -2;
#else
  size_t total = 0;
  int64_t nb = 0;
  for (size_t off = 0; off < len; off += block) {
    const size_t chunk = len - off < block ? len - off : block;
    uLongf dst = uLongf(out_cap - total);
    if (compress2(out + total, &dst, data + off, uLong(chunk),
                  int(level)) != Z_OK)
      return -1;
    total += size_t(dst);
    ends[nb++] = int64_t(total);
  }
  return int64_t(total);
#endif
}

// Inflate stored blocks [first_block, first_block + nblocks) out of a
// stored window whose byte 0 sits at absolute stored offset
// `stored_base`. `ends` are the ABSOLUTE cumulative compressed ends
// (crypto/compress.py index). Returns plaintext bytes written, -1 on a
// corrupt block / window mismatch / overflow, -2 without zlib.
int64_t mtpu_inflate_blocks(const uint8_t* stored, size_t slen,
                            const int64_t* ends, int64_t first_block,
                            int64_t nblocks, int64_t stored_base,
                            uint8_t* out, size_t out_cap) {
#ifdef MTPU_NO_ZLIB
  (void)stored; (void)slen; (void)ends; (void)first_block; (void)nblocks;
  (void)stored_base; (void)out; (void)out_cap;
  return -2;
#else
  size_t total = 0;
  for (int64_t b = first_block; b < first_block + nblocks; ++b) {
    const int64_t lo = (b ? ends[b - 1] : 0) - stored_base;
    const int64_t hi = ends[b] - stored_base;
    if (lo < 0 || hi < lo || size_t(hi) > slen) return -1;
    uLongf dst = uLongf(out_cap - total);
    if (uncompress(out + total, &dst, stored + lo, uLong(hi - lo)) != Z_OK)
      return -1;
    total += size_t(dst);
  }
  return int64_t(total);
#endif
}

// ---------------------------------------------------------------------------
// Fused PUT transform: digest + compress + DARE + erasure frame
// ---------------------------------------------------------------------------
//
// The whole buffered-PUT data plane in ONE GIL-free call (ROADMAP item
// "single-pass device data plane"): over the request body compute the
// etag md5 and any declared checksums, deflate into the block scheme,
// seal into DARE packages, and run mtpu_put_frame over the stored
// stream's full erasure blocks — one pass over bytes the staged
// pipeline already owns, instead of a separate Python walk per stage.
//
// flags: 1 md5(logical)  2 sha256  4 sha1  8 crc32
//        16 compress     32 encrypt
//        64 frame full stored blocks via mtpu_put_frame
//        128 md5 over the STORED stream instead of the logical bytes
//            (the layered path's etag for pure-SSE objects is the md5
//            of what the object layer was handed = the ciphertext)
//
// digests layout (always 72 bytes): md5[16] sha256[32] sha1[20] crc32[4].
// scratch: required only for compress+encrypt (holds the compressed
//   stream; cap >= len + 64). stored_cap must cover the worst case
//   (encrypt_stream_size(len) when encrypting, len + 64 otherwise).
// comp_ends: cap >= ceil(len / comp_block) entries.
// info out: [0] stored_len  [1] full blocks framed  [2] compress used
//           [3] ndigest_ns [4] ncomp_ns [5] nenc_ns [6] nframe_ns
//           [7] n_comp_blocks
// Returns stored_len, or -1 on capacity/parameter error, -2 when a
// compress stage was requested without zlib.

namespace {
inline int64_t NowNs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1000000000 + ts.tv_nsec;
}
}  // namespace

int64_t mtpu_transform_frame(
    const uint8_t* data, size_t len, int64_t flags, const uint8_t* enc_key32,
    const uint8_t* nonce12, uint8_t* digests, uint8_t* stored,
    size_t stored_cap, uint8_t* scratch, size_t scratch_cap,
    int64_t* comp_ends, int64_t comp_ends_cap, size_t comp_block,
    const uint8_t* hh_key32, const uint8_t* matrix, size_t k, size_t m,
    size_t S, size_t block_size, uint8_t* framed, size_t framed_cap,
    int64_t* info) {
  GcmInit();
  const bool want_md5 = flags & 1, want_sha256 = flags & 2,
             want_sha1 = flags & 4, want_crc = flags & 8;
  const bool compress = flags & 16, encrypt = flags & 32;
  const bool frame = flags & 64, md5_stored = flags & 128;
  for (int i = 0; i < 8; ++i) info[i] = 0;
  std::memset(digests, 0, 72);
  // Stage 1: logical-byte digests. The etag md5 hashes the LOGICAL
  // bytes except for pure-SSE objects, where the layered path's etag
  // is the md5 of what the object layer was handed (the ciphertext) —
  // including the compressed-but-incompressible fallback, which the
  // post-transform recompute below covers.
  int64_t t0 = NowNs();
  const bool md5_plain_first =
      want_md5 && (!encrypt || compress) && !md5_stored;
  if (md5_plain_first) {
    Md5Ctx c;
    Md5Init(&c);
    Md5Update(&c, data, len);
    Md5Final(&c, digests);
  }
  if (want_sha256) {
    Sha256Ctx c;
    Sha256Init(&c);
    Sha256Update(&c, data, len);
    Sha256Final(&c, digests + 16);
  }
  if (want_sha1) {
    Sha1Ctx c;
    Sha1Init(&c);
    Sha1Update(&c, data, len);
    Sha1Final(&c, digests + 48);
  }
  if (want_crc) {
    const uint32_t crc = Crc32Run(0, data, len);
    PutBe32(digests + 68, crc);
  }
  int64_t t1 = NowNs();
  info[3] = t1 - t0;
  // Stage 2: compression (into scratch when encryption follows, else
  // straight into the stored buffer). Falls back to stored-uncompressed
  // when the scheme does not win (the caller reads info[2]).
  const uint8_t* body = data;
  size_t body_len = len;
  int64_t n_comp = 0;
  if (compress) {
    uint8_t* dst = encrypt ? scratch : stored;
    const size_t cap = encrypt ? scratch_cap : stored_cap;
    const int64_t nmax = comp_block ? int64_t((len + comp_block - 1) /
                                              comp_block) : 0;
    if (!comp_block || nmax > comp_ends_cap) return -1;
    const int64_t got =
        mtpu_deflate_blocks(data, len, comp_block, 6, dst, cap, comp_ends);
    if (got == -2) return -2;
    if (got >= 0 && size_t(got) < len) {
      info[2] = 1;
      n_comp = nmax;
      body = dst;
      body_len = size_t(got);
    }
    // got < 0 (overflow => incompressible beyond cap) or got >= len:
    // store uncompressed, same as crypto/compress.compress() -> None.
  }
  info[7] = n_comp;
  int64_t t2 = NowNs();
  info[4] = t2 - t1;
  // Stage 3: DARE encryption into the stored buffer.
  size_t stored_len;
  if (encrypt) {
    const size_t pkgs = body_len ? (body_len + kDarePkg - 1) / kDarePkg : 0;
    if (body_len + pkgs * kDareTag > stored_cap) return -1;
    stored_len = size_t(
        mtpu_dare_seal(enc_key32, nonce12, 0, body, body_len, stored));
  } else {
    if (body_len > stored_cap) return -1;
    if (body != stored) std::memcpy(stored, body, body_len);
    stored_len = body_len;
  }
  int64_t t3 = NowNs();
  info[5] = t3 - t2;
  if (want_md5 &&
      (md5_stored || (encrypt && !(compress && info[2])))) {
    Md5Ctx c;
    Md5Init(&c);
    Md5Update(&c, stored, stored_len);
    Md5Final(&c, digests);
    // Digest-stage accounting: the stored-md5 rides the encrypt pass.
  }
  // Stage 4: erasure frame of the stored stream's FULL blocks (the
  // ragged tail frames through the caller's split path, exactly like
  // the layered pipeline).
  size_t full = 0;
  if (frame && block_size && k && k * S == block_size) {
    full = stored_len / block_size;
    if ((k + m) * full * (32 + S) > framed_cap) return -1;
    if (full)
      mtpu_put_frame(hh_key32, matrix, stored, full, k, m, S, framed);
  }
  info[6] = NowNs() - t3;
  info[0] = int64_t(stored_len);
  info[1] = int64_t(full);
  return int64_t(stored_len);
}

// ---------------------------------------------------------------------------
// Fused GET transform: DARE open + block inflate out of one window
// ---------------------------------------------------------------------------
//
// The read-side mirror: one call per pooled stored window decrypts the
// covered DARE packages and inflates the covered compressed blocks —
// no whole-blob hop, no per-package Python loop. For the combined
// scheme the window must be package-aligned AND cover whole compressed
// blocks (the windowed reader in object/transform.py aligns it).
// flags: 16 decompress, 32 decrypt. Returns plaintext bytes written,
// -1 structural error, -2 no zlib, -(100 + i) auth failure at package
// index i.
int64_t mtpu_untransform(const uint8_t* stored, size_t slen, int64_t flags,
                         const uint8_t* key32, const uint8_t* nonce12,
                         int64_t first_seq, const int64_t* ends,
                         int64_t first_block, int64_t nblocks,
                         int64_t comp_base, uint8_t* work, size_t work_cap,
                         uint8_t* out, size_t out_cap) {
  const bool decrypt = flags & 32, decompress = flags & 16;
  const uint8_t* body = stored;
  size_t body_len = slen;
  if (decrypt) {
    uint8_t* dst = decompress ? work : out;
    const size_t cap = decompress ? work_cap : out_cap;
    const size_t pkgs =
        slen ? (slen + kDarePkg + kDareTag - 1) / (kDarePkg + kDareTag) : 0;
    if (slen < pkgs * kDareTag || slen - pkgs * kDareTag > cap) return -1;
    const int64_t got = mtpu_dare_open(key32, nonce12, uint64_t(first_seq),
                                       stored, slen, dst);
    if (got < 0) return -100 - (-got - 1);  // -(100 + bad package index)
    body = dst;
    body_len = size_t(got);
    if (!decompress) return got;
  }
  if (decompress)
    return mtpu_inflate_blocks(body, body_len, ends, first_block, nblocks,
                               comp_base, out, out_cap);
  if (body_len > out_cap) return -1;
  if (body != out) std::memcpy(out, body, body_len);
  return int64_t(body_len);
}

// Streaming PUT companion: md5-extend the window THEN frame it, one
// GIL-free call — the per-window hashlib update the streaming hot loop
// used to run on the Python side rides the same native pass as the
// encode+frame (md5ctx nullable for callers that only want framing).
void mtpu_put_frame_md5(uint8_t* md5ctx, const uint8_t* key32,
                        const uint8_t* matrix, const uint8_t* data,
                        size_t full, size_t k, size_t m, size_t S,
                        size_t nbytes, uint8_t* out) {
  if (md5ctx)
    Md5Update(reinterpret_cast<Md5Ctx*>(md5ctx), data, nbytes);
  mtpu_put_frame(key32, matrix, data, full, k, m, S, out);
}

}  // extern "C"
