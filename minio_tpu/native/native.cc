// Native data-path kernels for minio_tpu (host side).
//
// The reference gets its host performance from Go-assembly dependencies
// (AVX2/AVX512 HighwayHash in github.com/minio/highwayhash, GFNI/AVX2
// Galois kernels in klauspost/reedsolomon, assembly xxhash — SURVEY.md
// §2.7). This module is our native equivalent, compiled with -O3
// -march=native so the compiler vectorizes the hot loops; the TPU path
// (ops/rs_device.py) handles bulk stripes, this handles the host-side
// cases: bitrot hashing, small-block GF math, digests for self-tests.
//
// Exposed via a plain C ABI for ctypes (no pybind11 in the image).
//
// Implementations are from-scratch from the public algorithm specs
// (HighwayHash: github.com/google/highwayhash paper/spec; xxHash spec),
// byte-validated in tests against the reference's golden digests.

#include <cstdint>
#include <cstddef>
#include <cstring>
#include <mutex>

#if defined(__AVX2__) || (defined(__GFNI__) && defined(__AVX512F__))
#include <immintrin.h>
#endif
#if defined(__GFNI__) && defined(__AVX512F__) && defined(__AVX512BW__)
#define MTPU_GFNI 1
#endif

extern "C" {

// ---------------------------------------------------------------------------
// HighwayHash-256
// ---------------------------------------------------------------------------

namespace {

struct HHState {
  uint64_t v0[4], v1[4], mul0[4], mul1[4];
};

const uint64_t kInit0[4] = {0xdbe6d5d5fe4cce2fULL, 0xa4093822299f31d0ULL,
                            0x13198a2e03707344ULL, 0x243f6a8885a308d3ULL};
const uint64_t kInit1[4] = {0x3bd39e10cb0ef593ULL, 0xc0acf169b5f18a8cULL,
                            0xbe5466cf34e90c6cULL, 0x452821e638d01377ULL};

inline uint64_t Rot32(uint64_t x) { return (x >> 32) | (x << 32); }

inline uint64_t Le64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86/arm64)
}

inline void Reset(const uint64_t key[4], HHState* s) {
  for (int i = 0; i < 4; ++i) {
    s->v0[i] = kInit0[i] ^ key[i];
    s->v1[i] = kInit1[i] ^ Rot32(key[i]);
    s->mul0[i] = kInit0[i];
    s->mul1[i] = kInit1[i];
  }
}

inline void ZipperMergeAndAdd(uint64_t v1, uint64_t v0, uint64_t* add1,
                              uint64_t* add0) {
  *add0 += (((v0 & 0xff000000ULL) | (v1 & 0xff00000000ULL)) >> 24) |
           (((v0 & 0xff0000000000ULL) | (v1 & 0xff000000000000ULL)) >> 16) |
           (v0 & 0xff0000ULL) | ((v0 & 0xff00ULL) << 32) |
           ((v1 & 0xff00000000000000ULL) >> 8) | (v0 << 56);
  *add1 += (((v1 & 0xff000000ULL) | (v0 & 0xff00000000ULL)) >> 24) |
           (v1 & 0xff0000ULL) | ((v1 & 0xff0000000000ULL) >> 16) |
           ((v1 & 0xff00ULL) << 24) | ((v0 & 0xff000000000000ULL) >> 8) |
           ((v1 & 0xffULL) << 48) | (v0 & 0xff00000000000000ULL);
}

inline void Update(const uint64_t lanes[4], HHState* s) {
  for (int i = 0; i < 4; ++i) {
    s->v1[i] += s->mul0[i] + lanes[i];
    s->mul0[i] ^= (s->v1[i] & 0xffffffffULL) * (s->v0[i] >> 32);
    s->v0[i] += s->mul1[i];
    s->mul1[i] ^= (s->v0[i] & 0xffffffffULL) * (s->v1[i] >> 32);
  }
  ZipperMergeAndAdd(s->v1[1], s->v1[0], &s->v0[1], &s->v0[0]);
  ZipperMergeAndAdd(s->v1[3], s->v1[2], &s->v0[3], &s->v0[2]);
  ZipperMergeAndAdd(s->v0[1], s->v0[0], &s->v1[1], &s->v1[0]);
  ZipperMergeAndAdd(s->v0[3], s->v0[2], &s->v1[3], &s->v1[2]);
}

inline void UpdatePacket(const uint8_t* p, HHState* s) {
  uint64_t lanes[4] = {Le64(p), Le64(p + 8), Le64(p + 16), Le64(p + 24)};
  Update(lanes, s);
}

inline uint32_t Rol32(uint32_t x, unsigned c) {
  return c ? (x << c) | (x >> (32 - c)) : x;
}

inline void UpdateRemainder(const uint8_t* bytes, size_t size_mod32,
                            HHState* s) {
  const size_t size_mod4 = size_mod32 & 3;
  const uint8_t* remainder = bytes + (size_mod32 & ~size_t(3));
  uint8_t packet[32] = {0};
  for (int i = 0; i < 4; ++i)
    s->v0[i] += (uint64_t(size_mod32) << 32) + size_mod32;
  for (int i = 0; i < 4; ++i) {
    uint32_t lo = uint32_t(s->v1[i]), hi = uint32_t(s->v1[i] >> 32);
    s->v1[i] = (uint64_t(Rol32(hi, size_mod32)) << 32) | Rol32(lo, size_mod32);
  }
  std::memcpy(packet, bytes, size_mod32 & ~size_t(3));
  if (size_mod32 & 16) {
    for (int i = 0; i < 4; ++i)
      packet[28 + i] = remainder[i + size_mod4 - 4];
  } else if (size_mod4) {
    packet[16] = remainder[0];
    packet[17] = remainder[size_mod4 >> 1];
    packet[18] = remainder[size_mod4 - 1];
  }
  UpdatePacket(packet, s);
}

inline void Finalize256(HHState* s, uint64_t hash[4]) {
  for (int r = 0; r < 10; ++r) {
    uint64_t permuted[4] = {Rot32(s->v0[2]), Rot32(s->v0[3]),
                            Rot32(s->v0[0]), Rot32(s->v0[1])};
    Update(permuted, s);
  }
  auto mod = [](uint64_t a3u, uint64_t a2, uint64_t a1, uint64_t a0,
                uint64_t* m1, uint64_t* m0) {
    const uint64_t a3 = a3u & 0x3fffffffffffffffULL;
    *m1 = a1 ^ ((a3 << 1) | (a2 >> 63)) ^ ((a3 << 2) | (a2 >> 62));
    *m0 = a0 ^ (a2 << 1) ^ (a2 << 2);
  };
  mod(s->v1[1] + s->mul1[1], s->v1[0] + s->mul1[0], s->v0[1] + s->mul0[1],
      s->v0[0] + s->mul0[0], &hash[1], &hash[0]);
  mod(s->v1[3] + s->mul1[3], s->v1[2] + s->mul1[2], s->v0[3] + s->mul0[3],
      s->v0[2] + s->mul0[2], &hash[3], &hash[2]);
}

#ifdef __AVX2__
// The 4-lane HighwayHash state vectorizes exactly onto 256-bit
// registers: each of v0/v1/mul0/mul1 is one __m256i, the 32->64 bit
// lane multiplies are VPMULUDQ, and the zipper-merge byte permutation
// (which scalar code spells as mask-and-shift soup) is one VPSHUFB per
// 128-bit pair — the same mapping the reference's assembly dependency
// (github.com/minio/highwayhash AVX2 path) exploits. Bulk packets run
// vectorized; the ragged remainder and finalization spill to the
// byte-identical scalar state.
inline __m256i HHZipper(__m256i x) {
  const __m256i kMask = _mm256_setr_epi8(
      3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7,
      3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7);
  return _mm256_shuffle_epi8(x, kMask);
}

struct HHVec {
  __m256i v0, v1, mul0, mul1;
};

inline void UpdateVec(__m256i lanes, HHVec* s) {
  s->v1 = _mm256_add_epi64(s->v1, _mm256_add_epi64(s->mul0, lanes));
  s->mul0 = _mm256_xor_si256(
      s->mul0, _mm256_mul_epu32(s->v1, _mm256_srli_epi64(s->v0, 32)));
  s->v0 = _mm256_add_epi64(s->v0, s->mul1);
  s->mul1 = _mm256_xor_si256(
      s->mul1, _mm256_mul_epu32(s->v0, _mm256_srli_epi64(s->v1, 32)));
  s->v0 = _mm256_add_epi64(s->v0, HHZipper(s->v1));
  s->v1 = _mm256_add_epi64(s->v1, HHZipper(s->v0));
}

inline void BulkPackets(const uint8_t* data, size_t full, HHState* s) {
  HHVec v;
  v.v0 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->v0));
  v.v1 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->v1));
  v.mul0 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->mul0));
  v.mul1 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->mul1));
  for (size_t i = 0; i < full; ++i)
    UpdateVec(_mm256_loadu_si256(
                  reinterpret_cast<const __m256i*>(data + 32 * i)),
              &v);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->v0), v.v0);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->v1), v.v1);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->mul0), v.mul0);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->mul1), v.mul1);
}
#else
inline void BulkPackets(const uint8_t* data, size_t full, HHState* s) {
  for (size_t i = 0; i < full; ++i) UpdatePacket(data + 32 * i, s);
}
#endif  // __AVX2__

}  // namespace

void mtpu_hh256(const uint8_t* key32, const uint8_t* data, size_t len,
                uint8_t* out32) {
  uint64_t key[4] = {Le64(key32), Le64(key32 + 8), Le64(key32 + 16),
                     Le64(key32 + 24)};
  HHState s;
  Reset(key, &s);
  size_t full = len / 32;
  BulkPackets(data, full, &s);
  if (len % 32) UpdateRemainder(data + 32 * full, len % 32, &s);
  uint64_t hash[4];
  Finalize256(&s, hash);
  std::memcpy(out32, hash, 32);
}

// Hash `nstreams` blocks, each `len` bytes, laid out contiguously with
// byte stride `stride` (stride >= len). Out: nstreams x 32 bytes.
void mtpu_hh256_many(const uint8_t* key32, const uint8_t* data,
                     size_t nstreams, size_t stride, size_t len,
                     uint8_t* out) {
  for (size_t i = 0; i < nstreams; ++i)
    mtpu_hh256(key32, data + i * stride, len, out + 32 * i);
}

// ---------------------------------------------------------------------------
// xxHash64 (spec: cyan4973.github.io/xxHash)
// ---------------------------------------------------------------------------

namespace {
const uint64_t P1 = 0x9E3779B185EBCA87ULL, P2 = 0xC2B2AE3D27D4EB4FULL,
               P3 = 0x165667B19E3779F9ULL, P4 = 0x85EBCA77C2B2AE63ULL,
               P5 = 0x27D4EB2F165667C5ULL;
inline uint64_t Rotl64(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }
inline uint64_t XxhRound(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = Rotl64(acc, 31);
  return acc * P1;
}
inline uint64_t XxhMerge(uint64_t acc, uint64_t val) {
  acc ^= XxhRound(0, val);
  return acc * P1 + P4;
}
}  // namespace

uint64_t mtpu_xxh64(const uint8_t* p, size_t len, uint64_t seed) {
  const uint8_t* end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    do {
      v1 = XxhRound(v1, Le64(p)); p += 8;
      v2 = XxhRound(v2, Le64(p)); p += 8;
      v3 = XxhRound(v3, Le64(p)); p += 8;
      v4 = XxhRound(v4, Le64(p)); p += 8;
    } while (p + 32 <= end);
    h = Rotl64(v1, 1) + Rotl64(v2, 7) + Rotl64(v3, 12) + Rotl64(v4, 18);
    h = XxhMerge(h, v1); h = XxhMerge(h, v2);
    h = XxhMerge(h, v3); h = XxhMerge(h, v4);
  } else {
    h = seed + P5;
  }
  h += uint64_t(len);
  while (p + 8 <= end) {
    h ^= XxhRound(0, Le64(p));
    h = Rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    h ^= uint64_t(v) * P1;
    h = Rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= uint64_t(*p) * P5;
    h = Rotl64(h, 11) * P1;
    ++p;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// ---------------------------------------------------------------------------
// GF(2^8) shard transform (host fallback for small blocks)
// ---------------------------------------------------------------------------
//
// out[r][:] = XOR_j mul(matrix[r][j], shards[j][:]) using 4-bit split
// tables (the classic PSHUFB decomposition: one 16-entry table for each
// nibble), which compilers auto-vectorize well with -O3 -march=native.

namespace {
uint8_t kGfMul[256][256];
std::once_flag kGfOnce;

// ctypes releases the GIL, so concurrent first calls are real races —
// call_once publishes the fully-built table before anyone reads it.
void GfInit() {
  std::call_once(kGfOnce, [] {
    // GF(2^8) with poly 0x11d (same field as the codec).
    for (int a = 1; a < 256; ++a) {
      for (int b = 1; b < 256; ++b) {
        int x = a, y = b, acc = 0;
        while (y) {
          if (y & 1) acc ^= x;
          x <<= 1;
          if (x & 0x100) x ^= 0x11d;
          y >>= 1;
        }
        kGfMul[a][b] = uint8_t(acc);
      }
    }
  });
}
}  // namespace

#ifdef MTPU_GFNI
namespace {

// GF2P8AFFINEQB computes, per byte x of src: out bit i =
// parity(A.byte[7-i] & x) (+ imm bit). Multiplication by a constant c
// in ANY GF(2^8) representation is GF(2)-linear, so an 8x8 bit matrix
// whose column j is the byte c*x^j (field poly 0x11d here, NOT the
// instruction's native AES poly) implements mul-by-c exactly — the
// same trick the reference's dependency uses for its GFNI kernels
// (klauspost/reedsolomon galois_amd64). Row i of the matrix (bit i of
// every column) lands in qword byte 7-i.
uint64_t kGfAffine[256];
bool kGfniOk = false;
std::once_flag kAffineOnce;

void AffineInit() {
  std::call_once(kAffineOnce, [] {
    GfInit();
    for (int c = 0; c < 256; ++c) {
      uint64_t m = 0;
      for (int j = 0; j < 8; ++j) {
        const uint8_t col = c ? kGfMul[c][1 << j] : 0;  // c * x^j
        for (int i = 0; i < 8; ++i)
          if (col & (1 << i)) m |= 1ULL << ((7 - i) * 8 + j);
      }
      kGfAffine[c] = m;
    }
    // Trust nothing about bit-order conventions: validate the packed
    // matrices against the multiplication table with the instruction
    // itself before enabling the fast path.
    alignas(64) uint8_t x[64], got[64];
    for (int t = 0; t < 64; ++t) x[t] = uint8_t(4 * t + 3);
    kGfniOk = true;
    for (int c = 0; c < 256 && kGfniOk; c += 17) {
      __m512i vx = _mm512_load_si512(reinterpret_cast<const void*>(x));
      __m512i va = _mm512_set1_epi64(int64_t(kGfAffine[c]));
      _mm512_store_si512(reinterpret_cast<void*>(got),
                         _mm512_gf2p8affine_epi64_epi8(vx, va, 0));
      for (int t = 0; t < 64; ++t)
        if (got[t] != kGfMul[c][x[t]]) { kGfniOk = false; break; }
    }
  });
}

}  // namespace
#endif  // MTPU_GFNI

void mtpu_gf_apply(const uint8_t* matrix, size_t r, size_t k,
                   const uint8_t* shards, size_t stride, size_t len,
                   uint8_t* out, size_t out_stride) {
  GfInit();
#ifdef MTPU_GFNI
  AffineInit();
  if (kGfniOk) {
    // Coefficient classification and affine-matrix broadcasts are
    // loop-invariant per output row; hoist them so the 64-byte inner
    // loop is loads + affine + xor only (char aliasing otherwise stops
    // the compiler from hoisting past the output stores).
    enum : uint8_t { kSkip, kXor, kAffine };
    uint8_t cls[64];
    __m512i aff[64];
    for (size_t i = 0; i < r; ++i) {
      const size_t kk = k > 64 ? 64 : k;
      for (size_t j = 0; j < kk; ++j) {
        const uint8_t c = matrix[i * k + j];
        cls[j] = c == 0 ? kSkip : (c == 1 ? kXor : kAffine);
        aff[j] = _mm512_set1_epi64(int64_t(kGfAffine[c]));
      }
      uint8_t* dst = out + i * out_stride;
      size_t t = 0;
      if (k <= 64) {
        for (; t + 64 <= len; t += 64) {
          __m512i acc = _mm512_setzero_si512();
          for (size_t j = 0; j < k; ++j) {
            if (cls[j] == kSkip) continue;
            __m512i x = _mm512_loadu_si512(
                reinterpret_cast<const void*>(shards + j * stride + t));
            acc = _mm512_xor_si512(
                acc, cls[j] == kXor
                         ? x
                         : _mm512_gf2p8affine_epi64_epi8(x, aff[j], 0));
          }
          _mm512_storeu_si512(reinterpret_cast<void*>(dst + t), acc);
        }
      }
      for (; t < len; ++t) {
        uint8_t acc = 0;
        for (size_t j = 0; j < k; ++j)
          acc ^= kGfMul[matrix[i * k + j]][shards[j * stride + t]];
        dst[t] = acc;
      }
    }
    return;
  }
#endif  // MTPU_GFNI
  for (size_t i = 0; i < r; ++i) {
    uint8_t* dst = out + i * out_stride;
    std::memset(dst, 0, len);
    for (size_t j = 0; j < k; ++j) {
      const uint8_t c = matrix[i * k + j];
      if (c == 0) continue;
      const uint8_t* src = shards + j * stride;
      if (c == 1) {
        for (size_t t = 0; t < len; ++t) dst[t] ^= src[t];
      } else {
        // Nibble-split tables: mul(c, x) = lo[x & 15] ^ hi[x >> 4].
        uint8_t lo[16], hi[16];
        for (int v = 0; v < 16; ++v) {
          lo[v] = kGfMul[c][v];
          hi[v] = kGfMul[c][v << 4];
        }
        for (size_t t = 0; t < len; ++t)
          dst[t] ^= lo[src[t] & 15] ^ hi[src[t] >> 4];
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Fused PUT framing: GF parity + HighwayHash-256 + on-disk interleave
// ---------------------------------------------------------------------------
//
// The whole host-side PutObject hot loop in one GIL-free call: for each
// erasure block, compute the m parity rows (same coding matrix as
// mtpu_gf_apply), then emit every shard's on-disk frame
// `digest || block` directly into per-shard-file contiguous output —
// no intermediate shard tensors, no Python-side interleave copies.
//
//   data: full * k * S bytes, block-major ([full][k][S]); each block's
//         k data rows are the stripe split of one BLOCK_SIZE chunk.
//   out:  n * full * (32 + S) bytes, shard-major — shard i's framed
//         file body is out[i * full * (32+S) ..).
//
// Byte-identical to frame_shards_batch(encode(data)) by construction:
// the same GF tables produce the parity, the same HighwayHash-256
// produces the digests, and the frame layout is digest-then-block
// (reference: cmd/bitrot-streaming.go:44-75).

void mtpu_put_frame(const uint8_t* key32, const uint8_t* matrix,
                    const uint8_t* data, size_t full, size_t k, size_t m,
                    size_t S, uint8_t* out) {
  const size_t n = k + m;
  const size_t frame = 32 + S;
  const size_t shard_span = full * frame;
  for (size_t b = 0; b < full; ++b) {
    const uint8_t* block = data + b * k * S;
    // Data rows: copy into their frames.
    for (size_t j = 0; j < k; ++j)
      std::memcpy(out + j * shard_span + b * frame + 32, block + j * S, S);
    // Parity rows: GF apply straight into the output frames (the rows
    // of one block land in DIFFERENT shard files => out_stride spans
    // a whole shard file).
    if (m)
      mtpu_gf_apply(matrix, m, k, block, S, S,
                    out + k * shard_span + b * frame + 32, shard_span);
  }
  // Bitrot digests over every framed block (data + parity alike).
  for (size_t i = 0; i < n; ++i) {
    uint8_t* shard = out + i * shard_span;
    for (size_t b = 0; b < full; ++b)
      mtpu_hh256(key32, shard + b * frame + 32, S, shard + b * frame);
  }
}

// ---------------------------------------------------------------------------
// Fused GET framing: bitrot verify + block-major interleave
// ---------------------------------------------------------------------------
//
// The read-side mirror of mtpu_put_frame: one GIL-free call that takes
// the k data shards' framed byte windows (`digest || block` per erasure
// block, exactly as stored), re-hashes every block against its stored
// digest, and interleaves the verified data blocks block-major straight
// into the caller's (pooled) output buffer — replacing the GET path's
// Python-level verify -> per-slice .tobytes() -> b"".join loop.
//
//   shards:    k pointers, shard j's framed window of nb blocks. All
//              blocks carry S data bytes except the LAST, which carries
//              slast (<= S; the shard file's ragged tail when the
//              window reaches it).
//   take_full: object bytes emitted per full block (BLOCK_SIZE — the
//              k*S concatenation may exceed it by the split padding).
//   take_last: object bytes emitted for the last block (the part tail).
//
// Emission per block = min(take, k*slen), walking shards in index
// order — byte-identical to the numpy reassembly by construction.
//
// Returns a bitmask of shards whose digest verification FAILED (bit j
// = shard j); nonzero means `out` holds no usable data and the caller
// falls back to the reconstruct path, treating failed shards as
// missing. Verification runs over EVERY shard before returning so the
// caller learns all bad shards in one pass.

uint64_t mtpu_get_frame(const uint8_t* key32, const uint8_t* const* shards,
                        size_t k, size_t S, size_t nb, size_t slast,
                        size_t take_full, size_t take_last, uint8_t* out) {
  const size_t frame = 32 + S;
  uint64_t bad = 0;
  for (size_t j = 0; j < k && j < 64; ++j) {
    const uint8_t* sh = shards[j];
    for (size_t b = 0; b < nb; ++b) {
      const size_t slen = (b + 1 == nb) ? slast : S;
      const uint8_t* fr = sh + b * frame;
      uint8_t dig[32];
      mtpu_hh256(key32, fr + 32, slen, dig);
      if (std::memcmp(dig, fr, 32) != 0) {
        bad |= uint64_t(1) << j;
        break;
      }
    }
  }
  if (bad) return bad;
  uint8_t* dst = out;
  for (size_t b = 0; b < nb; ++b) {
    const size_t slen = (b + 1 == nb) ? slast : S;
    size_t take = (b + 1 == nb) ? take_last : take_full;
    for (size_t j = 0; j < k && take; ++j) {
      const size_t c = slen < take ? slen : take;
      std::memcpy(dst, shards[j] + b * frame + 32, c);
      dst += c;
      take -= c;
    }
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Serve hot loop: HTTP/1.1 request-head framer + aws-chunked frame scanner
// ---------------------------------------------------------------------------
//
// The front-end's per-request parse cost in Python is readline-per-header
// plus an email.Message build; these two functions replace that with one
// GIL-free scan straight out of the worker's pooled recv buffer
// (reference: the reference rides net/http's C-backed textproto reader;
// this is our equivalent). The Python HTTP parser stays as the
// conformance fallback for anything these reject.

namespace {
// Bounded forward search (memmem without the _GNU_SOURCE dependency).
inline const uint8_t* FindSeq(const uint8_t* hay, size_t hay_len,
                              const char* needle, size_t needle_len) {
  if (hay_len < needle_len) return nullptr;
  const uint8_t* end = hay + hay_len - needle_len;
  for (const uint8_t* p = hay; p <= end; ++p) {
    p = static_cast<const uint8_t*>(
        std::memchr(p, needle[0], (size_t)(end - p) + 1));
    if (!p) return nullptr;
    if (std::memcmp(p, needle, needle_len) == 0) return p;
  }
  return nullptr;
}
}  // namespace

// Parse one HTTP/1.x request head out of buf[0:len).
//
// On success header NAMES are lowercased IN PLACE (the caller owns the
// recv buffer; SigV4 canonicalization wants lowercase anyway) and `out`
// (int32, 6 + 4*max_headers entries) is filled:
//   out[0]=method_off  out[1]=method_len
//   out[2]=target_off  out[3]=target_len
//   out[4]=version (10 | 11)
//   out[5]=nheaders, then per header: name_off, name_len, val_off, val_len.
// Returns the head length in bytes (through the final CRLFCRLF),
// 0 if the head is still incomplete, -1 malformed (caller falls back to
// the Python parser), -2 more than max_headers headers.
int64_t mtpu_http_head(uint8_t* buf, size_t len, int32_t* out,
                       size_t max_headers) {
  const uint8_t* end4 = FindSeq(buf, len, "\r\n\r\n", 4);
  if (!end4) return 0;
  const size_t head_len = (size_t)(end4 - buf) + 4;
  size_t p = 0;
  // Request line: METHOD SP request-target SP HTTP/1.x CRLF
  const size_t m0 = p;
  while (p < head_len && buf[p] != ' ' && buf[p] != '\r') ++p;
  if (p >= head_len || buf[p] != ' ' || p == m0 || p - m0 > 32) return -1;
  for (size_t i = m0; i < p; ++i)
    if (buf[i] <= ' ' || buf[i] >= 127) return -1;
  const size_t mlen = p - m0;
  ++p;
  const size_t t0 = p;
  while (p < head_len && buf[p] != ' ' && buf[p] != '\r' &&
         buf[p] != '\n') ++p;
  if (p >= head_len || buf[p] != ' ' || p == t0) return -1;
  const size_t tlen = p - t0;
  ++p;
  if (p + 10 > head_len || std::memcmp(buf + p, "HTTP/1.", 7) != 0)
    return -1;
  const uint8_t v = buf[p + 7];
  if (v != '0' && v != '1') return -1;
  p += 8;
  if (buf[p] != '\r' || buf[p + 1] != '\n') return -1;
  p += 2;
  size_t nh = 0;
  while (p < head_len) {
    if (buf[p] == '\r') {              // blank line terminates the head
      if (p + 2 != head_len || buf[p + 1] != '\n') return -1;
      break;
    }
    if (nh >= max_headers) return -2;
    if (buf[p] == ' ' || buf[p] == '\t') return -1;   // obs-fold: refuse
    const size_t n0 = p;
    while (p < head_len && buf[p] != ':' && buf[p] != '\r') ++p;
    if (p >= head_len || buf[p] != ':' || p == n0) return -1;
    for (size_t i = n0; i < p; ++i) {
      const uint8_t c = buf[i];
      if (c <= ' ' || c >= 127) return -1;   // WS before ':' = smuggling
      if (c >= 'A' && c <= 'Z') buf[i] = c + 32;
    }
    const size_t nlen = p - n0;
    ++p;
    while (p < head_len && (buf[p] == ' ' || buf[p] == '\t')) ++p;
    const size_t v0 = p;
    // A bare LF inside a field value is a request-smuggling primitive
    // (line-based parsers would see two headers where we saw one):
    // reject so the stock parser's line discipline decides.
    while (p < head_len && buf[p] != '\r' && buf[p] != '\n') ++p;
    if (p + 1 >= head_len || buf[p] != '\r' || buf[p + 1] != '\n')
      return -1;
    size_t v1 = p;
    while (v1 > v0 && (buf[v1 - 1] == ' ' || buf[v1 - 1] == '\t')) --v1;
    int32_t* h = out + 6 + 4 * nh;
    h[0] = (int32_t)n0;
    h[1] = (int32_t)nlen;
    h[2] = (int32_t)v0;
    h[3] = (int32_t)(v1 - v0);
    ++nh;
    p += 2;
  }
  out[0] = (int32_t)m0;
  out[1] = (int32_t)mlen;
  out[2] = (int32_t)t0;
  out[3] = (int32_t)tlen;
  out[4] = (v == '1') ? 11 : 10;
  out[5] = (int32_t)nh;
  return (int64_t)head_len;
}

// Scan one aws-chunked frame header (`hex-size[;ext]\r\n`) at
// buf[pos:len). out (int64, 4 entries):
//   out[0]=header length through its CRLF
//   out[1]=declared chunk size
//   out[2]=ABSOLUTE offset of the chunk-signature ext value (0 if none)
//   out[3]=signature length
// Returns 1 parsed, 0 incomplete (need more bytes), -1 malformed or
// over the 4 KiB header / 16 MiB chunk bounds (the Python reader's own
// discipline, cmd/streaming-signature-v4.go's maxLineLength).
int64_t mtpu_chunk_head(const uint8_t* buf, size_t len, size_t pos,
                        int64_t* out) {
  const size_t kMaxHeader = 4096;
  const int64_t kMaxChunk = 16ll << 20;
  if (pos > len) return -1;
  const size_t avail = len - pos;
  const size_t scan = avail < kMaxHeader ? avail : kMaxHeader;
  const uint8_t* nl = FindSeq(buf + pos, scan, "\r\n", 2);
  if (!nl) return avail > kMaxHeader ? -1 : 0;
  const size_t hlen = (size_t)(nl - (buf + pos)) + 2;
  const size_t line_end = pos + hlen - 2;
  size_t p = pos;
  int64_t size = 0;
  int digits = 0;
  while (p < line_end) {
    const uint8_t c = buf[p];
    int dv;
    if (c >= '0' && c <= '9') dv = c - '0';
    else if (c >= 'a' && c <= 'f') dv = c - 'a' + 10;
    else if (c >= 'A' && c <= 'F') dv = c - 'A' + 10;
    else break;
    size = size * 16 + dv;
    ++digits;
    ++p;
    if (size > kMaxChunk) return -1;
  }
  if (!digits) return -1;
  int64_t sig_off = 0, sig_len = 0;
  while (p < line_end && buf[p] == ';') {
    ++p;
    const size_t k0 = p;
    while (p < line_end && buf[p] != '=' && buf[p] != ';') ++p;
    const size_t klen = p - k0;
    size_t val0 = 0, vlen = 0;
    if (p < line_end && buf[p] == '=') {
      ++p;
      val0 = p;
      while (p < line_end && buf[p] != ';') ++p;
      vlen = p - val0;
    }
    if (klen == 15 && std::memcmp(buf + k0, "chunk-signature", 15) == 0) {
      sig_off = (int64_t)val0;
      sig_len = (int64_t)vlen;
    }
  }
  if (p != line_end) return -1;
  out[0] = (int64_t)hlen;
  out[1] = size;
  out[2] = sig_off;
  out[3] = sig_len;
  return 1;
}

// ---------------------------------------------------------------------------
// Batched xl.meta journal scan
// ---------------------------------------------------------------------------
//
// The listing walk's per-object hot loop: given N concatenated xl.meta
// blobs (magic + msgpack, storage/meta.py layout) in one buffer, extract
// for each blob the per-version fields the metadata plane needs —
// delete-marker/inline flags, mod-time, size, version id, data dir, and
// the three listing metadata values (etag, content-type, x-amz-tagging)
// — in one GIL-free call. Anything the scanner does not fully
// understand (unknown msgpack types where a known one is required,
// journals longer than `maxv` versions, meta maps carrying keys beyond
// the three captured ones) is REJECTED per blob: the caller falls back
// to the Python XLMeta.load path for that blob alone, so the scan can
// stay a strict, simple subset of msgpack while the slow path keeps
// full fidelity.
//
// Out records (int64), stride 2 + 13*maxv per blob:
//   [0] status: 0 parsed; -1 malformed/unsupported; -2 over maxv
//   [1] nversions
//   per version v at 2 + 13*v:
//     [+0] flags: bit0 delete-marker, bit1 inline, bit2 meta-extra
//          (meta holds keys/value-types beyond the captured three — the
//          summary is not sufficient to rebuild listing metadata)
//     [+1] mod-time   [+2] size
//     [+3..4]   vid  (absolute offset, length into buf)
//     [+5..6]   ddir
//     [+7..8]   etag
//     [+9..10]  content-type
//     [+11..12] x-amz-tagging
// Returns the number of blobs with status == 0.

namespace {

struct Mp {
  const uint8_t* p;
  const uint8_t* end;

  bool ok(size_t n) const { return size_t(end - p) >= n; }
  uint64_t be(size_t n) {
    uint64_t v = 0;
    for (size_t i = 0; i < n; ++i) v = (v << 8) | p[i];
    p += n;
    return v;
  }
};

// Read any msgpack value header we might see; for containers returns the
// element count, for str/bin the byte length (and leaves p at payload).
enum MpType { MP_ERR, MP_NIL, MP_BOOL, MP_INT, MP_STR, MP_BIN, MP_ARR,
              MP_MAP, MP_FLOAT, MP_EXT };

MpType mp_head(Mp* m, int64_t* val) {
  if (!m->ok(1)) return MP_ERR;
  const uint8_t c = *m->p++;
  if (c <= 0x7f) { *val = c; return MP_INT; }             // pos fixint
  if (c >= 0xe0) { *val = int8_t(c); return MP_INT; }     // neg fixint
  if ((c & 0xf0) == 0x80) { *val = c & 0x0f; return MP_MAP; }
  if ((c & 0xf0) == 0x90) { *val = c & 0x0f; return MP_ARR; }
  if ((c & 0xe0) == 0xa0) { *val = c & 0x1f; return MP_STR; }
  switch (c) {
    case 0xc0: return MP_NIL;
    case 0xc2: *val = 0; return MP_BOOL;
    case 0xc3: *val = 1; return MP_BOOL;
    case 0xc4: if (!m->ok(1)) return MP_ERR; *val = int64_t(m->be(1));
               return MP_BIN;
    case 0xc5: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               return MP_BIN;
    case 0xc6: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               return MP_BIN;
    case 0xca: if (!m->ok(4)) return MP_ERR; m->p += 4; return MP_FLOAT;
    case 0xcb: if (!m->ok(8)) return MP_ERR; m->p += 8; return MP_FLOAT;
    case 0xcc: if (!m->ok(1)) return MP_ERR; *val = int64_t(m->be(1));
               return MP_INT;
    case 0xcd: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               return MP_INT;
    case 0xce: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               return MP_INT;
    case 0xcf: {
      if (!m->ok(8)) return MP_ERR;
      const uint64_t u = m->be(8);
      if (u > uint64_t(INT64_MAX)) return MP_ERR;   // out of our range
      *val = int64_t(u);
      return MP_INT;
    }
    case 0xd0: if (!m->ok(1)) return MP_ERR; *val = int8_t(m->be(1));
               return MP_INT;
    case 0xd1: if (!m->ok(2)) return MP_ERR; *val = int16_t(m->be(2));
               return MP_INT;
    case 0xd2: if (!m->ok(4)) return MP_ERR; *val = int32_t(m->be(4));
               return MP_INT;
    case 0xd3: if (!m->ok(8)) return MP_ERR; *val = int64_t(m->be(8));
               return MP_INT;
    case 0xd4: case 0xd5: case 0xd6: case 0xd7: case 0xd8: {
      const size_t n = size_t(1) << (c - 0xd4);
      if (!m->ok(1 + n)) return MP_ERR;
      m->p += 1 + n;
      return MP_EXT;
    }
    case 0xc7: if (!m->ok(1)) return MP_ERR; *val = int64_t(m->be(1));
               if (!m->ok(size_t(*val) + 1)) return MP_ERR;
               m->p += *val + 1; return MP_EXT;
    case 0xc8: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               if (!m->ok(size_t(*val) + 1)) return MP_ERR;
               m->p += *val + 1; return MP_EXT;
    case 0xc9: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               if (!m->ok(size_t(*val) + 1)) return MP_ERR;
               m->p += *val + 1; return MP_EXT;
    case 0xd9: if (!m->ok(1)) return MP_ERR; *val = int64_t(m->be(1));
               return MP_STR;
    case 0xda: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               return MP_STR;
    case 0xdb: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               return MP_STR;
    case 0xdc: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               return MP_ARR;
    case 0xdd: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               return MP_ARR;
    case 0xde: if (!m->ok(2)) return MP_ERR; *val = int64_t(m->be(2));
               return MP_MAP;
    case 0xdf: if (!m->ok(4)) return MP_ERR; *val = int64_t(m->be(4));
               return MP_MAP;
    default: return MP_ERR;   // reserved / never-used (0xc1)
  }
}

bool mp_skip(Mp* m, int depth = 0) {
  if (depth > 32) return false;
  int64_t v = 0;
  switch (mp_head(m, &v)) {
    case MP_ERR: return false;
    case MP_NIL: case MP_BOOL: case MP_INT: case MP_FLOAT: case MP_EXT:
      return true;
    case MP_STR: case MP_BIN:
      if (!m->ok(size_t(v))) return false;
      m->p += v;
      return true;
    case MP_ARR:
      for (int64_t i = 0; i < v; ++i)
        if (!mp_skip(m, depth + 1)) return false;
      return true;
    case MP_MAP:
      for (int64_t i = 0; i < 2 * v; ++i)
        if (!mp_skip(m, depth + 1)) return false;
      return true;
  }
  return false;
}

bool mp_str(Mp* m, const uint8_t** s, int64_t* len) {
  int64_t v = 0;
  if (mp_head(m, &v) != MP_STR || !m->ok(size_t(v))) return false;
  *s = m->p;
  *len = v;
  m->p += v;
  return true;
}

bool key_is(const uint8_t* s, int64_t len, const char* k) {
  const size_t kl = strlen(k);
  return size_t(len) == kl && std::memcmp(s, k, kl) == 0;
}

enum { MSCAN_FLAG_DELETED = 1, MSCAN_FLAG_INLINE = 2, MSCAN_FLAG_EXTRA = 4 };

// One version map -> out[0..12]; offsets absolute against `base`.
bool scan_version(Mp* m, const uint8_t* base, int64_t* o) {
  int64_t nfields = 0;
  if (mp_head(m, &nfields) != MP_MAP) return false;
  int64_t flags = 0, mt = 0, size = 0, kind = 0;
  bool saw_kind = false, saw_vid = false, saw_mt = false;
  for (int i = 0; i < 13; ++i) o[i] = 0;
  for (int64_t f = 0; f < nfields; ++f) {
    const uint8_t* ks;
    int64_t klen = 0, v = 0;
    if (!mp_str(m, &ks, &klen)) return false;
    if (key_is(ks, klen, "kind")) {
      if (mp_head(m, &v) != MP_INT) return false;
      kind = v;
      saw_kind = true;
    } else if (key_is(ks, klen, "vid")) {
      const uint8_t* s;
      int64_t len;
      if (!mp_str(m, &s, &len)) return false;
      o[3] = s - base;
      o[4] = len;
      saw_vid = true;
    } else if (key_is(ks, klen, "mt")) {
      if (mp_head(m, &v) != MP_INT) return false;
      mt = v;
      saw_mt = true;
    } else if (key_is(ks, klen, "ddir")) {
      const uint8_t* s;
      int64_t len;
      if (!mp_str(m, &s, &len)) return false;
      o[5] = s - base;
      o[6] = len;
    } else if (key_is(ks, klen, "size")) {
      if (mp_head(m, &v) != MP_INT) return false;
      size = v;
    } else if (key_is(ks, klen, "inline")) {
      MpType t = mp_head(m, &v);
      if (t != MP_BOOL && t != MP_NIL) return false;
      if (t == MP_BOOL && v) flags |= MSCAN_FLAG_INLINE;
    } else if (key_is(ks, klen, "meta")) {
      int64_t nm = 0;
      if (mp_head(m, &nm) != MP_MAP) return false;
      for (int64_t j = 0; j < nm; ++j) {
        const uint8_t* ms;
        int64_t mlen = 0;
        if (!mp_str(m, &ms, &mlen)) return false;
        int slot = -1;
        if (key_is(ms, mlen, "etag")) slot = 7;
        else if (key_is(ms, mlen, "content-type")) slot = 9;
        else if (key_is(ms, mlen, "x-amz-tagging")) slot = 11;
        if (slot < 0) {
          flags |= MSCAN_FLAG_EXTRA;       // key beyond the captured set
          if (!mp_skip(m)) return false;
          continue;
        }
        const uint8_t* vs;
        int64_t vlen = 0;
        Mp save = *m;
        if (!mp_str(m, &vs, &vlen)) {
          // Captured key with a non-string value: keep parsing (the
          // Python path will rebuild it), but flag the summary as
          // insufficient.
          *m = save;
          if (!mp_skip(m)) return false;
          flags |= MSCAN_FLAG_EXTRA;
          continue;
        }
        o[slot] = vs - base;
        o[slot + 1] = vlen;
      }
    } else {
      // parts / ec / future keys: skipped, same as the Python reader.
      if (!mp_skip(m)) return false;
    }
  }
  if (!saw_kind || !saw_vid || !saw_mt) return false;
  if (kind == 2) flags |= MSCAN_FLAG_DELETED;
  else if (kind != 1) return false;
  o[0] = flags;
  o[1] = mt;
  o[2] = size;
  return true;
}

int64_t scan_one(const uint8_t* blob, size_t len, const uint8_t* base,
                 int64_t maxv, int64_t* out) {
  const int64_t stride_v = 13;
  out[0] = -1;
  out[1] = 0;
  if (len < 4 || std::memcmp(blob, "XTP1", 4) != 0) return -1;
  Mp m{blob + 4, blob + len};
  int64_t ntop = 0;
  if (mp_head(&m, &ntop) != MP_MAP) return -1;
  int64_t nver = -1;
  for (int64_t t = 0; t < ntop; ++t) {
    const uint8_t* ks;
    int64_t klen = 0;
    if (!mp_str(&m, &ks, &klen)) return -1;
    if (key_is(ks, klen, "versions")) {
      if (mp_head(&m, &nver) != MP_ARR) return -1;
      out[1] = nver;
      if (nver > maxv) { out[0] = -2; return -2; }
      for (int64_t v = 0; v < nver; ++v)
        if (!scan_version(&m, base, out + 2 + stride_v * v)) return -1;
    } else {
      if (!mp_skip(&m)) return -1;
    }
  }
  if (nver < 0) return -1;
  out[0] = 0;
  return 0;
}

}  // namespace

int64_t mtpu_meta_scan(const uint8_t* buf, const int64_t* offs,
                       int64_t nblobs, int64_t maxv, int64_t* out) {
  const int64_t stride = 2 + 13 * maxv;
  int64_t okcnt = 0;
  for (int64_t i = 0; i < nblobs; ++i) {
    const int64_t lo = offs[i], hi = offs[i + 1];
    int64_t* rec = out + i * stride;
    if (lo < 0 || hi < lo) {
      rec[0] = -1;
      rec[1] = 0;
      continue;
    }
    if (scan_one(buf + lo, size_t(hi - lo), buf, maxv, rec) == 0) ++okcnt;
  }
  return okcnt;
}

}  // extern "C"
