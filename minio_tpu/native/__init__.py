"""Build-on-first-import loader for the native kernel library.

Compiles native.cc with g++ -O3 -march=native into _native.so next to
this file (rebuilt when the source is newer) and exposes it via ctypes.
Falls back to None if no compiler is available — pure-Python/numpy paths
take over, slower but byte-identical.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "native.cc")
_SO = os.path.join(_DIR, "_native.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    # Per-process tmp name: concurrent builders must not interleave into
    # one tmp file (a corrupt .so with a fresh mtime would permanently
    # disable the native path).
    tmp = f"{_SO}.{os.getpid()}.tmp"
    base = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-o", tmp,
            _SRC]
    # zlib backs the fused transform's block compression; a container
    # without the headers still gets every other kernel (the deflate/
    # inflate entry points then answer -2 and Python keeps its own
    # zlib path).
    for cmd in (base + ["-lz"], base + ["-DMTPU_NO_ZLIB"]):
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, _SO)
            return True
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return False


def load():
    """The ctypes library handle, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            stale = not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SO) < os.path.getmtime(_SRC))
            if stale and not _build():
                return None
            lib = ctypes.CDLL(_SO)
        except Exception:
            return None
        # A stale .so can predate newer symbols (e.g. mtpu_put_frame)
        # even when mtimes look fresh: declare, and on a missing
        # symbol rebuild once and re-declare.
        try:
            _declare(lib)
        except AttributeError:
            if not _build():
                return None
            lib = ctypes.CDLL(_SO)
            try:
                _declare(lib)
            except AttributeError:
                return None
        _lib = lib
        return _lib


def _declare(lib) -> None:
    """ctypes prototypes for every exported symbol — the ONE place the
    C ABI is spelled on the Python side (raises AttributeError when the
    loaded .so lacks a symbol)."""
    u8p = ctypes.POINTER(ctypes.c_uint8)
    for name, argt in (
            ("mtpu_hh256", [u8p, u8p, ctypes.c_size_t, u8p]),
            ("mtpu_hh256_many", [u8p, u8p, ctypes.c_size_t,
                                 ctypes.c_size_t, ctypes.c_size_t, u8p]),
            ("mtpu_gf_apply", [u8p, ctypes.c_size_t, ctypes.c_size_t,
                               u8p, ctypes.c_size_t, ctypes.c_size_t,
                               u8p, ctypes.c_size_t]),
            ("mtpu_put_frame", [u8p, u8p, u8p, ctypes.c_size_t,
                                ctypes.c_size_t, ctypes.c_size_t,
                                ctypes.c_size_t, u8p])):
        fn = getattr(lib, name)
        fn.argtypes = argt
        fn.restype = None
    lib.mtpu_xxh64.argtypes = [u8p, ctypes.c_size_t, ctypes.c_uint64]
    lib.mtpu_xxh64.restype = ctypes.c_uint64
    # Serve hot loop: HTTP head framer + aws-chunked frame scanner.
    lib.mtpu_http_head.argtypes = [u8p, ctypes.c_size_t,
                                   ctypes.POINTER(ctypes.c_int32),
                                   ctypes.c_size_t]
    lib.mtpu_http_head.restype = ctypes.c_int64
    lib.mtpu_chunk_head.argtypes = [u8p, ctypes.c_size_t, ctypes.c_size_t,
                                    ctypes.POINTER(ctypes.c_int64)]
    lib.mtpu_chunk_head.restype = ctypes.c_int64
    lib.mtpu_get_frame.argtypes = [u8p, ctypes.POINTER(u8p),
                                   ctypes.c_size_t, ctypes.c_size_t,
                                   ctypes.c_size_t, ctypes.c_size_t,
                                   ctypes.c_size_t, ctypes.c_size_t, u8p]
    lib.mtpu_get_frame.restype = ctypes.c_uint64
    # Metadata plane: batched xl.meta journal scan (storage/meta_scan).
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.mtpu_meta_scan.argtypes = [u8p, i64p, ctypes.c_int64,
                                   ctypes.c_int64, i64p]
    lib.mtpu_meta_scan.restype = ctypes.c_int64
    # Fused data plane: streaming digests, AES-256-GCM / DARE, block
    # deflate/inflate, and the single-pass transform+frame kernels.
    sz = ctypes.c_size_t
    i64 = ctypes.c_int64
    lib.mtpu_digest_init.argtypes = [i64, u8p]
    lib.mtpu_digest_init.restype = None
    lib.mtpu_digest_update.argtypes = [i64, u8p, u8p, sz]
    lib.mtpu_digest_update.restype = None
    lib.mtpu_digest_final.argtypes = [i64, u8p, u8p]
    lib.mtpu_digest_final.restype = None
    lib.mtpu_crc32.argtypes = [ctypes.c_uint32, u8p, sz]
    lib.mtpu_crc32.restype = ctypes.c_uint32
    lib.mtpu_gcm_seal.argtypes = [u8p, u8p, u8p, sz, u8p, sz, u8p]
    lib.mtpu_gcm_seal.restype = None
    lib.mtpu_gcm_open.argtypes = [u8p, u8p, u8p, sz, u8p, sz, u8p]
    lib.mtpu_gcm_open.restype = i64
    lib.mtpu_dare_seal.argtypes = [u8p, u8p, ctypes.c_uint64, u8p, sz, u8p]
    lib.mtpu_dare_seal.restype = i64
    lib.mtpu_dare_open.argtypes = [u8p, u8p, ctypes.c_uint64, u8p, sz, u8p]
    lib.mtpu_dare_open.restype = i64
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.mtpu_deflate_blocks.argtypes = [u8p, sz, sz, i64, u8p, sz, i64p]
    lib.mtpu_deflate_blocks.restype = i64
    lib.mtpu_inflate_blocks.argtypes = [u8p, sz, i64p, i64, i64, i64,
                                        u8p, sz]
    lib.mtpu_inflate_blocks.restype = i64
    lib.mtpu_transform_frame.argtypes = [
        u8p, sz, i64, u8p, u8p, u8p, u8p, sz, u8p, sz, i64p, i64, sz,
        u8p, u8p, sz, sz, sz, sz, u8p, sz, i64p]
    lib.mtpu_transform_frame.restype = i64
    lib.mtpu_untransform.argtypes = [u8p, sz, i64, u8p, u8p, i64, i64p,
                                     i64, i64, i64, u8p, sz, u8p, sz]
    lib.mtpu_untransform.restype = i64
    lib.mtpu_put_frame_md5.argtypes = [u8p, u8p, u8p, u8p, sz, sz, sz,
                                       sz, sz, u8p]
    lib.mtpu_put_frame_md5.restype = None


def feature(symbol: str, gated: bool = True):
    """The library handle when it carries `symbol`, else None — the
    ONE gate every fused-transform-plane call site shares. With
    `gated` (the default) the MTPU_TRANSFORM_FUSED=off kill-switch
    also answers None, so "off" reverts the whole plane (fused
    orchestration AND the dare/compress native bulk paths) to the
    layered pipeline; pass gated=False for primitives that must keep
    working regardless (the AES-GCM backend — without it a wheel-less
    container loses SSE entirely, which is availability, not an
    optimization the switch governs)."""
    if gated and os.environ.get("MTPU_TRANSFORM_FUSED", "") \
            .strip().lower() in ("off", "0", "false", "no"):
        return None
    try:
        lib = load()
    except Exception:  # noqa: BLE001 - loader failure = unavailable
        return None
    return lib if lib is not None and hasattr(lib, symbol) else None


def _u8(arr) -> "ctypes.POINTER(ctypes.c_uint8)":
    import numpy as np
    a = arr if isinstance(arr, (bytes, bytearray)) else np.ascontiguousarray(arr)
    if isinstance(a, (bytes, bytearray)):
        return (ctypes.c_uint8 * len(a)).from_buffer_copy(a)
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
