"""Build-on-first-import loader for the native kernel library.

Compiles native.cc with g++ -O3 -march=native into _native.so next to
this file (rebuilt when the source is newer) and exposes it via ctypes.
Falls back to None if no compiler is available — pure-Python/numpy paths
take over, slower but byte-identical.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "native.cc")
_SO = os.path.join(_DIR, "_native.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    # Per-process tmp name: concurrent builders must not interleave into
    # one tmp file (a corrupt .so with a fresh mtime would permanently
    # disable the native path).
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load():
    """The ctypes library handle, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            stale = not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SO) < os.path.getmtime(_SRC))
            if stale and not _build():
                return None
            lib = ctypes.CDLL(_SO)
        except Exception:
            return None
        # A stale .so can predate newer symbols (e.g. mtpu_put_frame)
        # even when mtimes look fresh: declare, and on a missing
        # symbol rebuild once and re-declare.
        try:
            _declare(lib)
        except AttributeError:
            if not _build():
                return None
            lib = ctypes.CDLL(_SO)
            try:
                _declare(lib)
            except AttributeError:
                return None
        _lib = lib
        return _lib


def _declare(lib) -> None:
    """ctypes prototypes for every exported symbol — the ONE place the
    C ABI is spelled on the Python side (raises AttributeError when the
    loaded .so lacks a symbol)."""
    u8p = ctypes.POINTER(ctypes.c_uint8)
    for name, argt in (
            ("mtpu_hh256", [u8p, u8p, ctypes.c_size_t, u8p]),
            ("mtpu_hh256_many", [u8p, u8p, ctypes.c_size_t,
                                 ctypes.c_size_t, ctypes.c_size_t, u8p]),
            ("mtpu_gf_apply", [u8p, ctypes.c_size_t, ctypes.c_size_t,
                               u8p, ctypes.c_size_t, ctypes.c_size_t,
                               u8p, ctypes.c_size_t]),
            ("mtpu_put_frame", [u8p, u8p, u8p, ctypes.c_size_t,
                                ctypes.c_size_t, ctypes.c_size_t,
                                ctypes.c_size_t, u8p])):
        fn = getattr(lib, name)
        fn.argtypes = argt
        fn.restype = None
    lib.mtpu_xxh64.argtypes = [u8p, ctypes.c_size_t, ctypes.c_uint64]
    lib.mtpu_xxh64.restype = ctypes.c_uint64
    # Serve hot loop: HTTP head framer + aws-chunked frame scanner.
    lib.mtpu_http_head.argtypes = [u8p, ctypes.c_size_t,
                                   ctypes.POINTER(ctypes.c_int32),
                                   ctypes.c_size_t]
    lib.mtpu_http_head.restype = ctypes.c_int64
    lib.mtpu_chunk_head.argtypes = [u8p, ctypes.c_size_t, ctypes.c_size_t,
                                    ctypes.POINTER(ctypes.c_int64)]
    lib.mtpu_chunk_head.restype = ctypes.c_int64
    lib.mtpu_get_frame.argtypes = [u8p, ctypes.POINTER(u8p),
                                   ctypes.c_size_t, ctypes.c_size_t,
                                   ctypes.c_size_t, ctypes.c_size_t,
                                   ctypes.c_size_t, ctypes.c_size_t, u8p]
    lib.mtpu_get_frame.restype = ctypes.c_uint64
    # Metadata plane: batched xl.meta journal scan (storage/meta_scan).
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.mtpu_meta_scan.argtypes = [u8p, i64p, ctypes.c_int64,
                                   ctypes.c_int64, i64p]
    lib.mtpu_meta_scan.restype = ctypes.c_int64


def _u8(arr) -> "ctypes.POINTER(ctypes.c_uint8)":
    import numpy as np
    a = arr if isinstance(arr, (bytes, bytearray)) else np.ascontiguousarray(arr)
    if isinstance(a, (bytes, bytearray)):
        return (ctypes.c_uint8 * len(a)).from_buffer_copy(a)
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
