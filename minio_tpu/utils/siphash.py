"""SipHash-2-4 (64-bit) — object->set routing hash.

The reference routes each object key to its erasure set with
sipHashMod(key, numSets, deploymentID) (cmd/erasure-sets.go:663, via
dchest/siphash). Implemented from the public SipHash specification
(Aumasson & Bernstein, 2012); validated against the reference vectors
published with the spec (see tests/test_topology.py).
"""

from __future__ import annotations

MASK = 0xFFFFFFFFFFFFFFFF


def _rotl(x: int, b: int) -> int:
    return ((x << b) | (x >> (64 - b))) & MASK


def siphash24(key: bytes, data: bytes) -> int:
    """SipHash-2-4 of data under a 16-byte key -> 64-bit int."""
    if len(key) != 16:
        raise ValueError("siphash key must be 16 bytes")
    k0 = int.from_bytes(key[:8], "little")
    k1 = int.from_bytes(key[8:], "little")
    v0 = k0 ^ 0x736F6D6570736575
    v1 = k1 ^ 0x646F72616E646F6D
    v2 = k0 ^ 0x6C7967656E657261
    v3 = k1 ^ 0x7465646279746573

    def rounds(n: int) -> None:
        nonlocal v0, v1, v2, v3
        for _ in range(n):
            v0 = (v0 + v1) & MASK
            v1 = _rotl(v1, 13) ^ v0
            v0 = _rotl(v0, 32)
            v2 = (v2 + v3) & MASK
            v3 = _rotl(v3, 16) ^ v2
            v0 = (v0 + v3) & MASK
            v3 = _rotl(v3, 21) ^ v0
            v2 = (v2 + v1) & MASK
            v1 = _rotl(v1, 17) ^ v2
            v2 = _rotl(v2, 32)

    b = len(data) & 0xFF
    end = len(data) - (len(data) % 8)
    for off in range(0, end, 8):
        m = int.from_bytes(data[off:off + 8], "little")
        v3 ^= m
        rounds(2)
        v0 ^= m
    tail = data[end:]
    m = (b << 56) | int.from_bytes(tail + b"\x00" * (8 - len(tail)), "little") \
        if tail else (b << 56)
    v3 ^= m
    rounds(2)
    v0 ^= m
    v2 ^= 0xFF
    rounds(4)
    return (v0 ^ v1 ^ v2 ^ v3) & MASK


def sip_hash_mod(key: str, cardinality: int, id_: bytes) -> int:
    """key -> [0, cardinality) under a 16-byte deployment id (reference:
    sipHashMod, cmd/erasure-sets.go:663)."""
    if cardinality <= 0:
        return -1
    return siphash24(id_, key.encode()) % cardinality
