"""Pure-Python XXH64 (seed 0) — used to check the erasure golden vectors.

The reference validates its erasure codec at boot against golden xxhash64
digests (reference: cmd/erasure-coding.go:152-209, via cespare/xxhash). We
only need it for the self-test's 256-byte vectors, so a straightforward
implementation suffices; nothing on the data path uses it.
"""

from __future__ import annotations

import struct

_PRIME1 = 0x9E3779B185EBCA87
_PRIME2 = 0xC2B2AE3D27D4EB4F
_PRIME3 = 0x165667B19E3779F9
_PRIME4 = 0x85EBCA77C2B2AE63
_PRIME5 = 0x27D4EB2F165667C5
_MASK = 0xFFFFFFFFFFFFFFFF


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _MASK


def _round(acc: int, lane: int) -> int:
    acc = (acc + lane * _PRIME2) & _MASK
    acc = _rotl(acc, 31)
    return (acc * _PRIME1) & _MASK


def _merge_round(acc: int, val: int) -> int:
    acc ^= _round(0, val)
    return ((acc * _PRIME1) + _PRIME4) & _MASK


def xxh64(data: bytes, seed: int = 0) -> int:
    try:
        from minio_tpu import native
        lib = native.load()
        if lib is not None:
            import numpy as np
            buf = np.frombuffer(data, dtype=np.uint8)
            return int(lib.mtpu_xxh64(native._u8(buf), buf.size, seed))
    except Exception:
        pass
    return _xxh64_py(data, seed)


def _xxh64_py(data: bytes, seed: int = 0) -> int:
    n = len(data)
    p = 0
    if n >= 32:
        v1 = (seed + _PRIME1 + _PRIME2) & _MASK
        v2 = (seed + _PRIME2) & _MASK
        v3 = seed & _MASK
        v4 = (seed - _PRIME1) & _MASK
        limit = n - 32
        while p <= limit:
            lanes = struct.unpack_from("<4Q", data, p)
            v1 = _round(v1, lanes[0])
            v2 = _round(v2, lanes[1])
            v3 = _round(v3, lanes[2])
            v4 = _round(v4, lanes[3])
            p += 32
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)) & _MASK
        h = _merge_round(h, v1)
        h = _merge_round(h, v2)
        h = _merge_round(h, v3)
        h = _merge_round(h, v4)
    else:
        h = (seed + _PRIME5) & _MASK
    h = (h + n) & _MASK
    while p + 8 <= n:
        (lane,) = struct.unpack_from("<Q", data, p)
        h ^= _round(0, lane)
        h = (_rotl(h, 27) * _PRIME1 + _PRIME4) & _MASK
        p += 8
    if p + 4 <= n:
        (lane,) = struct.unpack_from("<I", data, p)
        h ^= (lane * _PRIME1) & _MASK
        h = (_rotl(h, 23) * _PRIME2 + _PRIME3) & _MASK
        p += 4
    while p < n:
        h ^= (data[p] * _PRIME5) & _MASK
        h = (_rotl(h, 11) * _PRIME1) & _MASK
        p += 1
    h ^= h >> 33
    h = (h * _PRIME2) & _MASK
    h ^= h >> 29
    h = (h * _PRIME3) & _MASK
    h ^= h >> 32
    return h
