"""Continuous SLO engine: declared objectives evaluated against the
live rolling windows.

The evaluation substrate for SLO-gated load generation (ROADMAP item
6) and the fleet dashboards: operators declare objectives per API
class — a p99 latency ceiling, an error budget (fraction of requests
allowed to fail), a shed-rate ceiling — and the engine evaluates them
continuously against the same per-second structures the metrics layer
already maintains (utils/latency.LastMinute for p99; its own
per-second counter rings for error/shed rates). Each objective exports
a burn rate (observed error rate divided by the declared budget: 1.0
means burning exactly the budget, sustained), the remaining budget
fraction, and a pass/warn/burn verdict — the multiwindow burn-rate
alerting shape from the SRE workbook, reduced to the one rolling
window the server already keeps.

Declaration (env `MTPU_SLO`): inline JSON, `@/path/to/file.json`, or
`off` to disable. The JSON is a list of objectives:

    [{"name": "get-availability",
      "match": ["GET:object", "HEAD:object"],
      "p99_ms": 1000, "error_budget": 0.01,
      "shed_ceiling": 0.05, "window_s": 3600}]

`match` lists API labels (method:scope, the metrics layer's request
labels); a trailing "*" matches by prefix. Unset fields take the
defaults above. With no declaration the two DEFAULTS below (GET and
PUT availability) apply, so every deployment carries evaluated
objectives out of the box.

Environment:
  MTPU_SLO         objective declarations (JSON / @file / off)
  MTPU_SLO_EVAL_S  background evaluation period seconds (default 5)
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from minio_tpu.utils.latency import LastMinute, summarize

DEFAULTS = [
    {"name": "get-availability",
     "match": ["GET:object", "HEAD:object"],
     "p99_ms": 1000.0, "error_budget": 0.01, "shed_ceiling": 0.05,
     "window_s": 3600},
    {"name": "put-availability",
     "match": ["PUT:object", "POST:object"],
     "p99_ms": 2000.0, "error_budget": 0.01, "shed_ceiling": 0.05,
     "window_s": 3600},
]

# Verdict thresholds: "warn" fires at half the burn ceiling (or 80% of
# the latency ceiling) so the operator sees the trend before the
# budget is gone.
_WARN_BURN = 0.5
_WARN_P99 = 0.8


class _SecondRing:
    """Per-second (total, error, shed) counters over a fixed window.

    O(1) observe: one slot per wall second, lazily reset on reuse —
    the rollover arithmetic the unit tests pin down. Sums walk the
    ring (bounded by window_s, done on the eval tick, never the
    request path)."""

    __slots__ = ("size", "stamp", "total", "err", "shed", "_mu")

    def __init__(self, window_s: int):
        self.size = max(1, int(window_s))
        self.stamp = [0] * self.size
        self.total = [0] * self.size
        self.err = [0] * self.size
        self.shed = [0] * self.size
        self._mu = threading.Lock()

    def observe(self, sec: int, error: bool, shed: bool) -> None:
        i = sec % self.size
        with self._mu:
            if self.stamp[i] != sec:
                self.stamp[i] = sec
                self.total[i] = self.err[i] = self.shed[i] = 0
            self.total[i] += 1
            if error:
                self.err[i] += 1
            if shed:
                self.shed[i] += 1

    def sums(self, now_sec: int) -> tuple:
        """(total, errors, sheds) across slots still inside the
        window ending at `now_sec`."""
        lo = now_sec - self.size
        t = e = s = 0
        with self._mu:
            for i in range(self.size):
                if lo < self.stamp[i] <= now_sec:
                    t += self.total[i]
                    e += self.err[i]
                    s += self.shed[i]
        return t, e, s


class Objective:
    __slots__ = ("name", "match", "p99_ms", "error_budget",
                 "shed_ceiling", "window_s", "ring")

    def __init__(self, spec: dict):
        self.name = str(spec.get("name") or "objective")
        self.match = [str(m) for m in spec.get("match") or []]
        self.p99_ms = float(spec.get("p99_ms", 1000.0))
        self.error_budget = max(1e-9,
                                float(spec.get("error_budget", 0.01)))
        self.shed_ceiling = float(spec.get("shed_ceiling", 0.05))
        self.window_s = int(spec.get("window_s", 3600))
        self.ring = _SecondRing(self.window_s)

    def matches(self, api: str) -> bool:
        for m in self.match:
            if m.endswith("*"):
                if api.startswith(m[:-1]):
                    return True
            elif api == m:
                return True
        return False


class SLOEngine:
    """Holds the declared objectives, ingests request outcomes, and
    evaluates verdicts continuously (background thread) or lazily on
    snapshot(). `now` is injectable for the unit tests."""

    def __init__(self, objectives: Optional[list] = None,
                 eval_s: Optional[float] = None, now=time.time):
        specs = DEFAULTS if objectives is None else objectives
        self.objectives = [Objective(dict(s)) for s in specs]
        self.eval_s = float(eval_s if eval_s is not None
                            else _env_float("MTPU_SLO_EVAL_S", 5.0))
        self._now = now
        self._mu = threading.Lock()
        self._last_eval: list = []
        self._last_eval_t = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- configuration ---------------------------------------------------

    @classmethod
    def from_env(cls) -> Optional["SLOEngine"]:
        raw = (os.environ.get("MTPU_SLO", "") or "").strip()
        if raw.lower() in ("off", "0", "false", "no"):
            return None
        specs = None
        if raw:
            try:
                if raw.startswith("@"):
                    with open(raw[1:], encoding="utf-8") as fh:
                        specs = json.load(fh)
                else:
                    specs = json.loads(raw)
            except (OSError, ValueError):
                specs = None    # malformed declaration: defaults apply
        return cls(objectives=specs)

    # -- ingestion (request path) ----------------------------------------

    def observe(self, api: str, status: int) -> None:
        """One finished request. Errors are 5xx; 503 is the admission
        shed signal (it counts as both)."""
        error = status >= 500
        shed = status == 503
        sec = int(self._now())
        for obj in self.objectives:
            if obj.matches(api):
                obj.ring.observe(sec, error, shed)

    # -- evaluation ------------------------------------------------------

    def _p99_s(self, obj: Objective, metrics) -> float:
        """Observed p99 (seconds) over the metric layer's last-minute
        windows of the objective's matching APIs, merged."""
        if metrics is None:
            return 0.0
        try:
            with metrics._mu:
                wins = [lm.window()
                        for api, lm in metrics._last_minute.items()
                        if obj.matches(api)]
        except AttributeError:
            return 0.0
        if not wins:
            return 0.0
        return float(summarize(LastMinute.merge(wins)).get("p99", 0.0))

    def evaluate(self, metrics=None) -> list:
        """One evaluation pass: per-objective burn rate, remaining
        budget, shed rate, p99, verdict."""
        now_sec = int(self._now())
        out = []
        for obj in self.objectives:
            total, errors, sheds = obj.ring.sums(now_sec)
            error_rate = errors / total if total else 0.0
            shed_rate = sheds / total if total else 0.0
            burn = error_rate / obj.error_budget
            budget_remaining = max(0.0, 1.0 - burn)
            p99_s = self._p99_s(obj, metrics)
            p99_ceiling_s = obj.p99_ms / 1000.0
            verdict = "pass"
            if burn > 1.0 or (p99_s > p99_ceiling_s > 0) \
                    or shed_rate > obj.shed_ceiling:
                verdict = "burn"
            elif burn > _WARN_BURN \
                    or (p99_ceiling_s > 0
                        and p99_s > _WARN_P99 * p99_ceiling_s) \
                    or shed_rate > _WARN_BURN * obj.shed_ceiling:
                verdict = "warn"
            out.append({
                "name": obj.name,
                "match": list(obj.match),
                "window_s": obj.window_s,
                "requests": total,
                "errors": errors,
                "sheds": sheds,
                "error_rate": round(error_rate, 6),
                "shed_rate": round(shed_rate, 6),
                "burn_rate": round(burn, 4),
                "budget_remaining": round(budget_remaining, 4),
                "p99_s": round(p99_s, 6),
                "p99_ceiling_s": p99_ceiling_s,
                "verdict": verdict,
            })
        with self._mu:
            self._last_eval = out
            self._last_eval_t = self._now()
        return out

    def snapshot(self, metrics=None) -> dict:
        """The admin-info / Prometheus view: the last evaluation,
        refreshed in-line when stale (covers deployments where the
        background thread was never started — tests, bench)."""
        with self._mu:
            fresh = self._last_eval \
                and self._now() - self._last_eval_t < 2 * self.eval_s
            objs = list(self._last_eval)
        if not fresh:
            objs = self.evaluate(metrics=metrics)
        worst = "pass"
        for o in objs:
            if o["verdict"] == "burn":
                worst = "burn"
                break
            if o["verdict"] == "warn":
                worst = "warn"
        return {"objectives": objs, "verdict": worst,
                "eval_s": self.eval_s}

    # -- background evaluation -------------------------------------------

    def start(self, metrics=None) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def _run():
            while not self._stop.wait(self.eval_s):
                try:
                    self.evaluate(metrics=metrics)
                except Exception:  # noqa: BLE001 - eval must survive
                    pass

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="slo-eval")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)


def _env_float(key: str, default: float) -> float:
    try:
        return float(os.environ.get(key, "") or default)
    except ValueError:
        return default
