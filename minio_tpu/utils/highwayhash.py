"""HighwayHash-256 — the reference's bitrot checksum algorithm.

The reference protects every erasure shard block with keyed
HighwayHash-256 (reference: cmd/bitrot.go:28,37,55-59, via
github.com/minio/highwayhash with AVX2/NEON lane kernels). This is a
from-scratch implementation of the public HighwayHash algorithm
(Google, https://github.com/google/highwayhash) written as vectorized
numpy over a leading stream axis, so MANY shard blocks hash in parallel
— the same lane-parallel trick the SIMD kernels use, applied across
streams instead. The per-packet recurrence is sequential by
construction; parallelism comes from hashing independent shard blocks
(one stream per shard x block), which is exactly the shape of the bitrot
workload (each shard block is checksummed independently,
cmd/bitrot-streaming.go:44-75).

Correctness oracles (both must hold, enforced in tests):
  * the reference's bitrotSelfTest golden digests (cmd/bitrot.go:224-232)
    — covers packet updates + finalize for sizes 0,32,...,992;
  * the magic bitrot key itself: HighwayHash-256 of the first 100
    decimals of pi (utf-8) under a zero key equals
    magicHighwayHash256Key (cmd/bitrot.go:36-37) — covers the
    remainder (non-multiple-of-32) path.
"""

from __future__ import annotations

import numpy as np

_U64 = np.uint64
_MASK32 = _U64(0xFFFFFFFF)

_INIT0 = np.array([0xDBE6D5D5FE4CCE2F, 0xA4093822299F31D0,
                   0x13198A2E03707344, 0x243F6A8885A308D3], dtype=_U64)
_INIT1 = np.array([0x3BD39E10CB0EF593, 0xC0ACF169B5F18A8C,
                   0xBE5466CF34E90C6C, 0x452821E638D01377], dtype=_U64)

# The reference's magic bitrot key (cmd/bitrot.go:37): HH-256 of the first
# 100 decimals of pi under a zero key.
MAGIC_KEY = bytes.fromhex(
    "4be734fa8e238acd263e83e6bb968552040f935da39f441497e09d1322de36a0")


def _rot32(x: np.ndarray) -> np.ndarray:
    """Swap the 32-bit halves of each uint64."""
    return (x >> _U64(32)) | (x << _U64(32))


class HighwayState:
    """Vectorized HighwayHash state over S independent streams.

    All four state vectors are uint64 arrays of shape [S, 4]. Every method
    advances all streams in lockstep; streams are completely independent.
    """

    def __init__(self, key: bytes, streams: int = 1):
        if len(key) != 32:
            raise ValueError("HighwayHash-256 requires a 32-byte key")
        self._key_lanes = np.frombuffer(key, dtype="<u8").astype(_U64)
        self.streams = streams
        self.reset()

    def reset(self) -> None:
        s = self.streams
        k = self._key_lanes
        self.v0 = np.broadcast_to(_INIT0 ^ k, (s, 4)).copy()
        self.v1 = np.broadcast_to(_INIT1 ^ _rot32(k), (s, 4)).copy()
        self.mul0 = np.broadcast_to(_INIT0, (s, 4)).copy()
        self.mul1 = np.broadcast_to(_INIT1, (s, 4)).copy()

    # -- core permutation ---------------------------------------------------

    def _zipper_merge_add(self, v1e, v0e, add1, add0, idx1, idx0):
        """add{0,1}[:, idx] += zipper-merge of the (v1e, v0e) lane pair."""
        u = _U64
        m = lambda x: u(x)  # noqa: E731 - terse 64-bit literals
        add0[:, idx0] += ((((v0e & m(0xFF000000)) | (v1e & m(0xFF00000000))) >> u(24))
                          | (((v0e & m(0xFF0000000000)) | (v1e & m(0xFF000000000000))) >> u(16))
                          | (v0e & m(0xFF0000)) | ((v0e & m(0xFF00)) << u(32))
                          | ((v1e & m(0xFF00000000000000)) >> u(8)) | (v0e << u(56)))
        add1[:, idx1] += ((((v1e & m(0xFF000000)) | (v0e & m(0xFF00000000))) >> u(24))
                          | (v1e & m(0xFF0000)) | ((v1e & m(0xFF0000000000)) >> u(16))
                          | ((v1e & m(0xFF00)) << u(24)) | ((v0e & m(0xFF000000000000)) >> u(8))
                          | ((v1e & m(0xFF)) << u(48)) | (v0e & m(0xFF00000000000000)))

    def update(self, lanes: np.ndarray) -> None:
        """One 32-byte packet per stream: lanes uint64 [S, 4]."""
        v0, v1, mul0, mul1 = self.v0, self.v1, self.mul0, self.mul1
        v1 += mul0 + lanes
        mul0 ^= (v1 & _MASK32) * (v0 >> _U64(32))
        v0 += mul1
        mul1 ^= (v0 & _MASK32) * (v1 >> _U64(32))
        self._zipper_merge_add(v1[:, 1], v1[:, 0], v0, v0, 1, 0)
        self._zipper_merge_add(v1[:, 3], v1[:, 2], v0, v0, 3, 2)
        self._zipper_merge_add(v0[:, 1], v0[:, 0], v1, v1, 1, 0)
        self._zipper_merge_add(v0[:, 3], v0[:, 2], v1, v1, 3, 2)

    def update_packets(self, packets: np.ndarray) -> None:
        """packets: uint8 [S, n_packets, 32] — sequential over n_packets."""
        lanes = packets.reshape(self.streams, -1, 32).view("<u8").astype(_U64)
        for p in range(lanes.shape[1]):
            self.update(lanes[:, p, :])

    def update_remainder(self, tail: np.ndarray, size_mod32: int) -> None:
        """Final partial packet: tail uint8 [S, size_mod32], 0 < size_mod32 < 32."""
        s = self.streams
        size_mod4 = size_mod32 & 3
        rem = size_mod32 & ~3
        packet = np.zeros((s, 32), dtype=np.uint8)
        packet[:, :rem] = tail[:, :rem]
        self.v0 += (_U64(size_mod32) << _U64(32)) + _U64(size_mod32)
        # Rotate each 32-bit half of every v1 lane left by size_mod32 bits.
        c = _U64(size_mod32)
        lo = self.v1 & _MASK32
        hi = self.v1 >> _U64(32)
        if size_mod32:
            lo = ((lo << c) | (lo >> (_U64(32) - c))) & _MASK32
            hi = ((hi << c) | (hi >> (_U64(32) - c))) & _MASK32
        self.v1 = (hi << _U64(32)) | lo
        if size_mod32 & 16:
            for i in range(4):
                packet[:, 28 + i] = tail[:, rem + i + size_mod4 - 4]
        elif size_mod4:
            packet[:, 16] = tail[:, rem]
            packet[:, 17] = tail[:, rem + (size_mod4 >> 1)]
            packet[:, 18] = tail[:, rem + size_mod4 - 1]
        self.update(packet.reshape(s, 1, 32).view("<u8").astype(_U64)[:, 0, :])

    def _permute_and_update(self) -> None:
        v0 = self.v0
        permuted = np.empty_like(v0)
        permuted[:, 0] = _rot32(v0[:, 2])
        permuted[:, 1] = _rot32(v0[:, 3])
        permuted[:, 2] = _rot32(v0[:, 0])
        permuted[:, 3] = _rot32(v0[:, 1])
        self.update(permuted)

    def finalize256(self) -> np.ndarray:
        """Returns uint8 [S, 32]. State is consumed (call reset to reuse)."""
        for _ in range(10):
            self._permute_and_update()
        h = np.empty((self.streams, 4), dtype=_U64)
        self._modular_reduction(self.v1[:, 1] + self.mul1[:, 1],
                                self.v1[:, 0] + self.mul1[:, 0],
                                self.v0[:, 1] + self.mul0[:, 1],
                                self.v0[:, 0] + self.mul0[:, 0], h, 1, 0)
        self._modular_reduction(self.v1[:, 3] + self.mul1[:, 3],
                                self.v1[:, 2] + self.mul1[:, 2],
                                self.v0[:, 3] + self.mul0[:, 3],
                                self.v0[:, 2] + self.mul0[:, 2], h, 3, 2)
        return h.astype("<u8").view(np.uint8).reshape(self.streams, 32)

    @staticmethod
    def _modular_reduction(a3u, a2, a1, a0, out, i1, i0):
        a3 = a3u & _U64(0x3FFFFFFFFFFFFFFF)
        out[:, i1] = a1 ^ ((a3 << _U64(1)) | (a2 >> _U64(63))) \
            ^ ((a3 << _U64(2)) | (a2 >> _U64(62)))
        out[:, i0] = a0 ^ (a2 << _U64(1)) ^ (a2 << _U64(2))


def _hh256_python(key: bytes, buf: np.ndarray) -> bytes:
    st = HighwayState(key, streams=1)
    n = buf.size
    full = n // 32
    if full:
        st.update_packets(buf[:full * 32].reshape(1, full, 32))
    if n % 32:
        st.update_remainder(buf[full * 32:][None, :], n % 32)
    return st.finalize256()[0].tobytes()


def highwayhash256(key: bytes, data: bytes | np.ndarray) -> bytes:
    """One-shot single-stream HighwayHash-256 (native C++ when built)."""
    if len(key) != 32:
        raise ValueError("HighwayHash-256 requires a 32-byte key")
    from minio_tpu import native
    lib = native.load()
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray, memoryview)) \
        else np.ascontiguousarray(data, dtype=np.uint8)
    if lib is not None:
        out = np.empty(32, dtype=np.uint8)
        lib.mtpu_hh256(native._u8(np.frombuffer(key, dtype=np.uint8)),
                       native._u8(buf), buf.size, native._u8(out))
        return out.tobytes()
    return _hh256_python(key, buf)


def highwayhash256_many(key: bytes, blocks: np.ndarray) -> np.ndarray:
    """Hash S equal-length blocks: uint8 [S, L] -> uint8 [S, 32].

    This is the bitrot hot path — native C++ per stream when built, else
    the vectorized lockstep numpy recurrence across streams.
    """
    if len(key) != 32:
        raise ValueError("HighwayHash-256 requires a 32-byte key")
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    s, n = blocks.shape
    from minio_tpu import native
    lib = native.load()
    if lib is not None:
        out = np.empty((s, 32), dtype=np.uint8)
        lib.mtpu_hh256_many(native._u8(np.frombuffer(key, dtype=np.uint8)),
                            native._u8(blocks), s, n, n, native._u8(out))
        return out
    st = HighwayState(key, streams=s)
    full = n // 32
    if full:
        st.update_packets(blocks[:, :full * 32].reshape(s, full, 32))
    if n % 32:
        st.update_remainder(blocks[:, full * 32:], n % 32)
    return st.finalize256()
