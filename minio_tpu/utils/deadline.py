"""Per-request deadline budgets, propagated across fan-out threads.

The analogue of the reference's context deadline plumbing: a request
gets one monotonic budget at admission (s3/server.py), every layer
below consumes from it — erasure fan-outs bound their waits, the drive
health wrapper clamps op timeouts, grid calls clamp their reply waits
and stop retrying — so a hung drive or dead peer bounds the WHOLE
request instead of stacking timeouts per layer.

Python threads have no context inheritance, so propagation is explicit:
`current()` reads the calling thread's binding and fan-out helpers
re-`bind()` it inside their worker threads (erasure_object._fanout).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional


class DeadlineExceeded(Exception):
    """The request's deadline budget is exhausted.

    Deliberately NOT a StorageError: the drive did nothing wrong, the
    REQUEST ran out of time — the health breaker must never count it
    as drive fuel, and the S3 layer maps it to 408 RequestTimeout."""


class Deadline:
    """A fixed point in monotonic time the request must not outlive."""

    __slots__ = ("expires_at",)

    def __init__(self, seconds: float):
        self.expires_at = time.monotonic() + seconds

    def remaining(self) -> float:
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self) -> None:
        if self.expired():
            raise DeadlineExceeded("request deadline exceeded")

    def clamp(self, timeout: Optional[float]) -> float:
        """Smaller of `timeout` and the remaining budget (never
        negative — a 0 timeout fails the wait immediately, which is
        the correct shape for an exhausted budget)."""
        rem = max(0.0, self.remaining())
        if timeout is None:
            return rem
        return min(timeout, rem)


_local = threading.local()


def current() -> Optional[Deadline]:
    return getattr(_local, "deadline", None)


@contextlib.contextmanager
def bind(dl: Optional[Deadline]):
    """Bind `dl` as the calling thread's deadline for the block.
    Binding None is a no-op passthrough (callers thread an optional
    deadline without branching)."""
    prev = getattr(_local, "deadline", None)
    _local.deadline = dl if dl is not None else prev
    try:
        yield dl
    finally:
        _local.deadline = prev


@contextlib.contextmanager
def shield():
    """Run a block with NO deadline bound (bind(None) is a
    passthrough, not an unbind). For rollback/cleanup work that must
    complete even though the request's own budget is spent — skipping
    a rollback because the request timed out would leave exactly the
    partial state the rollback exists to remove."""
    prev = getattr(_local, "deadline", None)
    _local.deadline = None
    try:
        yield
    finally:
        _local.deadline = prev


def clamp(timeout: Optional[float]) -> Optional[float]:
    """Clamp `timeout` to the current thread's remaining budget;
    passthrough when no deadline is bound."""
    dl = current()
    if dl is None:
        return timeout
    return dl.clamp(timeout)


def check() -> None:
    """Raise DeadlineExceeded if the bound budget is exhausted."""
    dl = current()
    if dl is not None:
        dl.check()
