"""Sized byte streams for the O(block) data path.

The reference never holds whole objects in memory: PutObject pipes the
request body through 1 MiB-block encode with readahead (reference:
cmd/erasure-encode.go:69, cmd/erasure-object.go:1415-1428) and its hash
readers verify content digests incrementally as bytes flow
(internal/hash/reader.go:42). `Payload` is this framework's equivalent
seam: a sized `.read(n)` source with an optional `finish()` hook that
runs exactly once after the last byte is consumed — where incremental
sha256/aws-chunk-signature verification rejects a tampered body BEFORE
the object commit.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Optional


class StreamError(Exception):
    """Body ended early or a streaming integrity check failed."""


class Payload:
    """A sized byte source for put paths.

    reader: object with read(n) -> bytes (may return fewer; b'' at EOF).
    size: exact number of payload bytes the reader will deliver.
    finish: optional hook called once after `size` bytes were consumed;
        raises to abort the upload before commit (content-sha256 /
        trailer verification lives here).
    """

    def __init__(self, reader, size: int,
                 finish: Optional[Callable[[], None]] = None):
        if size < 0:
            raise ValueError("payload size must be known and non-negative")
        self.size = size
        self._reader = reader
        self._finish = finish
        self._remaining = size
        self._finished = False

    @property
    def remaining(self) -> int:
        return self._remaining

    @classmethod
    def wrap(cls, data) -> "Payload":
        """bytes-like or Payload -> Payload."""
        if isinstance(data, Payload):
            return data
        return cls(_BytesReader(data), len(data))

    def read(self, n: int) -> bytes:
        """Up to n payload bytes; b'' at end. Runs the finish hook on the
        read that consumes the final byte (and on the first read of an
        empty payload)."""
        if self._remaining <= 0:
            self._run_finish()
            return b""
        if n <= 0:
            return b""
        n = min(n, self._remaining)
        chunk = self._reader.read(n)
        if not chunk:
            raise StreamError(
                f"body ended {self._remaining} bytes short of declared size")
        self._remaining -= len(chunk)
        if self._remaining == 0:
            self._run_finish()
        return chunk

    def read_exact(self, n: int) -> bytes:
        """Exactly min(n, remaining) bytes."""
        parts = []
        want = n
        while want > 0:
            chunk = self.read(want)
            if not chunk:
                break
            parts.append(chunk)
            want -= len(chunk)
        if not parts:
            # Nothing left (or an empty payload): make sure the finish
            # hook still runs — a 0-byte body must be verified too.
            self.read(0)
            return b""
        return parts[0] if len(parts) == 1 else b"".join(parts)

    def read_all(self) -> bytes:
        return self.read_exact(self._remaining)

    def _run_finish(self):
        if not self._finished:
            self._finished = True
            if self._finish is not None:
                self._finish()


class _BytesReader:
    def __init__(self, data):
        self._mv = memoryview(data)
        self._pos = 0

    def read(self, n: int) -> bytes:
        chunk = self._mv[self._pos:self._pos + n]
        self._pos += len(chunk)
        return bytes(chunk)


class HashingReader:
    """Wraps a reader, feeding every byte to a digest as it passes.

    The put path reads through this so the content hash the client
    declared can be checked the moment the body ends — no second pass,
    no buffering (reference: internal/hash/reader.go:42)."""

    def __init__(self, reader, algorithm: str = "sha256"):
        self._reader = reader
        self.digest = hashlib.new(algorithm)

    def read(self, n: int) -> bytes:
        chunk = self._reader.read(n)
        if chunk:
            self.digest.update(chunk)
        return chunk

    def hexdigest(self) -> str:
        return self.digest.hexdigest()


class LimitedReader:
    """At most `limit` bytes from an underlying file-like reader."""

    def __init__(self, raw, limit: int):
        self._raw = raw
        self._remaining = limit

    def read(self, n: int) -> bytes:
        if self._remaining <= 0:
            return b""
        chunk = self._raw.read(min(n, self._remaining))
        self._remaining -= len(chunk)
        return chunk

    def readinto(self, b) -> int:
        """Limit-capped readinto so pooled-buffer consumers (sigv4
        PooledChunkedReader) fill straight from the socket reader with
        no intermediate bytes object."""
        if self._remaining <= 0:
            return 0
        mv = memoryview(b).cast("B")
        want = min(len(mv), self._remaining)
        ri = getattr(self._raw, "readinto", None)
        if ri is not None:
            n = ri(mv[:want])
        else:
            chunk = self._raw.read(want)
            n = len(chunk)
            mv[:n] = chunk
        self._remaining -= n
        return n


class HttpChunkedReader:
    """Incremental Transfer-Encoding: chunked decoder over a buffered
    socket file (needs .readline()/.read()). Consumes the terminal
    0-chunk and trailer lines fully so keep-alive connections see a
    clean request boundary."""

    def __init__(self, rfile, max_size: int = 5 * (1 << 40)):
        self._rfile = rfile
        self._max = max_size
        self._seen = 0
        self._left = 0          # unread bytes of the current chunk
        self._done = False

    def _next_chunk(self) -> None:
        line = self._rfile.readline()
        if not line:
            raise StreamError("truncated chunked body")
        try:
            size = int(line.strip().split(b";")[0], 16)
        except ValueError:
            raise StreamError("bad chunk size") from None
        self._seen += size
        if self._seen > self._max:
            raise StreamError("chunked body exceeds size limit")
        if size == 0:
            # Trailer section: zero or more header lines, then CRLF.
            while True:
                t = self._rfile.readline()
                if not t or t in (b"\r\n", b"\n"):
                    break
            self._done = True
        else:
            self._left = size

    def read(self, n: int) -> bytes:
        while self._left == 0:
            if self._done:
                return b""
            self._next_chunk()
        take = min(n, self._left)
        data = self._rfile.read(take)
        if len(data) != take:
            raise StreamError("truncated chunk data")
        self._left -= take
        if self._left == 0:
            self._rfile.read(2)   # chunk-terminating CRLF
        return data
