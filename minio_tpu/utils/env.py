"""Shared env-knob parsing: positive number with a default.

Every subsystem grew its own `_env_int`/`_env_float` copy of this
logic; new code imports these instead so the parse rules (empty/unset
-> default, unparsable -> default, <= 0 -> default) cannot drift
per-module. Knobs where 0 is meaningful (disable semantics) parse
themselves — these helpers are for strictly-positive tunables.
"""

from __future__ import annotations

import os


def env_num(key: str, default, cast=float):
    try:
        v = cast(os.environ.get(key, "") or default)
        return v if v > 0 else default
    except ValueError:
        return default


def env_float(key: str, default: float) -> float:
    return env_num(key, default, float)


def env_int(key: str, default: int) -> int:
    return env_num(key, default, int)
