"""Bucketed latency histograms and rolling last-minute windows.

The analogue of the reference's metrics-v3 histograms plus its
per-drive last-minute latency tracking (cmd/last-minute.gen.go): every
observation lands in a fixed-boundary cumulative histogram (Prometheus
`_bucket{le=}` shape) and in a 60-slot one-second ring whose merged
view answers "p50/p99/max over the LAST minute" — the question a
dashboard sum/count pair cannot (a counter pair never forgets the
past; the ring does, by design).

Both structures are a few ints under one short lock per observe —
cheap enough to stay always-on under every drive op and API request.
Snapshots are plain JSON-safe dicts so pre-forked workers ship them
over the control pipe and any worker can merge the fleet's view.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

# Prometheus-style cumulative upper bounds, seconds. The +Inf bucket is
# implicit (== count).
BUCKETS: tuple = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                  0.5, 1.0, 2.5, 5.0, 10.0)

_SLOTS = 60


class Histogram:
    """Fixed-boundary latency histogram (cumulative on render)."""

    __slots__ = ("_mu", "counts", "sum", "count")

    def __init__(self):
        self._mu = threading.Lock()
        self.counts = [0] * (len(BUCKETS) + 1)   # last = overflow (+Inf)
        self.sum = 0.0
        self.count = 0

    def observe(self, seconds: float) -> None:
        i = _bucket_index(seconds)
        with self._mu:
            self.counts[i] += 1
            self.sum += seconds
            self.count += 1

    def state(self) -> dict:
        with self._mu:
            return {"counts": list(self.counts),
                    "sum": round(self.sum, 6), "count": self.count}

    @staticmethod
    def merge(states: Sequence[dict]) -> dict:
        counts = [0] * (len(BUCKETS) + 1)
        total_sum, total_count = 0.0, 0
        for st in states:
            for i, c in enumerate(st.get("counts", [])[:len(counts)]):
                counts[i] += c
            total_sum += st.get("sum", 0.0)
            total_count += st.get("count", 0)
        return {"counts": counts, "sum": round(total_sum, 6),
                "count": total_count}

    @staticmethod
    def cumulative(state: dict) -> list[tuple[str, int]]:
        """[(le_label, cumulative_count)] including +Inf — the
        Prometheus exposition shape."""
        out = []
        acc = 0
        counts = state.get("counts", [])
        for i, ub in enumerate(BUCKETS):
            acc += counts[i] if i < len(counts) else 0
            out.append((_le(ub), acc))
        acc += counts[len(BUCKETS)] if len(counts) > len(BUCKETS) else 0
        out.append(("+Inf", acc))
        return out


def _bucket_index(seconds: float) -> int:
    for i, ub in enumerate(BUCKETS):
        if seconds <= ub:
            return i
    return len(BUCKETS)


def _le(ub: float) -> str:
    s = f"{ub:g}"
    return s


class LastMinute:
    """60 one-second slots of (count, max, per-bucket counts); merged
    on read into the trailing-minute window. Stale slots (older than
    60 s) are zeroed lazily on the write path, so an idle series decays
    to empty without a sweeper thread."""

    __slots__ = ("_mu", "_slots")

    def __init__(self):
        self._mu = threading.Lock()
        # slot: [epoch_second, count, max_seconds, bucket_counts]
        self._slots = [[0, 0, 0.0, None] for _ in range(_SLOTS)]

    def observe(self, seconds: float, now: Optional[float] = None) -> None:
        sec = int(now if now is not None else time.time())
        slot = self._slots[sec % _SLOTS]
        i = _bucket_index(seconds)
        with self._mu:
            if slot[0] != sec:
                slot[0] = sec
                slot[1] = 0
                slot[2] = 0.0
                slot[3] = [0] * (len(BUCKETS) + 1)
            slot[1] += 1
            if seconds > slot[2]:
                slot[2] = seconds
            slot[3][i] += 1

    def window(self, now: Optional[float] = None) -> dict:
        """The merged trailing-minute view: {count, max, counts}."""
        cutoff = int(now if now is not None else time.time()) - _SLOTS
        counts = [0] * (len(BUCKETS) + 1)
        total, mx = 0, 0.0
        with self._mu:
            for slot in self._slots:
                if slot[0] <= cutoff or slot[3] is None:
                    continue
                total += slot[1]
                if slot[2] > mx:
                    mx = slot[2]
                for i, c in enumerate(slot[3]):
                    counts[i] += c
        return {"count": total, "max": round(mx, 6), "counts": counts}

    def stats(self, now: Optional[float] = None) -> dict:
        """{count, p50, p99, max} over the last minute (seconds)."""
        return summarize(self.window(now))

    @staticmethod
    def merge(windows: Sequence[dict]) -> dict:
        counts = [0] * (len(BUCKETS) + 1)
        total, mx = 0, 0.0
        for w in windows:
            total += w.get("count", 0)
            mx = max(mx, w.get("max", 0.0))
            for i, c in enumerate(w.get("counts", [])[:len(counts)]):
                counts[i] += c
        return {"count": total, "max": round(mx, 6), "counts": counts}


def percentile(counts: Sequence[int], total: int, q: float,
               overflow: Optional[float] = None) -> float:
    """Upper-bound estimate of the q-quantile (0..1) from bucket
    counts — the bucket's upper edge, the standard histogram_quantile
    shape. Quantiles landing in the +Inf bucket report `overflow`
    (callers pass the window's tracked max so a 60 s stall reads as
    60 s, not a silent cap). Returns 0.0 on an empty window."""
    if total <= 0:
        return 0.0
    if overflow is None:
        overflow = BUCKETS[-1] * 2
    rank = max(1, int(total * q + 0.999999))
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= rank:
            return BUCKETS[i] if i < len(BUCKETS) else overflow
    return overflow


def summarize(window: dict) -> dict:
    counts = window.get("counts", [])
    total = window.get("count", 0)
    mx = window.get("max", 0.0)
    # Overflow-bucket quantiles report the observed max: anything past
    # the last bucket edge IS at least that slow, and the true worst
    # case is already tracked.
    ov = mx if mx > BUCKETS[-1] else None
    return {
        "count": total,
        "p50": round(percentile(counts, total, 0.50, overflow=ov), 6),
        "p99": round(percentile(counts, total, 0.99, overflow=ov), 6),
        "max": round(mx, 6),
    }
