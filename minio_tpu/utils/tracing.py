"""Request-scoped span-tree tracing, threaded through every layer.

The deep half of the observability pair (the shallow half — top-level
request records — lives in s3/trace.py): one cheap span context rides
the same thread-local channel the deadline budget already rides
(utils/deadline.py), and every layer a request traverses — erasure
fan-out, per-drive engine queue, storage op, grid RPC, native kernel
window — records a span into the request's bounded ring. Per
Dapper-style tracing (Sigelman et al., 2010) the context is armed only
when somebody is watching: a trace subscriber asking for internal types
(`mc admin trace`-style) or a configured slow-op threshold. Disarmed,
every call site reduces to ONE module-attribute check (`tracing.ACTIVE`)
so the request path pays near-zero when nobody looks.

Span records are plain dicts:
    {"type": "storage", "name": "disk.read_file", "span": 3,
     "parent": 1, "start": <epoch s>, "duration_ms": 1.25,
     "tags": {...}}
Parent linkage crosses thread boundaries explicitly: fan-out helpers
capture (ctx, parent span id) at submission and re-`bind()` inside the
worker thread, exactly like the deadline re-bind next to them.

Slow-op log: any span (armed by MTPU_SLOW_OP_MS > 0, independently of
trace subscribers) whose duration crosses the threshold emits one
structured record carrying its ancestry — a slow GET names the slow
drive — into a bounded in-process ring surfaced via admin info, the
trace stream (type unchanged, `"slow": true`), and stderr.

Environment:
  MTPU_SLOW_OP_MS      slow-op threshold in ms (0/unset = off)
  MTPU_TRACE_MAX_SPANS per-request span ring size (default 512)
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import uuid
from typing import Optional

# Every trace type a span may carry; admin trace filters on these.
TRACE_TYPES = ("s3", "storage", "grid", "kernel", "scanner", "heal",
               "repl")

# -- node identity ----------------------------------------------------------
# The node's self-declared identity ("host:port" of its S3 plane, the
# same string PeerCoherence uses). Stamped on slow-op records and trace
# entries so cluster-merged streams stay attributable. Empty until the
# distributed boot calls set_node(); single-node deployments stay
# unstamped.

NODE = ""


def set_node(node_id: str) -> None:
    global NODE
    NODE = str(node_id or "")

# -- arming -----------------------------------------------------------------
# ACTIVE is THE fast-path gate: call sites check it before touching any
# span machinery. It is true while any source (a trace subscriber
# wanting internal types, a remote worker relay, a configured slow-op
# threshold, a bench harness) holds an arm() token.

ACTIVE = False
_arm_mu = threading.Lock()
_arm_sources: set = set()
_slow_ms = 0.0


def _refresh_locked() -> None:
    global ACTIVE
    ACTIVE = bool(_arm_sources) or _slow_ms > 0


def arm(source) -> None:
    """Arm span collection on behalf of `source` (any hashable)."""
    with _arm_mu:
        _arm_sources.add(source)
        _refresh_locked()


def disarm(source) -> None:
    with _arm_mu:
        _arm_sources.discard(source)
        _refresh_locked()


def slow_ms() -> float:
    return _slow_ms


def set_slow_ms(ms: float) -> None:
    """Set the slow-op threshold (tests / config hot-apply); ms <= 0
    disables. Arms span collection on its own."""
    global _slow_ms
    with _arm_mu:
        _slow_ms = max(0.0, float(ms))
        _refresh_locked()


def _env_float(key: str, default: float) -> float:
    try:
        return float(os.environ.get(key, "") or default)
    except ValueError:
        return default


def _env_int(key: str, default: int) -> int:
    try:
        v = int(os.environ.get(key, "") or default)
        return v if v > 0 else default
    except ValueError:
        return default


set_slow_ms(_env_float("MTPU_SLOW_OP_MS", 0.0))
MAX_SPANS = _env_int("MTPU_TRACE_MAX_SPANS", 512)


# -- slow-op ring -----------------------------------------------------------

SLOW_RING = 256
# stderr lines per second cap: an aggressive threshold (every span
# over 1 ms) must degrade to a sampled log, not a flood that can wedge
# the data path behind an undrained stderr pipe. The ring and the
# total counter still capture every record.
SLOW_LOG_PER_S = 20
_slow_mu = threading.Lock()
_slow_ops: collections.deque = collections.deque(maxlen=SLOW_RING)
slow_total = 0
_slow_log_sec = 0
_slow_log_n = 0


def slow_ops() -> list[dict]:
    """Snapshot of the most recent slow-op records (newest last)."""
    with _slow_mu:
        return list(_slow_ops)


def slow_event(type_: str, name: str, ms: float = 0.0,
               tags: Optional[dict] = None) -> None:
    """Record one event on the slow-op channel UNCONDITIONALLY (no
    MTPU_SLOW_OP_MS threshold): for rare operational failures — a peer
    that would not ack an invalidation, a swallowed best-effort
    broadcast — that must reach the ring, the counters, and stderr
    even on a box with slow-op sampling disarmed. The rate limiter
    still bounds stderr volume."""
    _record_slow({"type": type_, "name": name, "ms": round(ms, 3),
                  "time": time.time(), "event": True,
                  "tags": dict(tags or {})})


def _record_slow(rec: dict) -> None:
    global slow_total, _slow_log_sec, _slow_log_n
    if NODE and "node" not in rec:
        rec["node"] = NODE
    sec = int(time.time())
    with _slow_mu:
        _slow_ops.append(rec)
        slow_total += 1
        if sec != _slow_log_sec:
            _slow_log_sec = sec
            _slow_log_n = 0
        _slow_log_n += 1
        emit = _slow_log_n <= SLOW_LOG_PER_S
    if not emit:
        return
    try:
        print("mtpu slow-op: " + json.dumps(rec), file=sys.stderr,
              flush=True)
    except Exception:  # noqa: BLE001 - telemetry must not raise
        pass


# -- publisher hook ---------------------------------------------------------
# Background spans (scanner/heal cycles with no request context) and
# slow-op records publish straight to the live broadcaster via this
# hook; the S3 server sets it at boot (last server wins in-process —
# only tests run several).

_publisher = None


def set_publisher(fn) -> None:
    global _publisher
    _publisher = fn


def publish_entry(entry: dict) -> None:
    pub = _publisher
    if pub is not None:
        try:
            pub(entry)
        except Exception:  # noqa: BLE001 - telemetry must not raise
            pass


# -- the context ------------------------------------------------------------

class TraceContext:
    """One request's span ring. Span id 0 is the (implicit) root — the
    top-level S3 entry the server publishes at request end."""

    __slots__ = ("trace_id", "spans", "dropped", "_mu", "_next", "start",
                 "_open")

    def __init__(self, trace_id: str = ""):
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.spans: list[dict] = []
        self.dropped = 0
        self._mu = threading.Lock()
        self._next = 1
        self.start = time.time()
        # Spans currently in flight: sid -> (name, parent). A child
        # exits BEFORE its parent, so slow-op ancestry must see parents
        # that have no completed record yet.
        self._open: dict[int, tuple] = {}

    def add(self, rec: dict) -> None:
        with self._mu:
            if len(self.spans) >= MAX_SPANS:
                self.dropped += 1
                return
            self.spans.append(rec)

    def next_id(self) -> int:
        with self._mu:
            sid = self._next
            self._next += 1
            return sid

    def open_span(self, sid: int, name: str, parent: int) -> None:
        with self._mu:
            self._open[sid] = (name, parent)

    def close_span(self, sid: int) -> None:
        with self._mu:
            self._open.pop(sid, None)

    def ancestry(self, parent: int) -> list[str]:
        """Names of the span's ancestors, root-first ('<root>' for span
        id 0). Used by slow-op records so one line names the path."""
        with self._mu:
            by_id = {s["span"]: (s["name"], s["parent"])
                     for s in self.spans}
            by_id.update(self._open)
        chain: list[str] = []
        seen = set()
        cur = parent
        while cur and cur in by_id and cur not in seen:
            seen.add(cur)
            name, nxt = by_id[cur]
            chain.append(name)
            cur = nxt
        chain.append("<root>")
        chain.reverse()
        return chain


_local = threading.local()


def current() -> Optional[TraceContext]:
    return getattr(_local, "ctx", None)


def current_parent() -> int:
    return getattr(_local, "parent", 0)


def capture() -> tuple[Optional[TraceContext], int]:
    """(ctx, parent span id) of the calling thread — what a fan-out
    helper captures at submission to re-bind() inside its worker."""
    return current(), current_parent()


class _Bind:
    """Context manager binding (ctx, parent) as the calling thread's
    trace scope. bind(None) is a passthrough, mirroring deadline.bind."""

    __slots__ = ("_ctx", "_parent", "_prev")

    def __init__(self, ctx, parent):
        self._ctx = ctx
        self._parent = parent

    def __enter__(self):
        self._prev = (getattr(_local, "ctx", None),
                      getattr(_local, "parent", 0))
        if self._ctx is not None:
            _local.ctx = self._ctx
            _local.parent = self._parent
        return self._ctx

    def __exit__(self, *exc):
        _local.ctx, _local.parent = self._prev
        return False


def bind(ctx: Optional[TraceContext], parent: int = 0) -> _Bind:
    return _Bind(ctx, parent)


# -- spans ------------------------------------------------------------------

class _NoopSpan:
    """Shared, stateless, reentrant no-op for the disarmed path."""

    __slots__ = ()
    tags: Optional[dict] = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def tag(self, **kv):
        pass


NOOP = _NoopSpan()


class _Span:
    __slots__ = ("_ctx", "_type", "_name", "tags", "_sid", "_parent",
                 "_t0", "_wall", "_prev_parent")

    def __init__(self, ctx, type_, name, tags):
        self._ctx = ctx
        self._type = type_
        self._name = name
        self.tags = tags

    def tag(self, **kv):
        if self.tags is None:
            self.tags = {}
        self.tags.update(kv)

    def __enter__(self):
        ctx = self._ctx
        self._sid = ctx.next_id()
        self._parent = getattr(_local, "parent", 0)
        ctx.open_span(self._sid, self._name, self._parent)
        _local.parent = self._sid
        self._wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur_ms = (time.perf_counter() - self._t0) * 1000.0
        _local.parent = self._parent
        self._ctx.close_span(self._sid)
        rec = {"type": self._type, "name": self._name,
               "span": self._sid, "parent": self._parent,
               "start": self._wall, "duration_ms": round(dur_ms, 3)}
        if self.tags:
            rec["tags"] = self.tags
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        thr = _slow_ms
        if thr > 0 and dur_ms >= thr:
            # Slow markers ride the span record itself: the ONE place
            # the span is published (request end / _OpSpan exit)
            # carries them — publishing here too would stream every
            # slow span twice under the same trace/span id.
            rec["slow"] = True
            rec["threshold_ms"] = thr
            rec["ancestry"] = self._ctx.ancestry(self._parent)
            slow = dict(rec)
            slow["trace"] = self._ctx.trace_id
            _record_slow(slow)
        self._ctx.add(rec)
        return False


def span(type_: str, name: str, tags: Optional[dict] = None):
    """A child span of the calling thread's bound context; the shared
    no-op when tracing is disarmed or no context is bound. Call sites
    on the hottest paths should pre-guard with `if tracing.ACTIVE:`."""
    if not ACTIVE:
        return NOOP
    ctx = getattr(_local, "ctx", None)
    if ctx is None:
        return NOOP
    return _Span(ctx, type_, name, tags)


class _OpSpan:
    """A standalone single-span trace for background work (scanner
    cycles, heals outside any request): creates a throwaway context,
    records the one span, publishes it directly at exit."""

    __slots__ = ("_ctx", "_bind", "_span")

    def __init__(self, type_, name, tags):
        self._ctx = TraceContext()
        self._bind = bind(self._ctx, 0)
        self._span = _Span(self._ctx, type_, name, tags)

    def __enter__(self):
        self._bind.__enter__()
        self._span.__enter__()
        return self._span

    def __exit__(self, exc_type, exc, tb):
        self._span.__exit__(exc_type, exc, tb)
        self._bind.__exit__(exc_type, exc, tb)
        for rec in self._ctx.spans:
            publish_entry(_entry_from(rec, self._ctx.trace_id))
        return False


def op_span(type_: str, name: str, tags: Optional[dict] = None):
    """span() when a request context is bound; a standalone published
    trace otherwise (background scanner/heal work); NOOP disarmed."""
    if not ACTIVE:
        return NOOP
    if getattr(_local, "ctx", None) is not None:
        return _Span(_local.ctx, type_, name, tags)
    return _OpSpan(type_, name, tags)


def record(type_: str, name: str, start_wall: float, duration_ms: float,
           tags: Optional[dict] = None, parent: Optional[int] = None) -> None:
    """Record an already-measured span (call sites that time manually,
    e.g. grid streams). No-op without a bound context. Over-threshold
    records feed the slow-op log exactly like _Span exits do."""
    ctx = getattr(_local, "ctx", None)
    if ctx is None or not ACTIVE:
        return
    record_into(ctx, current_parent() if parent is None else parent,
                type_, name, start_wall, duration_ms, tags)


def record_into(ctx: Optional[TraceContext], parent: int, type_: str,
                name: str, start_wall: float, duration_ms: float,
                tags: Optional[dict] = None) -> None:
    """record() into an explicitly captured (ctx, parent) scope.

    For work executed on a thread bound to no single request — e.g. one
    coalesced device dispatch serving many PUTs at once: the batcher
    captures each member's scope at submission and fans the ONE kernel
    span into every member's span tree, so each request's trace shows
    the shared dispatch it rode (with per-batch tags), not a gap."""
    if ctx is None or not ACTIVE:
        return
    record_span(ctx, parent, type_, name, start_wall, duration_ms, tags)


def record_span(ctx: TraceContext, parent: int, type_: str, name: str,
                start_wall: float, duration_ms: float,
                tags: Optional[dict] = None) -> int:
    """record_into(), returning the allocated span id so the caller can
    hang children (a grid call's stitched remote subtree) under it."""
    sid = ctx.next_id()
    rec = {"type": type_, "name": name, "span": sid, "parent": parent,
           "start": start_wall, "duration_ms": round(duration_ms, 3)}
    if tags:
        rec["tags"] = tags
    thr = _slow_ms
    if thr > 0 and rec["duration_ms"] >= thr:
        rec["slow"] = True
        rec["threshold_ms"] = thr
        rec["ancestry"] = ctx.ancestry(parent)
        slow = dict(rec)
        slow["trace"] = ctx.trace_id
        _record_slow(slow)
    ctx.add(rec)
    return sid


# -- cross-node propagation -------------------------------------------------
# A grid peer executing an armed call records its spans into a local
# TraceContext seeded with the caller's trace id, then ships the
# completed subtree back piggybacked on the reply (export_spans — wire-
# safe copies, capped). The caller grafts them under an explicit `wire`
# span (stitch_wire) that splits serialize / transit / peer-queue-wait
# / peer-service, remapping the remote span ids into its own sequence.

# Cap on spans shipped back per reply: bounds the piggyback bytes the
# way MAX_SPANS bounds the local ring.
REMOTE_MAX = _env_int("MTPU_TRACE_REMOTE_MAX", 128)

_WIRE_KEYS = ("type", "name", "span", "parent", "start", "duration_ms",
              "tags", "error", "slow", "threshold_ms")


def export_spans(ctx: TraceContext, limit: Optional[int] = None) -> dict:
    """The context's spans as a wire-safe payload: plain-dict copies
    (ancestry stripped — the caller re-derives paths in its own tree),
    capped at `limit` (default REMOTE_MAX) with the overflow counted
    in `dropped` alongside spans the ring itself already shed."""
    cap = REMOTE_MAX if limit is None else max(0, int(limit))
    with ctx._mu:
        spans = list(ctx.spans)
        dropped = ctx.dropped
    if len(spans) > cap:
        dropped += len(spans) - cap
        spans = spans[:cap]
    out = []
    for rec in spans:
        out.append({k: rec[k] for k in _WIRE_KEYS if k in rec})
    return {"spans": out, "dropped": dropped}


def stitch_wire(ctx: TraceContext, parent: int, start_wall: float,
                duration_ms: float, tags: Optional[dict],
                shipped: Optional[dict]) -> int:
    """Graft a peer's shipped subtree into the caller's tree under an
    explicit `wire` span. `tags` carries the timing split (serialize_ms
    / transit_ms / peer_queue_ms / peer_service_ms, plus peer identity
    or a transport fault annotation); `shipped` is the peer's
    export_spans() payload (None when the call faulted before a reply).
    Returns the wire span id."""
    wire_sid = record_span(ctx, parent, "grid", "wire", start_wall,
                           duration_ms, tags)
    if not shipped:
        return wire_sid
    remote = shipped.get("spans") or []
    node = shipped.get("node", "")
    # Remap remote span ids into the caller's sequence in ascending
    # order so every parent is remapped before its children (remote
    # ids are allocated monotonically).
    sid_map: dict[int, int] = {}
    for rec in sorted(remote, key=lambda r: r.get("span", 0)):
        try:
            new = dict(rec)
            new["span"] = sid_map[rec["span"]] = ctx.next_id()
            new["parent"] = sid_map.get(rec.get("parent", 0), wire_sid)
            if node:
                new["node"] = node
            ctx.add(new)
        except Exception:  # noqa: BLE001 - a malformed remote span
            pass           # must not break the caller's request
    extra = shipped.get("dropped", 0)
    if extra:
        with ctx._mu:
            ctx.dropped += int(extra)
    return wire_sid


# -- entry conversion -------------------------------------------------------

def _iso_ms(epoch: float) -> str:
    whole = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(epoch))
    return f"{whole}.{int(epoch * 1000) % 1000:03d}Z"


def _entry_from(rec: dict, trace_id: str) -> dict:
    entry = {
        "version": "1",
        "trace_type": rec["type"],
        "time": _iso_ms(rec["start"]),
        "api": rec["name"],
        "trace": trace_id,
        "span": rec["span"],
        "parent": rec["parent"],
        "durationMs": rec["duration_ms"],
    }
    for k in ("tags", "error", "slow", "threshold_ms", "ancestry",
              "node"):
        if k in rec:
            entry[k] = rec[k]
    if NODE and "node" not in entry:
        entry["node"] = NODE
    return entry


def entries_from(ctx: TraceContext, worker: int = 0) -> list[dict]:
    """The request's child spans rendered as trace entries (the root
    s3 entry is built by the server from make_entry and carries span
    id 0)."""
    with ctx._mu:
        spans = list(ctx.spans)
    out = []
    for rec in spans:
        e = _entry_from(rec, ctx.trace_id)
        e["worker"] = worker
        out.append(e)
    if ctx.dropped:
        # Truncation marker: `broadcast` bypasses subscriber type
        # filters — a storage-only stream must still learn its span
        # tree is incomplete.
        out.append({"version": "1", "trace_type": "s3",
                    "broadcast": True,
                    "time": _iso_ms(time.time()), "api": "trace.dropped",
                    "trace": ctx.trace_id, "span": -1, "parent": 0,
                    "durationMs": 0.0, "worker": worker,
                    "tags": {"dropped_spans": ctx.dropped}})
    return out
