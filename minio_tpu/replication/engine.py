"""Replication rules and the async replication engine."""

from __future__ import annotations

import dataclasses
import json
import queue
import threading
import time
import xml.etree.ElementTree as ET
from typing import Optional

REPL_STATUS_KEY = "x-internal-repl-status"
REMOTE_TARGET_META = "config:remote-target"
REPLICATION_META = "config:replication"

PENDING = "PENDING"
COMPLETED = "COMPLETED"
FAILED = "FAILED"


class ReplicationError(Exception):
    pass


@dataclasses.dataclass
class ReplicationRule:
    rule_id: str = ""
    enabled: bool = True
    prefix: str = ""
    delete_markers: bool = False

    def matches(self, key: str) -> bool:
        return self.enabled and key.startswith(self.prefix)


def parse_replication_xml(xml: bytes | str) -> list[ReplicationRule]:
    """ReplicationConfiguration XML -> rules (reference:
    internal/bucket/replication/replication.go)."""
    try:
        root = ET.fromstring(xml)
    except ET.ParseError as e:
        raise ReplicationError(f"malformed replication XML: {e}") from None
    for el in root.iter():
        if isinstance(el.tag, str) and "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    rules = []
    for rel in root.iter("Rule"):
        r = ReplicationRule()
        r.rule_id = rel.findtext("ID") or ""
        r.enabled = (rel.findtext("Status") or "Enabled") != "Disabled"
        filt = rel.find("Filter")
        r.prefix = (filt.findtext("Prefix") if filt is not None else None) \
            or rel.findtext("Prefix") or ""
        dmr = rel.find("DeleteMarkerReplication")
        if dmr is not None and (dmr.findtext("Status") or "") == "Enabled":
            r.delete_markers = True
        if rel.find("Destination") is None:
            raise ReplicationError("Rule missing Destination")
        rules.append(r)
    if not rules:
        raise ReplicationError("replication configuration has no rules")
    return rules


class ReplicationEngine:
    """Per-server replication worker pool.

    object_layer: the local object layer (bucket meta + object reads +
    status updates). Targets resolve from each bucket's stored remote
    target record ({endpoint, accessKey, secretKey, bucket}); clients
    cache per bucket. SSE objects are not replicated in v1 (their data
    keys are bound to this cluster) — they mark FAILED immediately.
    """

    _RETRIES = 5

    def __init__(self, object_layer, workers: int = 2):
        self.object_layer = object_layer
        self.queued = 0
        self.completed = 0
        self.failed = 0
        self._clients: dict[str, tuple] = {}
        self._rules_cache: dict[str, tuple] = {}
        self._q: "queue.Queue[tuple]" = queue.Queue(maxsize=100_000)
        self._stop = threading.Event()
        self._threads = [threading.Thread(target=self._run, daemon=True)
                         for _ in range(workers)]
        for t in self._threads:
            t.start()

    # -- configuration ---------------------------------------------------

    def rules_for(self, bucket: str) -> Optional[list[ReplicationRule]]:
        try:
            doc = self.object_layer.get_bucket_meta(bucket) \
                .get(REPLICATION_META)
        except Exception:  # noqa: BLE001
            return None
        if not doc:
            return None
        # Parse once per distinct document — this runs on every PUT and
        # DELETE of a replicated bucket.
        hit = self._rules_cache.get(bucket)
        if hit is not None and hit[0] == doc:
            return hit[1]
        try:
            rules = parse_replication_xml(doc)
        except ReplicationError:
            rules = None
        self._rules_cache[bucket] = (doc, rules)
        return rules

    def target_for(self, bucket: str):
        """(RemoteS3 client, target bucket) or None."""
        try:
            doc = self.object_layer.get_bucket_meta(bucket) \
                .get(REMOTE_TARGET_META)
        except Exception:  # noqa: BLE001
            return None
        if not doc:
            return None
        hit = self._clients.get(bucket)
        if hit is not None and hit[0] == doc:
            return hit[1]
        try:
            rec = json.loads(doc)
            from minio_tpu.s3.client import RemoteS3
            client = RemoteS3(rec["endpoint"], rec["accessKey"],
                              rec["secretKey"])
            target = (client, rec.get("bucket", bucket))
        except (ValueError, KeyError):
            target = None
        self._clients[bucket] = (doc, target)
        return target

    def should_replicate(self, bucket: str, key: str,
                         delete: bool = False) -> bool:
        rules = self.rules_for(bucket)
        if not rules or self.target_for(bucket) is None:
            return False
        for r in rules:
            if r.matches(key):
                return not delete or r.delete_markers
        return False

    # -- ingestion -------------------------------------------------------

    def enqueue(self, bucket: str, key: str, version_id: str = "",
                op: str = "put") -> None:
        try:
            self._q.put_nowait((bucket, key, version_id, op, 0))
            self.queued += 1
        except queue.Full:
            self.failed += 1

    # -- delivery --------------------------------------------------------

    def _set_status(self, bucket, key, version_id, status) -> None:
        try:
            self.object_layer.update_version_metadata(
                bucket, key, version_id,
                lambda meta: meta.__setitem__(REPL_STATUS_KEY, status))
        except Exception:  # noqa: BLE001 - status is advisory
            pass

    def _replicate_put(self, bucket, key, version_id) -> None:
        target = self.target_for(bucket)
        if target is None:
            raise ReplicationError("no remote target")
        client, tbucket = target
        from minio_tpu.replication.common import DeliveryError, push_object
        try:
            push_object(self.object_layer, client, bucket, key,
                        version_id, tbucket)
        except DeliveryError as e:
            raise ReplicationError(str(e)) from None

    def _replicate_delete(self, bucket, key) -> None:
        target = self.target_for(bucket)
        if target is None:
            raise ReplicationError("no remote target")
        client, tbucket = target
        client.delete_object(tbucket, key)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                bucket, key, vid, op, attempt = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                if op == "put":
                    self._replicate_put(bucket, key, vid)
                    self._set_status(bucket, key, vid, COMPLETED)
                else:
                    self._replicate_delete(bucket, key)
                self.completed += 1
            except Exception:  # noqa: BLE001 - retry then FAILED
                if attempt + 1 < self._RETRIES and not self._stop.is_set():
                    time.sleep(min(0.2 * 2 ** attempt, 5.0))
                    try:
                        self._q.put_nowait((bucket, key, vid, op,
                                            attempt + 1))
                    except queue.Full:
                        self.failed += 1
                else:
                    self.failed += 1
                    if op == "put":
                        self._set_status(bucket, key, vid, FAILED)
            finally:
                self._q.task_done()

    # -- resync (scanner hook) -------------------------------------------

    def scanner_hook(self, es, bucket: str, key: str, versions) -> None:
        """Re-queue versions stuck PENDING/FAILED (crash recovery /
        target-outage resync, reference: replication resync)."""
        if not versions or versions[0].deleted:
            return
        latest = versions[0]
        if latest.metadata.get("x-internal-sse-alg"):
            # SSE objects never replicate in v1: their FAILED state is
            # terminal, not resync fuel.
            return
        status = latest.metadata.get(REPL_STATUS_KEY, "")
        if status in (PENDING, FAILED) and \
                self.should_replicate(bucket, key):
            self.enqueue(bucket, key, latest.version_id, "put")

    def drain(self, timeout: float = 15.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self._q.unfinished_tasks == 0:
                return True
            time.sleep(0.05)
        return False

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
