"""Replication rules and the durable, ordered replication engine.

v2 of the bucket-replication plane (reference:
cmd/bucket-replication.go + the MRF/resync machinery around it).  The
v1 engine was a bounded in-memory queue.Queue: intents vanished on
SIGKILL, queue.Full counted silently as `failed`, retry backoff slept
ON the worker thread (one dead target wedged the pool), and versions
of one key delivered concurrently so the target's latest could be an
older source version.  This rebuild makes replication meet the same
survivability bar as the rest of the tree:

  * Durable queue — every intent lands in a per-node WAL
    (`<first-local-drive>/.mtpu.sys/repl/wal-p<pid>-<uid>.log`, the
    group-commit frame format: magic + crc32 + t_ns + msgpack) BEFORE
    enqueue returns to the PUT/DELETE handler; completions append a
    `done` marker; boot-time replay re-queues every incomplete intent
    (torn tails discarded — they were never acked).  Overflow past the
    admission cap spills to a persisted pending set (the MRF pattern)
    instead of dropping: `spilled` is lossless, `dropped` stays 0 and
    is the alertable counter.
  * Per-target lanes — each remote endpoint gets its own delivery lane
    with a circuit breaker mirroring grid/client.py (trip after N
    consecutive TRANSPORT faults, one half-open probe per cooldown,
    jittered doubling backoff across failed probes).  Retries and
    breaker re-probes are scheduled on a shared timer heap — no worker
    thread ever sleeps a backoff, so a dead target costs one fast
    failure per probe while healthy targets keep replicating.
  * Ordering — intents for one (bucket, key) serialize per lane in
    source-version order (mod_time, then enqueue seq): the target's
    latest is always the source's latest.  Delete markers replicate as
    versioned marker intents carrying the source marker's version id,
    never as anonymous bare deletes.
  * Resync — a checkpointed, resumable full-bucket sweep
    (`start_resync`) re-queues every version whose status is not
    COMPLETED; the scanner hook walks the FULL version stack (older
    stuck versions and delete markers included, not just versions[0]).

`MTPU_REPLICATION_DURABLE=off` reverts to the v1 in-memory plane:
no WAL, no breakers — only the v1 bug fixes remain (overflow spills
instead of dropping, retries ride the timer heap instead of sleeping
on the worker).
"""

from __future__ import annotations

import dataclasses
import heapq
import json
import os
import queue
import random
import struct
import threading
import time
import uuid as uuid_mod
import xml.etree.ElementTree as ET
import zlib
from typing import Optional

from minio_tpu.utils import tracing

REPL_STATUS_KEY = "x-internal-repl-status"
REMOTE_TARGET_META = "config:remote-target"
REPLICATION_META = "config:replication"

PENDING = "PENDING"
COMPLETED = "COMPLETED"
FAILED = "FAILED"

SYS_VOL = ".mtpu.sys"
WAL_DIR = "repl"
WAL_MAGIC = b"RPW1"
_FRAME_HEAD = struct.Struct("<I")        # crc32(body)
_FRAME_BODY_HEAD = struct.Struct("<QI")  # t_ns, payload length

_PERSIST_EVERY = 2.0      # pending-set persistence throttle (seconds)
_CKPT_EVERY = 64          # resync checkpoint cadence (keys)
_COMPACT_DONE = 256       # WAL compaction threshold (done marks)


def durable_enabled() -> bool:
    return os.environ.get("MTPU_REPLICATION_DURABLE", "on").lower() \
        not in ("0", "off", "false")


def _wal_fsync_enabled() -> bool:
    return os.environ.get("MTPU_REPL_WAL_FSYNC", "on").lower() \
        not in ("0", "off", "false")


def _env_num(name: str, default, cast=float):
    try:
        return cast(os.environ.get(name, ""))
    except (TypeError, ValueError):
        return default


class ReplicationError(Exception):
    pass


class BreakerOpen(ReplicationError):
    """Lane circuit open: fail fast, re-probe later (never a retry
    attempt — breaker waits are scheduling, not delivery failures)."""


@dataclasses.dataclass
class ReplicationRule:
    rule_id: str = ""
    enabled: bool = True
    prefix: str = ""
    delete_markers: bool = False

    def matches(self, key: str) -> bool:
        return self.enabled and key.startswith(self.prefix)


def parse_replication_xml(xml: bytes | str) -> list[ReplicationRule]:
    """ReplicationConfiguration XML -> rules (reference:
    internal/bucket/replication/replication.go)."""
    try:
        root = ET.fromstring(xml)
    except ET.ParseError as e:
        raise ReplicationError(f"malformed replication XML: {e}") from None
    for el in root.iter():
        if isinstance(el.tag, str) and "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    rules = []
    for rel in root.iter("Rule"):
        r = ReplicationRule()
        r.rule_id = rel.findtext("ID") or ""
        r.enabled = (rel.findtext("Status") or "Enabled") != "Disabled"
        filt = rel.find("Filter")
        r.prefix = (filt.findtext("Prefix") if filt is not None else None) \
            or rel.findtext("Prefix") or ""
        dmr = rel.find("DeleteMarkerReplication")
        if dmr is not None and (dmr.findtext("Status") or "") == "Enabled":
            r.delete_markers = True
        if rel.find("Destination") is None:
            raise ReplicationError("Rule missing Destination")
        rules.append(r)
    if not rules:
        raise ReplicationError("replication configuration has no rules")
    return rules


# ---------------------------------------------------------------------------
# Shared retry timer: backoffs and breaker re-probes live on ONE heap
# serviced by one daemon thread — a delivery worker never sleeps.
# ---------------------------------------------------------------------------

class RetryTimer:
    def __init__(self, name: str = "repl-timer"):
        self._cv = threading.Condition(threading.Lock())
        self._heap: list = []      # (due, tiebreak, fn)
        self._n = 0
        self._stopped = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    def call_later(self, delay: float, fn) -> None:
        with self._cv:
            if self._stopped:
                return
            self._n += 1
            heapq.heappush(self._heap,
                           (time.monotonic() + max(0.0, delay),
                            self._n, fn))
            self._cv.notify()

    def pending(self) -> int:
        with self._cv:
            return len(self._heap)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stopped:
                    if self._heap:
                        wait = self._heap[0][0] - time.monotonic()
                        if wait <= 0:
                            break
                        self._cv.wait(wait)
                    else:
                        self._cv.wait()
                if self._stopped:
                    return
                _, _, fn = heapq.heappop(self._heap)
            try:
                fn()
            except Exception:  # noqa: BLE001 - timer must survive callbacks
                pass

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._heap.clear()
            self._cv.notify()
        self._thread.join(timeout=2)


# ---------------------------------------------------------------------------
# Per-lane circuit breaker (mirrors grid/client.py): consecutive
# TRANSPORT faults open it, one half-open probe per cooldown window,
# failed probes double the cooldown (jittered, bounded).
# ---------------------------------------------------------------------------

class LaneBreaker:
    PROBE_TTL = 30.0

    def __init__(self, trip_after: Optional[int] = None,
                 cooldown: Optional[float] = None,
                 cooldown_max: Optional[float] = None):
        self.trip_after = trip_after if trip_after is not None \
            else _env_num("MTPU_REPL_TRIP_AFTER", 3, int)
        self.cooldown = cooldown if cooldown is not None \
            else _env_num("MTPU_REPL_COOLDOWN", 0.5)
        self.cooldown_max = cooldown_max if cooldown_max is not None \
            else _env_num("MTPU_REPL_COOLDOWN_MAX", 15.0)
        self._mu = threading.Lock()
        self._consecutive = 0
        self._open_since = 0.0           # 0 = closed
        self._open_for = 0.0
        self._probe_streak = 0
        self._half_open_probe = False
        self._probe_started = 0.0
        self._probe_owner = 0
        self.opens_total = 0
        self.faults_total = 0

    def admit(self) -> None:
        with self._mu:
            if self._open_since == 0.0:
                return
            now = time.monotonic()
            if now - self._open_since < self._open_for:
                raise BreakerOpen("target circuit open")
            if self._half_open_probe and \
                    now - self._probe_started < self.PROBE_TTL:
                raise BreakerOpen("target circuit half-open, probing")
            self._half_open_probe = True
            self._probe_started = now
            self._probe_owner = threading.get_ident()

    def fault(self) -> None:
        with self._mu:
            self._consecutive += 1
            self.faults_total += 1
            if self._open_since != 0.0:
                # Failed half-open PROBE: restart the cooldown, doubled
                # (jittered, bounded).  Only the probe OWNER's failure
                # counts — stragglers admitted before the trip must not
                # inflate the backoff or release a live probe's slot.
                if not self._half_open_probe or \
                        self._probe_owner != threading.get_ident():
                    return
                self._half_open_probe = False
                self._probe_streak += 1
                self._open_since = time.monotonic()
                self._open_for = min(
                    self.cooldown * (2 ** self._probe_streak),
                    self.cooldown_max) * (0.75 + random.random() / 2)
            elif self._consecutive >= self.trip_after:
                self.opens_total += 1
                self._open_since = time.monotonic()
                self._probe_streak = 0
                self._open_for = self.cooldown * \
                    (0.75 + random.random() / 2)

    def ok(self) -> None:
        with self._mu:
            self._consecutive = 0
            self._open_since = 0.0
            self._open_for = 0.0
            self._probe_streak = 0
            self._half_open_probe = False

    def state(self) -> str:
        with self._mu:
            if self._open_since == 0.0:
                return "closed"
            if time.monotonic() - self._open_since >= self._open_for:
                return "half-open"
            return "open"

    def retry_in(self) -> float:
        """Suggested delay until the next admission attempt is worth
        making: the remaining cooldown while open, a short re-check
        while another thread holds the half-open probe."""
        with self._mu:
            if self._open_since == 0.0:
                return 0.0
            remaining = self._open_for - \
                (time.monotonic() - self._open_since)
            if remaining > 0:
                return remaining
            return min(0.25, self.cooldown)


# ---------------------------------------------------------------------------
# Durable intent WAL (the group-commit frame format: PR-14 pattern).
# ---------------------------------------------------------------------------

class ReplWAL:
    """Per-node replication intent log.

    Frames: `RPW1 | crc32(body) u32 | body = t_ns u64 | len u32 |
    msgpack payload`.  Intent payloads carry {seq,b,k,v,op,mt};
    completion payloads carry {done: seq}.  A torn tail (or alien
    bytes) ends replay — a torn frame was never any intent's
    durability point, so discarding it loses nothing acked.  Files are
    per-engine-instance (`wal-p<pid>-<uid>.log`); replay adopts every
    OTHER file in the directory (dead processes / prior boots),
    returns their incomplete intents, and unlinks them once the caller
    has re-logged the survivors into the live file."""

    def __init__(self, root: str, fsync: Optional[bool] = None):
        self.dir = os.path.join(root, SYS_VOL, WAL_DIR)
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(
            self.dir,
            f"wal-p{os.getpid()}-{uuid_mod.uuid4().hex[:8]}.log")
        self.fsync = _wal_fsync_enabled() if fsync is None else fsync
        self._mu = threading.Lock()
        self._fd = os.open(self.path,
                           os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
        self._live: dict[int, dict] = {}   # seq -> intent payload
        self._done_since_compact = 0
        self.appended = 0
        self.done_marks = 0
        self.discarded = 0
        self.compactions = 0

    # -- framing --------------------------------------------------------

    @staticmethod
    def _frame(payload: dict) -> bytes:
        import msgpack
        mp = msgpack.packb(payload, use_bin_type=True)
        body = _FRAME_BODY_HEAD.pack(time.time_ns(), len(mp)) + mp
        return WAL_MAGIC + _FRAME_HEAD.pack(zlib.crc32(body)) + body

    @staticmethod
    def iter_frames(blob: bytes):
        """Yield (t_ns, payload) per intact frame; stop at the first
        torn/alien bytes (the discard count is the StopIteration
        value, mirroring group_commit.iter_frames)."""
        import msgpack
        off = 0
        n = len(blob)
        while off < n:
            if blob[off:off + 4] != WAL_MAGIC:
                return 1
            head_end = off + 4 + _FRAME_HEAD.size
            if head_end + _FRAME_BODY_HEAD.size > n:
                return 1
            (crc,) = _FRAME_HEAD.unpack(blob[off + 4:head_end])
            t_ns, plen = _FRAME_BODY_HEAD.unpack(
                blob[head_end:head_end + _FRAME_BODY_HEAD.size])
            body_end = head_end + _FRAME_BODY_HEAD.size + plen
            if body_end > n:
                return 1
            body = blob[head_end:body_end]
            if zlib.crc32(body) != crc:
                return 1
            try:
                payload = msgpack.unpackb(
                    body[_FRAME_BODY_HEAD.size:], raw=False)
            except Exception:  # noqa: BLE001 - corrupt payload = torn
                return 1
            yield t_ns, payload
            off = body_end
        return 0

    # -- appends --------------------------------------------------------

    def _append_locked(self, payload: dict) -> None:
        os.write(self._fd, self._frame(payload))
        if self.fsync:
            try:
                os.fdatasync(self._fd)
            except OSError:
                pass

    def append_intent(self, rec: dict) -> None:
        with self._mu:
            self._append_locked(rec)
            self._live[rec["seq"]] = rec
            self.appended += 1

    def mark_done(self, seq: int) -> None:
        with self._mu:
            if self._live.pop(seq, None) is None:
                return
            self._append_locked({"done": seq})
            self.done_marks += 1
            self._done_since_compact += 1
            if self._done_since_compact >= _COMPACT_DONE:
                self._compact_locked()

    def _compact_locked(self) -> None:
        """Rewrite the WAL with only the live intents: done markers
        and their retired frames drop, so a long-lived process's WAL
        stays proportional to its backlog, not its history."""
        tmp = self.path + ".compact"
        fd = os.open(tmp, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
        try:
            for rec in self._live.values():
                os.write(fd, self._frame(rec))
            if self.fsync:
                try:
                    os.fdatasync(fd)
                except OSError:
                    pass
        finally:
            os.close(fd)
        os.replace(tmp, self.path)
        os.close(self._fd)
        self._fd = os.open(self.path,
                           os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
        self._done_since_compact = 0
        self.compactions += 1

    # -- replay ---------------------------------------------------------

    def replay_others(self) -> list[dict]:
        """Incomplete intents from every OTHER WAL file in the dir
        (earlier boots / SIGKILLed processes), oldest-first, deduped
        by (bucket, key, version, op).  Caller re-logs them through
        the normal enqueue path, then `retire_replayed` unlinks the
        source files."""
        out: list[tuple[int, dict]] = []
        self._replayed_files: list[str] = []
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return []
        for name in names:
            if not name.startswith("wal-") or not name.endswith(".log"):
                continue
            path = os.path.join(self.dir, name)
            if path == self.path:
                continue
            try:
                with open(path, "rb") as fh:
                    blob = fh.read()
            except OSError:
                continue
            live: dict[int, tuple[int, dict]] = {}
            it = self.iter_frames(blob)
            while True:
                try:
                    t_ns, payload = next(it)
                except StopIteration as stop:
                    self.discarded += stop.value or 0
                    break
                if "done" in payload:
                    live.pop(payload["done"], None)
                elif "seq" in payload:
                    live[payload["seq"]] = (t_ns, payload)
            out.extend(live.values())
            self._replayed_files.append(path)
        out.sort(key=lambda t: (t[0], t[1].get("seq", 0)))
        seen = set()
        recs = []
        for _, rec in out:
            idk = (rec.get("b"), rec.get("k"), rec.get("v"),
                   rec.get("op"))
            if idk in seen:
                continue
            seen.add(idk)
            recs.append(rec)
        return recs

    def retire_replayed(self) -> None:
        for path in getattr(self, "_replayed_files", []):
            try:
                os.unlink(path)
            except OSError:
                pass
        self._replayed_files = []

    def live_count(self) -> int:
        with self._mu:
            return len(self._live)

    def close(self) -> None:
        with self._mu:
            try:
                os.close(self._fd)
            except OSError:
                pass
            if not self._live:
                # Nothing incomplete: the file is pure history — drop
                # it so restarts replay only real backlogs.
                try:
                    os.unlink(self.path)
                except OSError:
                    pass


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------

def _layer_sets(layer) -> list:
    pools = getattr(layer, "pools", None)
    if pools is not None:
        return [s for p in pools for s in p.sets]
    sets = getattr(layer, "sets", None)
    if sets is not None:
        return list(sets)
    return [layer] if hasattr(layer, "disks") else []


def _first_local_root(layer) -> Optional[str]:
    for es in _layer_sets(layer):
        for d in getattr(es, "disks", []):
            root = getattr(d, "root", None)
            if root:
                return root
    return None


class _Lane:
    """One remote target's delivery lane: per-key ordered chains plus
    the target's circuit breaker."""

    __slots__ = ("target", "chains", "active", "pending", "breaker",
                 "newest")

    def __init__(self, target: str, use_breaker: bool = True):
        self.target = target
        # (bucket, key) -> intents ordered by (mod_time, seq): the
        # chain head is the only deliverable intent of its key, so
        # versions serialize in source order per target.
        self.chains: dict[tuple, list] = {}
        self.active: set = set()
        self.pending = 0
        self.breaker = LaneBreaker() if use_breaker else None
        # Newest successfully-delivered version per live chain
        # (mod_time, version_id, op): when an out-of-order older
        # delivery ends a chain, the newest re-delivers so the
        # target's latest converges back to the source's latest.
        self.newest: dict[tuple, tuple] = {}


@dataclasses.dataclass
class _Intent:
    seq: int
    bucket: str
    key: str
    version_id: str
    op: str                   # "put" | "delete"
    mod_time: int = 0         # source version mod_time (ns); 0 unknown
    attempt: int = 0
    t_enq: float = 0.0        # monotonic enqueue stamp (lag histogram)

    @property
    def idk(self) -> tuple:
        return (self.bucket, self.key, self.version_id, self.op)

    def rec(self) -> dict:
        return {"seq": self.seq, "b": self.bucket, "k": self.key,
                "v": self.version_id, "op": self.op, "mt": self.mod_time}


class ReplicationEngine:
    """Per-server replication plane (see module docstring).

    object_layer: the local object layer (bucket meta + object reads +
    status updates).  Targets resolve from each bucket's stored remote
    target record ({endpoint, accessKey, secretKey, bucket}); clients
    cache per bucket.  SSE objects are not replicated (their data keys
    are bound to this cluster) — they mark FAILED immediately and
    count in `sse_skipped`."""

    _RETRIES = 5

    def __init__(self, object_layer, workers: int = 2,
                 durable: Optional[bool] = None):
        self.object_layer = object_layer
        self.durable = durable_enabled() if durable is None else durable
        self.queued = 0
        self.completed = 0
        self.failed = 0
        self.spilled = 0
        self.dropped = 0
        self.sse_skipped = 0
        self.replayed = 0
        self._clients: dict[str, tuple] = {}
        self._rules_cache: dict[str, tuple] = {}
        self._q_max = _env_num("MTPU_REPL_QUEUE_MAX", 100_000, int)
        self._mu = threading.Lock()
        self._lanes: dict[str, _Lane] = {}
        self._seen: set = set()
        self._spill: dict[tuple, dict] = {}
        self._spill_saved = 0.0
        self._unfinished = 0
        self._seq = 0
        from minio_tpu.utils.latency import Histogram
        self.lag_hist = Histogram()
        self._work: "queue.Queue[tuple]" = queue.Queue()
        self._stop = threading.Event()
        self.timer = RetryTimer()
        self._resyncs: dict[str, dict] = {}
        self._resync_threads: dict[str, threading.Thread] = {}
        # Durable state rides the first LOCAL drive (the events-store
        # location pattern); a layer with no local drive degrades to
        # the in-memory plane.
        self._root = _first_local_root(object_layer)
        self.wal: Optional[ReplWAL] = None
        if self.durable and self._root is None:
            self.durable = False
        if self.durable:
            self.wal = ReplWAL(self._root)
        self._threads = [threading.Thread(target=self._run, daemon=True,
                                          name=f"repl-{i}")
                         for i in range(workers)]
        for t in self._threads:
            t.start()
        self._load_spill()
        if self.wal is not None:
            self._replay_wal()
            self._resume_resyncs()

    # -- configuration ---------------------------------------------------

    def rules_for(self, bucket: str) -> Optional[list[ReplicationRule]]:
        try:
            doc = self.object_layer.get_bucket_meta(bucket) \
                .get(REPLICATION_META)
        except Exception:  # noqa: BLE001
            return None
        if not doc:
            return None
        # Parse once per distinct document — this runs on every PUT and
        # DELETE of a replicated bucket.
        hit = self._rules_cache.get(bucket)
        if hit is not None and hit[0] == doc:
            return hit[1]
        try:
            rules = parse_replication_xml(doc)
        except ReplicationError:
            rules = None
        self._rules_cache[bucket] = (doc, rules)
        return rules

    def target_for(self, bucket: str):
        """(RemoteS3 client, target bucket) or None."""
        try:
            doc = self.object_layer.get_bucket_meta(bucket) \
                .get(REMOTE_TARGET_META)
        except Exception:  # noqa: BLE001
            return None
        if not doc:
            return None
        hit = self._clients.get(bucket)
        if hit is not None and hit[0] == doc:
            return hit[1]
        try:
            rec = json.loads(doc)
            from minio_tpu.s3.client import RemoteS3
            client = RemoteS3(rec["endpoint"], rec["accessKey"],
                              rec["secretKey"])
            target = (client, rec.get("bucket", bucket))
        except (ValueError, KeyError):
            target = None
        self._clients[bucket] = (doc, target)
        return target

    def should_replicate(self, bucket: str, key: str,
                         delete: bool = False) -> bool:
        rules = self.rules_for(bucket)
        if not rules or self.target_for(bucket) is None:
            return False
        for r in rules:
            if r.matches(key):
                return not delete or r.delete_markers
        return False

    # -- ingestion -------------------------------------------------------

    def _lane_key(self, bucket: str) -> str:
        t = self.target_for(bucket)
        return t[0].address if t is not None else "?"

    def enqueue(self, bucket: str, key: str, version_id: str = "",
                op: str = "put", mod_time: int = 0) -> None:
        """Admit one replication intent.  Durable mode logs it to the
        WAL BEFORE returning — the caller's ack implies the intent
        survives SIGKILL.  Overflow past the admission cap spills to
        the persisted pending set (lossless) instead of dropping."""
        idk = (bucket, key, version_id, op)
        with self._mu:
            if idk in self._seen:
                return
            self._seen.add(idk)
            self._seq += 1
            seq = self._seq
        intent = _Intent(seq=seq, bucket=bucket, key=key,
                         version_id=version_id, op=op, mod_time=mod_time,
                         t_enq=time.monotonic())
        if self.wal is not None:
            # Rides the caller's request span tree when armed: the WAL
            # append (+fsync) sits on the PUT ack path, so a slow PUT
            # trace names the durability tax explicitly.
            with tracing.span("repl", "repl.wal_append",
                              {"bucket": bucket, "op": op}) \
                    if tracing.ACTIVE else tracing.NOOP:
                self.wal.append_intent(intent.rec())
        self._admit(intent)

    def _admit(self, intent: _Intent) -> None:
        lane_key = self._lane_key(intent.bucket)
        with self._mu:
            self.queued += 1
            self._unfinished += 1
            lane = self._lanes.get(lane_key)
            if lane is None:
                lane = self._lanes[lane_key] = _Lane(
                    lane_key, use_breaker=self.durable)
            if lane.pending >= self._q_max:
                # Overflow: spill (lossless, replayed on drain) — the
                # v1 plane counted this as `failed` and LOST the item.
                self._spill[intent.idk] = intent.rec()
                self.spilled += 1
                self._maybe_save_spill_locked()
                return
            self._chain_insert_locked(lane, intent)
        self._maybe_save_spill()

    def _chain_insert_locked(self, lane: _Lane, intent: _Intent) -> None:
        ck = (intent.bucket, intent.key)
        chain = lane.chains.get(ck)
        if chain is None:
            lane.chains[ck] = [intent]
            lane.pending += 1
            self._work.put((lane.target, ck))
            return
        # Source-version order: a resync-discovered OLDER version must
        # deliver before an already-queued newer one, or the target's
        # latest ends up older than the source's.  The head is only
        # pinned while a worker is actually delivering it.
        floor = 1 if ck in lane.active else 0
        pos = len(chain)
        while pos > floor and (intent.mod_time, intent.seq) < \
                (chain[pos - 1].mod_time, chain[pos - 1].seq):
            pos -= 1
        chain.insert(pos, intent)
        lane.pending += 1

    # -- spill persistence (MRF pattern) ---------------------------------

    def _spill_path(self) -> Optional[str]:
        if self._root is None:
            return None
        return os.path.join(self._root, SYS_VOL, WAL_DIR, "pending.json")

    def _maybe_save_spill_locked(self, force: bool = False) -> None:
        path = self._spill_path()
        if path is None:
            return
        now = time.monotonic()
        if not force and now - self._spill_saved < _PERSIST_EVERY:
            return
        if not self._spill:
            # Drained: a stale pending.json would re-enqueue already-
            # delivered intents at the next boot (an old PUT replayed
            # after a completed DELETE regresses the target's latest),
            # so remove the file rather than leave it behind.
            try:
                os.unlink(path)
            except OSError:
                pass
            self._spill_saved = 0.0
            return
        self._spill_saved = now
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"items": list(self._spill.values())}, fh)
            os.replace(tmp, path)
        except OSError:
            pass

    def _maybe_save_spill(self) -> None:
        with self._mu:
            if self._spill or self._spill_saved:
                self._maybe_save_spill_locked()

    def _load_spill(self) -> None:
        path = self._spill_path()
        if path is None:
            return
        try:
            with open(path, encoding="utf-8") as fh:
                items = json.load(fh).get("items") or []
        except (OSError, ValueError):
            return
        for rec in items:
            try:
                self.enqueue(rec["b"], rec["k"], rec.get("v", ""),
                             rec.get("op", "put"), rec.get("mt", 0))
            except Exception:  # noqa: BLE001 - malformed entry
                continue
        try:
            os.unlink(path)
        except OSError:
            pass

    def _refill_one(self) -> None:
        """Promote one spilled intent when a delivery frees room — the
        MRF `_refill_one` pattern."""
        with self._mu:
            if not self._spill:
                return
            idk, rec = next(iter(self._spill.items()))
        # Resolve the lane outside the lock (bucket-meta read).
        lane_key = self._lane_key(idk[0])
        with self._mu:
            lane = self._lanes.get(lane_key)
            if lane is not None and lane.pending >= self._q_max:
                return
            rec = self._spill.pop(idk, None)
            if rec is None:
                return
            if lane is None:
                lane = self._lanes[lane_key] = _Lane(
                    lane_key, use_breaker=self.durable)
            self._chain_insert_locked(lane, _Intent(
                seq=rec.get("seq", 0), bucket=rec["b"], key=rec["k"],
                version_id=rec.get("v", ""), op=rec.get("op", "put"),
                mod_time=rec.get("mt", 0), t_enq=time.monotonic()))
            # Keep the on-disk pending set in step with the pops
            # (forced on the drain-to-empty transition so the file is
            # removed, not left listing delivered intents).
            self._maybe_save_spill_locked(force=not self._spill)

    # -- WAL replay ------------------------------------------------------

    def _replay_wal(self) -> None:
        recs = self.wal.replay_others()
        for rec in recs:
            try:
                self.enqueue(rec["b"], rec["k"], rec.get("v", ""),
                             rec.get("op", "put"), rec.get("mt", 0))
                self.replayed += 1
            except Exception:  # noqa: BLE001 - malformed frame payload
                continue
        self.wal.retire_replayed()

    # -- delivery --------------------------------------------------------

    def _set_status(self, bucket, key, version_id, status,
                    allow_delete_marker: bool = False) -> bool:
        try:
            self.object_layer.update_version_metadata(
                bucket, key, version_id,
                lambda meta: meta.__setitem__(REPL_STATUS_KEY, status),
                allow_delete_marker=allow_delete_marker)
            return True
        except TypeError:
            # Layer without the allow_delete_marker parameter (older
            # wrapper): plain call, markers stay unstamped.
            try:
                self.object_layer.update_version_metadata(
                    bucket, key, version_id,
                    lambda meta: meta.__setitem__(REPL_STATUS_KEY, status))
                return True
            except Exception:  # noqa: BLE001 - status is advisory
                return False
        except Exception:  # noqa: BLE001 - status is advisory
            return False

    def _replicate_put(self, bucket, key, version_id) -> None:
        target = self.target_for(bucket)
        if target is None:
            raise ReplicationError("no remote target")
        client, tbucket = target
        from minio_tpu.replication.common import push_object
        push_object(self.object_layer, client, bucket, key,
                    version_id, tbucket)

    def _replicate_delete(self, bucket, key, version_id) -> None:
        target = self.target_for(bucket)
        if target is None:
            raise ReplicationError("no remote target")
        client, tbucket = target
        from minio_tpu.replication.common import push_delete_marker
        push_delete_marker(client, tbucket, key, version_id)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                lane_key, ck = self._work.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._service(lane_key, ck)
            except Exception:  # noqa: BLE001 - worker must survive
                pass

    def _requeue_token(self, lane_key, ck) -> None:
        if not self._stop.is_set():
            self._work.put((lane_key, ck))

    def _service(self, lane_key: str, ck: tuple) -> None:
        if not tracing.ACTIVE:
            self._service_inner(lane_key, ck, tracing.NOOP)
            return
        # Armed: each delivery attempt is one standalone published span
        # chain (repl.deliver with lane-wait/breaker tags, repl.wire
        # for the target apply) so the lag histogram's p99 decomposes
        # into dequeue wait vs breaker park vs wire time.
        with tracing.op_span("repl", "repl.deliver",
                             {"target": lane_key}) as sp:
            self._service_inner(lane_key, ck, sp)

    def _service_inner(self, lane_key: str, ck: tuple, sp) -> None:
        with self._mu:
            lane = self._lanes.get(lane_key)
            if lane is None:
                return
            chain = lane.chains.get(ck)
            if not chain or ck in lane.active:
                return
            if lane.breaker is not None:
                try:
                    lane.breaker.admit()
                except BreakerOpen:
                    # Parked, not failed: the chain waits out the
                    # cooldown on the timer heap — no attempt burned,
                    # no worker blocked.
                    delay = lane.breaker.retry_in() or 0.05
                    sp.tag(breaker="open",
                           retry_in_ms=round(delay * 1000.0, 1))
                    self.timer.call_later(
                        delay, lambda: self._requeue_token(lane_key, ck))
                    return
            intent = chain[0]
            lane.active.add(ck)
        sp.tag(bucket=intent.bucket, key=intent.key, op=intent.op,
               attempt=intent.attempt + 1,
               lane_wait_ms=round(
                   (time.monotonic() - intent.t_enq) * 1000.0, 1)
               if intent.t_enq else 0.0)
        err: Optional[Exception] = None
        try:
            with tracing.span("repl", "repl.wire",
                              {"target": lane_key}) \
                    if tracing.ACTIVE else tracing.NOOP:
                if intent.op == "put":
                    self._replicate_put(intent.bucket, intent.key,
                                        intent.version_id)
                else:
                    self._replicate_delete(intent.bucket, intent.key,
                                           intent.version_id)
        except Exception as e:  # noqa: BLE001 - classified below
            err = e
            sp.tag(error=type(e).__name__)
        if err is None:
            self._finish(lane, ck, intent, ok=True)
            return
        from minio_tpu.replication.common import (DeliveryError,
                                                  is_transport_error)
        if isinstance(err, DeliveryError):
            # SSE (or otherwise non-replicable) version: terminal on
            # the first attempt, accounted separately from real
            # delivery failures.
            self.sse_skipped += 1
            self._finish(lane, ck, intent, ok=False)
            return
        if lane.breaker is not None and is_transport_error(err):
            lane.breaker.fault()
        intent.attempt += 1
        if intent.attempt < self._RETRIES and not self._stop.is_set():
            with self._mu:
                lane.active.discard(ck)
            # Off-thread backoff: the v1 plane slept this on the
            # worker (head-of-line blocking during target outages).
            delay = min(0.2 * 2 ** (intent.attempt - 1), 5.0)
            self.timer.call_later(
                delay, lambda: self._requeue_token(lane_key, ck))
            return
        self.failed += 1
        self._finish(lane, ck, intent, ok=False)

    def _finish(self, lane: _Lane, ck: tuple, intent: _Intent,
                ok: bool) -> None:
        """Terminal outcome for the chain-head intent: pop it, release
        the chain, stamp status, retire the WAL entry."""
        if ok and lane.breaker is not None:
            lane.breaker.ok()
        stamped = True
        if intent.op == "put":
            stamped = self._set_status(intent.bucket, intent.key,
                                       intent.version_id,
                                       COMPLETED if ok else FAILED)
        elif intent.version_id or not ok:
            # Versioned delete markers carry their own status so the
            # scanner can resync them like any stuck version.
            stamped = self._set_status(intent.bucket, intent.key,
                                       intent.version_id,
                                       COMPLETED if ok else FAILED,
                                       allow_delete_marker=True)
        if ok:
            self.completed += 1
            if intent.t_enq:
                self.lag_hist.observe(time.monotonic() - intent.t_enq)
        if self.wal is not None:
            if ok or intent.op == "put" or stamped:
                self.wal.mark_done(intent.seq)
            # A failed DELETE whose marker could not be stamped keeps
            # its WAL entry: with no durable status to drive the
            # scanner resync, replay is its only road back.
        refresh = None
        with self._mu:
            chain = lane.chains.get(ck)
            if chain and chain[0] is intent:
                chain.pop(0)
                lane.pending -= 1
            if ok:
                nm = lane.newest.get(ck)
                if nm is None or intent.mod_time > nm[0]:
                    lane.newest[ck] = (intent.mod_time,
                                       intent.version_id, intent.op)
            if not chain:
                lane.chains.pop(ck, None)
                # Chain drained on an out-of-order OLDER delivery (an
                # in-flight head pinned ahead of a late resync insert):
                # re-deliver the newest so the target's latest
                # converges back to the source's.
                nm = lane.newest.pop(ck, None)
                if ok and nm is not None and nm[0] > intent.mod_time:
                    refresh = nm
            lane.active.discard(ck)
            self._seen.discard(intent.idk)
            self._unfinished -= 1
            if lane.chains.get(ck):
                self._work.put((lane.target, ck))
        if refresh is not None:
            self.enqueue(intent.bucket, intent.key, refresh[1],
                         refresh[2], mod_time=refresh[0])
        self._refill_one()

    # -- resync (scanner hook) -------------------------------------------

    def scanner_hook(self, es, bucket: str, key: str, versions) -> None:
        """Re-queue versions stuck PENDING/FAILED (crash recovery /
        target-outage resync).  Walks the FULL version stack: older
        stuck versions and delete markers resync too, not just
        versions[0]."""
        del es
        if not versions:
            return
        rules = self.rules_for(bucket)
        if not rules or self.target_for(bucket) is None:
            return
        rule = next((r for r in rules if r.matches(key)), None)
        if rule is None:
            return
        for v in versions:
            meta = getattr(v, "metadata", None) or {}
            status = meta.get(REPL_STATUS_KEY, "")
            if status not in (PENDING, FAILED):
                continue
            if getattr(v, "deleted", False):
                if rule.delete_markers:
                    self.enqueue(bucket, key, v.version_id, "delete",
                                 mod_time=getattr(v, "mod_time", 0))
            elif not meta.get("x-internal-sse-alg"):
                # SSE objects never replicate: their FAILED state is
                # terminal, not resync fuel.
                self.enqueue(bucket, key, v.version_id, "put",
                             mod_time=getattr(v, "mod_time", 0))

    def ilm_deleted(self, bucket: str, key: str, deleted) -> None:
        """Lifecycle-created delete markers replicate like API deletes
        when the bucket's rules replicate markers (ILM expiry on the
        source must not strand a live latest on the target)."""
        if deleted is None or not getattr(deleted, "delete_marker", False):
            return
        if not self.should_replicate(bucket, key, delete=True):
            return
        vid = getattr(deleted, "delete_marker_version_id", "") or ""
        self._set_status(bucket, key, vid, PENDING,
                         allow_delete_marker=True)
        self.enqueue(bucket, key, vid, "delete", mod_time=time.time_ns())

    # -- full-bucket resync (checkpointed, resumable) --------------------

    def _resync_path(self, bucket: str) -> Optional[str]:
        if self._root is None:
            return None
        return os.path.join(self._root, SYS_VOL, WAL_DIR,
                            f"resync-{bucket}.json")

    def _save_resync(self, doc: dict) -> None:
        path = self._resync_path(doc["bucket"])
        if path is None:
            return
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
            os.replace(tmp, path)
        except OSError:
            pass

    def start_resync(self, bucket: str) -> dict:
        """Kick (or resume) a full-bucket resync sweep: every version
        whose status is not COMPLETED re-queues, drive_heal-style
        checkpoint every 64 keys so a crashed sweep resumes where it
        stopped instead of at 'a'."""
        with self._mu:
            t = self._resync_threads.get(bucket)
            if t is not None and t.is_alive():
                return dict(self._resyncs[bucket])
            doc = self._resyncs.get(bucket)
            if doc is None or doc.get("state") != "running":
                # A FAILED sweep resumes at its last checkpoint (the
                # walk up to there already queued); done/fresh sweeps
                # start over.  `running` docs fall through above and
                # keep their own set/checkpoint.
                prior = doc if doc and doc.get("state") == "failed" \
                    else None
                doc = {"bucket": bucket, "state": "running",
                       "set": (prior or {}).get("set", 0),
                       "checkpoint": (prior or {}).get("checkpoint", ""),
                       "scanned": 0, "queued": 0,
                       "started": time.time(), "finished": 0.0}
            self._resyncs[bucket] = doc
            t = threading.Thread(target=self._resync_run,
                                 args=(bucket, doc), daemon=True,
                                 name=f"repl-resync-{bucket}")
            self._resync_threads[bucket] = t
        self._save_resync(doc)
        t.start()
        return dict(doc)

    def _resume_resyncs(self) -> None:
        """Boot-time pickup of sweeps that were mid-flight when the
        process died (state still `running` in the checkpoint doc)."""
        if self._root is None:
            return
        d = os.path.join(self._root, SYS_VOL, WAL_DIR)
        try:
            names = os.listdir(d)
        except OSError:
            return
        for name in names:
            if not name.startswith("resync-") or \
                    not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(d, name), encoding="utf-8") as fh:
                    doc = json.load(fh)
            except (OSError, ValueError):
                continue
            if isinstance(doc, dict) and doc.get("state") == "running" \
                    and doc.get("bucket"):
                with self._mu:
                    self._resyncs[doc["bucket"]] = doc
                self.start_resync(doc["bucket"])

    def _resync_run(self, bucket: str, doc: dict) -> None:
        from minio_tpu.object.scanner import walk_bucket_versions
        rules = self.rules_for(bucket) or []
        start_set = int(doc.get("set", 0))
        try:
            for i, es in enumerate(_layer_sets(self.object_layer)):
                if i < start_set:
                    # Finished before the crash/restart.
                    continue
                if i != start_set:
                    # Keys are hash-distributed across sets: each set's
                    # walk restarts at '' — carrying one set's (lexically
                    # late) checkpoint into the next would skip most of
                    # its keys.
                    doc["set"] = i
                    doc["checkpoint"] = ""
                    self._save_resync(doc)
                for path, versions in walk_bucket_versions(
                        es, bucket, forward_from=doc.get("checkpoint",
                                                         "")):
                    if self._stop.is_set():
                        return
                    doc["scanned"] += 1
                    # Delete-marker policy is per matching rule, same as
                    # scanner_hook — the first rule's prefix says nothing
                    # about keys under a later rule's.
                    rule = next((r for r in rules if r.matches(path)),
                                None)
                    for v in versions:
                        meta = getattr(v, "metadata", None) or {}
                        if meta.get(REPL_STATUS_KEY) == COMPLETED:
                            continue
                        if getattr(v, "deleted", False):
                            if rule is not None and rule.delete_markers:
                                self.enqueue(bucket, path, v.version_id,
                                             "delete",
                                             mod_time=v.mod_time)
                                doc["queued"] += 1
                        elif not meta.get("x-internal-sse-alg") and \
                                self.should_replicate(bucket, path):
                            if not meta.get(REPL_STATUS_KEY):
                                # Pre-config data has no stamp: mark it
                                # so the delivery's COMPLETED/FAILED
                                # transition has a base state.
                                self._set_status(bucket, path,
                                                 v.version_id, PENDING)
                            self.enqueue(bucket, path, v.version_id,
                                         "put", mod_time=v.mod_time)
                            doc["queued"] += 1
                    doc["checkpoint"] = path
                    if doc["scanned"] % _CKPT_EVERY == 0:
                        self._save_resync(doc)
            doc["state"] = "done"
        except Exception as e:  # noqa: BLE001 - surfaced in status
            doc["state"] = "failed"
            doc["error"] = str(e)[:300]
        doc["finished"] = time.time()
        self._save_resync(doc)

    def resync_status(self, bucket: Optional[str] = None):
        with self._mu:
            if bucket:
                doc = self._resyncs.get(bucket)
                return dict(doc) if doc else None
            return {b: dict(d) for b, d in self._resyncs.items()}

    # -- introspection ---------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            lanes = [{"target": ln.target,
                      "state": ln.breaker.state()
                      if ln.breaker is not None else "closed",
                      "pending": ln.pending,
                      "chains": len(ln.chains),
                      "breaker_opens": ln.breaker.opens_total
                      if ln.breaker is not None else 0}
                     for ln in self._lanes.values()]
            out = {"durable": self.durable,
                   "queued": self.queued,
                   "completed": self.completed,
                   "failed": self.failed,
                   "spilled": self.spilled,
                   "dropped": self.dropped,
                   "sse_skipped": self.sse_skipped,
                   "replayed": self.replayed,
                   "pending": self._unfinished,
                   "spill_backlog": len(self._spill),
                   "lanes": lanes,
                   "lag_hist": self.lag_hist.state()}
            if self._resyncs:
                out["resync"] = {b: dict(d)
                                 for b, d in self._resyncs.items()}
        if self.wal is not None:
            out["wal"] = {"path": self.wal.path,
                          "live": self.wal.live_count(),
                          "appended": self.wal.appended,
                          "done": self.wal.done_marks,
                          "discarded": self.wal.discarded,
                          "compactions": self.wal.compactions}
        return out

    def drain(self, timeout: float = 15.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._mu:
                if self._unfinished == 0:
                    return True
            time.sleep(0.05)
        return False

    def stop(self) -> None:
        self._stop.set()
        self.timer.stop()
        for t in self._threads:
            t.join(timeout=2)
        with self._mu:
            # Unconditional: an empty backlog must unlink any stale
            # pending.json, or the next boot replays delivered intents.
            self._maybe_save_spill_locked(force=True)
        if self.wal is not None:
            self.wal.close()
