"""Shared delivery helpers for bucket- and site-replication workers.

One implementation of "push this object's latest bytes + metadata to a
remote S3 endpoint" — the SSE gate, decompression, header rebuild, and
replica marker live HERE so a fix reaches both engines.
"""

from __future__ import annotations

# Replica marker header: set on everything we push so the far side can
# tell replicas apart and never replicates them back (the active-active
# ping-pong breaker).  Shared by PUTs and delete markers.
H_REPLICA = "x-amz-meta-mtpu-replica"
# Source delete-marker version id, carried on replicated deletes.  The
# far side's S3 delete handler mints its marker WITH this id (versioned
# buckets only), so an active-active pair holds the SAME marker version
# and a re-delivered delete replaces in place instead of stacking a
# second marker.
H_REPLICA_DM = "x-mtpu-replica-dm-version"


class DeliveryError(Exception):
    pass


def is_transport_error(exc: BaseException) -> bool:
    """True when the failure means the TARGET (or the path to it) is
    down — connection refused/reset, timeouts, torn responses.  These
    feed the lane circuit breaker.  A decoded S3 error response means
    the peer is alive and answering; it retries but never trips."""
    import http.client as _hc

    from minio_tpu.s3.client import S3ClientError
    if isinstance(exc, S3ClientError):
        return False
    return isinstance(exc, (OSError, _hc.HTTPException))


def push_delete_marker(client, target_bucket: str, key: str,
                       marker_version_id: str = "") -> None:
    """Replicate a delete: a versioned DELETE on the target carrying
    the replica marker (so an active-active peer does not replicate
    the resulting marker back) and the source marker's version id."""
    headers = {H_REPLICA: "true"}
    if marker_version_id:
        headers[H_REPLICA_DM] = marker_version_id
    client.delete_object(target_bucket, key, headers=headers)


def push_object(object_layer, client, bucket: str, key: str,
                version_id: str, target_bucket: str,
                skip_sse: bool = False) -> bool:
    """Replicate one version to `client` (a RemoteS3). Returns False
    when the object is SSE-encrypted and skip_sse is set (encrypted
    objects do not replicate in v1 — their keys bind to one cluster);
    raises DeliveryError for it otherwise."""
    from minio_tpu.object.types import GetOptions
    info, body = object_layer.get_object(
        bucket, key, GetOptions(version_id=version_id))
    if info.internal_metadata.get("x-internal-sse-alg"):
        if skip_sse:
            return False
        raise DeliveryError("SSE objects do not replicate in v1")
    if info.internal_metadata.get("x-internal-comp"):
        # The stored stream is compressed: replicate PLAINTEXT (the
        # target applies its own transforms).
        from minio_tpu.crypto import compress as comp
        body = comp.decompress_range(body, info.internal_metadata,
                                     0, info.size)
    headers = {f"x-amz-meta-{k}": v
               for k, v in info.user_metadata.items()}
    if info.content_type:
        headers["Content-Type"] = info.content_type
    if info.user_tags:
        headers["x-amz-tagging"] = info.user_tags
    # Mark the replica so the far side can tell it apart (and never
    # replicates it back — the ping-pong breaker).
    headers["x-amz-meta-mtpu-replica"] = "true"
    client.put_object(target_bucket, key, body, headers=headers)
    return True
